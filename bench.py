"""Driver benchmark: ResNet-50 synthetic training throughput.

TPU-native counterpart of the reference's headline benchmark
(``examples/tensorflow2_synthetic_benchmark.py``, ResNet-50 synthetic
data, img/sec — ``docs/benchmarks.rst:66-80``).  Trains
:class:`horovod_tpu.models.resnet.ResNet50` with
``DistributedTrainStep`` on whatever devices are present (one real TPU
chip under the driver) and prints ONE JSON line::

    {"metric": "resnet50_img_sec_per_chip", "value": N, "unit": "img/sec/chip",
     "vs_baseline": N}

``vs_baseline`` compares against the only absolute per-accelerator
throughput the reference publishes: ResNet-101 at 1,656.82 img/sec on 16
Pascal P100s (``docs/benchmarks.rst:43``) → 103.55 img/sec per GPU.
(The reference's other numbers are scaling efficiencies; BASELINE.md.)
"""

import argparse
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

# Reference docs/benchmarks.rst:43 — 1656.82 img/sec on 16 GPUs.
BASELINE_IMG_SEC_PER_ACCEL = 1656.82 / 16


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch-size", type=int, default=256,
                   help="per-chip batch size")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--num-iters", type=int, default=5)
    p.add_argument("--num-batches-per-iter", type=int, default=10)
    p.add_argument("--num-warmup-batches", type=int, default=3)
    p.add_argument("--dtype", default="bfloat16",
                   choices=["bfloat16", "float32"])
    p.add_argument("--space-to-depth", action="store_true",
                   help="use the TPU space-to-depth stem instead of the "
                        "reference 7x7 stride-2 stem (round-1 profiling "
                        "saw ~+2%%; does not reproduce outside noise on "
                        "this chip, so the reference stem stays the "
                        "default for metric fidelity)")
    args = p.parse_args()

    import horovod_tpu as hvd
    from horovod_tpu.models.resnet import ResNet50

    hvd.init()
    n_chips = hvd.size()
    platform = jax.devices()[0].platform
    if platform == "cpu" and args.dtype == "bfloat16":
        args.dtype = "float32"       # bf16 is emulated (slow) on host CPU
        if args.image_size == 224:
            args.image_size = 96     # keep the CPU smoke run tractable
            args.batch_size = 16
    log(f"bench: {n_chips} chip(s) on {platform}, "
        f"batch {args.batch_size}/chip, {args.image_size}px, {args.dtype}")

    compute_dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    model = ResNet50(num_classes=1000, dtype=compute_dtype,
                     space_to_depth=args.space_to_depth)

    def loss_fn(params, batch):
        logits = model.apply(params, batch["x"], train=False)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["y"]).mean()

    step = hvd.DistributedTrainStep(
        loss_fn, optax.sgd(0.01 * n_chips, momentum=0.9))
    x0 = jnp.zeros((1, args.image_size, args.image_size, 3), jnp.float32)
    params, opt_state = step.init(
        model.init(jax.random.PRNGKey(0), x0, train=False))

    global_bs = args.batch_size * n_chips
    rng = np.random.RandomState(0)
    batch = step.shard_batch({
        "x": jnp.asarray(
            rng.rand(global_bs, args.image_size, args.image_size, 3),
            jnp.float32),
        "y": jnp.asarray(rng.randint(0, 1000, (global_bs,)), jnp.int32),
    })

    t0 = time.perf_counter()
    for _ in range(args.num_warmup_batches):
        params, opt_state, loss = step(params, opt_state, batch)
    # fence on a host fetch of the loss, not jax.block_until_ready: through
    # remote-device tunnels block_until_ready can return before the step
    # finishes, silently inflating rates; a scalar device_get cannot
    float(loss)
    log(f"bench: warmup (incl. compile) {time.perf_counter() - t0:.1f}s, "
        f"loss={float(loss):.3f}")

    img_secs = []
    for it in range(args.num_iters):
        t0 = time.perf_counter()
        for _ in range(args.num_batches_per_iter):
            params, opt_state, loss = step(params, opt_state, batch)
        float(loss)
        dt = time.perf_counter() - t0
        img_secs.append(global_bs * args.num_batches_per_iter / dt)
        log(f"bench: iter {it}: {img_secs[-1]:.1f} img/sec total")

    # median across iters: robust to single-iteration tunnel/scheduler
    # hiccups (observed ±3% run-to-run drift, PERF_NOTES.md)
    per_chip = float(np.median(img_secs)) / n_chips
    # MFU: fwd+bwd ≈ 3 × 4.1 GFLOP/img at 224px (scaled for other sizes).
    # PERF_NOTES.md derives why the structural ceiling for this model on
    # v5e is ≈26% MFU (HBM-bound).
    flops_per_img = 3 * 4.1e9 * (args.image_size / 224.0) ** 2
    mfu = None
    if platform == "tpu":
        kind = jax.devices()[0].device_kind.lower()
        peaks = {"v5 lite": 197e12, "v5e": 197e12, "v4": 275e12,
                 "v5p": 459e12, "v5": 459e12, "v6 lite": 918e12,
                 "v6e": 918e12}
        hw_peak = next((p for k, p in peaks.items() if k in kind), None)
        if hw_peak:
            mfu = per_chip * flops_per_img / hw_peak
    print(json.dumps({
        "metric": "resnet50_img_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "img/sec/chip",
        "vs_baseline": round(per_chip / BASELINE_IMG_SEC_PER_ACCEL, 3),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "model_tflops_per_sec": round(per_chip * flops_per_img / 1e12, 1),
    }), flush=True)


if __name__ == "__main__":
    main()
