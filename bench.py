"""Driver benchmark: ResNet-50 + transformer-LM synthetic training throughput.

TPU-native counterpart of the reference's synthetic benchmarks
(``examples/tensorflow2_synthetic_benchmark.py`` /
``examples/pytorch_synthetic_benchmark.py`` — ResNet, synthetic data,
img/sec; ``docs/benchmarks.rst:66-80``).  Trains both flagship models
with ``DistributedTrainStep`` on whatever devices are present (one real
TPU chip under the driver) and prints ONE JSON line::

    {"metric": "resnet50_img_sec_per_chip", "value": N, "unit": "img/sec/chip",
     "vs_baseline": N, "mfu": N,
     "transformer_tokens_per_sec": N, "transformer_mfu": N, ...}

``vs_baseline`` compares against the only absolute per-accelerator
throughput the reference publishes: ResNet-101 at 1,656.82 img/sec on 16
Pascal P100s (``docs/benchmarks.rst:43``) → 103.55 img/sec per GPU.
(The reference's other numbers are scaling efficiencies; BASELINE.md.)

The transformer entry (870.9M params, 16L/2048d/16h, seq 1024, bf16,
Pallas flash attention fwd+bwd) is the long-context flagship; the round-4
model-shape scan (PERF_NOTES.md) found head_dim 128 — the MXU lane width
— worth ~+13 MFU points over head_dim 64 at every size, and width >>
depth; 512-lane flash blocks then collapsed the online-softmax
overhead, landing this config at 69.4% MFU / 136.8 model-TF/s
(batch 6) on one v5e — level with the chip's measured matmul envelope.
"""

import argparse
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

# Reference docs/benchmarks.rst:43 — 1656.82 img/sec on 16 GPUs.
BASELINE_IMG_SEC_PER_ACCEL = 1656.82 / 16


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def tpu_compiler_options(args):
    """Per-compile XLA options for the bench step on TPU (measured
    ≈+3% on ResNet-50 from the latency-hiding scheduler; see
    examples/resnet_compile_experiments.py for the A/B harness)."""
    if jax.devices()[0].platform != "tpu" or args.no_compiler_options:
        return None
    return {"xla_tpu_enable_latency_hiding_scheduler": "true"}


def hw_peak_flops():
    """Per-chip peak bf16 TFLOP/s for MFU, or None off-TPU/unknown."""
    if jax.devices()[0].platform != "tpu":
        return None
    kind = jax.devices()[0].device_kind.lower()
    peaks = {"v5 lite": 197e12, "v5e": 197e12, "v4": 275e12,
             "v5p": 459e12, "v5": 459e12, "v6 lite": 918e12,
             "v6e": 918e12}
    return next((p for k, p in peaks.items() if k in kind), None)


def median_rate(step_fn, state, warmup_batches, iters, batches_per_iter,
                units_per_batch, label, on_warmup_end=None):
    """Warm up (compile), then median units/sec across ``iters`` timed
    iterations.  Returns ``(median, warmup_s, state)`` — the warmup
    time (compile + first fenced steps) is the cold-start cost the
    persistent compile cache collapses on a hit, and ``state`` is the
    live post-loop train state (the checkpoint probe snapshots it).
    ``on_warmup_end`` fires once between the fenced warmup and the
    first timed iteration — the hook the input-pipeline path uses to
    snapshot its stall counters so cold-start assembly never pollutes
    the steady-state ``input_stall_s``.

    Fences on a host fetch of the loss, not ``jax.block_until_ready``:
    through remote-device tunnels block_until_ready can return before
    the step finishes, silently inflating rates; a scalar device_get
    cannot.  The HEADLINE metric is the median of the per-iteration
    rates — robust to single-iteration tunnel/scheduler hiccups
    (observed ±3% run-to-run drift, and one BENCH_r05 transformer
    iteration collapsing 25,364→3,061 tok/s) — and any iteration
    deviating >20% from that median is flagged so tail anomalies are
    visible in the log instead of silently polluting the trajectory.
    """
    t0 = time.perf_counter()
    for _ in range(warmup_batches):
        state = step_fn(state)
    warmup_s = 0.0
    if warmup_batches:
        float(state[-1])
        warmup_s = time.perf_counter() - t0
        log(f"bench[{label}]: warmup (incl. compile) "
            f"{warmup_s:.1f}s, loss={float(state[-1]):.3f}")
    if on_warmup_end is not None:
        on_warmup_end()

    def timed_iter(state):
        t0 = time.perf_counter()
        for _ in range(batches_per_iter):
            state = step_fn(state)
        float(state[-1])
        return state, \
            units_per_batch * batches_per_iter / (time.perf_counter() - t0)

    rates = []
    for it in range(iters):
        state, r = timed_iter(state)
        rates.append(r)
        log(f"bench[{label}]: iter {it}: {rates[-1]:.1f}/sec")
    median = float(np.median(rates))

    def dev(r):
        return abs(r - median) / median if median > 0 else 0.0

    # BENCH_r05 anomaly (transformer iter 4: 25,364 -> 3,061 tok/s):
    # deferred host/tunnel work raised by the run's EARLIER windows —
    # warmup compile teardown, probe-buffer frees, transfer-queue
    # flushes — drains at whichever fence it reaches last, and on short
    # runs that is the FINAL timed window.  The cost belongs to the run,
    # not to that window's steps, so when the last iteration is the
    # *sole* >20% low outlier we drain (one untimed fenced iteration,
    # absorbing any still-pending work) and re-measure once.  A genuine
    # slowdown re-measures just as slow and is kept; mid-run outliers
    # are never touched (they still warn below).
    if (len(rates) >= 3 and rates[-1] < median and dev(rates[-1]) > 0.2
            and all(dev(r) <= 0.2 for r in rates[:-1])):
        state, _drain = timed_iter(state)       # untimed role: drain
        state, r = timed_iter(state)
        log(f"bench[{label}]: final iter ({rates[-1]:.1f}/sec) was the "
            f"sole >20% low outlier — trailing-drain re-measure gives "
            f"{r:.1f}/sec; "
            + ("substituting (teardown cost, not throughput)"
               if dev(r) <= 0.2 else "keeping the original (reproduced)"))
        if dev(r) <= 0.2:
            rates[-1] = r
            median = float(np.median(rates))

    for it, r in enumerate(rates):
        if dev(r) > 0.2:
            log(f"bench[{label}]: WARNING iter {it} ({r:.1f}/sec) "
                f"deviates {dev(r) * 100:.0f}% from the median "
                f"{median:.1f}/sec; the headline stays median-of-iters "
                f"— treat this run's tail as anomalous, not the trend")
    return median, warmup_s, state


def run_overlap_probe(args, loss_fn, params, batch, prefix, label):
    """Measure the backward/exchange/fused timings and the achieved
    comm/compute overlap fraction for this model's gradient exchange
    (utils/overlap_probe.py) — the scaling model consumes the measured
    ``overlap_fraction`` instead of assuming one (docs/overlap.md).
    The probed exchange runs the same bucket schedule and hierarchy
    mode the step under test would, so the per-level fields
    (``overlap_exchange_intra_s``/``_cross_s``, ``exchange_rs_scopes``)
    describe the schedule that actually ships."""
    if args.no_overlap_probe:
        return {}
    from horovod_tpu.ops.pallas_kernels import resolve_fused_collectives
    from horovod_tpu.utils.overlap_probe import measure_overlap

    bucket = args.overlap_bucket_bytes if args.overlap_bucket_bytes \
        is not None else args.exchange_bucket_bytes
    main_mode = getattr(args, "fused_collectives", "auto")
    main_on = resolve_fused_collectives(main_mode)

    def probe(fused_mode):
        return measure_overlap(
            loss_fn, params, batch,
            bucket_bytes=bucket, hierarchy=args.hierarchy,
            fused_collectives=fused_mode, iters=3, warmup=1)

    try:
        rep = probe("on" if main_on else "off")
    except Exception as e:  # noqa: BLE001 — probe must not sink the bench
        log(f"bench[{label}]: overlap probe failed ({e}); "
            f"omitting overlap fields")
        return {}
    level = "" if rep.exchange_intra_s is None else (
        f" (intra {rep.exchange_intra_s * 1e3:.2f}ms / cross "
        f"{rep.exchange_cross_s * 1e3:.2f}ms, rs scopes "
        f"{list(rep.rs_scopes)})")
    log(f"bench[{label}]: overlap probe [{rep.hierarchy}/"
        f"fused={rep.fused_collectives}] "
        f"bwd {rep.backward_s * 1e3:.2f}ms "
        f"exch {rep.exchange_s * 1e3:.2f}ms{level} "
        f"fused {rep.fused_s * 1e3:.2f}ms "
        f"-> overlap {rep.overlap_fraction:.2f} "
        f"tail {rep.tail_exchange_s * 1e3:.2f}ms "
        f"({rep.payload_bytes / 1e6:.1f} MB payload, world {rep.world})")
    fields = rep.as_bench_fields(prefix)
    # the OTHER final-bucket schedule, as a control: the artifact then
    # carries tail_exchange_s/overlap_fraction for BOTH paths (the
    # acceptance quantity of docs/fused_kernels.md — the fused tail
    # must shrink relative to its own run's unfused control)
    alt_prefix = prefix + ("unfused_" if main_on else "fused_")
    try:
        alt = probe("off" if main_on else "on")
        fields.update(alt.as_bench_fields(alt_prefix))
        log(f"bench[{label}]: overlap probe control "
            f"[fused={alt.fused_collectives}] tail "
            f"{alt.tail_exchange_s * 1e3:.2f}ms vs "
            f"{rep.tail_exchange_s * 1e3:.2f}ms main")
    except Exception as e:  # noqa: BLE001
        log(f"bench[{label}]: fused-control probe failed ({e}); "
            f"omitting {alt_prefix}* fields")
    return fields


def _rand_images(rng, n, hw):
    """(n, hw, hw, 3) float32 uniform images, generated in chunks so
    the float64 intermediate never materializes the whole dataset."""
    out = np.empty((n, hw, hw, 3), np.float32)
    for i in range(0, n, 64):
        out[i:i + 64] = rng.rand(min(64, n - i), hw, hw, 3)
    return out


def run_pipeline_fed(args, step, host_data, init_state, global_bs,
                     units_per_batch, label, prefix):
    """``--input-mode host``: the pipeline-fed bench path.

    The timed loop consumes host batches through ``ShardedDataset`` →
    ``PrefetchIterator`` (assembly + H2D on background threads, double
    buffered onto the step's sharding), exactly the production feed —
    so the headline rate includes whatever input cost is left exposed.
    Emits the input-plane contract fields: ``input_stall_s`` (per-step
    time the loop blocked waiting for a batch, steady-state only),
    ``input_stall_sync_s`` (a synchronous-feed control: same assembly
    + placement run inline on the critical path), ``prefetch_depth``,
    and the ``h2d_overlap_fraction`` timing probe verifying the
    transfer really hides under an in-flight step
    (utils/input_probe.py).  Returns ``(rate, warmup_s, state,
    fields)``."""
    from horovod_tpu.data import (
        ArraySource,
        PrefetchIterator,
        ShardedDataset,
    )
    from horovod_tpu.utils.input_probe import (
        fence_batch,
        measure_h2d_overlap,
    )

    # the driver process feeds the whole mesh: one rank, global batches
    ds = ShardedDataset(ArraySource(host_data), batch_size=global_bs,
                        rank=0, world=1, seed=0)
    feed = PrefetchIterator(ds.iter_epochs(), place=step.shard_batch,
                            depth=args.prefetch_depth, name=label)
    snap = {"n": 0}

    def on_warm():
        snap["n"] = len(feed.stall_samples)

    rate, warmup_s, state = median_rate(
        lambda s: step(s[0], s[1], next(feed)), init_state,
        args.num_warmup_batches, args.num_iters,
        args.num_batches_per_iter, units_per_batch, label,
        on_warmup_end=on_warm)
    # median per-step stall over the steady-state (timed) window only —
    # robust to one-off queue-wakeup spikes, same discipline as the
    # headline median-of-iters
    timed = feed.stall_samples[snap["n"]:]
    stall = float(np.median(timed)) if timed else 0.0
    depth = feed.depth
    feed.close()

    # synchronous-feed control at the same steady state: identical
    # assembly + placement, inline on the critical path, fenced — the
    # cost the pipeline exists to hide
    gen = ds.iter_epochs()
    sync = []
    for i in range(args.num_batches_per_iter + 1):
        t0 = time.perf_counter()
        b = step.shard_batch(next(gen))
        fence_batch(b)
        dt = time.perf_counter() - t0
        state = step(state[0], state[1], b)
        if i:                    # first call absorbs generator warm-up
            sync.append(dt)
    float(state[-1])
    sync_stall = float(np.median(sync))

    holder = [state]

    def probe_step(batch):
        p, o, loss = step(holder[0][0], holder[0][1], batch)
        holder[0] = (p, o, loss)
        return loss

    gen2 = ds.iter_epochs()
    probe = measure_h2d_overlap(probe_step, lambda: next(gen2),
                                step.shard_batch)
    state = holder[0]
    log(f"bench[{label}]: input feed [pipeline] stall "
        f"{stall * 1e3:.2f}ms/step vs {sync_stall * 1e3:.2f}ms "
        f"synchronous ({sync_stall / stall:.1f}x hidden, depth {depth}, "
        f"h2d overlap {probe.overlap_fraction:.2f})"
        if stall > 0 else
        f"bench[{label}]: input feed [pipeline] stall 0ms/step vs "
        f"{sync_stall * 1e3:.2f}ms synchronous (depth {depth})")
    fields = {
        prefix + "input_mode": "host",
        prefix + "input_stall_s": round(stall, 6),
        prefix + "input_stall_sync_s": round(sync_stall, 6),
        prefix + "input_stall_speedup":
            round(sync_stall / stall, 1) if stall > 0 else None,
        prefix + "prefetch_depth": depth,
        **probe.as_bench_fields(prefix),
    }
    return rate, warmup_s, state, fields


def warmstart_fields(step, warmup_s, prefix=""):
    """Warm-start contract fields (ISSUE 3 / docs/warmstart.md):
    ``warmup_s`` is this run's measured compile+first-steps cost,
    ``cache_hit`` whether the step's executable came from the
    persistent AOT store, and ``warmup_cached_s`` the warm-path cost —
    set only when the cache actually hit, so a second bench run
    reports it against the first run's cold ``warmup_s``."""
    hit = step.compile_cache_hit
    return {
        prefix + "warmup_s": round(warmup_s, 2),
        prefix + "cache_hit": hit,
        prefix + "warmup_cached_s": round(warmup_s, 2) if hit else None,
    }


def run_checkpoint_probe(args, state, label, prefix=""):
    """Measure the checkpoint cost of the live train state two ways:
    ``checkpoint_stall_s`` — train-loop blocking time of an async save
    (the D2H consistent cut only) — vs ``checkpoint_sync_s`` — the
    end-to-end synchronous save (copy + pickle + fsync), the cost the
    async writer takes off the training clock.  The acceptance bar is
    stall ≤ 20% of sync for the 870.9M-param transformer state."""
    if args.no_checkpoint_probe:
        return {}
    import shutil
    import tempfile

    from horovod_tpu.checkpoint import Checkpointer

    root = tempfile.mkdtemp(prefix="bench_ckpt_")
    try:
        payload = {"params": state[0], "opt_state": state[1]}
        # untimed warm-up save: prime the OS page cache and allocator so
        # neither timed variant gets a cold-start penalty — without it
        # the second (async) run measures warm against the sync run's
        # cold, biasing the stall/sync ratio the acceptance bar judges
        warm = Checkpointer(os.path.join(root, "warm"), async_save=False)
        warm.save(0, payload)
        sync = Checkpointer(os.path.join(root, "sync"), async_save=False)
        t0 = time.perf_counter()
        sync.save(0, payload)
        sync_s = time.perf_counter() - t0

        actx = Checkpointer(os.path.join(root, "async"), async_save=True)
        t0 = time.perf_counter()
        actx.save(0, payload)
        stall_s = time.perf_counter() - t0
        actx.wait()
        write_s = actx.last_write_s
        log(f"bench[{label}]: checkpoint stall {stall_s * 1e3:.0f}ms "
            f"(async D2H cut) vs {sync_s * 1e3:.0f}ms synchronous "
            f"end-to-end (background write {write_s * 1e3:.0f}ms)")
        return {
            prefix + "checkpoint_stall_s": round(stall_s, 4),
            prefix + "checkpoint_sync_s": round(sync_s, 4),
        }
    except Exception as e:  # noqa: BLE001 — probe must not sink the bench
        log(f"bench[{label}]: checkpoint probe failed ({e}); "
            f"omitting checkpoint fields")
        return {}
    finally:
        shutil.rmtree(root, ignore_errors=True)


def _apply_wire_dtype(wire):
    """Route a ``wire_dtype`` choice into the runtime: the codec dtype
    lives in the runtime config (``HOROVOD_EXCHANGE_WIRE_DTYPE``), the
    wire *reduction* itself is enabled by the int8-bits compression
    marker.  Returns the ``compression=`` kwarg value ("fp32"/None =
    uncompressed wire)."""
    import horovod_tpu as hvd
    from horovod_tpu.runtime import state as rt_state

    if not wire or wire == "fp32":
        return None
    if rt_state.is_initialized():
        rt_state.global_state().config.exchange_wire_dtype = wire
    os.environ["HOROVOD_EXCHANGE_WIRE_DTYPE"] = wire
    return hvd.Compression.int8


def _apply_reduction(reduction):
    """Route a ``reduction`` choice (``sum``/``adasum``) into the
    runtime config + env, mirroring :func:`_apply_wire_dtype`, so a
    step built after this call resolves it (arg > config > env).
    Returns the resolved value (None = default plain sum, nothing to
    report)."""
    from horovod_tpu.runtime import state as rt_state

    if not reduction or reduction == "sum":
        return None
    if rt_state.is_initialized():
        rt_state.global_state().config.exchange_reduction = reduction
    os.environ["HOROVOD_EXCHANGE_REDUCTION"] = reduction
    return reduction


def exchange_step_kwargs(args):
    """DistributedTrainStep kwargs for ``--shard-optimizer-states``:
    the ZeRO-style sharded exchange with the bucket/hierarchy/wire
    schedule under test (the autotuner varies these per sample point).
    ``--plan`` rides along even without the sharded exchange — the
    plan then just builds the step's mesh and batch sharding."""
    kw = {}
    if getattr(args, "plan", None):
        from horovod_tpu.parallel import ShardingPlan

        # pipeline plans (pp>1) don't flow into the data-parallel train
        # step — they are probed via plan_probe_fields instead
        plan0 = ShardingPlan.from_string(args.plan)
        if plan0.pp == 1:
            kw["plan"] = args.plan
            if plan0.sp > 1:
                # only the shard_map step binds the sp mesh axis the
                # ring attention permutes over
                kw["mode"] = "shard_map"
    if not getattr(args, "shard_optimizer_states", False):
        return kw
    kw.update({"mode": "shard_map", "shard_optimizer_states": True,
               "exchange_bucket_bytes": args.exchange_bucket_bytes,
               "hierarchy": args.hierarchy,
               "fused_collectives": getattr(args, "fused_collectives",
                                            "auto")})
    compression = _apply_wire_dtype(getattr(args, "wire_dtype", None))
    if compression is not None:
        kw["compression"] = compression
    reduction = _apply_reduction(getattr(args, "reduction", None))
    if reduction is not None:
        kw["reduction"] = reduction
    return kw


def exchange_report_fields(args, step):
    """The chosen exchange schedule, emitted next to the throughput it
    produced (the BENCH-JSON half of the acceptance contract)."""
    fields = {}
    if step.plan is not None:
        fields["plan"] = step.plan.to_string()
    if not getattr(args, "shard_optimizer_states", False):
        return fields
    fields.update({"exchange_hierarchy": step.exchange_hierarchy,
                   "exchange_bucket_bytes": args.exchange_bucket_bytes,
                   "step_fused_collectives": step.fused_collectives})
    if getattr(args, "wire_dtype", None):
        fields["exchange_wire_dtype"] = args.wire_dtype
    if getattr(step, "reduction", None) not in (None, "sum"):
        fields["reduction"] = step.reduction
    return fields


#: Microbatch depth of the pipeline probe fields — mirrors the cost
#: model's ``PLAN_SCORE_MICROBATCHES`` so the probe and the plan scorer
#: report the same schedule point.
PLAN_PROBE_MICROBATCHES = 8


def plan_probe_fields(args, hvd):
    """``--plan`` BENCH fields: the canonical (resolved) plan string,
    plus — for pipeline plans — the schedule geometry of both pipeline
    variants at the probe depth: ticks and bubble fraction for GPipe
    (``v=1``) and interleaved-1F1B (the plan's ``v``), straight from
    ``parallel/pipeline``'s schedule math.  The acceptance check reads
    ``pipeline_bubble_1f1b < pipeline_bubble_gpipe`` off these."""
    if not getattr(args, "plan", None):
        return {}
    from horovod_tpu.parallel import (ShardingPlan, bubble_fraction,
                                      pipeline_ticks)

    plan = ShardingPlan.from_string(args.plan).resolve(hvd.size())
    fields = {"plan": plan.to_string()}
    if plan.pp > 1:
        s, v = plan.pp, plan.virtual_stages
        m = PLAN_PROBE_MICROBATCHES
        if m % s:
            m = s * max(1, PLAN_PROBE_MICROBATCHES // s)
        fields.update({
            "pipeline_stages": s,
            "pipeline_virtual": v,
            "pipeline_microbatches": m,
            "pipeline_ticks_gpipe": pipeline_ticks(s, m),
            "pipeline_ticks_1f1b": pipeline_ticks(s, m, v),
            "pipeline_bubble_gpipe": round(bubble_fraction(s, m), 6),
            "pipeline_bubble_1f1b": round(bubble_fraction(s, m, v), 6),
        })
    return fields


def run_resnet(args, hvd):
    from horovod_tpu.models.resnet import ResNet50

    n_chips = hvd.size()
    platform = jax.devices()[0].platform
    batch_size, image_size, dtype = \
        args.batch_size, args.image_size, args.dtype
    if platform == "cpu" and dtype == "bfloat16":
        dtype = "float32"            # bf16 is emulated (slow) on host CPU
        if image_size == 224:
            image_size = 96          # keep the CPU smoke run tractable
            batch_size = 16
    spc = args.steps_per_call if platform == "tpu" else 1
    log(f"bench[resnet]: {n_chips} chip(s) on {platform}, "
        f"batch {batch_size}/chip, {image_size}px, {dtype}, "
        f"steps_per_call {spc}")

    compute_dtype = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    model = ResNet50(num_classes=1000, dtype=compute_dtype,
                     space_to_depth=args.space_to_depth,
                     fused_bwd=args.fused_bwd)

    def loss_fn(params, batch):
        logits = model.apply(params, batch["x"], train=False)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["y"]).mean()

    step = hvd.DistributedTrainStep(
        loss_fn, optax.sgd(0.01 * n_chips, momentum=0.9),
        steps_per_call=spc,
        compiler_options=tpu_compiler_options(args),
        # pipeline-fed batches are fresh per call, so the input slot
        # may be donated (host mode only; synthetic reuses one batch)
        donate_batch=args.input_mode == "host",
        **exchange_step_kwargs(args))
    x0 = jnp.zeros((1, image_size, image_size, 3), jnp.float32)
    params, opt_state = step.init(jax.jit(
        lambda k: model.init(k, x0, train=False))(jax.random.PRNGKey(0)))

    global_bs = batch_size * n_chips
    rng = np.random.RandomState(0)
    batch = step.shard_batch({
        "x": jnp.asarray(
            rng.rand(global_bs, image_size, image_size, 3), jnp.float32),
        "y": jnp.asarray(rng.randint(0, 1000, (global_bs,)), jnp.int32),
    })

    # probe BEFORE the throughput loop: the step donates params, so
    # they are only alive up to the first timed call
    overlap = run_overlap_probe(args, loss_fn, params, batch,
                                "resnet_", "resnet")

    input_fields = {}
    if args.input_mode == "host":
        # pipeline-fed path: host-resident dataset streamed through
        # ShardedDataset -> PrefetchIterator (assembly + H2D off the
        # critical path); 4 epochs' worth of distinct samples, epochs
        # reshuffle
        host = {
            "x": _rand_images(rng, global_bs * 4, image_size),
            "y": rng.randint(0, 1000, (global_bs * 4,)).astype(np.int32),
        }
        rate, warmup_s, _state, input_fields = run_pipeline_fed(
            args, step, host, (params, opt_state, None), global_bs,
            global_bs * spc, "resnet", "resnet_")
    else:
        rate, warmup_s, _state = median_rate(
            lambda s: step(s[0], s[1], batch), (params, opt_state, None),
            args.num_warmup_batches, args.num_iters,
            args.num_batches_per_iter,
            global_bs * spc, "resnet")
    per_chip = rate / n_chips

    # MFU: fwd+bwd ≈ 3 × 4.1 GFLOP/img at 224px (scaled for other sizes).
    # PERF_NOTES.md derives why the structural ceiling for this model on
    # v5e is ≈26% MFU (HBM-bound).
    flops_per_img = 3 * 4.1e9 * (image_size / 224.0) ** 2
    peak = hw_peak_flops()
    return {
        "metric": "resnet50_img_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "img/sec/chip",
        "vs_baseline": round(per_chip / BASELINE_IMG_SEC_PER_ACCEL, 3),
        "mfu": round(per_chip * flops_per_img / peak, 4) if peak else None,
        "model_tflops_per_sec": round(per_chip * flops_per_img / 1e12, 1),
        **warmstart_fields(step, warmup_s, "resnet_"),
        **exchange_report_fields(args, step),
        **overlap,
        **input_fields,
    }


def _sp_ring_twin(args, sp, heads, head_dim, seq_local, causal=True):
    """``--plan`` dp×sp: the fused/jnp ring-attention twin probe.

    Runs the SAME (q, k, v) through the sp ring twice over a dedicated
    sp-only mesh — once through the fused ring-flash dispatch (Pallas
    interpret mode off-TPU), once through the jnp log-sum-exp ring —
    asserts logits AND dq parity, and emits the structural fields
    HLO007 judges from the fused program text:
    ``sp_serial_tail_permutes`` (collective-permute start..done windows
    with no overlapped compute — must be 0), ``sp_collective_permutes``
    (the ring hops; must be >= 2·(sp-1)) and
    ``sp_attention_allgathers`` (full-sequence gathers — must be 0).
    Ring-step geometry (launches, causal skips) comes from
    ``ring_step_schedule``; the wire gauge prices one forward K/V ring.
    Every non-timing field is deterministic across runs (seeded
    tensors, structural counts)."""
    from jax.sharding import PartitionSpec as P

    from horovod_tpu import telemetry
    from horovod_tpu.analysis import cost_model as CM
    from horovod_tpu.ops import pallas_kernels as PK
    from horovod_tpu.parallel.mesh import make_parallel_mesh
    from horovod_tpu.parallel.ring_attention import ring_attention
    from horovod_tpu.utils import hlo as H

    devices = jax.devices()[:sp]
    mesh = make_parallel_mesh(sp=sp, devices=devices)
    layout = os.environ.get("HOROVOD_SP_LAYOUT", "contiguous")
    interpret = devices[0].platform != "tpu"

    b = 2
    rng = np.random.RandomState(0)
    shape = (b, sp * seq_local, heads, head_dim)
    q, k, v = (jnp.asarray(rng.standard_normal(shape) * 0.5, jnp.float32)
               for _ in range(3))
    spec = P(None, "sp", None, None)

    def make(fused):
        def run(q_, k_, v_):
            def f(qq):
                o = ring_attention(qq, k_, v_, "sp", causal=causal,
                                   fused=fused, layout=layout,
                                   interpret=interpret)
                return (o.astype(jnp.float32) ** 2).sum(), o

            (_, o), dq = jax.value_and_grad(f, has_aux=True)(q_)
            return o, dq

        return jax.jit(jax.shard_map(
            run, mesh=mesh, in_specs=(spec,) * 3,
            out_specs=(spec, spec), check_vma=False))

    def timed(fn):
        o, g = fn(q, k, v)          # compile + warm
        jax.block_until_ready(g)
        ts = []
        for _ in range(5):
            t0 = time.perf_counter()
            o, g = fn(q, k, v)
            jax.block_until_ready(g)
            ts.append(time.perf_counter() - t0)
        return float(np.median(ts)), np.asarray(o), np.asarray(g)

    fused_fn, jnp_fn = make(True), make(False)
    fused_s, o_fused, g_fused = timed(fused_fn)
    jnp_s, o_jnp, g_jnp = timed(jnp_fn)
    if not (np.allclose(o_fused, o_jnp, rtol=2e-4, atol=2e-4)
            and np.allclose(g_fused, g_jnp, rtol=2e-4, atol=2e-4)):
        raise SystemExit(
            "bench[sp]: fused ring-flash diverged from the jnp ring "
            "beyond tolerance (logits or dq)")

    text = fused_fn.lower(q, k, v).compile().as_text()
    serial = H.serial_tail_collectives(text,
                                       kinds=("collective-permute",))
    lines = text.splitlines()
    permutes = sum("collective-permute" in ln for ln in lines)
    allgathers = sum("all-gather" in ln for ln in lines)

    sched = PK.ring_step_schedule(sp, causal=causal, layout=layout)
    wire = CM.sp_ring_wire_bytes(seq_local, heads, head_dim, sp, batch=b)
    telemetry.gauge(
        "hvd_sp_ring_wire_bytes",
        "per-chip K/V wire bytes of one forward sp ring").set(wire)
    telemetry.counter(
        "hvd_sp_ring_steps",
        "ring-step kernel launches across the sp ring").inc(
        sched["launches"])
    telemetry.counter(
        "hvd_sp_skipped_ring_steps",
        "fully-masked causal ring steps skipped").inc(sched["skipped"])
    log(f"bench[sp]: ring twin over sp={sp} ({layout}) — fused "
        f"{fused_s:.4f}s vs jnp {jnp_s:.4f}s per call (parity ok), "
        f"launches {sched['launches']}/{sp * sp} "
        f"(skipped {sched['skipped']}), serial tail permutes {serial}")
    return {
        "sp_fused_collectives": "on",
        "sp_layout": layout,
        "sp_ring_steps": sched["launches"],
        "sp_skipped_ring_steps": sched["skipped"],
        "sp_attn_fused_s": round(fused_s, 6),
        "sp_attn_unfused_s": round(jnp_s, 6),
        "sp_tail_s": round(max(0.0, jnp_s - fused_s), 6),
        "sp_serial_tail_permutes": serial,
        "sp_collective_permutes": permutes,
        "sp_attention_allgathers": allgathers,
        "sp_ring_wire_bytes": wire,
    }


def run_transformer(args, hvd):
    import dataclasses as _dc

    from jax import lax

    from horovod_tpu.models import TransformerConfig, TransformerLM

    n_chips = hvd.size()
    platform = jax.devices()[0].platform
    if platform == "cpu":
        # smoke-scale twin for the driver's CPU path / local dev
        layers, d_model, heads, seq, batch, dtype, attn = \
            2, 128, 4, 128, 4, jnp.float32, "dense"
    else:
        layers, d_model, heads, seq, batch, dtype, attn = (
            args.tf_layers, args.tf_d_model, args.tf_heads, args.tf_seq_len,
            args.tf_batch_size, jnp.bfloat16, args.tf_attention)
    # a dp×sp plan shards the sequence through the loss, which forces
    # ring attention (dense/flash would attend within the local chunk
    # only — silently wrong math) — docs/fused_kernels.md
    sp_extent = 1
    if getattr(args, "plan", None):
        from horovod_tpu.parallel import ShardingPlan

        sp_extent = ShardingPlan.from_string(args.plan) \
            .resolve(n_chips).sp
    if sp_extent > 1 and attn in ("dense", "flash"):
        log(f"bench[transformer]: plan has sp={sp_extent} — switching "
            f"attention {attn} -> ring (sequence is sharded)")
        attn = "ring"
    spc = args.steps_per_call if platform == "tpu" else 1
    log(f"bench[transformer]: {n_chips} chip(s) on {platform}, "
        f"{layers}L/{d_model}d, seq {seq}, batch {batch}/chip, "
        f"attention={attn}, steps_per_call {spc}")

    remat = bool(getattr(args, "tf_remat", False))
    if remat and platform == "cpu":
        log("bench[transformer]: --tf-remat ignored on the CPU "
            "smoke-scale config (tiny model, nothing to rematerialize)")
        remat = False
    cfg = TransformerConfig(
        vocab_size=32_000, num_layers=layers, num_heads=heads,
        d_model=d_model, d_ff=4 * d_model, max_seq_len=seq,
        dtype=dtype, attention_impl=attn, remat=remat,
        flash_block=args.tf_flash_block)
    model = TransformerLM(cfg)

    def loss_fn(params, batch):
        kwargs = {}
        if sp_extent > 1:
            # the sp shard holds a contiguous sequence chunk: offset
            # the positional embedding by this rank's chunk start
            t_local = batch["inputs"].shape[1]
            kwargs["positions"] = (lax.axis_index("sp") * t_local
                                   + jnp.arange(t_local))
        logits = model.apply(params, batch["inputs"], **kwargs)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["labels"]).mean()

    step = hvd.DistributedTrainStep(
        loss_fn, optax.adamw(3e-4),
        steps_per_call=spc,
        compiler_options=tpu_compiler_options(args),
        donate_batch=args.input_mode == "host",
        **exchange_step_kwargs(args))
    tokens0 = jnp.zeros((1, seq), jnp.int32)
    # jit the init: eager flax init dispatches hundreds of per-op calls,
    # minutes for an ~1B model through a remote-device tunnel.  Ring/
    # ulysses attention needs a bound sp mesh axis the init does not
    # have — init through a dense twin (identical param shapes).
    init_model = model if attn not in ("ring", "ulysses") else \
        TransformerLM(_dc.replace(cfg, attention_impl="dense"))
    variables = jax.jit(init_model.init)(jax.random.PRNGKey(0), tokens0)
    nparams = sum(x.size for x in jax.tree_util.tree_leaves(variables))
    params, opt_state = step.init(variables)

    global_bs = batch * n_chips
    rng = np.random.RandomState(0)
    raw = rng.randint(0, cfg.vocab_size, (global_bs, seq + 1))
    batch_data = step.shard_batch({
        "inputs": jnp.asarray(raw[:, :-1], jnp.int32),
        "labels": jnp.asarray(raw[:, 1:], jnp.int32),
    })

    log(f"bench[transformer]: {nparams / 1e6:.1f}M params")
    # headline overlap_fraction rides the flagship model (probe before
    # the timed loop — the step donates params on its first call).
    # sp>1: the loss binds the sp mesh axis the standalone probe does
    # not have — the probe rides the dp exchange only, skip it
    if sp_extent > 1:
        log("bench[transformer]: sp>1 — skipping the overlap probe "
            "(its standalone exchange has no sp mesh axis)")
        overlap = {}
    else:
        overlap = run_overlap_probe(args, loss_fn, params, batch_data,
                                    "", "transformer")
    input_fields = {}
    if args.input_mode == "host":
        raw_host = rng.randint(0, cfg.vocab_size,
                               (global_bs * 8, seq + 1))
        host = {"inputs": raw_host[:, :-1].astype(np.int32),
                "labels": raw_host[:, 1:].astype(np.int32)}
        rate, warmup_s, final_state, input_fields = run_pipeline_fed(
            args, step, host, (params, opt_state, None), global_bs,
            global_bs * seq * spc, "transformer", "")
    else:
        rate, warmup_s, final_state = median_rate(
            lambda s: step(s[0], s[1], batch_data),
            (params, opt_state, None),
            args.num_warmup_batches, args.num_iters,
            args.num_batches_per_iter,
            global_bs * seq * spc, "transformer")
    tokens_per_chip_sec = rate / n_chips
    # checkpoint probe on the live 870.9M-param train state: the
    # acceptance quantity is the async save's train-loop stall vs the
    # synchronous end-to-end save (docs/warmstart.md)
    ckpt = run_checkpoint_probe(args, final_state, "transformer")

    # fwd+bwd FLOPs/token: 6·P (params incl. the tied embedding head,
    # whose 6·V·d logits share stands in for the lookup) + causal
    # attention ≈ 6·L·T·d (QKᵀ + AV, fwd 4·T·d + bwd 8·T·d, halved by
    # the causal mask).  PERF_NOTES.md's flagship table uses this same
    # accounting (136.8 TF/s at 25,209 tok/s for 16L/2048d, batch 6).
    flops_per_token = 6 * nparams + 6 * layers * seq * d_model
    peak = hw_peak_flops()
    tf_s = tokens_per_chip_sec * flops_per_token
    # dp×sp plans: the ring twin probe rides along and emits the
    # structural sp_* fields HLO007 judges
    sp_fields = {}
    if sp_extent > 1:
        sp_fields = _sp_ring_twin(args, sp_extent, heads,
                                  d_model // heads, seq // sp_extent)
    return {
        "transformer_tokens_per_sec": round(tokens_per_chip_sec, 1),
        "transformer_mfu": round(tf_s / peak, 4) if peak else None,
        "transformer_tflops_per_sec": round(tf_s / 1e12, 1),
        "transformer_params_m": round(nparams / 1e6, 1),
        # perf-gate comparability keys: tokens/sec at sp=4 is not the
        # same experiment as sp=1, nor seq 4096 as 2048
        "transformer_seq_len": seq,
        "sp": sp_extent,
        **sp_fields,
        **warmstart_fields(step, warmup_s),
        **ckpt,
        **exchange_report_fields(args, step),
        **overlap,
        **input_fields,
    }


def run_vit(args, hvd):
    """Opt-in (--model vit) third benchmark family: ViT-B/16-class.

    Not part of the default driver run; exists to bracket the ResNet
    MFU question — ViT is vision like ResNet but matmul-dense like the
    LM, so its MFU shows whether the vision gap is conv/BN-specific.
    """
    from horovod_tpu.models.vit import ViTConfig, VisionTransformer

    n_chips = hvd.size()
    platform = jax.devices()[0].platform
    if platform == "cpu":
        batch, image, heads, dtype = 4, 32, 4, jnp.float32
        cfg = ViTConfig(image_size=image, patch_size=16, num_layers=2,
                        num_heads=heads, d_model=128, d_ff=512, dtype=dtype)
    else:
        batch, image, heads = \
            args.vit_batch_size, args.image_size, args.vit_heads
        cfg = ViTConfig(image_size=image, patch_size=16,
                        num_heads=heads, dtype=jnp.bfloat16)
    spc = args.steps_per_call if platform == "tpu" else 1
    tokens = cfg.num_patches
    log(f"bench[vit]: {n_chips} chip(s) on {platform}, "
        f"{cfg.num_layers}L/{cfg.d_model}d/{heads}h "
        f"(head_dim {cfg.d_model // heads}), {image}px -> {tokens} patches, "
        f"batch {batch}/chip, steps_per_call {spc}")

    model = VisionTransformer(cfg)

    def loss_fn(params, batch):
        logits = model.apply(params, batch["x"])
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["y"]).mean()

    step = hvd.DistributedTrainStep(
        loss_fn, optax.adamw(3e-4),
        steps_per_call=spc,
        compiler_options=tpu_compiler_options(args))
    x0 = jnp.zeros((1, image, image, 3), jnp.float32)
    variables = jax.jit(model.init)(jax.random.PRNGKey(0), x0)
    nparams = sum(x.size for x in jax.tree_util.tree_leaves(variables))
    params, opt_state = step.init(variables)

    global_bs = batch * n_chips
    rng = np.random.RandomState(0)
    batch_data = step.shard_batch({
        "x": jnp.asarray(rng.rand(global_bs, image, image, 3), jnp.float32),
        "y": jnp.asarray(rng.randint(0, 1000, (global_bs,)), jnp.int32),
    })

    log(f"bench[vit]: {nparams / 1e6:.1f}M params")
    rate, _warmup_s, _state = median_rate(
        lambda s: step(s[0], s[1], batch_data), (params, opt_state, None),
        args.num_warmup_batches, args.num_iters,
        args.num_batches_per_iter,
        global_bs * spc, "vit")
    per_chip = rate / n_chips

    # fwd+bwd FLOPs/img: every param matmul applies per patch token
    # (6·P·T; the classifier head applies once per image — <1%
    # over-count) plus bidirectional attention 12·L·T²·d.  Same 6·P
    # accounting as the transformer entry, without the causal halving.
    flops_per_img = (6 * nparams * tokens
                     + 12 * cfg.num_layers * tokens ** 2 * cfg.d_model)
    peak = hw_peak_flops()
    tf_s = per_chip * flops_per_img
    return {
        "vit_img_sec_per_chip": round(per_chip, 1),
        "vit_mfu": round(tf_s / peak, 4) if peak else None,
        "vit_tflops_per_sec": round(tf_s / 1e12, 1),
        "vit_params_m": round(nparams / 1e6, 1),
    }


def _moe_capacity_factor(args):
    """--moe-capacity-factor, falling back to HOROVOD_MOE_CAPACITY_FACTOR
    then the Switch default 1.25."""
    cf = getattr(args, "moe_capacity_factor", None)
    if cf is None:
        env_cf = os.environ.get("HOROVOD_MOE_CAPACITY_FACTOR")
        cf = float(env_cf) if env_cf else 1.25
    return float(cf)


def _moe_ep_extent(args, hvd):
    """The ep extent of this run — the --plan's ep axis when one is
    given (the expert-parallel execution shape), else 1 (local
    experts).  A perf-gate comparability key: runs at different ep
    extents measure different dispatch schedules."""
    if getattr(args, "plan", None):
        from horovod_tpu.parallel import ShardingPlan

        return ShardingPlan.from_string(args.plan).resolve(hvd.size()).ep
    return 1


def _moe_fused_twin(args, hvd, cfg):
    """``--moe-fused``: the fused/unfused expert-dispatch twin probe.

    Runs the SAME routed SwitchFFN (same params, same tokens, seeded)
    over an ep ring spanning every device twice — once through the
    tile-fused ``a2a ⊗ expert-matmul`` ppermute ring, once through the
    boundary-wide ``all_to_all`` formulation — asserts drop-fraction
    parity (the fused schedule must not change which tokens fit), and
    emits the measured per-call seconds of each schedule plus the
    structural fields HLO006 judges: ``moe_serial_tail_alltoalls``
    (all-to-all start..done windows with no compute, scanned from the
    fused program — must be 0) and the cost-model
    ``moe_ep_wire_bytes``.  Every non-timing field is deterministic
    across two runs (seeded params/tokens, structural counts)."""
    import dataclasses as _dc

    from jax.sharding import PartitionSpec as P

    from horovod_tpu import telemetry
    from horovod_tpu.analysis.cost_model import moe_dispatch_wire_bytes
    from horovod_tpu.models.moe import SwitchFFN
    from horovod_tpu.ops.pallas_kernels import resolve_fused_collectives
    from horovod_tpu.parallel.mesh import make_parallel_mesh
    from horovod_tpu.utils import hlo as H

    devices = jax.devices()
    experts = cfg.num_experts
    ep = len(devices)
    while experts % ep:        # ep must divide the expert count
        ep -= 1
    resolved = "on" if resolve_fused_collectives(args.moe_fused) \
        else "off"
    d = cfg.d_model
    seq = min(128, cfg.max_seq_len)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.standard_normal((ep, seq, d)), jnp.float32)

    local = SwitchFFN(_dc.replace(cfg, ep_axis=None))
    variables = local.init(jax.random.PRNGKey(1), x[:1])
    params = variables["params"]
    mesh = make_parallel_mesh(ep=ep, devices=devices[:ep])

    def make(mode):
        ffn = SwitchFFN(_dc.replace(cfg, ep_axis="ep",
                                    fused_dispatch=mode))

        def run(p, xs):
            y, state = ffn.apply({"params": p}, xs,
                                 mutable=["intermediates"])
            return y, state["intermediates"]["moe_drop_fraction"][0][None]

        return jax.jit(jax.shard_map(
            run, mesh=mesh, in_specs=(P(), P("ep")),
            out_specs=(P("ep"), P("ep")), check_vma=False))

    def timed(fn):
        y, drop = fn(params, x)          # compile + warm
        jax.block_until_ready(y)
        ts = []
        for _ in range(5):
            t0 = time.perf_counter()
            y, drop = fn(params, x)
            jax.block_until_ready(y)
            ts.append(time.perf_counter() - t0)
        return float(np.median(ts)), np.asarray(y), float(
            np.asarray(drop).mean())

    fused_fn, unfused_fn = make("on"), make("off")
    fused_s, y_fused, drop_fused = timed(fused_fn)
    unfused_s, y_unfused, drop_unfused = timed(unfused_fn)
    if drop_fused != drop_unfused:
        raise SystemExit(
            f"bench[moe]: fused dispatch changed the drop fraction "
            f"({drop_fused} vs {drop_unfused}) — the ring schedule "
            f"must route identically to the alltoall formulation")
    if not np.allclose(y_fused, y_unfused, rtol=2e-4, atol=2e-4):
        raise SystemExit(
            "bench[moe]: fused dispatch diverged from the unfused "
            "formulation beyond tolerance")

    text = fused_fn.lower(params, x).compile().as_text()
    serial_a2a = H.serial_tail_collectives(text, kinds=("all-to-all",))
    a2a_lines = sum("all-to-all" in ln for ln in text.splitlines())
    tokens = seq                        # per-shard tokens per dispatch
    elem_bits = 16 if cfg.dtype == jnp.bfloat16 else 32
    wire = moe_dispatch_wire_bytes(
        tokens, d, experts, ep, capacity_factor=cfg.capacity_factor,
        elem_bits=elem_bits)
    telemetry.gauge(
        "hvd_moe_ep_wire_bytes",
        "per-chip ep-ring wire bytes of one dispatch+combine").set(wire)
    log(f"bench[moe]: fused twin over ep={ep} — fused {fused_s:.4f}s "
        f"vs unfused {unfused_s:.4f}s per call, drop {drop_fused:.3f} "
        f"(parity ok), serial tail alltoalls {serial_a2a}, "
        f"fused-program all-to-all lines {a2a_lines}")
    return {
        "moe_fused_collectives": resolved,
        "moe_dispatch_s": round(fused_s, 6),
        "moe_dispatch_unfused_s": round(unfused_s, 6),
        "moe_tail_s": round(max(0.0, unfused_s - fused_s), 6),
        "moe_dispatch_drop_fraction": round(drop_fused, 4),
        "moe_serial_tail_alltoalls": serial_a2a,
        "moe_fused_alltoall_lines": a2a_lines,
        "moe_ep_wire_bytes": wire,
    }


def run_moe(args, hvd):
    """Opt-in (--model moe) fourth benchmark family: Switch-MoE LM.

    Single-chip measurement runs the experts in local mode (all
    resident); the ep_axis dispatch plane is exercised by the dryrun
    and the virtual-mesh tests.  MFU is computed against ACTIVE
    FLOPs/token (top-1 routing: one expert per token), the standard
    MoE accounting."""
    from horovod_tpu.models import MoEConfig, MoETransformerLM, moe_aux_loss

    n_chips = hvd.size()
    platform = jax.devices()[0].platform
    if platform == "cpu":
        layers, d_model, heads, seq, batch, dtype, experts = \
            2, 128, 4, 128, 4, jnp.float32, 4
    else:
        layers, d_model, heads, seq, batch, dtype, experts = (
            args.moe_layers, args.moe_d_model, args.moe_heads,
            args.tf_seq_len, args.moe_batch_size, jnp.bfloat16,
            args.moe_experts)
    spc = args.steps_per_call if platform == "tpu" else 1
    cf = _moe_capacity_factor(args)
    log(f"bench[moe]: {n_chips} chip(s) on {platform}, "
        f"{layers}L/{d_model}d/{heads}h, {experts} experts "
        f"(moe_every 2), seq {seq}, batch {batch}/chip, "
        f"cf {cf}, steps_per_call {spc}")

    cfg = MoEConfig(
        vocab_size=32_000, num_layers=layers, num_heads=heads,
        d_model=d_model, d_ff=4 * d_model, max_seq_len=seq, dtype=dtype,
        attention_impl="flash" if platform == "tpu" else "dense",
        flash_block=args.tf_flash_block, num_experts=experts,
        capacity_factor=cf, moe_every=2)
    model = MoETransformerLM(cfg)

    def loss_fn(params, batch):
        logits, state = model.apply({"params": params}, batch["inputs"],
                                    mutable=["intermediates"])
        ce = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["labels"]).mean()
        return ce + 0.01 * moe_aux_loss(state["intermediates"])

    step = hvd.DistributedTrainStep(
        loss_fn, optax.adamw(3e-4), steps_per_call=spc,
        compiler_options=tpu_compiler_options(args),
        moe_fused=getattr(args, "moe_fused", None),
        moe_capacity_factor=cf)
    tokens0 = jnp.zeros((1, seq), jnp.int32)
    variables = jax.jit(model.init)(jax.random.PRNGKey(0), tokens0)
    leaves = jax.tree_util.tree_flatten_with_path(variables["params"])[0]
    nparams = sum(x.size for _, x in leaves)
    expert_params = sum(
        x.size for path, x in leaves
        if any(getattr(p, "key", "") in ("w1", "w2") for p in path))
    # top-1 active params: one of E experts per token
    active = nparams - expert_params + expert_params // experts
    params, opt_state = step.init(variables["params"])

    global_bs = batch * n_chips
    rng = np.random.RandomState(0)
    raw = rng.randint(0, cfg.vocab_size, (global_bs, seq + 1))
    batch_data = step.shard_batch({
        "inputs": jnp.asarray(raw[:, :-1], jnp.int32),
        "labels": jnp.asarray(raw[:, 1:], jnp.int32),
    })

    # auditability of the active-FLOP MFU: dropped tokens do zero
    # expert work but still count full active FLOPs, so the headline
    # is optimistic by the drop rate — measure and report it, along
    # with the per-expert routing shares behind it
    @jax.jit
    def _probe_routing(params, tokens):
        _, state0 = model.apply({"params": params}, tokens,
                                mutable=["intermediates"])
        # sow tuples flatten away: leaves are the sowed values
        flat = jax.tree_util.tree_flatten_with_path(
            state0["intermediates"])[0]

        def sowed(key):
            return [v for path, v in flat
                    if any(getattr(p, "key", "") == key for p in path)]

        drops = sowed("moe_drop_fraction")
        fracs = sowed("moe_expert_fraction")
        drop = jnp.mean(jnp.stack(drops)) if drops else jnp.zeros(())
        util = jnp.mean(jnp.stack(fracs), axis=0) if fracs \
            else jnp.zeros((experts,))
        return drop, util

    probe_tokens = jnp.asarray(raw[:batch, :-1], jnp.int32)
    drop_init, _ = _probe_routing(variables["params"], probe_tokens)
    drop_init = float(drop_init)
    log(f"bench[moe]: {nparams / 1e6:.1f}M params "
        f"({active / 1e6:.1f}M active/token), init drop fraction "
        f"{drop_init:.3f} at cf {cfg.capacity_factor}")
    rate, _warmup_s, final_state = median_rate(
        lambda s: step(s[0], s[1], batch_data), (params, opt_state, None),
        args.num_warmup_batches, args.num_iters,
        args.num_batches_per_iter,
        global_bs * seq * spc, "moe")
    tokens_per_chip_sec = rate / n_chips
    # the honesty fields are measured AFTER the run's warmup+timed
    # steps trained the router (aux loss pushes toward balance): the
    # init-state routing the old probe reported (41% of tokens doing
    # no expert work in BENCH_r05) never describes the steady state
    # the headline rate was measured in
    drop_fraction, util = _probe_routing(final_state[0], probe_tokens)
    drop_fraction = float(drop_fraction)
    util = [round(float(u), 4) for u in np.asarray(util)]
    log(f"bench[moe]: warmed routing — drop fraction "
        f"{drop_fraction:.3f} (init {drop_init:.3f}), per-expert "
        f"shares {util} (uniform = {1.0 / experts:.3f})")

    from horovod_tpu import telemetry
    telemetry.gauge(
        "hvd_moe_drop_fraction",
        "post-warmup MoE token drop fraction").set(drop_fraction)
    telemetry.gauge(
        "hvd_moe_expert_utilization",
        "minimum per-expert routed-token share").set(
            min(util) if util else 0.0)

    flops_per_token = 6 * active + 6 * layers * seq * d_model
    peak = hw_peak_flops()
    tf_s = tokens_per_chip_sec * flops_per_token
    out = {
        "moe_tokens_per_sec": round(tokens_per_chip_sec, 1),
        "moe_mfu": round(tf_s / peak, 4) if peak else None,
        "moe_active_tflops_per_sec": round(tf_s / 1e12, 1),
        "moe_params_m": round(nparams / 1e6, 1),
        "moe_active_params_m": round(active / 1e6, 1),
        "moe_drop_fraction": round(drop_fraction, 4),
        "moe_drop_fraction_init": round(drop_init, 4),
        "moe_expert_utilization": util,
        "moe_expert_util_min": min(util) if util else None,
        # perf-gate comparability keys: a routing-config change is a
        # schedule change, never diffed as a regression
        "moe_capacity_factor": cf,
        "moe_ep": _moe_ep_extent(args, hvd),
    }
    if getattr(args, "moe_fused", None):
        out.update(_moe_fused_twin(args, hvd, cfg))
    return out


def run_chaos(args, hvd):
    """``--chaos``: the seeded fault-injection probe (docs/faults.md).

    Exercises the detect→decide→recover loop with real components and
    deterministic faults, and emits the robustness contract numbers
    into BENCH JSON:

    * ``detect_s`` — a worker heartbeats, then hangs (beats stop, the
      process never exits); a real ``HealthMonitor`` on a fake clock
      declares it dead.  Detection latency is the silence span at
      declaration — deterministic by construction.
    * ``recovery_s`` / ``steps_lost`` — a seeded ``FaultPlan`` crashes
      a real ``TpuState`` + async-``Checkpointer`` training loop at
      step k; a cold state restores from the last durable checkpoint
      and finishes the run.  ``steps_lost`` is the commits between the
      last durable step and the crash — bounded by
      ``--chaos-checkpoint-every`` by construction.
    * ``chaos_deterministic`` — the whole scenario runs twice from
      scratch; crash point, restored step and the full loss trajectory
      must match exactly.

    With ``--degrade`` the probe additionally runs the plan-aware
    degradation scenario (docs/elastic.md "Degraded mode"): a ``dp=4``
    world loses half its devices mid-interval, the resolver shrinks
    the plan to ``dp=2``, the sharded state reshards to the survivors,
    the lost steps replay, and the next checkpoint boundary promotes
    back — emitting ``degrade_from_plan`` / ``degrade_to_plan`` /
    ``degrade_transition_s`` / ``promoted_step`` and a two-run
    ``degrade_deterministic`` verdict.
    """
    import shutil
    import tempfile

    import numpy as np

    from horovod_tpu import faults, telemetry
    from horovod_tpu.elastic.health import HealthMonitor

    # the probe consumes the structured telemetry the health plane and
    # the elastic state publish (hvd_elastic_* gauges) instead of
    # re-deriving detect/recovery/steps_lost from timing locals
    telemetry.enable()
    seed = args.chaos_seed
    k = args.chaos_crash_step
    every = args.chaos_checkpoint_every
    steps = args.chaos_steps
    if not 1 <= k <= steps:
        raise SystemExit(f"--chaos-crash-step must be in [1, "
                         f"--chaos-steps], got {k} vs {steps}")

    # -- hang detection: heartbeats stop, the "process" stays alive ------
    declared = []
    now = [0.0]
    mon = HealthMonitor(
        lambda h, lr, d, r: declared.append((h, lr, d, r)),
        interval_s=1.0, suspect_misses=2, dead_s=5.0,
        clock=lambda: now[0], start_thread=False)
    for t in range(4):               # healthy beats at t = 0..3
        now[0] = float(t)
        mon.record_heartbeat("chaos-worker", 0, step=t)
    while not declared:              # silence from t = 3 on
        now[0] += 1.0
        mon.check()
    # the monitor published its verdict to the registry before the
    # callback ran — read the detection latency from there
    detect_s = telemetry.value("hvd_elastic_detect_seconds")
    log(f"bench[chaos]: hang declared dead after detect_s={detect_s:.1f} "
        f"(reason: {declared[0][3]}; worker process never exited)")

    # -- seeded crash at step k + cold recovery --------------------------
    def lr_step(params, batch):
        return {"w": params["w"] - 0.1 * (params["w"] - batch)}

    def trajectory(root):
        rng = np.random.RandomState(seed)
        data = rng.rand(steps, 4).astype(np.float32)
        plan = faults.FaultPlan(seed=seed, sim=True).add(
            "worker.commit", "crash", at=k)
        faults.set_plan(plan)
        ckpt = hvd.checkpoint.Checkpointer(root, use_orbax=False)
        state = hvd.elastic.TpuState(
            params={"w": np.full((4,), 2.0, np.float32)},
            checkpointer=ckpt, checkpoint_every=every)
        losses = []
        crashed_at = None
        try:
            while state._commit_count < steps:
                state.params = lr_step(state.params,
                                       data[state._commit_count])
                state.commit()
                losses.append(round(float(np.sum(state.params["w"])), 6))
        except faults.WorkerCrash:
            crashed_at = state._commit_count + 1   # commit k never landed
        finally:
            faults.clear_plan()
        state.wait()
        cold = hvd.elastic.TpuState(
            params={"w": np.zeros((4,), np.float32)},
            checkpointer=ckpt, checkpoint_every=every)
        restored = cold.restore_from_checkpoint()
        if not restored:
            raise RuntimeError("chaos probe: no durable checkpoint to "
                               "recover from")
        # the restore published its own record: latency, restored step,
        # and steps_lost diffed against the committed-step gauge the
        # crashed loop left behind (elastic/state.py)
        recovery_s = telemetry.value("hvd_elastic_restore_seconds")
        resumed_step = int(telemetry.value("hvd_elastic_restored_step"))
        steps_lost = int(telemetry.value("hvd_elastic_steps_lost"))
        while cold._commit_count < steps:
            cold.params = lr_step(cold.params, data[cold._commit_count])
            cold.commit()
            losses.append(round(float(np.sum(cold.params["w"])), 6))
        cold.wait()
        return {"crashed_at": crashed_at, "resumed_step": resumed_step,
                "steps_lost": steps_lost, "recovery_s": recovery_s,
                "losses": losses}

    root = tempfile.mkdtemp(prefix="bench_chaos_")
    try:
        r1 = trajectory(os.path.join(root, "run1"))
        r2 = trajectory(os.path.join(root, "run2"))
    finally:
        shutil.rmtree(root, ignore_errors=True)
    deterministic = (
        r1["crashed_at"] == r2["crashed_at"]
        and r1["resumed_step"] == r2["resumed_step"]
        and r1["losses"] == r2["losses"])
    log(f"bench[chaos]: crash at step {r1['crashed_at']}, resumed from "
        f"durable step {r1['resumed_step']} in "
        f"recovery_s={r1['recovery_s']:.3f} "
        f"(steps_lost={r1['steps_lost']} <= checkpoint_every={every}); "
        f"two-run determinism: {deterministic}")

    # -- guard: silent corruption → detect → rollback → replay -----------
    # the same seeded scenario hvdci gates on (guard/smoke.py), run
    # twice: a corrupt fault perturbs one replica's parameters, the
    # checksum vote names the rank within one check interval, the
    # run rolls back to the pinned last-good checkpoint and replays to
    # a trajectory bit-identical to a fault-free run
    import time as _time

    from horovod_tpu import guard as hvd_guard
    from horovod_tpu.guard import checksum as guard_checksum
    from horovod_tpu.guard import smoke as guard_smoke
    from horovod_tpu.utils.overlap_probe import _median_time

    groot = tempfile.mkdtemp(prefix="bench_guard_chaos_")
    try:
        g1 = guard_smoke._run_chaos(os.path.join(groot, "run1"))
        g2 = guard_smoke._run_chaos(os.path.join(groot, "run2"))
    finally:
        shutil.rmtree(groot, ignore_errors=True)
    guard_deterministic = (
        g1["detected_at"] == g2["detected_at"]
        and g1["steps_replayed"] == g2["steps_replayed"]
        and g1["trajectory"] == g2["trajectory"]
        and np.array_equal(g1["final"], g2["final"]))
    # enabled-path cost: one replica-checksum pass over a params-sized
    # tree (the overlap probe's median-timing harness; amortize by the
    # check interval for the per-step figure)
    probe_params = {"w%d" % i: np.random.RandomState(seed + i)
                    .rand(256, 256).astype(np.float32) for i in range(4)}
    checksum_s = _median_time(
        lambda t: guard_checksum.fingerprint(t), (probe_params,),
        iters=5, warmup=1)
    # disabled-path cost: the module-level hook with no guard armed —
    # the contract tier-1 pins < 5µs/call
    hvd_guard.clear_guard()
    n = 100_000
    t0 = _time.perf_counter()
    for i in range(n):
        hvd_guard.check(i)
    disabled_s = (_time.perf_counter() - t0) / n
    log(f"bench[chaos]: guard corrupt at step {guard_smoke.CORRUPT_AT} "
        f"detected at step {g1['detected_at']} (rank "
        f"{g1['diverged_rank']}), rolled back and replayed "
        f"{g1['steps_replayed']} steps "
        f"(<= every+interval={guard_smoke.EVERY + guard_smoke.INTERVAL}); "
        f"checksum {checksum_s * 1e3:.2f} ms/check, disabled hook "
        f"{disabled_s * 1e9:.0f} ns/step; two-run determinism: "
        f"{guard_deterministic}")
    out = {
        "metric": "chaos_probe",
        "chaos_seed": seed,
        "chaos_steps": steps,
        "chaos_crash_step": k,
        "chaos_checkpoint_every": every,
        "detect_s": round(detect_s, 3),
        "recovery_s": round(r1["recovery_s"], 4),
        "steps_lost": r1["steps_lost"],
        "chaos_resumed_step": r1["resumed_step"],
        "chaos_deterministic": deterministic,
        "guard_corrupt_step": guard_smoke.CORRUPT_AT,
        "guard_check_interval": guard_smoke.INTERVAL,
        "guard_detected_step": g1["detected_at"],
        "guard_diverged_rank": g1["diverged_rank"],
        "guard_steps_replayed": g1["steps_replayed"],
        "guard_deterministic": guard_deterministic,
        "guard_checksum_seconds": round(checksum_s, 6),
        "guard_disabled_overhead_seconds": round(disabled_s, 9),
    }

    # -- plan-aware degradation: kill a slice -> shrink -> replay -> ----
    # -- promote (docs/elastic.md "Degraded mode") ----------------------
    if getattr(args, "degrade", False):
        from horovod_tpu.elastic import smoke as degrade_smoke

        droot = tempfile.mkdtemp(prefix="bench_degrade_")
        try:
            # the seeded scenario runs on a fake clock, so the whole
            # result dict (events, history, trajectory) is comparable
            # bit-for-bit across the two runs — no wall-clock exclusion
            # needed
            d1 = degrade_smoke._scenario(os.path.join(droot, "run1"))
            d2 = degrade_smoke._scenario(os.path.join(droot, "run2"))
            # wall-clock the transition's restore leg against a real
            # checkpointer: re-slice the 4-way sharded state (momentum
            # + error-feedback residuals) to the 2-way survivors
            tckpt = hvd.checkpoint.Checkpointer(
                os.path.join(droot, "time"), use_orbax=False)
            width = degrade_smoke.WIDTH
            degrade_smoke._save(
                tckpt, 1, np.full((width,), 1.5, np.float32),
                np.zeros((width,), np.float32),
                np.zeros((width,), np.float32), degrade_smoke.WORLD)
            t0 = _time.perf_counter()
            degrade_smoke._restore(tckpt, 1, degrade_smoke.SHRUNK)
            transition_s = _time.perf_counter() - t0
        finally:
            shutil.rmtree(droot, ignore_errors=True)
        degrade_deterministic = d1 == d2
        shrink = next(e for e in d1["history"] if e["kind"] == "shrink")
        log(f"bench[chaos]: degrade {d1['from_plan']} -> "
            f"{shrink['to_plan']} at step {shrink['step']} "
            f"(grad_accum={shrink['grad_accum']}, reshard "
            f"{transition_s * 1e3:.1f} ms), replayed "
            f"{d1['steps_lost']} step(s) "
            f"<= checkpoint_every={degrade_smoke.EVERY}, promoted back "
            f"to {d1['final_plan']} at step {d1['promoted_step']}; "
            f"matches fault-free: {d1['final_matches_fault_free']}; "
            f"two-run determinism: {degrade_deterministic}")
        out.update({
            "degrade_from_plan": d1["from_plan"],
            "degrade_to_plan": shrink["to_plan"],
            "degrade_step": shrink["step"],
            "degrade_grad_accum": shrink["grad_accum"],
            "degrade_steps_lost": d1["steps_lost"],
            "degrade_transition_s": round(transition_s, 4),
            "promoted_step": d1["promoted_step"],
            "degrade_matches_fault_free": d1["final_matches_fault_free"],
            "degrade_deterministic": degrade_deterministic,
        })
    return out


def run_serve(args, hvd):
    """``--serve``: the serving-plane SLO probe (docs/serving.md).

    A seeded open-loop traffic generator (arrivals march at
    ``--serve-rps`` regardless of completions) drives the real
    admission queue → continuous batcher → replica pool stack on a
    logical clock the fake executor advances, so every latency is a
    pure function of the seed:

    * the **baseline** pass emits ``serve_p50_latency_s`` /
      ``serve_p99_latency_s`` / ``serve_throughput_rps`` — the fields
      the perf gate diffs (PERF001/PERF005) under the
      ``serve_offered_rps`` comparability key;
    * the **chaos** pass kills one replica mid-load through the
      ``serve.batch`` fault site and asserts the exactly-once
      contract: zero lost responses, zero duplicated responses, every
      in-flight request re-executed exactly once, graceful drain for
      the survivor, and p99 inflation bounded by
      ``--serve-p99-inflation-max``;
    * both passes run **twice**; ``serve_deterministic`` is the
      bit-identity of the full result dicts;
    * the **fleet** pass (``--serve-models``, default 3) drives the
      hvdfleet stack: ``--serve-models`` tenant models behind the
      weighted-fair scheduler, a live weight swap staged mid-load and
      flipped atomically between batches (every post-flip response
      must carry the new fingerprint), a chaos replica kill whose
      lease re-enqueues exactly once AND feeds the autoscale loop,
      and a scale-up that must recover p99 within the probe window.
      The emitted ``serve_models`` / ``serve_tenant_mix`` fields are
      comparability keys: a fleet artifact is never diffed against a
      single-model one (PERF001/PERF005).
    """
    import numpy as np

    from horovod_tpu import faults
    from horovod_tpu.faults import FaultPlan
    from horovod_tpu.serve import (
        ADMITTED,
        AdmissionQueue,
        AutoscaleController,
        ContinuousBatcher,
        FleetBatcher,
        InferenceRequest,
        MultiTenantQueue,
        Replica,
        ReplicaPool,
        WeightRefresher,
    )

    seed = args.serve_seed
    n_requests = args.serve_requests
    rps = float(args.serve_rps)
    max_batch = args.serve_max_batch
    n_models = max(int(args.serve_models), 1)

    def scenario(crash_at=None):
        plan = None
        if crash_at is not None:
            plan = FaultPlan(seed=seed, sim=True).add(
                "serve.batch", "crash", at=crash_at)
            faults.set_plan(plan)
        try:
            now = [0.0]

            def clock():
                return now[0]

            def executor(payloads):
                # service time is a pure function of occupancy: the
                # logical clock makes every latency seeded-deterministic
                now[0] += 0.004 + 0.001 * len(payloads)
                return [round(float(np.asarray(p).sum()), 6)
                        for p in payloads]

            queue = AdmissionQueue(depth=max(2 * n_requests, 64),
                                   clock=clock)
            pool = ReplicaPool(queue, drain_timeout_s=1.0, clock=clock)
            replicas = [pool.add_replica(
                Replica(f"r{i}", executor, host=f"serve-host-{i}",
                        clock=clock)) for i in range(2)]

            got = {}
            batcher = ContinuousBatcher(
                queue, pool, max_batch=max_batch, clock=clock,
                on_response=lambda r: got.setdefault(
                    r.request_id, []).append((r.latency_s, r.requeues)))

            rng = np.random.RandomState(seed)
            payloads = [rng.rand(8).astype(np.float32)
                        for _ in range(n_requests)]
            arrivals = [i / rps for i in range(n_requests)]
            admitted = []
            i = 0
            # open-loop: the next arrival is due at its precomputed
            # time whether or not the pool keeps up; between arrivals
            # the batcher drains, and an idle queue fast-forwards the
            # clock to the next arrival
            while i < n_requests or len(queue):
                if i < n_requests and now[0] >= arrivals[i]:
                    req = InferenceRequest(
                        request_id=f"req-{i:04d}", payload=payloads[i],
                        arrival_s=arrivals[i],
                        deadline_s=arrivals[i] + 2.0)
                    if queue.submit(req) == ADMITTED:
                        admitted.append(req.request_id)
                    i += 1
                    continue
                if len(queue) and pool.serving_count():
                    batcher.step()
                    continue
                if i < n_requests:
                    now[0] = arrivals[i]
                    continue
                break
            drains = [pool.drain(r) for r in pool.replicas() if r.alive]
            lat = sorted(ls[0][0] for ls in got.values() if ls)
            makespan = max(now[0], 1e-9)
            return {
                "admitted": len(admitted),
                "lost": len(set(admitted) - set(got)),
                "duplicates": sum(1 for ls in got.values()
                                  if len(ls) != 1),
                "requeued": sum(1 for ls in got.values()
                                if any(r > 0 for _, r in ls)),
                "p50": round(float(np.percentile(lat, 50)), 6)
                if lat else None,
                "p99": round(float(np.percentile(lat, 99)), 6)
                if lat else None,
                "throughput_rps": round(len(got) / makespan, 3),
                "drains": drains,
                "states": sorted(r.state for r in replicas),
                "makespan_s": round(makespan, 6),
            }
        finally:
            if plan is not None:
                faults.clear_plan()

    _classes = ("interactive", "standard", "batch")
    _weights = (4.0, 2.0, 1.0)

    def fleet_scenario(crash_at):
        """The hvdfleet pass: tenancy + live refresh + closed-loop
        autoscale under a seeded chaos kill, all on the logical
        clock (module docstring bullet 4)."""
        plan = FaultPlan(seed=seed, sim=True).add(
            "serve.batch", "crash", at=crash_at)
        faults.set_plan(plan)
        try:
            now = [0.0]

            def clock():
                return now[0]

            def executor(payloads, model_id=None, weights=None):
                now[0] += 0.004 + 0.001 * len(payloads)
                w = float(np.asarray(weights).sum())
                return [round(float(np.asarray(p).sum()) + w, 6)
                        for p in payloads]

            fleet = MultiTenantQueue(clock=clock)
            models = [f"m{i}" for i in range(n_models)]
            for i, model_id in enumerate(models):
                fleet.add_model(
                    model_id, weight=_weights[i % len(_weights)],
                    slo_class=_classes[i % len(_classes)],
                    depth=max(2 * n_requests // n_models, 32))

            refresher = WeightRefresher(clock=clock)
            old_fp = {m: refresher.register(
                m, np.full(8, i + 1.0, np.float32))
                for i, m in enumerate(models)}

            pool = ReplicaPool(fleet, drain_timeout_s=1.0,
                               scale_up_depth=3 * max_batch,
                               scale_down_depth=0,
                               scale_hold_s=0.01, clock=clock)
            for i in range(2):
                pool.add_replica(Replica(
                    f"r{i}", executor, host=f"serve-host-{i}",
                    clock=clock))

            got = {}
            flips_at_response = {}

            def on_response(r):
                got.setdefault(r.request_id, []).append(
                    (r.model_id, r.weights_fp, r.latency_s,
                     r.requeues))
                flips_at_response.setdefault(
                    r.request_id, refresher.flips)

            batcher = FleetBatcher(
                fleet, pool, refresher=refresher,
                max_batch=max_batch, clock=clock,
                on_response=on_response)

            names = [0]

            def acquire():
                names[0] += 1
                return Replica(f"scale-{names[0]}", executor,
                               host=f"serve-scale-{names[0]}",
                               clock=clock)

            scale_t = [None]
            controller = AutoscaleController(
                pool, acquire, cooldown_s=0.02, min_replicas=1,
                max_replicas=4, clock=clock)

            rng = np.random.RandomState(seed)
            payloads = [rng.rand(8).astype(np.float32)
                        for _ in range(n_requests)]
            arrivals = [i / rps for i in range(n_requests)]
            refresh_at = n_requests // 3
            admitted = []
            i = 0
            while i < n_requests or len(fleet):
                if i < n_requests and now[0] >= arrivals[i]:
                    req = InferenceRequest(
                        request_id=f"req-{i:04d}",
                        payload=payloads[i],
                        model_id=models[i % n_models],
                        arrival_s=arrivals[i],
                        deadline_s=arrivals[i] + 2.0)
                    if fleet.submit(req) == ADMITTED:
                        admitted.append(req.request_id)
                    if i == refresh_at:
                        # the live weight swap, staged mid-load
                        refresher.stage(
                            "m0", np.full(8, 9.0, np.float32))
                    i += 1
                    continue
                if len(fleet) and pool.serving_count():
                    batcher.step()
                    if controller.poll() > 0 and scale_t[0] is None:
                        scale_t[0] = now[0]
                    continue
                if i < n_requests:
                    now[0] = arrivals[i]
                    continue
                break
            drains = [pool.drain(r) for r in pool.replicas()
                      if r.alive]

            new_fp = refresher.fingerprint_of("m0")
            # freshness proof: every m0 response minted after the flip
            # carries the new fingerprint, every pre-flip one the old
            post_flip_fp_ok = all(
                (rs[0][1] == new_fp) if flips_at_response[rid] > 0
                else (rs[0][1] == old_fp["m0"])
                for rid, rs in got.items() if rs[0][0] == "m0")
            # recovery probe: p99 over requests that ARRIVED after the
            # scale-up actuated — the acquired capacity must pull the
            # tail back inside the inflation budget
            req_arrival = {f"req-{j:04d}": arrivals[j]
                           for j in range(n_requests)}
            recover = sorted(
                rs[0][2] for rid, rs in got.items()
                if scale_t[0] is not None
                and req_arrival[rid] >= scale_t[0])
            lat = sorted(rs[0][2] for rs in got.values())
            return {
                "admitted": len(admitted),
                "lost": len(set(admitted) - set(got)),
                "duplicates": sum(1 for ls in got.values()
                                  if len(ls) != 1),
                "requeued": sum(1 for ls in got.values()
                                if any(r[3] > 0 for r in ls)),
                "flips": refresher.flips,
                "rollbacks": refresher.rollbacks,
                "post_flip_fp_ok": post_flip_fp_ok,
                "scale_ups": controller.scale_ups,
                "deaths": pool.deaths,
                "p99": round(float(np.percentile(lat, 99)), 6)
                if lat else None,
                "recover_p99": round(
                    float(np.percentile(recover, 99)), 6)
                if recover else None,
                "picks": dict(sorted(fleet.pick_counts.items())),
                "drains": drains,
                "makespan_s": round(max(now[0], 1e-9), 6),
            }
        finally:
            faults.clear_plan()

    crash_at = max(2, n_requests // (2 * max_batch))
    base1, base2 = scenario(), scenario()
    chaos1, chaos2 = scenario(crash_at=crash_at), scenario(crash_at=crash_at)
    fleet1, fleet2 = fleet_scenario(crash_at), fleet_scenario(crash_at)
    deterministic = base1 == base2 and chaos1 == chaos2 \
        and fleet1 == fleet2

    inflation = round(chaos1["p99"] / base1["p99"], 4) \
        if base1["p99"] else None
    mix = {}
    for i in range(n_models):
        cls = _classes[i % len(_classes)]
        mix[cls] = mix.get(cls, 0) + 1
    tenant_mix = "|".join(f"{c}:{n}" for c, n in sorted(mix.items()))
    fleet_recovered = (fleet1["recover_p99"] is not None
                      and base1["p99"] is not None
                      and fleet1["recover_p99"]
                      <= args.serve_p99_inflation_max * base1["p99"])
    ok = (deterministic
          and base1["lost"] == 0 and base1["duplicates"] == 0
          and chaos1["lost"] == 0 and chaos1["duplicates"] == 0
          and chaos1["requeued"] > 0
          and all(chaos1["drains"])
          and inflation is not None
          and inflation <= args.serve_p99_inflation_max
          and fleet1["lost"] == 0 and fleet1["duplicates"] == 0
          and fleet1["requeued"] > 0
          and fleet1["flips"] == 1 and fleet1["rollbacks"] == 0
          and fleet1["post_flip_fp_ok"]
          and fleet1["scale_ups"] >= 1
          and fleet_recovered
          and all(fleet1["drains"]))
    return {
        "metric": "serve",
        "ok": ok,
        "serve_offered_rps": rps,
        "serve_requests": n_requests,
        "serve_max_batch": max_batch,
        "serve_models": n_models,
        "serve_tenant_mix": tenant_mix,
        "serve_admitted": base1["admitted"],
        "serve_p50_latency_s": base1["p50"],
        "serve_p99_latency_s": base1["p99"],
        "serve_throughput_rps": base1["throughput_rps"],
        "serve_deterministic": deterministic,
        "serve_chaos_lost": chaos1["lost"],
        "serve_chaos_duplicates": chaos1["duplicates"],
        "serve_chaos_requeued": chaos1["requeued"],
        "serve_chaos_p99_latency_s": chaos1["p99"],
        "serve_chaos_p99_inflation": inflation,
        "serve_chaos_drain_graceful": all(chaos1["drains"]),
        "serve_fleet_admitted": fleet1["admitted"],
        "serve_fleet_lost": fleet1["lost"],
        "serve_fleet_duplicates": fleet1["duplicates"],
        "serve_fleet_requeued": fleet1["requeued"],
        "serve_fleet_refresh_flips": fleet1["flips"],
        "serve_fleet_refresh_rollbacks": fleet1["rollbacks"],
        "serve_fleet_post_flip_fp_ok": fleet1["post_flip_fp_ok"],
        "serve_fleet_scale_ups": fleet1["scale_ups"],
        "serve_fleet_deaths": fleet1["deaths"],
        "serve_fleet_p99_latency_s": fleet1["p99"],
        "serve_fleet_recover_p99_latency_s": fleet1["recover_p99"],
        "serve_fleet_p99_recovered": fleet_recovered,
        "serve_fleet_picks": fleet1["picks"],
        "serve_fleet_drain_graceful": all(fleet1["drains"]),
    }


def _plan_axis_values(world, seq_len=0):
    """Canonical dp×fsdp — and, at long context, dp×sp —
    factorizations of ``world``: the sharding plan's data-extent
    search axis for ``--autotune``.  Model extents (pp/ep/tp)
    repartition the network and cannot be flipped inside a timed bench
    loop; sp rides the same shard_map data plane as dp (the batch's
    sequence dim shards instead of its batch dim), so dp×sp splits ARE
    raceable — but only worth sampling once the sequence is long
    enough for attention wire/memory to matter (seq >= 4096,
    docs/fused_kernels.md "Ring-flash attention")."""
    from horovod_tpu.parallel import ShardingPlan

    plans = []
    for fsdp in range(1, world + 1):
        if world % fsdp:
            continue
        plans.append(ShardingPlan(dp=world // fsdp, fsdp=fsdp).to_string())
    if seq_len >= 4096:
        for sp in range(2, world + 1):
            # sp must divide both the world and the sequence
            if world % sp or seq_len % sp:
                continue
            plans.append(
                ShardingPlan(dp=world // sp, sp=sp).to_string())
    return plans


def run_autotune(args, hvd):
    """``--autotune``: tune the jit-path knobs that set the BENCH
    numbers (steps_per_call, flash block) against the measured rate —
    the offline counterpart of the runtime ParameterManager (see
    horovod_tpu/utils/bench_autotune.py).  Cold start: the seed is the
    axis midpoint, NOT the hand-tuned default."""
    import copy

    from horovod_tpu.utils.bench_autotune import ThroughputAutotuner

    if args.model not in ("resnet", "transformer", "moe"):
        raise SystemExit(
            "--autotune tunes one model's knobs per run; pass "
            "--model resnet, --model transformer or --model moe "
            "explicitly")
    model = args.model
    # short measurement windows: relative ranking needs ~2x2 timed
    # calls per point, not the full bench's 5x5
    base = copy.copy(args)
    base.num_iters, base.num_batches_per_iter, base.num_warmup_batches = \
        2, 2, 1

    # measured hardware model for every pruning predictor below:
    # calibration artifact > HOROVOD_HW_PRESET > device_kind preset >
    # v5e (docs/calibration.md).  device_kind steers the preset only on
    # real TPU — the CPU twin keeps pruning against the target-chip
    # default so its autotune walk stays deterministic
    from horovod_tpu.analysis import cost_model as _CM

    dev0 = jax.devices()[0]
    hw = _CM.resolve_hardware_model(
        device_kind=dev0.device_kind if dev0.platform == "tpu" else None)

    # exchange-schedule axes ride any model when the sharded exchange
    # is on: bucket cap (0 = monolithic) and hierarchy mode become
    # cold-start-discoverable knobs exactly like spc/flash_block.  The
    # autotuner's coordinate descent recovers (bucket, hierarchy) from
    # the midpoint seed; every sample lands in the CSV artifact.
    MiB = 1 << 20
    exchange_axes = {}
    if args.shard_optimizer_states:
        exchange_axes = {
            "exchange_bucket_bytes": [0, 1 * MiB, 4 * MiB,
                                      16 * MiB, 64 * MiB],
            "hierarchy": ["flat", "two_level"],
            # the tile-fused final-bucket schedule rides the same
            # coordinate descent (docs/fused_kernels.md); the cost
            # model below prunes this axis without hardware
            "fused_collectives": ["off", "on"],
            # wire codec per exchange hop (fp32 = uncompressed) —
            # cost-model-priced via WIRE_DTYPE_BITS
            "wire_dtype": ["fp32", "int8", "fp8_e4m3"],
            # reduction operator of the outer exchange level
            # (docs/adasum.md) — the cost model prunes adasum unless
            # the batch is large enough to pay its extra DCN round
            "reduction": ["sum", "adasum"],
        }
        plans = _plan_axis_values(
            hvd.size(),
            seq_len=(args.tf_seq_len if args.model == "transformer"
                     else 0))
        if len(plans) > 1:
            # plan space: every dp×fsdp factorization of the world
            # (plus dp×sp at seq>=4096) — the sharding-plan compiler's
            # search axis, pruned by plan_cost_s like the other
            # exchange knobs
            exchange_axes["plan"] = plans
    if args.model == "moe":
        # run_moe never threads the exchange knobs into its step —
        # racing them would sample noise, so the moe grid is the
        # routing axes only
        exchange_axes = {}

    def apply_exchange_point(a, point):
        if exchange_axes:
            a.exchange_bucket_bytes = \
                point["exchange_bucket_bytes"] or None
            a.hierarchy = point["hierarchy"]
            a.fused_collectives = point["fused_collectives"]
            a.wire_dtype = point["wire_dtype"]
            a.reduction = point["reduction"]
            if "plan" in point:
                a.plan = point["plan"]

    def exchange_predictor():
        """Static exchange-schedule scorer for the autotuner's prune
        pass (analysis/cost_model.py): ranks the hierarchy/fused axes
        by predicted exposed wire seconds; axes the model cannot price
        score identically and stay fully measured."""
        if not exchange_axes:
            return None
        from horovod_tpu.analysis.cost_model import (
            score_exchange_schedule,
        )
        from horovod_tpu.runtime import state as rt_state

        sp_wire_s = sp_compute_s = 0.0
        if model == "transformer":
            from horovod_tpu.analysis.cost_model import (
                sp_attention_compute_s,
            )

            d, layers, v = args.tf_d_model, args.tf_layers, 32_000
            payload = 4.0 * (12 * layers * d * d + v * d)
            # 6 FLOPs/param/token forward+backward at the resolved
            # chip's matmul peak (measured when calibrated)
            compute_s = (6.0 * (payload / 4.0) * args.tf_batch_size
                         * args.tf_seq_len) / hw.peak_flops_per_s
            # sp pricing, normalized to sp=1 (the scorer rescales by
            # the sampled plan's sp extent): wire = seconds to move
            # one full K+V through ICI, compute = the full t_global²
            # causal attention of one layer stack
            seq, b = args.tf_seq_len, args.tf_batch_size
            sp_wire_s = (2.0 * 4.0 * b * seq * d * layers
                         / hw.ici_bytes_per_s)
            sp_compute_s = layers * sp_attention_compute_s(
                seq, args.tf_heads, d // args.tf_heads, sp=1,
                batch=b, causal=True, hw=hw)
        else:
            payload = 4.0 * 25.6e6          # ResNet-50 fp32 grads
            compute_s = 3.0 * 4.1e9 * 128 / hw.peak_flops_per_s
        shape = list(rt_state.global_state().mesh.shape.values())
        n_dcn = shape[0] if len(shape) == 2 else 1
        n_ici = shape[-1]
        return lambda point: score_exchange_schedule(
            point, payload, n_dcn=n_dcn, n_ici=n_ici,
            compute_s=compute_s, hw=hw,
            sp_attn_wire_s=sp_wire_s, sp_attn_compute_s=sp_compute_s)

    def moe_predictor():
        """Routing-axis scorer (analysis/cost_model.py): prices each
        capacity_factor / tokens_per_expert sample by predicted expert
        compute + exposed dispatch seconds so the tuner prunes the
        grid before anything races.  Shapes mirror what run_moe will
        actually measure on this platform (CPU pins a tiny twin)."""
        from horovod_tpu.analysis.cost_model import score_moe_schedule

        if jax.devices()[0].platform == "cpu":
            tokens, d, d_ff, experts = 4 * 128, 128, 512, 4
        else:
            tokens = args.moe_batch_size * args.tf_seq_len
            d, d_ff = args.moe_d_model, 4 * args.moe_d_model
            experts = args.moe_experts
        # ep=1: the bench twin's experts are chip-local; the wire term
        # activates when a --plan with an ep extent is under test
        ep = _moe_ep_extent(args, hvd)
        return lambda point: score_moe_schedule(
            point, tokens=tokens, d_model=d, d_ff=d_ff,
            num_experts=experts, ep=ep, fused=True, hw=hw)

    def hbm_feasible():
        """Hard HBM-budget gate for the autotuner (docs/memory.md):
        under HOROVOD_HBM_BUDGET_BYTES every candidate is priced by
        plan_memory_bytes before it is allowed to race, so the tuner
        returns the fastest *feasible* point.  Unset budget = no gate
        (the pre-memory-plane behavior)."""
        budget = _env_budget_bytes()
        if budget is None:
            return None
        from horovod_tpu.analysis.cost_model import (
            plan_fits,
            plan_memory_bytes,
        )

        default_plan = f"dp={hvd.size()}"
        if model == "moe":
            from horovod_tpu.analysis.cost_model import moe_capacity

            d, layers, experts = (args.moe_d_model, args.moe_layers,
                                  args.moe_experts)
            # dense trunk (attention + embeddings + the dense-FFN half
            # of the blocks); expert FFNs priced separately so the
            # budget sees them divide across a plan's ep extent, and
            # the (E, C, d) dispatch+combine buffers grow with the
            # sampled capacity
            param_bytes = 4.0 * (8 * layers * d * d + 32_000 * d)
            expert_bytes = 4.0 * (layers // 2) * experts * 8.0 * d * d
            act_bytes = 4.0 * args.moe_batch_size * args.tf_seq_len \
                * d * layers * 14.0
            tokens = args.moe_batch_size * args.tf_seq_len

            def moe_fits(point):
                tpe = point.get("tokens_per_expert")
                if tpe is not None:
                    slack = float(point.get("capacity_factor") or 1.0)
                    cap = max(1, int(-(-slack * int(tpe) // 1)))
                else:
                    cap = moe_capacity(
                        tokens, experts,
                        float(point.get("capacity_factor") or 1.25))
                buf = 2.0 * experts * cap * d * 4.0
                return plan_fits(
                    plan_memory_bytes(
                        point.get("plan", default_plan),
                        param_bytes=param_bytes,
                        activation_bytes=act_bytes,
                        shard_optimizer_states=(
                            args.shard_optimizer_states),
                        expert_param_bytes=expert_bytes,
                        moe_capacity_buffer_bytes=buf),
                    budget, hw=hw)

            return moe_fits
        if model == "transformer":
            d, layers = args.tf_d_model, args.tf_layers
            param_bytes = 4.0 * (12 * layers * d * d + 32_000 * d)
            act_bytes = 4.0 * args.tf_batch_size * args.tf_seq_len \
                * d * layers * 14.0
        else:
            param_bytes = 4.0 * 25.6e6
            act_bytes = 4.0 * args.batch_size * 16.8e6
        return lambda point: plan_fits(
            plan_memory_bytes(
                point.get("plan", default_plan),
                param_bytes=param_bytes, activation_bytes=act_bytes,
                shard_optimizer_states=args.shard_optimizer_states,
                exchange_bucket_bytes=(
                    point.get("exchange_bucket_bytes") or None)),
            budget, hw=hw)

    if model == "transformer":
        axes = {"steps_per_call": [1, 5, 10, 20, 40],
                "flash_block": [128, 256, 512, 1024],
                **exchange_axes}

        def measure(point):
            a = copy.copy(base)
            a.steps_per_call = point["steps_per_call"]
            a.tf_flash_block = point["flash_block"]
            apply_exchange_point(a, point)
            return run_transformer(a, hvd)["transformer_tokens_per_sec"]
    elif model == "resnet":
        axes = {"steps_per_call": [1, 5, 10, 20, 40],
                **exchange_axes}

        def measure(point):
            a = copy.copy(base)
            a.steps_per_call = point["steps_per_call"]
            apply_exchange_point(a, point)
            return run_resnet(a, hvd)["value"]
    elif model == "moe":
        # routing axes: capacity_factor trades drop fraction against
        # expert FLOPs + dispatch wire; tokens_per_expert scales the
        # nominal per-expert workload through the batch size.  Both
        # are cost-model-priced (moe_predictor) before anything races.
        experts, seq = args.moe_experts, args.tf_seq_len
        axes = {"steps_per_call": [1, 5, 10, 20, 40],
                "capacity_factor": [0.5, 1.0, 1.25, 1.5, 2.0],
                "tokens_per_expert": [32, 64, 128]}

        def measure(point):
            a = copy.copy(base)
            a.steps_per_call = point["steps_per_call"]
            a.moe_capacity_factor = point["capacity_factor"]
            a.moe_batch_size = max(1, round(
                point["tokens_per_expert"] * experts / seq))
            a.moe_fused = None      # no twin probe inside the race
            return run_moe(a, hvd)["moe_tokens_per_sec"]
    else:
        raise SystemExit(f"--autotune supports resnet/transformer/"
                         f"moe, not {model}")

    log_path = args.autotune_log or f"autotune_{model}.csv"
    tuner = ThroughputAutotuner(measure, axes, log_path=log_path,
                                predict=(moe_predictor()
                                         if model == "moe"
                                         else exchange_predictor()),
                                feasible=hbm_feasible())
    best, rate = tuner.run()
    return {"metric": f"autotune_{model}", "value": round(rate, 1),
            "unit": ("img/sec/chip" if model == "resnet"
                     else "tokens/sec/chip"),
            "vs_baseline": None, "best_point": best,
            "hw_model": hw.name,
            "autotune_log": log_path}


def run_hbm_budget(args, hvd):
    """``--hbm-budget``: the memory plane's measurement loop
    (docs/memory.md).  Runs an activation-dominated transformer twin —
    NOT the default smoke twin, whose 32k-vocab logits head dominates
    the high-water and hides remat entirely — at remat ``none`` and
    ``full``, and reports:

    * the donation-aware static HBM high-water of each compiled step
      (``utils/hlo.memory_high_water``) and the cost model's
      ``plan_memory_bytes`` prediction, with their relative error (the
      25% validation bar);
    * the measured recompute-overhead delta (tokens/sec none vs full);
    * the HBM-budgeted planner's winner over the candidate plan space
      for this workload (``HOROVOD_HBM_BUDGET_BYTES``; default 80% of
      the remat-none high-water, so the budget provably bites), run
      twice with a determinism verdict;
    * a live host-offload round-trip of the real optimizer state —
      bit-exactness and the measured ``offload_stall_s``.
    """
    from horovod_tpu import telemetry
    from horovod_tpu.analysis import cost_model as CM
    from horovod_tpu.memory import HostOffloadEngine, search_memory_plans
    from horovod_tpu.models import TransformerConfig, TransformerLM
    from horovod_tpu.parallel.plan import candidate_plans
    from horovod_tpu.utils import hlo as H

    n_chips = hvd.size()
    layers, d_model, heads, seq, batch = 4, 256, 4, 512, 8
    vocab = 512          # small head: activations, not logits, dominate
    plan_str = f"dp={n_chips}"
    log(f"bench[hbm]: {n_chips} chip(s), {layers}L/{d_model}d, "
        f"seq {seq}, batch {batch}/chip, vocab {vocab}")

    global_bs = batch * n_chips
    rng = np.random.RandomState(0)
    raw = rng.randint(0, vocab, (global_bs, seq + 1))

    measured = {}        # policy -> {"hw": bytes, "rate": tok/s, ...}
    nparams = None
    final_opt_state = None
    for policy in ("none", "full"):
        cfg = TransformerConfig(
            vocab_size=vocab, num_layers=layers, num_heads=heads,
            d_model=d_model, d_ff=4 * d_model, max_seq_len=seq,
            dtype=jnp.float32, attention_impl="dense",
            remat_policy=policy)
        model = TransformerLM(cfg)

        def loss_fn(params, batch, model=model):
            logits = model.apply(params, batch["inputs"])
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, batch["labels"]).mean()

        step = hvd.DistributedTrainStep(loss_fn, optax.adamw(3e-4))
        variables = jax.jit(model.init)(
            jax.random.PRNGKey(0), jnp.zeros((1, seq), jnp.int32))
        nparams = sum(x.size
                      for x in jax.tree_util.tree_leaves(variables))
        params, opt_state = step.init(variables)
        batch_data = step.shard_batch({
            "inputs": jnp.asarray(raw[:, :-1], jnp.int32),
            "labels": jnp.asarray(raw[:, 1:], jnp.int32),
        })
        hw = H.memory_high_water(
            step.compiled_text(params, opt_state, batch_data))
        rate, _, final_state = median_rate(
            lambda s: step(s[0], s[1], batch_data),
            (params, opt_state, None), 1, 3, 2,
            global_bs * seq, f"hbm:{policy}")
        measured[policy] = {"hw": hw, "rate": rate}
        final_opt_state = final_state[1]
        telemetry.gauge(
            "hvd_memory_hbm_high_water_bytes",
            "donation-aware static HBM high-water of the compiled "
            "step").labels(policy=policy).set(hw)
        log(f"bench[hbm:{policy}]: high_water "
            f"{hw / 1e6:.1f} MB, {rate:.0f} tok/s")

    # the roofline's inputs, derived from the remat-none dump: the
    # static residents (params + grads + 2 adam slots, fp32) are known
    # exactly, everything above them is the activation footprint
    param_bytes = 4.0 * nparams
    static_bytes = 4.0 * param_bytes
    act_bytes = max(measured["none"]["hw"] - static_bytes, 1.0)
    out = {
        "metric": "hbm_budget",
        "unit": "tokens/sec/chip",
        "plan": plan_str,
        "hbm_param_bytes": param_bytes,
        "hbm_activation_bytes": act_bytes,
    }
    for policy, m in measured.items():
        pred = CM.plan_memory_bytes(
            plan_str, param_bytes=param_bytes,
            activation_bytes=act_bytes, remat_policy=policy).total
        rel_err = abs(pred - m["hw"]) / m["hw"]
        telemetry.gauge(
            "hvd_memory_plan_bytes",
            "plan_memory_bytes roofline prediction").labels(
            policy=policy).set(pred)
        if rel_err > 0.25:
            log(f"bench[hbm:{policy}]: WARNING plan_memory_bytes "
                f"{pred / 1e6:.1f} MB is {rel_err * 100:.0f}% off the "
                f"measured {m['hw'] / 1e6:.1f} MB (25% bar)")
        out.update({
            f"hbm_high_water_bytes_{policy}": m["hw"],
            f"plan_memory_bytes_{policy}": round(pred, 1),
            f"plan_memory_rel_err_{policy}": round(rel_err, 4),
            f"hbm_tokens_per_sec_{policy}": round(m["rate"] / n_chips,
                                                  1),
        })
    out["recompute_overhead"] = round(
        measured["none"]["rate"] / measured["full"]["rate"] - 1.0, 4)

    # the offload=True point must price at the measured footprint, not
    # below it: the engine restores the whole shard before the step
    # (OFFLOAD_RESIDENT_FRACTION = 1.0), so its prediction is held to
    # the same remat-none high-water as the un-offloaded step
    pred_off = CM.plan_memory_bytes(
        plan_str, param_bytes=param_bytes, activation_bytes=act_bytes,
        remat_policy="none", offload_optimizer=True).total
    off_err = abs(pred_off - measured["none"]["hw"]) \
        / measured["none"]["hw"]
    if off_err > 0.25:
        log(f"bench[hbm:offload]: WARNING plan_memory_bytes(offload) "
            f"{pred_off / 1e6:.1f} MB is {off_err * 100:.0f}% off the "
            f"measured {measured['none']['hw'] / 1e6:.1f} MB (25% bar)")
    out.update({
        "plan_memory_bytes_offload": round(pred_off, 1),
        "plan_memory_rel_err_offload": round(off_err, 4),
    })

    # HBM-budgeted planner over the candidate plan space of this
    # workload — default budget 80% of the remat-none high-water so
    # the unconstrained winner cannot fit and the budget provably
    # steers; run twice, determinism is part of the artifact
    budget = _env_budget_bytes() or 0.8 * measured["none"]["hw"]
    world = max(n_chips, 8)
    step_s = global_bs * seq / measured["none"]["rate"]

    def _search():
        return search_memory_plans(
            [p.to_string() for p in candidate_plans(world)],
            param_bytes=param_bytes, activation_bytes=act_bytes,
            budget_bytes=budget, remat_policies=("none", "full"),
            shard_optimizer_states=True, compute_s=step_s,
            n_ici=world)

    winner, winner2 = _search(), _search()
    out.update({
        "hbm_budget_bytes": budget,
        "remat_policy": winner.remat_policy,
        "hbm_high_water_bytes":
            measured[winner.remat_policy]["hw"],
        "plan_memory_bytes": out[
            f"plan_memory_bytes_{winner.remat_policy}"],
        "value": out[f"hbm_tokens_per_sec_{winner.remat_policy}"],
        "budget_plan": winner.plan,
        "budget_microbatches": winner.microbatches,
        "budget_offload_optimizer": winner.offload_optimizer,
        "budget_predicted_bytes": round(winner.predicted_bytes.total, 1),
        "budget_deterministic": winner == winner2,
    })
    log(f"bench[hbm]: budget {budget / 1e6:.1f} MB -> "
        f"{winner.summary()}")

    # live host-offload round-trip of the real optimizer state: the
    # stall is the H2D wait (~0 when the D2H hid under the step), and
    # the restore must be bit-exact
    with HostOffloadEngine(name="bench", depth=2) as engine:
        engine.offload(0, final_opt_state)
        restored = engine.fetch(0, final_opt_state)
        exact = all(
            np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(
                jax.tree_util.tree_leaves(final_opt_state),
                jax.tree_util.tree_leaves(restored)))
        out.update({
            "offload_stall_s": round(engine.stall_s, 6),
            "offload_roundtrip_exact": exact,
            "offload_fallbacks": engine.fallbacks,
        })
    if not exact:
        log("bench[hbm]: WARNING offload round-trip was NOT bit-exact")
    return out


def run_sp_budget(args, hvd):
    """``--sp-budget``: the long-context memory certification loop
    (docs/fused_kernels.md "Ring-flash attention", docs/memory.md).

    Compiles the SAME tiny activation-dominated LM at seq 4096 twice —
    a flash sp=1 step (plan ``dp=n``) and a ring-flash sp=2 step
    (``dp=n/2,sp=2``), both through the blocked Pallas kernels
    (interpreter mode off-TPU) so neither twin materializes the (T, T)
    scores and the comparison isolates the sequence shard — no timed
    loop, the artifact is the compiled memory analysis:

    * validates ``plan_memory_bytes``' 1/sp activation scaling against
      the compiled high-waters (the 25% bar): the sp=2 prediction is
      priced from the sp=1-derived activation footprint, NOT from its
      own measurement, so the halving is a real cross-check;
    * picks an HBM budget between the two footprints (or
      ``HOROVOD_HBM_BUDGET_BYTES``) and certifies that ``plan_fits``
      admits the sp=2 plan while REFUSING sp=1 — the budgeted
      planner's long-context story in one artifact.
    """
    import dataclasses

    from jax import lax

    from horovod_tpu.analysis import cost_model as CM
    from horovod_tpu.models import TransformerConfig, TransformerLM
    from horovod_tpu.utils import hlo as H

    n_chips = hvd.size()
    if n_chips < 2 or n_chips % 2:
        raise SystemExit(
            f"bench[sp-budget]: needs an even device count >= 2 to "
            f"compile the dp×sp twin, got {n_chips} (force host "
            f"devices via XLA_FLAGS=--xla_force_host_platform_"
            f"device_count=N)")
    layers, d_model, heads, vocab = 2, 64, 4, 256
    seq = max(4096, args.tf_seq_len)
    plans = {1: f"dp={n_chips}", 2: f"dp={n_chips // 2},sp=2"}
    log(f"bench[sp-budget]: {n_chips} chip(s), {layers}L/{d_model}d, "
        f"seq {seq}, racing {plans[1]} vs {plans[2]}")

    interpret = jax.devices()[0].platform != "tpu"
    hw = {}
    nparams = None
    for sp, plan_str in plans.items():
        cfg = TransformerConfig(
            vocab_size=vocab, num_layers=layers, num_heads=heads,
            d_model=d_model, d_ff=4 * d_model, max_seq_len=seq,
            dtype=jnp.float32,
            attention_impl=("ring" if sp > 1 else "flash"),
            fused_collectives="on", flash_interpret=interpret)
        model = TransformerLM(cfg)
        init_model = model if sp == 1 else \
            TransformerLM(dataclasses.replace(
                cfg, attention_impl="dense", flash_interpret=False))

        def loss_fn(params, batch, model=model, sp=sp):
            kwargs = {}
            if sp > 1:
                t_local = batch["inputs"].shape[1]
                kwargs["positions"] = (lax.axis_index("sp") * t_local
                                       + jnp.arange(t_local))
            logits = model.apply(params, batch["inputs"], **kwargs)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, batch["labels"]).mean()

        step = hvd.DistributedTrainStep(
            loss_fn, optax.adamw(3e-4), plan=plan_str,
            mode=("shard_map" if sp > 1 else "pjit"))
        variables = jax.jit(init_model.init)(
            jax.random.PRNGKey(0), jnp.zeros((1, seq), jnp.int32))
        nparams = sum(x.size
                      for x in jax.tree_util.tree_leaves(variables))
        params, opt_state = step.init(variables)
        global_bs = n_chips // sp       # one sequence per dp replica
        rng = np.random.RandomState(0)
        raw = rng.randint(0, vocab, (global_bs, seq + 1))
        batch_data = step.shard_batch({
            "inputs": jnp.asarray(raw[:, :-1], jnp.int32),
            "labels": jnp.asarray(raw[:, 1:], jnp.int32),
        })
        hw[sp] = H.memory_high_water(
            step.compiled_text(params, opt_state, batch_data))
        log(f"bench[sp-budget:{plan_str}]: high_water "
            f"{hw[sp] / 1e6:.1f} MB")

    # roofline inputs, derived ONLY from the sp=1 twin: static
    # residents (params + grads + 2 adam slots, fp32) are known
    # exactly, everything above them is the activation footprint
    param_bytes = 4.0 * nparams
    act_bytes = max(hw[1] - 4.0 * param_bytes, 1.0)
    preds = {
        sp: CM.plan_memory_bytes(plan_str, param_bytes=param_bytes,
                                 activation_bytes=act_bytes)
        for sp, plan_str in plans.items()
    }
    rel_err = abs(preds[2].total - hw[2]) / hw[2]
    if rel_err > 0.25:
        log(f"bench[sp-budget]: WARNING plan_memory_bytes(sp=2) "
            f"{preds[2].total / 1e6:.1f} MB is {rel_err * 100:.0f}% "
            f"off the measured {hw[2] / 1e6:.1f} MB (25% bar)")

    budget = _env_budget_bytes() or (preds[1].total
                                     + preds[2].total) / 2.0
    fits = {sp: CM.plan_fits(preds[sp], budget) for sp in plans}
    if not fits[2] or fits[1]:
        log(f"bench[sp-budget]: WARNING budget {budget / 1e6:.1f} MB "
            f"did not separate the plans (sp=2 fits: {fits[2]}, "
            f"sp=1 fits: {fits[1]})")
    log(f"bench[sp-budget]: budget {budget / 1e6:.1f} MB -> "
        f"certified {plans[2] if fits[2] else None}, "
        f"refused {plans[1] if not fits[1] else None}")
    return {
        "metric": "sp_budget",
        "unit": "bytes",
        "value": hw[2],
        "plan": plans[2],
        "sp": 2,
        "transformer_seq_len": seq,
        "sp_budget_bytes": budget,
        "sp_hbm_high_water_bytes_sp1": hw[1],
        "sp_hbm_high_water_bytes_sp2": hw[2],
        "sp_plan_memory_bytes_sp1": round(preds[1].total, 1),
        "sp_plan_memory_bytes_sp2": round(preds[2].total, 1),
        "sp_plan_memory_rel_err": round(rel_err, 4),
        "sp_budget_certified_plan": plans[2] if fits[2] else None,
        "sp_budget_refused_plan": plans[1] if not fits[1] else None,
    }


def run_adasum(args, hvd):
    """``--adasum``: the reduction-operator convergence probe
    (docs/adasum.md "Batch-scaling procedure").

    Runs the seeded quadratic twin ``analysis/adasum_smoke.py``
    shares with hvdci gate 10 — three trajectories off one seed:
    plain sum at the base batch (the reference), adasum at
    ``--adasum-batch-scale``× the global batch, and plain summation at
    the same scale (the naive scale-out whose effective step crosses
    the stability edge) — and emits them plus the cost model's priced
    extra DCN wire (``adasum_extra_wire_bytes``, for the transformer
    payload this bench would exchange at the current mesh
    factorization) into BENCH JSON.  The fields are the artifact half
    of the acceptance contract: ``reduction`` keys perf-gate
    comparability, ``adasum_dot_wire_bytes`` is the modeled price the
    autotuner's batch crossover trades against."""
    from horovod_tpu.analysis import adasum_smoke as AS
    from horovod_tpu.analysis import cost_model as CM
    from horovod_tpu.runtime import state as rt_state

    scale = max(2, int(getattr(args, "adasum_batch_scale", 2)))
    seed = 42
    steps = 40
    # stability edge scales with the replica count: pick the base lr
    # so the single-replica step is stable while the scaled *summed*
    # step is not — scale·lr·h_max = 2.4 > 2 > lr·h_max (h_max = 1.5)
    lr = round(1.6 / scale, 4)
    base = AS.simulate_convergence(1, "sum", steps=steps, seed=seed,
                                   lr=lr)
    ada = AS.simulate_convergence(scale, "adasum", steps=steps,
                                  seed=seed, lr=lr)
    summed = AS.simulate_convergence(scale, "sum", steps=steps,
                                     seed=seed, lr=lr)
    log(f"bench[adasum]: scale {scale}x, lr {lr}: final loss "
        f"base {base[-1]:.4g} · adasum {ada[-1]:.4g} · "
        f"sum {summed[-1]:.4g}")

    # price the extra DCN round for the transformer payload this
    # bench's sharded exchange would move, at the runtime mesh's
    # factorization — the same inputs the autotune predictor uses
    d, layers, v = args.tf_d_model, args.tf_layers, 32_000
    payload = 4.0 * (12 * layers * d * d + v * d)
    shape = list(rt_state.global_state().mesh.shape.values())
    n_dcn = shape[0] if len(shape) == 2 else 1
    n_ici = shape[-1]
    dot_wire = CM.adasum_extra_wire_bytes(payload, n_dcn=n_dcn,
                                          n_ici=n_ici)
    from horovod_tpu import telemetry

    telemetry.gauge(
        "hvd_adasum_dot_wire_bytes",
        "modeled extra per-step DCN bytes of the adasum outer-level "
        "exchange (analysis/cost_model.py)").set(dot_wire)
    _apply_reduction("adasum")
    rnd = lambda xs: [round(float(x), 8) for x in xs]  # noqa: E731
    return {
        "metric": "adasum",
        "unit": "final_loss",
        "value": round(float(ada[-1]), 8),
        "reduction": "adasum",
        "adasum_batch_scale": scale,
        "adasum_seed": seed,
        "adasum_steps": steps,
        "adasum_lr": lr,
        "adasum_dot_wire_bytes": dot_wire,
        "adasum_loss_trajectory": rnd(ada),
        "sum_base_loss_trajectory": rnd(base),
        "sum_scaled_loss_trajectory": rnd(summed),
    }


def _env_budget_bytes():
    """HOROVOD_HBM_BUDGET_BYTES as a float, or None when unset."""
    raw = os.environ.get("HOROVOD_HBM_BUDGET_BYTES")
    return float(raw) if raw not in (None, "") else None


def telemetry_fields():
    """The hvdtel fold (docs/metrics.md): final counters of the run's
    registry under the ``metrics`` key — schema-checked by hvdci, and
    deterministic for a seeded workload (gauges/durations stay in the
    JSONL snapshot log, not here)."""
    from horovod_tpu import telemetry

    if not telemetry.enabled():
        return {}
    return {"metrics": telemetry.bench_metrics()}


def run_calibrate(args, hvd):
    """``--calibrate``: the collective microbenchmark suite — sweep
    every fabric level of the runtime mesh across message sizes for
    each collective family, time a matmul and an HBM stream, fit the
    alpha-beta model per (level, collective), and persist the
    versioned calibration artifact ``HardwareModel.from_calibration``
    and every pricing consumer read through
    ``HOROVOD_CALIBRATION_PATH`` (docs/calibration.md).

    ``--calibrate-sim`` swaps the measured sweeps for the seeded
    simulator (``analysis/calibration.py``) — the deterministic CI
    path hvdci gate 9 runs twice and requires bit-identical."""
    from jax import lax
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from horovod_tpu import telemetry
    from horovod_tpu.analysis import calibration as CAL
    from horovod_tpu.runtime import state as rt_state

    points = telemetry.counter("hvd_calibration_points_total",
                               "timed sweep points")
    fits = telemetry.counter("hvd_calibration_fits_total",
                             "fitted alpha-beta curves")
    out_path = args.calibrate_out or "CALIBRATION.json"

    if args.calibrate_sim:
        art = CAL.simulated_calibration(seed=args.calibrate_seed)
        for name in art["level_order"]:
            colls = art["levels"][name]["collectives"]
            fits.inc(len(colls))
            points.inc(sum(c["n_points"] for c in colls.values()))
        CAL.save_artifact(art, out_path)
        log(f"bench: wrote simulated calibration to {out_path} "
            f"(fingerprint {art['calibration_fingerprint']})")
        return {"metric": "calibrate", "value": art["fit_residual_max"],
                "unit": "rms_rel_residual", "vs_baseline": None,
                "calibration_out": out_path,
                "calibration_fingerprint":
                    art["calibration_fingerprint"],
                "calibration_source": "simulated"}

    mesh = rt_state.global_state().mesh
    # innermost-first level order, extent-1 axes dropped: a sweep over
    # a 1-extent axis times a no-op and the fit cannot separate alpha
    # from beta (non-positive slope)
    level_names = [n for n in reversed(list(mesh.shape.keys()))
                   if int(mesh.shape[n]) > 1]
    if not level_names:
        raise SystemExit("--calibrate needs a multi-device mesh to "
                         "time collectives; use --calibrate-sim for "
                         "the deterministic single-device path")
    platform = jax.devices()[0].platform
    sweep = [int(s) for s in CAL.DEFAULT_SWEEP_BYTES
             if s <= (args.calibrate_max_bytes
                      or (2 ** 22 if platform != "tpu" else 2 ** 27))]

    def time_s(fn, *xs, reps=3):
        jax.block_until_ready(fn(*xs))          # compile + warm
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*xs))
            best = min(best, time.perf_counter() - t0)
        return best

    def collective_body(coll, axis_name, n_axis):
        if coll == "allreduce":
            return lambda x: lax.psum(x, axis_name)
        if coll == "reduce_scatter":
            return lambda x: lax.psum_scatter(x, axis_name, tiled=True)
        if coll == "all_gather":
            return lambda x: lax.all_gather(x, axis_name, tiled=True)
        if coll == "ppermute":
            perm = [(i, (i + 1) % n_axis) for i in range(n_axis)]
            return lambda x: lax.ppermute(x, axis_name, perm)
        return lambda x: lax.all_to_all(
            x.reshape(n_axis, -1), axis_name, 0, 0).reshape(-1)

    level_fits = {}
    level_extents = {}
    for name in level_names:
        n_axis = int(mesh.shape[name])
        level_extents[name] = n_axis
        fits_here = []
        for coll in CAL.CALIBRATED_COLLECTIVES:
            sizes, times = [], []
            for nbytes in sweep:
                elems = max(n_axis, nbytes // 4)
                elems += (-elems) % n_axis      # a2a/RS divisibility
                body = collective_body(coll, name, n_axis)
                fn = jax.jit(shard_map(
                    lambda x, _b=body: jnp.sum(_b(x)), mesh=mesh,
                    in_specs=P(), out_specs=P(), check_rep=False))
                x = jnp.zeros((elems,), jnp.float32) + 1.0
                sizes.append(float(elems * 4))
                times.append(time_s(fn, x))
                points.inc()
            fits_here.append(CAL.fit_level(coll, sizes, times))
            fits.inc()
        level_fits[name] = fits_here

    # matmul FLOP rate + HBM stream rate on one chip
    k = 1024 if platform != "tpu" else 4096
    a = jnp.ones((k, k), jnp.bfloat16)
    t_mm = time_s(jax.jit(lambda m: m @ m), a)
    matmul_flops = 2.0 * k ** 3 / t_mm
    stream = jnp.ones((2 ** 22,), jnp.float32)
    t_hbm = time_s(jax.jit(lambda v: v * 1.0000001), stream)
    hbm_rate = 2.0 * stream.size * 4 / t_hbm    # read + write

    art = CAL.build_artifact(
        device_kind=jax.devices()[0].device_kind,
        platform=platform,
        n_devices=hvd.size(),
        mesh_shape=[int(s) for s in mesh.shape.values()],
        level_order=level_names,
        level_fits=level_fits,
        level_extents=level_extents,
        matmul_flops_per_s=matmul_flops,
        hbm_bytes_per_s=hbm_rate,
        source="measured",
        jax_version=jax.__version__)
    errs = CAL.validate_calibration(art)
    if errs:
        raise SystemExit("bench --calibrate produced an invalid "
                         "artifact: " + "; ".join(errs))
    CAL.save_artifact(art, out_path)
    log(f"bench: wrote measured calibration to {out_path} "
        f"(fingerprint {art['calibration_fingerprint']}, max fit "
        f"residual {art['fit_residual_max']:.4f})")
    return {"metric": "calibrate", "value": art["fit_residual_max"],
            "unit": "rms_rel_residual", "vs_baseline": None,
            "calibration_out": out_path,
            "calibration_fingerprint": art["calibration_fingerprint"],
            "calibration_source": "measured"}


def artifact_metadata(hvd):
    """BENCH-JSON provenance (``schema_version`` 1, docs/perf_gate.md):
    the perf gate validates these fields and REFUSES to diff artifacts
    whose device/mesh identity differs — a v5e number compared against
    a v4 run is not a regression, it's a category error.  Legacy
    artifacts without the block still load as schema 0."""
    meta = {
        "schema_version": 1,
        "jax_version": jax.__version__,
        "platform": jax.devices()[0].platform,
        "device_kind": jax.devices()[0].device_kind,
        "n_devices": hvd.size(),
    }
    try:
        import jaxlib

        meta["jaxlib_version"] = getattr(jaxlib, "__version__", None)
    except Exception:  # noqa: BLE001 — provenance must not sink the bench
        meta["jaxlib_version"] = None
    try:
        from horovod_tpu.runtime import state

        mesh = state.global_state().mesh
        meta["mesh_shape"] = [int(s) for s in mesh.shape.values()]
    except Exception:  # noqa: BLE001
        meta["mesh_shape"] = [1, hvd.size()]
    # calibration provenance: when this run priced/pruned against a
    # measured hardware model, stamp its identity so the perf gate can
    # refuse cross-hardware diffs (docs/calibration.md)
    cal_path = os.environ.get("HOROVOD_CALIBRATION_PATH")
    if cal_path:
        try:
            with open(cal_path) as f:
                cal = json.load(f)
            from horovod_tpu.analysis import cost_model as CM

            meta["calibration_fingerprint"] = \
                cal.get("calibration_fingerprint") \
                or CM.calibration_fingerprint(cal)
            meta["calibration_device_kind"] = cal.get("device_kind")
        except Exception:  # noqa: BLE001 — provenance must not sink the bench
            meta["calibration_fingerprint"] = None
            meta["calibration_device_kind"] = None
    return meta


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="both",
                   choices=["both", "resnet", "transformer", "vit",
                            "moe"])
    p.add_argument("--batch-size", type=int, default=128,
                   help="ResNet per-chip batch size")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--num-iters", type=int, default=5)
    p.add_argument("--num-batches-per-iter", type=int, default=5)
    p.add_argument("--num-warmup-batches", type=int, default=3)
    p.add_argument("--steps-per-call", type=int, default=40,
                   help="optimizer steps scanned into one dispatched "
                        "program (steps_per_execution); amortizes "
                        "per-call launch overhead.  40 = the offline "
                        "autotuner's cold-start pick, confirmed by "
                        "full-length A/B on both models (round 5)")
    p.add_argument("--input-mode", default="synthetic",
                   choices=["synthetic", "host"],
                   help="synthetic: one resident device batch reused "
                        "every step (pure compute envelope).  host: "
                        "the pipeline-fed path — host batches stream "
                        "through ShardedDataset -> PrefetchIterator "
                        "(background assembly, double-buffered H2D "
                        "onto the step's sharding, donated input "
                        "slot) and the BENCH JSON gains "
                        "input_stall_s / input_stall_sync_s / "
                        "prefetch_depth / h2d_overlap_fraction "
                        "(docs/data.md)")
    p.add_argument("--prefetch-depth", type=int, default=None,
                   help="input-pipeline queue bound for --input-mode "
                        "host (default: HOROVOD_PREFETCH_DEPTH, else 2)")
    p.add_argument("--no-compiler-options", action="store_true",
                   help="disable the default TPU XLA compile options")
    p.add_argument("--no-overlap-probe", action="store_true",
                   help="skip the comm/compute overlap microbenchmark "
                        "(backward-only vs exchange-only vs fused "
                        "timings; emits overlap_fraction)")
    p.add_argument("--no-checkpoint-probe", action="store_true",
                   help="skip the checkpoint cost probe (async-save "
                        "stall vs synchronous end-to-end save of the "
                        "transformer train state; emits "
                        "checkpoint_stall_s / checkpoint_sync_s)")
    p.add_argument("--json-out", default=None, metavar="PATH",
                   help="also write the BENCH JSON object to PATH "
                        "(atomic replace) — harnesses read the artifact "
                        "directly instead of tail-parsing stdout")
    p.add_argument("--overlap-bucket-bytes", type=int, default=None,
                   help="bucket the probed gradient exchange at this "
                        "byte cap (reverse-layer-order buckets, the "
                        "exchange_bucket_bytes knob); default: "
                        "--exchange-bucket-bytes, else one monolithic "
                        "bucket")
    p.add_argument("--shard-optimizer-states", action="store_true",
                   help="run the bench step through the ZeRO-style "
                        "sharded exchange (mode=shard_map, RS -> shard "
                        "update -> AG) so --exchange-bucket-bytes / "
                        "--hierarchy schedule the real wire; also "
                        "unlocks the exchange axes under --autotune")
    p.add_argument("--exchange-bucket-bytes", type=int, default=None,
                   help="byte cap for the sharded exchange's "
                        "reverse-layer-order buckets (the "
                        "exchange_bucket_bytes knob); default: one "
                        "monolithic bucket")
    p.add_argument("--fused-collectives", default="auto",
                   choices=["auto", "on", "off"],
                   help="tile-fused final-bucket exchange "
                        "(HOROVOD_FUSED_COLLECTIVES): the last "
                        "bucket's wire splits into independent "
                        "sub-collectives the scheduler overlaps with "
                        "the shard-update math; auto = TPU only "
                        "(docs/fused_kernels.md).  The overlap probe "
                        "reports tail_exchange_s for both paths "
                        "either way")
    p.add_argument("--plan", default=None, metavar="PLAN",
                   help="parallelism plan (HOROVOD_PLAN grammar, e.g. "
                        "'dp=4,fsdp=2' or 'dp=2,pp=2,v=2'): builds the "
                        "step's mesh from the plan and emits plan + "
                        "pipeline probe fields into BENCH JSON "
                        "(docs/parallelism.md)")
    p.add_argument("--wire-dtype", default=None,
                   choices=["fp32", "int8", "fp8_e4m3"],
                   help="exchange wire codec for the sharded exchange "
                        "(fp32 = uncompressed; int8/fp8_e4m3 set "
                        "HOROVOD_EXCHANGE_WIRE_DTYPE + the int8-bits "
                        "wire reduction); also an --autotune axis")
    p.add_argument("--reduction", default=None,
                   choices=["sum", "adasum"],
                   help="reduction operator of the sharded exchange's "
                        "outermost topology level "
                        "(HOROVOD_EXCHANGE_REDUCTION): adasum = the "
                        "pairwise adaptive summation that holds the "
                        "loss trajectory at 2-4x global batch "
                        "(docs/adasum.md); also an --autotune axis")
    p.add_argument("--adasum", action="store_true",
                   help="run the adasum convergence probe instead of "
                        "the throughput bench: the seeded quadratic "
                        "twin hvdci gate 10 shares — base-batch sum "
                        "vs adasum-at-scale vs sum-at-scale "
                        "trajectories plus the cost model's "
                        "adasum_dot_wire_bytes (docs/adasum.md)")
    p.add_argument("--adasum-batch-scale", type=int, default=2,
                   help="global-batch multiplier of the --adasum "
                        "probe's scaled trajectories (2-4x is the "
                        "operator's design envelope)")
    p.add_argument("--hierarchy", default="auto",
                   choices=["auto", "flat", "two_level"],
                   help="exchange topology: two_level reduce-scatters "
                        "within each ICI slice, runs the cross-slice "
                        "DCN phase on the 1/intra-size shards, then "
                        "allgathers intra-slice; auto consults the "
                        "mesh factorization (docs/overlap.md)")
    p.add_argument("--platform", default=None,
                   help="force a jax backend (e.g. cpu) — env "
                        "JAX_PLATFORMS alone is overridden by this "
                        "image's sitecustomize")
    p.add_argument("--dtype", default="bfloat16",
                   choices=["bfloat16", "float32"])
    p.add_argument("--space-to-depth", dest="space_to_depth",
                   action="store_true", default=True,
                   help="use the TPU space-to-depth stem (the standard "
                        "MLPerf TPU ResNet stem: 2x2 pixel shuffle + 4x4 "
                        "conv — same computation class, dense MXU "
                        "lanes). Default on; measured +0.8%% once "
                        "steps_per_call removed the timing noise")
    p.add_argument("--no-space-to-depth", dest="space_to_depth",
                   action="store_false",
                   help="use the reference 7x7 stride-2 stem")
    p.add_argument("--fused-bwd", action="store_true",
                   help="fused one-pass Pallas backward for the ResNet "
                        "stride-1 3x3 block segments (A/B candidate for "
                        "the BN-reduction bottleneck)")
    p.add_argument("--tf-layers", type=int, default=16)
    p.add_argument("--tf-d-model", type=int, default=2048)
    p.add_argument("--tf-heads", type=int, default=16)
    p.add_argument("--tf-seq-len", type=int, default=1024)
    p.add_argument("--tf-batch-size", type=int, default=6,
                   help="transformer per-chip batch size")
    p.add_argument("--tf-remat", action="store_true",
                   help="checkpoint each transformer block (recompute "
                        "activations in backward)")
    p.add_argument("--tf-attention", default="flash",
                   choices=["dense", "flash", "ring"],
                   help="ring = sp ring-flash attention; needs a "
                        "--plan with sp>1 (docs/fused_kernels.md)")
    p.add_argument("--tf-flash-block", type=int, default=512,
                   help="flash-attention q/k block size (512 = round-4 "
                        "measured winner)")
    p.add_argument("--chaos", action="store_true",
                   help="run the seeded fault-injection probe instead "
                        "of the throughput bench: heartbeat hang "
                        "detection (detect_s), crash-at-step-k recovery "
                        "from the last durable checkpoint (recovery_s, "
                        "steps_lost) and a two-run determinism check "
                        "(docs/faults.md)")
    p.add_argument("--chaos-steps", type=int, default=12,
                   help="total training commits in the chaos scenario")
    p.add_argument("--chaos-crash-step", type=int, default=7,
                   help="commit at which the injected crash fires")
    p.add_argument("--chaos-checkpoint-every", type=int, default=2,
                   help="durable-checkpoint cadence; steps_lost is "
                        "bounded by this")
    p.add_argument("--chaos-seed", type=int, default=42,
                   help="FaultPlan / data seed for the chaos scenario")
    p.add_argument("--degrade", action="store_true",
                   help="with --chaos: also run the plan-aware "
                        "degradation scenario — kill half the dp=4 "
                        "world mid-interval, shrink to dp=2 via "
                        "reshard-restore, replay, promote back at the "
                        "next checkpoint boundary; emits "
                        "degrade_from_plan / degrade_to_plan / "
                        "degrade_transition_s / promoted_step "
                        "(docs/elastic.md)")
    p.add_argument("--serve", action="store_true",
                   help="run the serving-plane SLO probe instead of the "
                        "training bench: a seeded open-loop generator "
                        "through the admission queue / batcher / "
                        "replica pool, plus the replica-kill chaos "
                        "variant (docs/serving.md)")
    p.add_argument("--serve-requests", type=int, default=64,
                   help="requests per --serve pass")
    p.add_argument("--serve-rps", type=float, default=400.0,
                   help="offered open-loop arrival rate (logical "
                        "clock); also the PERF001/PERF005 "
                        "comparability key")
    p.add_argument("--serve-max-batch", type=int, default=4,
                   help="continuous-batcher packing limit for --serve")
    p.add_argument("--serve-models", type=int, default=3,
                   help="tenant models in the --serve fleet pass "
                        "(weighted-fair scheduling, live weight "
                        "refresh, autoscale); also a PERF001/PERF005 "
                        "comparability key")
    p.add_argument("--serve-seed", type=int, default=42,
                   help="traffic / FaultPlan seed for --serve")
    p.add_argument("--serve-p99-inflation-max", type=float, default=5.0,
                   help="chaos-variant p99 may inflate at most this "
                        "factor over the fault-free pass")
    p.add_argument("--hbm-budget", action="store_true",
                   help="memory-plane measurement loop: remat "
                        "none-vs-full high-water + recompute delta on "
                        "an activation-dominated twin, the "
                        "plan_memory_bytes 25%% validation, the "
                        "HBM-budgeted planner winner "
                        "(HOROVOD_HBM_BUDGET_BYTES) and a live offload "
                        "round-trip (docs/memory.md)")
    p.add_argument("--sp-budget", action="store_true",
                   help="long-context memory certification: compile a "
                        "seq-4096 twin at sp=1 (dense) and sp=2 (ring)"
                        ", validate plan_memory_bytes' 1/sp activation "
                        "scaling (25%% bar) and certify the HBM budget "
                        "admits sp=2 while refusing sp=1 "
                        "(docs/fused_kernels.md)")
    p.add_argument("--calibrate", action="store_true",
                   help="run the collective microbenchmark suite "
                        "(allreduce/RS/AG/ppermute/a2a per fabric "
                        "level + matmul/HBM rates), fit the "
                        "alpha-beta model and write the versioned "
                        "calibration artifact every pricing consumer "
                        "reads via HOROVOD_CALIBRATION_PATH "
                        "(docs/calibration.md)")
    p.add_argument("--calibrate-sim", action="store_true",
                   help="with --calibrate: seeded pure-sim sweeps "
                        "instead of measured ones — deterministic, "
                        "single-device-safe (hvdci gate 9 path)")
    p.add_argument("--calibrate-out", default=None, metavar="PATH",
                   help="calibration artifact path (default: "
                        "CALIBRATION.json in the cwd)")
    p.add_argument("--calibrate-seed", type=int, default=17,
                   help="noise seed for --calibrate-sim")
    p.add_argument("--calibrate-max-bytes", type=int, default=None,
                   help="cap the message-size sweep (default: 128 MiB "
                        "on TPU, 4 MiB elsewhere)")
    p.add_argument("--autotune", action="store_true",
                   help="tune the jit-path throughput knobs "
                        "(steps_per_call; flash block for the "
                        "transformer) by measurement instead of running "
                        "the plain bench; writes --autotune-log")
    p.add_argument("--autotune-log", default=None,
                   help="CSV sample log (default autotune_<model>.csv)")
    p.add_argument("--vit-batch-size", type=int, default=128,
                   help="ViT per-chip batch size (--model vit only)")
    p.add_argument("--moe-layers", type=int, default=12)
    p.add_argument("--moe-d-model", type=int, default=1024)
    p.add_argument("--moe-heads", type=int, default=8,
                   help="MoE LM heads (8 at d_model 1024 = head_dim "
                        "128, the MXU lane width)")
    p.add_argument("--moe-experts", type=int, default=8)
    p.add_argument("--moe-batch-size", type=int, default=16,
                   help="MoE per-chip batch size (--model moe only; "
                        "measured knee — 4: 41.6%%, 8: 49.4%%, "
                        "16: 50.3%%, 32: 40.7%% MFU)")
    p.add_argument("--moe-fused", default=None,
                   choices=["auto", "on", "off"],
                   help="run the fused/unfused expert-dispatch twin "
                        "probe and emit its fields into BENCH JSON "
                        "(docs/fused_kernels.md); also stamps the "
                        "resolved mode into the step's AOT key")
    p.add_argument("--moe-capacity-factor", type=float, default=None,
                   help="Switch capacity factor (default: "
                        "HOROVOD_MOE_CAPACITY_FACTOR, then 1.25); a "
                        "perf-gate comparability key")
    p.add_argument("--vit-heads", type=int, default=12,
                   help="ViT heads: 12 = standard ViT-B head_dim 64; "
                        "6 = TPU-shaped head_dim 128 (MXU lane width)")
    args = p.parse_args()
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    import horovod_tpu as hvd
    from horovod_tpu import telemetry

    hvd.init()
    # the bench IS the observability harness: collect unconditionally
    # (exporters still follow the HOROVOD_METRICS_* knobs) and stamp
    # the run context so logs/trace/metrics correlate
    telemetry.enable()
    telemetry.run_context().update()
    if args.chaos:
        emit(dict(run_chaos(args, hvd), **artifact_metadata(hvd),
                  **telemetry_fields()),
             args.json_out)
        return
    if args.serve:
        emit(dict(run_serve(args, hvd), **artifact_metadata(hvd),
                  **telemetry_fields()),
             args.json_out)
        return
    if args.hbm_budget:
        emit(dict(run_hbm_budget(args, hvd), **artifact_metadata(hvd),
                  **telemetry_fields()),
             args.json_out)
        return
    if args.sp_budget:
        emit(dict(run_sp_budget(args, hvd), **artifact_metadata(hvd),
                  **telemetry_fields()),
             args.json_out)
        return
    if args.adasum:
        emit(dict(run_adasum(args, hvd), **artifact_metadata(hvd),
                  **telemetry_fields()),
             args.json_out)
        return
    if args.calibrate:
        emit(dict(run_calibrate(args, hvd), **artifact_metadata(hvd),
                  **telemetry_fields()),
             args.json_out)
        return
    if args.autotune:
        emit(dict(run_autotune(args, hvd), **artifact_metadata(hvd),
                  **telemetry_fields()),
             args.json_out)
        return
    out = {}
    if args.model in ("both", "resnet"):
        out.update(run_resnet(args, hvd))
    if args.model in ("both", "transformer"):
        out.update(run_transformer(args, hvd))
    if args.model == "vit":
        out.update(run_vit(args, hvd))
    if args.model == "moe":
        out.update(run_moe(args, hvd))
    out.update(plan_probe_fields(args, hvd))
    # compiled-executable cache counters (runtime/state.py cache_stats):
    # hits/misses are the in-memory signature caches, the aot_disk pair
    # is the persistent warm-start store
    stats = hvd.cache_stats()
    out.update({"cache_hits": stats.get("hits", 0),
                "cache_misses": stats.get("misses", 0),
                "aot_disk_hits": stats.get("aot_disk_hits", 0),
                "aot_disk_misses": stats.get("aot_disk_misses", 0)})
    out.update(artifact_metadata(hvd))
    out.update(telemetry_fields())
    emit(out, args.json_out)


def emit(out, json_out_path=None):
    """Print the one BENCH JSON line; with ``--json-out`` also write it
    to a file (tmp + atomic replace, so a crashed run never leaves a
    half-written artifact for the harness to parse)."""
    line = json.dumps(out)
    print(line, flush=True)
    if json_out_path:
        tmp = f"{json_out_path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(line + "\n")
        os.replace(tmp, json_out_path)
        log(f"bench: wrote BENCH JSON to {json_out_path}")


if __name__ == "__main__":
    main()
