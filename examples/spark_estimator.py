"""Spark-style estimator end to end: store, streaming fit, transform.

Counterpart of the reference's ``examples/spark/keras/keras_spark_mnist.py``
flow: build a DataFrame, hand it to an Estimator backed by a Store, get a
fitted model back, and transform a DataFrame with it.  The fit streams
from row-group shards of the store's parquet (the petastorm-reader
analogue), and ``--distributed`` drives the whole thing through
``horovod_tpu.spark.run`` — Spark executors when pyspark is installed,
the built-in local executor pool otherwise.

Usage::

    python examples/spark_estimator.py [--distributed --np 2] [--platform cpu]
"""

import argparse
import tempfile

import flax.linen as nn


class Net(nn.Module):
    """Module-level so the store's model.pkl round trip works (locally
    defined classes don't pickle; load_model would then need model=)."""

    @nn.compact
    def __call__(self, x):
        return nn.Dense(3)(nn.relu(nn.Dense(32)(x)))


def build_frame(n=512, seed=0):
    import numpy as np
    import pandas as pd

    rng = np.random.RandomState(seed)
    x = rng.rand(n, 8).astype(np.float32)
    w = rng.rand(8, 3)
    y = (x @ w).argmax(axis=1).astype(np.int32)
    cols = {f"f{i}": x[:, i] for i in range(8)}
    cols["label"] = y
    return pd.DataFrame(cols)


def train(store_path, platform=None):
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)
    import numpy as np
    import optax

    from horovod_tpu.spark import Estimator, Store

    df = build_frame()
    est = Estimator(
        Net(),
        feature_cols=[f"f{i}" for i in range(8)],
        label_col="label",
        optimizer=optax.adam(1e-2),
        batch_size=16,
        epochs=15,
        store=Store.create(store_path),
        rows_per_group=64,          # the streaming shard unit
        validation_fraction=0.125,
    )
    model = est.fit(df)
    out = model.transform(df)
    preds = np.stack(out["prediction"]).argmax(axis=1)
    acc = float((preds == df["label"].to_numpy()).mean())

    # the save/load round trip: a fresh process reconstructs the fitted
    # model straight from the store run (pickled architecture +
    # checkpoint + schema metadata)
    from horovod_tpu.spark import load_model

    reloaded = load_model(store_path)
    re_preds = np.stack(
        reloaded.transform(df)["prediction"]).argmax(axis=1)
    assert (re_preds == preds).all(), "loaded model diverged"

    # prepare-once / fit-many: materialize the DataFrame into the store
    # a single time, then any number of fits stream from the shards
    prepared = Store.create(store_path).prepare_data(
        df, [f"f{i}" for i in range(8)], "label",
        validation_fraction=0.125, rows_per_group=64)
    est.fit(prepared)
    return acc


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--distributed", action="store_true",
                   help="run the fit on an executor pool via "
                        "horovod_tpu.spark.run")
    p.add_argument("--np", type=int, default=2)
    p.add_argument("--store", default=None)
    p.add_argument("--platform", default=None)
    args = p.parse_args()

    store_path = args.store or tempfile.mkdtemp(prefix="hvd_store_")
    if args.distributed:
        from horovod_tpu import spark as hvd_spark

        accs = hvd_spark.run(train, args=(store_path, args.platform or
                                          "cpu"),
                             num_proc=args.np)
        print(f"per-rank accuracy: {accs}")
        acc = accs[0]
    else:
        acc = train(store_path, args.platform)
    print(f"accuracy: {acc:.3f} (store: {store_path})")


if __name__ == "__main__":
    main()
