"""Per-op device profile of the ResNet-50 bench step (PERF_NOTES tables).

Captures a ``jax.profiler`` trace of the exact ``bench.py`` train step
on the real chip and prints exclusive per-op device times — the "XLA
Ops" line of the xplane proto (parsed with the proto bundled in
``tensorflow.tsl``; no tensorboard UI needed), with nested event
durations subtracted from their parents so wrapper events (the step
``while``, the jit module) and async copy spans don't double count.

Usage::

    python examples/profile_resnet.py --top 30 [--steps-per-call 4]
        [--no-lhs] [--no-space-to-depth]
"""

import argparse
import collections
import glob
import os
import re
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import optax


def build_step(batch_size, image_size, steps_per_call, lhs, s2d):
    import horovod_tpu as hvd
    from horovod_tpu.models.resnet import ResNet50

    hvd.init()
    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16,
                     space_to_depth=s2d,
                     fused_bwd=bool(int(os.environ.get(
                         "HOROVOD_PROFILE_FUSED_BWD", "0"))))

    def loss_fn(params, batch):
        logits = model.apply(params, batch["x"], train=False)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["y"]).mean()

    opts = {"xla_tpu_enable_latency_hiding_scheduler": "true"} if lhs \
        else None
    step = hvd.DistributedTrainStep(
        loss_fn, optax.sgd(0.01, momentum=0.9),
        steps_per_call=steps_per_call, compiler_options=opts)
    x0 = jnp.zeros((1, image_size, image_size, 3), jnp.float32)
    # jit the init: eagerly it is hundreds of per-op dispatches, minutes
    # through the remote tunnel
    params, opt_state = step.init(jax.jit(
        lambda k: model.init(k, x0, train=False))(jax.random.PRNGKey(0)))
    rng = np.random.RandomState(0)
    batch = step.shard_batch({
        "x": jnp.asarray(rng.rand(batch_size, image_size, image_size, 3),
                         jnp.float32),
        "y": jnp.asarray(rng.randint(0, 1000, (batch_size,)), jnp.int32),
    })
    return step, params, opt_state, batch


def exclusive_op_times(trace_dir):
    """{op name: self ps} from the device "XLA Ops" line, with child
    durations subtracted from enclosing events via an interval stack."""
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = sorted(glob.glob(
        os.path.join(trace_dir, "plugins/profile/*/*.xplane.pb")))
    xs = xplane_pb2.XSpace()
    xs.ParseFromString(open(paths[-1], "rb").read())
    self_ps: dict = collections.defaultdict(float)
    for plane in xs.planes:
        if not plane.name.startswith("/device:TPU"):
            continue
        ev_meta = dict(plane.event_metadata.items())
        for line in plane.lines:
            if line.name != "XLA Ops":
                continue
            evs = sorted(
                (e.offset_ps, e.offset_ps + e.duration_ps, e.metadata_id)
                for e in line.events)
            stack = []
            for s, t, mid in evs:
                while stack and stack[-1][1] <= s:
                    stack.pop()
                name = ev_meta[mid].name if mid in ev_meta else "?"
                if stack:
                    self_ps[stack[-1][2]] -= (t - s)
                self_ps[name] += (t - s)
                stack.append((s, t, name))
    return self_ps


def op_kind(name: str) -> str:
    m = re.match(r"%?([a-zA-Z_\-]+)", name.split(" = ")[0])
    return m.group(1) if m else name


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--steps-per-call", type=int, default=4)
    ap.add_argument("--top", type=int, default=30)
    ap.add_argument("--no-lhs", action="store_true")
    ap.add_argument("--space-to-depth", action="store_true", default=True)
    ap.add_argument("--no-space-to-depth", dest="space_to_depth",
                    action="store_false")
    ap.add_argument("--trace-dir", default=None)
    args = ap.parse_args()

    step, params, opt_state, batch = build_step(
        args.batch_size, args.image_size, args.steps_per_call,
        not args.no_lhs, args.space_to_depth)
    p, o, loss = step(params, opt_state, batch)       # compile + warm
    float(loss)

    trace_dir = args.trace_dir or tempfile.mkdtemp(prefix="rn50prof_")
    with jax.profiler.trace(trace_dir):
        p, o, loss = step(p, o, batch)
        float(loss)
    print(f"trace: {trace_dir}")

    self_ps = exclusive_op_times(trace_dir)
    nsteps = args.steps_per_call
    total_ms = sum(self_ps.values()) / 1e9 / nsteps
    print(f"device exclusive op time: {total_ms:.2f} ms/step "
          f"({len(self_ps)} distinct ops, {nsteps} steps traced)")

    by_kind = collections.defaultdict(float)
    for name, ps in self_ps.items():
        by_kind[op_kind(name)] += ps
    print("\n-- by op class (ms/step) --")
    for k, v in sorted(by_kind.items(), key=lambda kv: -kv[1])[:12]:
        ms = v / 1e9 / nsteps
        if ms >= 0.005:
            print(f"{k:36s} {ms:8.2f}  {ms / total_ms * 100:5.1f}%")

    print(f"\n-- top {args.top} ops (self ms/step) --")
    ranked = sorted(self_ps.items(), key=lambda kv: -kv[1])
    for name, ps in ranked[:args.top]:
        ms = ps / 1e9 / nsteps
        print(f"{name[:84]:84s} {ms:7.3f}")


if __name__ == "__main__":
    main()
