"""Per-op device profile of the ResNet-50 bench step (PERF_NOTES tables).

Captures a ``jax.profiler`` trace of the exact ``bench.py`` train step
on the real chip and prints the top device ops by total time, with
achieved HBM bandwidth where the op's ``bytes accessed`` stat is
recorded.  The xplane protobuf is parsed with the proto bundled in
tensorflow.tsl — no tensorboard UI needed.

Usage::

    python examples/profile_resnet.py --top 30 [--steps-per-call 4]
        [--no-lhs] [--space-to-depth]
"""

import argparse
import collections
import glob
import os
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import optax


def build_step(batch_size, image_size, steps_per_call, lhs, s2d):
    import horovod_tpu as hvd
    from horovod_tpu.models.resnet import ResNet50

    hvd.init()
    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16,
                     space_to_depth=s2d)

    def loss_fn(params, batch):
        logits = model.apply(params, batch["x"], train=False)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["y"]).mean()

    opts = {"xla_tpu_enable_latency_hiding_scheduler": "true"} if lhs \
        else None
    step = hvd.DistributedTrainStep(
        loss_fn, optax.sgd(0.01, momentum=0.9),
        steps_per_call=steps_per_call, compiler_options=opts)
    x0 = jnp.zeros((1, image_size, image_size, 3), jnp.float32)
    params, opt_state = step.init(jax.jit(
        lambda k: model.init(k, x0, train=False))(jax.random.PRNGKey(0)))
    rng = np.random.RandomState(0)
    batch = step.shard_batch({
        "x": jnp.asarray(rng.rand(batch_size, image_size, image_size, 3),
                         jnp.float32),
        "y": jnp.asarray(rng.randint(0, 1000, (batch_size,)), jnp.int32),
    })
    return step, params, opt_state, batch


def collect_op_stats(trace_dir):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = sorted(glob.glob(
        os.path.join(trace_dir, "plugins/profile/*/*.xplane.pb")))
    xs = xplane_pb2.XSpace()
    xs.ParseFromString(open(paths[-1], "rb").read())
    ops = collections.defaultdict(lambda: [0.0, 0, 0.0])  # ps, count, bytes
    for plane in xs.planes:
        if not plane.name.startswith("/device:TPU"):
            continue
        stat_names = dict(plane.stat_metadata.items())
        ev_meta = dict(plane.event_metadata.items())
        for line in plane.lines:
            for ev in line.events:
                name = ev_meta[ev.metadata_id].name \
                    if ev.metadata_id in ev_meta else "?"
                rec = ops[name]
                rec[0] += ev.duration_ps
                rec[1] += 1
                for st in ev.stats:
                    sname = stat_names[st.metadata_id].name \
                        if st.metadata_id in stat_names else ""
                    if "bytes accessed" in sname.lower() and \
                            not sname.lower().rstrip("0123456789}{ ") \
                                     .endswith("breakdown"):
                        rec[2] += st.uint64_value or st.int64_value
    return ops


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--steps-per-call", type=int, default=4)
    ap.add_argument("--top", type=int, default=30)
    ap.add_argument("--no-lhs", action="store_true")
    ap.add_argument("--space-to-depth", action="store_true", default=True)
    ap.add_argument("--no-space-to-depth", dest="space_to_depth",
                    action="store_false")
    ap.add_argument("--trace-dir", default=None)
    args = ap.parse_args()

    step, params, opt_state, batch = build_step(
        args.batch_size, args.image_size, args.steps_per_call,
        not args.no_lhs, args.space_to_depth)
    p, o, loss = step(params, opt_state, batch)       # compile + warm
    float(loss)

    trace_dir = args.trace_dir or tempfile.mkdtemp(prefix="rn50prof_")
    with jax.profiler.trace(trace_dir):
        p, o, loss = step(p, o, batch)
        float(loss)
    print(f"trace: {trace_dir}")

    ops = collect_op_stats(trace_dir)
    nsteps = args.steps_per_call
    total_ms = sum(v[0] for v in ops.values()) / 1e9 / nsteps
    print(f"device op time: {total_ms:.2f} ms/step "
          f"({len(ops)} distinct ops, {nsteps} steps traced)")
    print(f"{'op':60s} {'ms/step':>8s} {'%':>5s} {'GB/s':>6s}")
    ranked = sorted(ops.items(), key=lambda kv: -kv[1][0])
    for name, (ps, cnt, nbytes) in ranked[:args.top]:
        ms = ps / 1e9 / nsteps
        bw = (nbytes / nsteps) / (ms / 1e3) / 1e9 if nbytes else 0
        print(f"{name[:60]:60s} {ms:8.3f} {ms / total_ms * 100:5.1f} "
              f"{bw:6.0f}")


if __name__ == "__main__":
    main()
