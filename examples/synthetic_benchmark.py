"""Synthetic data-parallel training benchmark.

The TPU-native counterpart of the reference's
``examples/tensorflow2_synthetic_benchmark.py`` /
``pytorch_synthetic_benchmark.py``: train a model on synthetic data and
print images/sec (per chip and total) ± stdev over timed batches.

Usage::

    python examples/synthetic_benchmark.py                  # default MLP
    python examples/synthetic_benchmark.py --model resnet50 # flagship CNN
    HOROVOD_TIMELINE=/tmp/tl.json python examples/synthetic_benchmark.py
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="mlp",
                   choices=["mlp", "resnet50", "vit"])
    p.add_argument("--batch-size", type=int, default=32,
                   help="per-chip batch size")
    p.add_argument("--num-iters", type=int, default=10)
    p.add_argument("--num-batches-per-iter", type=int, default=10)
    p.add_argument("--num-warmup-batches", type=int, default=3)
    p.add_argument("--mode", default="pjit", choices=["pjit", "shard_map"])
    p.add_argument("--adasum", action="store_true")
    p.add_argument("--fp16-allreduce", action="store_true")
    p.add_argument("--platform", default=None,
                   help="force jax platform (cpu for the virtual mesh)")
    return p.parse_args()


def make_model(name: str):
    if name == "mlp":
        def init(key):
            k1, k2, k3 = jax.random.split(key, 3)
            return {
                "w1": jax.random.normal(k1, (784, 512)) * 0.05,
                "b1": jnp.zeros((512,)),
                "w2": jax.random.normal(k2, (512, 512)) * 0.05,
                "b2": jnp.zeros((512,)),
                "w3": jax.random.normal(k3, (512, 10)) * 0.05,
                "b3": jnp.zeros((10,)),
            }

        def apply(params, x):
            x = x.reshape(x.shape[0], -1)
            x = jax.nn.relu(x @ params["w1"] + params["b1"])
            x = jax.nn.relu(x @ params["w2"] + params["b2"])
            return x @ params["w3"] + params["b3"]

        input_shape = (28, 28, 1)
        return init, apply, input_shape

    if name == "vit":
        from horovod_tpu.models import ViT_S16

        model = ViT_S16(image_size=224, patch_size=16, num_classes=1000)

        def init(key):
            return model.init(key, jnp.zeros((1, 224, 224, 3), jnp.float32))

        def apply(params, x):
            return model.apply(params, x)

        return init, apply, (224, 224, 3)

    from horovod_tpu.models.resnet import ResNet50

    model = ResNet50(num_classes=1000)

    def init(key):
        x = jnp.zeros((1, 224, 224, 3), jnp.float32)
        return model.init(key, x, train=False)

    def apply(params, x):
        return model.apply(params, x, train=False)

    return init, apply, (224, 224, 3)


def main():
    args = parse_args()
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    import horovod_tpu as hvd

    hvd.init()

    init, apply, input_shape = make_model(args.model)
    num_classes = 10 if args.model == "mlp" else 1000  # vit/resnet: 1000

    def loss_fn(params, batch):
        logits = apply(params, batch["x"])
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["y"]).mean()

    mode = args.mode
    if (args.adasum or args.fp16_allreduce) and mode == "pjit":
        mode = "shard_map"  # custom reduction/wire format needs explicit mode
        if hvd.rank() == 0:
            print("note: --adasum/--fp16-allreduce require the explicit "
                  "reduction path; switching to --mode shard_map")
    step = hvd.DistributedTrainStep(
        loss_fn,
        optax.sgd(0.01 * hvd.size(), momentum=0.9),
        mode=mode,
        op=hvd.Adasum if args.adasum else hvd.Average,
        compression=hvd.Compression.fp16 if args.fp16_allreduce else None,
    )
    params, opt_state = step.init(init(jax.random.PRNGKey(0)))

    global_bs = args.batch_size * hvd.size()
    rng = np.random.RandomState(0)
    batch = step.shard_batch({
        "x": jnp.asarray(rng.rand(global_bs, *input_shape), jnp.float32),
        "y": jnp.asarray(rng.randint(0, num_classes, (global_bs,)), jnp.int32),
    })

    if hvd.rank() == 0:
        print(f"Model: {args.model}")
        print(f"Batch size: {args.batch_size} per chip "
              f"({global_bs} global, {hvd.size()} chips)")
        print(f"Mode: {mode}"
              + (" + adasum" if args.adasum else "")
              + (" + fp16-allreduce" if args.fp16_allreduce else ""))

    # warmup (compile)
    t0 = time.perf_counter()
    for _ in range(args.num_warmup_batches):
        params, opt_state, loss = step(params, opt_state, batch)
    jax.block_until_ready(loss)
    if hvd.rank() == 0:
        print(f"Warmup (incl. compile): {time.perf_counter() - t0:.1f}s, "
              f"loss={float(loss):.4f}")

    img_secs = []
    for it in range(args.num_iters):
        t0 = time.perf_counter()
        for _ in range(args.num_batches_per_iter):
            params, opt_state, loss = step(params, opt_state, batch)
        jax.block_until_ready(loss)
        dt = time.perf_counter() - t0
        img_sec = global_bs * args.num_batches_per_iter / dt
        img_secs.append(img_sec)
        if hvd.rank() == 0:
            print(f"Iter #{it}: {img_sec:.1f} img/sec total")

    if hvd.rank() == 0:
        mean, std = np.mean(img_secs), np.std(img_secs)
        print(f"Img/sec per chip: {mean / hvd.size():.1f} +- "
              f"{1.96 * std / hvd.size():.1f}")
        print(f"Total img/sec on {hvd.size()} chip(s): "
              f"{mean:.1f} +- {1.96 * std:.1f}")
        print(f"Final loss: {float(loss):.4f}")


if __name__ == "__main__":
    main()
