"""Adasum vs averaged-SGD on a small model (reference
``examples/adasum_small_model.py`` + ``docs/adasum_user_guide.rst``).

Trains the same tiny MLP twice across N processes — once with plain
gradient averaging, once with Adasum reduction — and prints the final
losses side by side.  Adasum's orthogonality-aware combine lets the
learning rate stay un-scaled as workers are added (the guide's headline
property).

Usage::

    python examples/adasum_small_model.py --np 2 --epochs 5
"""

import argparse


def worker(op_name: str, epochs: int, lr: float):
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    rng = np.random.RandomState(42)   # same data on every rank,
    n, d = 512, 16                    # sharded by rank below
    x = rng.randn(n, d).astype(np.float32)
    w_true = rng.randn(d).astype(np.float32)
    y = x @ w_true + 0.01 * rng.randn(n).astype(np.float32)
    shard = slice(hvd.process_rank() * n // hvd.process_count(),
                  (hvd.process_rank() + 1) * n // hvd.process_count())
    x, y = jnp.asarray(x[shard]), jnp.asarray(y[shard])

    op = hvd.Adasum if op_name == "adasum" else hvd.Average
    w = jnp.zeros((d,))
    grad_fn = jax.jit(jax.grad(
        lambda w: jnp.mean((x @ w - y) ** 2)))
    for epoch in range(epochs):
        g = hvd.allreduce(grad_fn(w), op=op, name=f"g.{op_name}.{epoch}")
        w = w - lr * g
    loss = float(jnp.mean((x @ w - y) ** 2))
    rank = hvd.process_rank()
    hvd.shutdown()
    return {"rank": rank, "loss": loss}


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--np", type=int, default=2)
    p.add_argument("--epochs", type=int, default=20)
    p.add_argument("--lr", type=float, default=0.05)
    args = p.parse_args()

    from horovod_tpu.runner import run

    for op_name in ("average", "adasum"):
        results = run(worker, args=(op_name, args.epochs, args.lr),
                      np=args.np)
        print(f"{op_name:>8}: final loss {results[0]['loss']:.6f}")


if __name__ == "__main__":
    main()
