"""Switch-MoE LM training — local experts or expert-parallel dispatch.

Two modes over identical parameters:

* default: every device holds all experts (single chip / pure DP);
* ``--ep N``: experts sharded over an ``ep`` mesh axis, tokens moved by
  ``all_to_all`` (``parallel/expert.py``), run under ``shard_map``.

Usage::

    python examples/moe_lm_example.py --platform cpu                # local
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/moe_lm_example.py --platform cpu --ep 8     # EP
"""

import argparse
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=32)
    p.add_argument("--experts", type=int, default=8)
    p.add_argument("--ep", type=int, default=0,
                   help="expert-parallel over an ep mesh of this size "
                        "(0 = local experts)")
    p.add_argument("--aux-weight", type=float, default=0.01)
    p.add_argument("--platform", default=None)
    return p.parse_args()


def main():
    args = parse_args()
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from horovod_tpu.models import MoEConfig, MoETransformerLM, moe_aux_loss
    from horovod_tpu.parallel.mesh import make_parallel_mesh

    cfg = MoEConfig(vocab_size=256, num_layers=2, num_heads=4,
                    d_model=64, d_ff=128, max_seq_len=args.seq_len,
                    dtype=jnp.float32, num_experts=args.experts,
                    capacity_factor=2.0, moe_every=2,
                    ep_axis="ep" if args.ep else None)
    model = MoETransformerLM(cfg)

    rng = np.random.RandomState(0)
    data = rng.randint(0, cfg.vocab_size,
                       (args.batch_size, args.seq_len + 1))
    x = jnp.asarray(data[:, :-1], jnp.int32)
    y = jnp.asarray(data[:, 1:], jnp.int32)

    # init with the local-mode twin (identical params, no bound axis);
    # shard_map mode needs UNBOXED params — flax applies Partitioned
    # metadata as sharding constraints, which are illegal inside a
    # manual mesh (same contract as TransformerLM's ring/ulysses modes)
    import flax.core.meta

    init_model = MoETransformerLM(dataclasses.replace(cfg, ep_axis=None))
    variables = jax.jit(init_model.init)(jax.random.PRNGKey(0), x[:1])
    params = flax.core.meta.unbox(variables["params"])
    opt = optax.adam(1e-2)
    opt_state = opt.init(params)

    def loss_fn(params, x, y):
        logits, state = model.apply({"params": params}, x,
                                    mutable=["intermediates"])
        ce = optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()
        return ce + args.aux_weight * moe_aux_loss(state["intermediates"])

    def train_step(params, opt_state, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    if args.ep:
        from jax.sharding import PartitionSpec as P

        mesh = make_parallel_mesh(ep=args.ep)

        def sharded_step(params, opt_state, x, y):
            loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
            # experts see only their token shard: average grads/loss
            # across the ep axis so every shard applies one update
            grads = jax.lax.pmean(grads, "ep")
            loss = jax.lax.pmean(loss, "ep")
            updates, opt_state = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, \
                loss[None]

        step = jax.jit(jax.shard_map(
            sharded_step, mesh=mesh,
            in_specs=(P(), P(), P("ep"), P("ep")),
            out_specs=(P(), P(), P()), check_vma=False))
        print(f"expert-parallel over ep={args.ep} "
              f"({cfg.num_experts} experts, "
              f"{cfg.num_experts // args.ep} per shard)")
    else:
        step = jax.jit(train_step)
        print(f"local mode ({cfg.num_experts} experts resident)")

    for i in range(args.steps):
        params, opt_state, loss = step(params, opt_state, x, y)
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i:3d}  loss {float(jnp.asarray(loss).mean()):.4f}")


if __name__ == "__main__":
    main()
