"""MNIST training — the reference's 5-line recipe, TPU-native.

Counterpart of ``examples/tensorflow2_mnist.py`` /
``pytorch_mnist.py``: the canonical "take a single-accelerator script,
add ~5 lines" demo.  The 5 lines here::

    hvd.init()                                           # 1
    step = hvd.DistributedTrainStep(loss_fn, opt)        # 2 (wraps optimizer)
    params = hvd.broadcast_variables(params)             # 3
    batch = step.shard_batch(batch)                      # 4
    if hvd.rank() == 0: ckpt.save(...)                   # 5

Uses synthetic MNIST-shaped data when the real dataset isn't on disk
(zero-egress environments); pass --data-dir with the standard npz
layout to train on the real digits.
"""

import argparse
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax


def load_mnist(data_dir):
    """(x_train, y_train) — real npz if present, synthetic otherwise."""
    path = data_dir and os.path.join(data_dir, "mnist.npz")
    if path and os.path.exists(path):
        with np.load(path) as d:
            return d["x_train"].astype(np.float32) / 255.0, \
                d["y_train"].astype(np.int32)
    rng = np.random.RandomState(0)
    n = 4096
    x = rng.rand(n, 28, 28).astype(np.float32)
    y = rng.randint(0, 10, (n,)).astype(np.int32)
    return x, y


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=64,
                   help="per-chip batch size")
    p.add_argument("--lr", type=float, default=0.001)
    p.add_argument("--data-dir", default=None)
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--platform", default=None)
    args = p.parse_args()
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    import flax.linen as nn

    import horovod_tpu as hvd

    hvd.init()                                               # (1)

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = x.reshape(x.shape[0], -1)
            x = nn.relu(nn.Dense(128)(x))
            return nn.Dense(10)(x)

    model = Net()

    def loss_fn(params, batch):
        logits = model.apply(params, batch["x"])
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["y"]).mean()

    # scale LR by world size; warm up for stability (reference recipe)
    sched = hvd.callbacks.warmup_schedule(args.lr, warmup_epochs=1,
                                          steps_per_epoch=50)
    step = hvd.DistributedTrainStep(loss_fn, optax.adam(sched))  # (2)

    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 28, 28), jnp.float32))
    params = hvd.broadcast_variables(params, root_rank=0)        # (3)
    params, opt_state = step.init(params)

    x, y = load_mnist(args.data_dir)
    global_bs = args.batch_size * hvd.size()
    nbatches = len(x) // global_bs

    ckpt = hvd.checkpoint.Checkpointer(args.checkpoint_dir) \
        if args.checkpoint_dir else None

    for epoch in range(args.epochs):
        t0 = time.perf_counter()
        perm = np.random.RandomState(epoch).permutation(len(x))
        for b in range(nbatches):
            idx = perm[b * global_bs:(b + 1) * global_bs]
            batch = step.shard_batch({"x": jnp.asarray(x[idx]),
                                      "y": jnp.asarray(y[idx])})  # (4)
            params, opt_state, loss = step(params, opt_state, batch)
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss={float(loss):.4f} "
                  f"({time.perf_counter() - t0:.1f}s, {nbatches} batches, "
                  f"{hvd.size()} chips)")
            if ckpt:
                ckpt.save(epoch, {"params": params,
                                  "opt_state": opt_state})       # (5)


if __name__ == "__main__":
    main()
