"""Eager-collective bandwidth microbenchmark over real worker processes.

Companion to the O(data) data-movement contract in
:mod:`horovod_tpu.ops.eager` (``_allgather_rows``/``_alltoall_rows``):
launches ``--np`` localhost processes through the programmatic runner and
reports per-collective effective bandwidth.  The reference benchmarks its
wire ops the same way (synthetic tensors, localhost multi-process).

Usage::

    python examples/eager_bandwidth_bench.py --np 2 --mb 64
"""

import argparse
import time


def worker(nbytes: int, iters: int):
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    # round down to a world-size multiple so the split-less alltoall is legal
    n = (nbytes // 4) // hvd.size() * hvd.size()
    x = jnp.asarray(np.random.RandomState(hvd.rank()).rand(n), jnp.float32)

    out = {}

    def timed(fn, label):
        fn(x, name=f"{label}_warm")
        t0 = time.perf_counter()
        for i in range(iters):
            fn(x, name=f"{label}_{i}")
        return (time.perf_counter() - t0) / iters

    out["allreduce_MBps"] = nbytes / timed(hvd.allreduce, "ar") / 1e6
    out["allgather_MBps"] = (nbytes * hvd.size()
                             / timed(hvd.allgather, "ag") / 1e6)
    out["alltoall_MBps"] = nbytes / timed(hvd.alltoall, "a2a") / 1e6

    hvd.shutdown()
    return out


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--np", type=int, default=2)
    p.add_argument("--mb", type=int, default=16, help="payload megabytes")
    p.add_argument("--iters", type=int, default=5)
    args = p.parse_args()

    from horovod_tpu.runner import run

    results = run(worker, args=(args.mb * 1024 * 1024, args.iters),
                  np=args.np)
    r0 = results[0]
    for k, v in r0.items():
        print(f"{k}: {v:,.0f} MB/s")


if __name__ == "__main__":
    main()
