"""Eager-collective bandwidth microbenchmark over real worker processes.

Companion to the O(data) data-movement contract in
:mod:`horovod_tpu.ops.eager` (``_allgather_rows``/``_alltoall_rows``):
launches ``--np`` localhost processes through the programmatic runner and
reports per-collective effective bandwidth.  The reference benchmarks its
wire ops the same way (synthetic tensors, localhost multi-process).

Usage::

    python examples/eager_bandwidth_bench.py --np 2 --mb 64
    python examples/eager_bandwidth_bench.py --np 1 --device   # real chip

``--device`` keeps the default backend (the real TPU under the driver)
and runs in-process, measuring the *per-eager-call* cost on device —
each flush is its own dispatched program, so through a remote tunnel
this is dominated by dispatch latency (PERF_NOTES.md: 4–18 ms).  The
printed ``in_jit`` row times the same reduction arithmetic fused inside
one compiled step, the cost the in-graph plane
(``DistributedTrainStep``/``ops.collectives``) pays instead.
"""

import argparse
import time


def worker(nbytes: int, iters: int, device: bool = False):
    import jax

    if not device:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    # round down to a world-size multiple so the split-less alltoall is legal
    n = (nbytes // 4) // hvd.size() * hvd.size()
    x = jnp.asarray(np.random.RandomState(hvd.rank()).rand(n), jnp.float32)

    out = {}

    def timed(fn, label):
        fn(x, name=f"{label}_warm")
        t0 = time.perf_counter()
        for i in range(iters):
            fn(x, name=f"{label}_{i}")
        return (time.perf_counter() - t0) / iters

    ar_s = timed(hvd.allreduce, "ar")
    out["allreduce_sync_ms_per_call"] = ar_s * 1e3
    out["allreduce_MBps"] = nbytes / ar_s / 1e6
    out["allgather_MBps"] = (nbytes * hvd.size()
                             / timed(hvd.allgather, "ag") / 1e6)
    out["alltoall_MBps"] = nbytes / timed(hvd.alltoall, "a2a") / 1e6

    def burst(r, tag):
        """Issue ``r`` async allreduces, then synchronize the batch."""
        t0 = time.perf_counter()
        handles = [hvd.allreduce_async(x, name=f"b{tag}_{i}")
                   for i in range(r)]
        for h in handles:
            hvd.synchronize(h)
        return time.perf_counter() - t0

    # marginal per-call cost by slope fit (PERF_NOTES.md metrology:
    # through a remote tunnel any single burst pays a fixed fence RTT,
    # so difference two burst sizes instead of trusting one)
    burst(2, "w")
    r1, r3 = iters, 3 * iters
    out["allreduce_async_ms_per_call"] =         (burst(r3, "3") - burst(r1, "1")) / (r3 - r1) * 1e3

    # the same arithmetic fused in one compiled program: what the
    # in-graph plane pays per reduction instead of a per-call dispatch
    scale = 1.0 / hvd.size()
    fused = jax.jit(lambda v: v * scale)

    def jit_burst(r):
        t0 = time.perf_counter()
        for _ in range(r):
            y = fused(x)
        np.asarray(jnp.ravel(y)[0])     # tunnel-safe fence
        return time.perf_counter() - t0

    jit_burst(2)
    out["in_jit_ms_per_call"] =         (jit_burst(r3) - jit_burst(r1)) / (r3 - r1) * 1e3

    hvd.shutdown()
    return out


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--np", type=int, default=2)
    p.add_argument("--mb", type=int, default=16, help="payload megabytes")
    p.add_argument("--iters", type=int, default=5)
    p.add_argument("--device", action="store_true",
                   help="keep the default backend (real TPU) and run "
                        "in-process; requires --np 1")
    args = p.parse_args()

    if args.device:
        if args.np != 1:
            raise SystemExit("--device measures the single-chip eager "
                             "path; use --np 1")
        r0 = worker(args.mb * 1024 * 1024, args.iters, device=True)
    else:
        from horovod_tpu.runner import run

        results = run(worker, args=(args.mb * 1024 * 1024, args.iters),
                      np=args.np)
        r0 = results[0]
    for k, v in r0.items():
        unit = "ms" if k.endswith("ms_per_call") else "MB/s"
        print(f"{k}: {v:,.2f} {unit}")


if __name__ == "__main__":
    main()
