"""A/B compiler-option experiments on the ResNet-50 bench step.

Round-2 profiling concluded client XLA_FLAGS are rejected by the axon
plugin (server-side compile) — but per-compile ``compiler_options``
through ``jit(...).lower(...).compile()`` DO reach the TPU compiler, so
the latency-hiding scheduler / fusion / vmem knobs are testable after
all.  This harness times the exact ``bench.py`` train step under each
option set and prints a ms/step table (median of iters, loss-fetch
fenced — see PERF_NOTES.md for why block_until_ready is not a fence
through remote tunnels).

Usage::

    python examples/resnet_compile_experiments.py \
        --set lhs=xla_tpu_enable_latency_hiding_scheduler:true \
        --set vmem=xla_tpu_scoped_vmem_limit_kib:65536 ...

Each ``--set name=opt:val[,opt:val...]`` adds one experiment; the
baseline (no options) always runs first.
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax


def build_step(batch_size=256, image_size=224):
    import horovod_tpu as hvd
    from horovod_tpu.models.resnet import ResNet50

    hvd.init()
    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16)

    def loss_fn(params, batch):
        logits = model.apply(params, batch["x"], train=False)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["y"]).mean()

    opt = optax.sgd(0.01, momentum=0.9)

    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    x0 = jnp.zeros((1, image_size, image_size, 3), jnp.float32)
    # jit the init: run eagerly it is hundreds of per-op dispatches,
    # minutes through the remote tunnel
    params = jax.jit(lambda k: model.init(k, x0, train=False))(
        jax.random.PRNGKey(0))
    opt_state = opt.init(params)
    # host copies so donation inside time_variant can't consume them
    params = jax.tree_util.tree_map(np.asarray, params)
    opt_state = jax.tree_util.tree_map(np.asarray, opt_state)
    rng = np.random.RandomState(0)
    batch = {
        "x": jnp.asarray(rng.rand(batch_size, image_size, image_size, 3),
                         jnp.float32),
        "y": jnp.asarray(rng.randint(0, 1000, (batch_size,)), jnp.int32),
    }
    return step, params, opt_state, batch


def time_variant(step, params, opt_state, batch, options, iters=4,
                 steps_per_iter=10):
    # params/opt_state arrive as host trees: the step donates its
    # arguments (like bench.py), so each variant starts from fresh
    # device buffers
    p = jax.tree_util.tree_map(jnp.asarray, params)
    o = jax.tree_util.tree_map(jnp.asarray, opt_state)
    lowered = jax.jit(step, donate_argnums=(0, 1)).lower(p, o, batch)
    t0 = time.perf_counter()
    compiled = lowered.compile(compiler_options=options or None)
    compile_s = time.perf_counter() - t0
    p, o, loss = compiled(p, o, batch)
    float(loss)                      # fence (see PERF_NOTES.md)
    rates = []
    for _ in range(iters):
        t0 = time.perf_counter()
        for _ in range(steps_per_iter):
            p, o, loss = compiled(p, o, batch)
        float(loss)
        rates.append((time.perf_counter() - t0) / steps_per_iter)
    del p, o
    return float(np.median(rates)) * 1e3, compile_s


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--set", action="append", default=[],
                    help="name=opt:val[,opt:val...]")
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--iters", type=int, default=4)
    args = ap.parse_args()

    experiments = [("baseline", {})]
    for spec in args.set:
        name, body = spec.split("=", 1)
        opts = {}
        for kv in body.split(","):
            k, v = kv.split(":", 1)
            opts[k] = v
        experiments.append((name, opts))

    step, params, opt_state, batch = build_step(args.batch_size)
    bs = batch["y"].shape[0]
    print(f"{'variant':24s} {'ms/step':>9s} {'img/s':>8s} {'compile':>8s}")
    for name, opts in experiments:
        try:
            ms, comp = time_variant(step, params, opt_state, batch, opts,
                                    iters=args.iters)
            print(f"{name:24s} {ms:9.2f} {bs / ms * 1e3:8.1f} {comp:7.1f}s",
                  flush=True)
        except Exception as e:
            print(f"{name:24s} FAILED: {str(e)[:140]}", flush=True)


if __name__ == "__main__":
    main()
