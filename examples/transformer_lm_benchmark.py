"""Synthetic transformer-LM training benchmark — the long-context flagship.

Counterpart of the reference's synthetic benchmarks for the LLM regime:
trains :class:`horovod_tpu.models.TransformerLM` on random tokens and
prints tokens/sec.  ``--attention ring`` shards the sequence over the
``sp`` mesh axis (K/V ppermute ring), letting context length scale with
chips; ``--tp`` shards the matmuls.

Usage::

    python examples/transformer_lm_benchmark.py --platform cpu \
        --attention ring --sp 4 --seq-len 512
"""

import argparse
import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--num-layers", type=int, default=4)
    p.add_argument("--d-model", type=int, default=512)
    p.add_argument("--num-heads", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=1024,
                   help="global sequence length")
    p.add_argument("--batch-size", type=int, default=8,
                   help="global batch size")
    p.add_argument("--vocab-size", type=int, default=32000)
    p.add_argument("--attention", default="dense",
                   choices=["dense", "flash", "ring", "ulysses"])
    p.add_argument("--sp", type=int, default=1, help="sequence-parallel degree")
    p.add_argument("--tp", type=int, default=1, help="tensor-parallel degree")
    p.add_argument("--num-iters", type=int, default=5)
    p.add_argument("--num-batches-per-iter", type=int, default=5)
    p.add_argument("--remat", action="store_true")
    p.add_argument("--dtype", default="float32",
                   choices=["float32", "bfloat16"])
    p.add_argument("--platform", default=None)
    return p.parse_args()


def main():
    args = parse_args()
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    import flax.core.meta as meta

    import horovod_tpu as hvd
    from horovod_tpu.models import TransformerConfig, TransformerLM
    from horovod_tpu.parallel import make_parallel_mesh

    hvd.init()
    n = hvd.size()
    dp = n // (args.sp * args.tp)
    mesh = make_parallel_mesh(dp=dp, sp=args.sp, tp=args.tp)

    cfg = TransformerConfig(
        vocab_size=args.vocab_size, num_layers=args.num_layers,
        num_heads=args.num_heads, d_model=args.d_model,
        d_ff=4 * args.d_model, max_seq_len=args.seq_len,
        dtype=jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32,
        attention_impl=args.attention, remat=args.remat)
    model = TransformerLM(cfg)

    t_local = args.seq_len // max(args.sp, 1)

    # the next-token shift happens ONCE globally (inputs = tokens[:-1],
    # labels = tokens[1:]) and both sides are sharded over sp — a
    # per-shard shift would drop one token per shard, not one globally
    def loss_fn(variables, inputs, labels):
        offset = lax.axis_index("sp") * t_local if args.sp > 1 else 0
        positions = offset + jnp.arange(inputs.shape[1])
        logits = model.apply(variables, inputs, positions=positions)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()
        return lax.pmean(lax.pmean(loss, "dp"), "sp") \
            if args.sp > 1 else lax.pmean(loss, "dp")

    opt = optax.adamw(3e-4)

    def train_step(variables, opt_state, inputs, labels):
        loss, grads = jax.value_and_grad(loss_fn)(variables, inputs, labels)
        grads = jax.tree_util.tree_map(
            lambda g: lax.pmean(lax.pmean(g, "dp"), "sp") if args.sp > 1
            else lax.pmean(g, "dp"), grads)
        updates, opt_state = opt.update(grads, opt_state, variables)
        return optax.apply_updates(variables, updates), opt_state, loss

    # init outside the mesh with a dense-attention twin (identical param
    # tree); the distributed attention only exists inside shard_map
    init_model = TransformerLM(
        dataclasses.replace(cfg, attention_impl="dense"))
    tokens0 = jnp.zeros((args.batch_size, max(t_local, 2)), jnp.int32)
    variables = meta.unbox(init_model.init(jax.random.PRNGKey(0), tokens0))
    opt_state = opt.init(variables)

    tok_spec = P("dp", "sp") if args.sp > 1 else P("dp", None)
    step = jax.jit(jax.shard_map(
        train_step, mesh=mesh,
        in_specs=(P(), P(), tok_spec, tok_spec),
        out_specs=(P(), P(), P()),
        check_vma=False), donate_argnums=(0, 1))

    rng = np.random.RandomState(0)
    raw = jnp.asarray(rng.randint(
        0, args.vocab_size, (args.batch_size, args.seq_len + 1)), jnp.int32)
    sharding = NamedSharding(mesh, tok_spec)
    inputs = jax.device_put(raw[:, :-1], sharding)
    labels = jax.device_put(raw[:, 1:], sharding)

    if hvd.rank() == 0:
        nparams = sum(x.size for x in jax.tree_util.tree_leaves(variables))
        print(f"TransformerLM: {nparams / 1e6:.1f}M params, "
              f"seq {args.seq_len}, batch {args.batch_size}, "
              f"mesh dp={dp} sp={args.sp} tp={args.tp}, "
              f"attention={args.attention}")

    t0 = time.perf_counter()
    variables, opt_state, loss = step(variables, opt_state, inputs, labels)
    # fence on a host fetch of the loss, not jax.block_until_ready: through
    # remote-device tunnels block_until_ready can return before the step
    # finishes, silently inflating rates; a scalar device_get cannot
    float(loss)
    if hvd.rank() == 0:
        print(f"Warmup (incl. compile): {time.perf_counter() - t0:.1f}s, "
              f"loss={float(loss):.4f}")

    tokens_per_batch = args.batch_size * args.seq_len
    rates = []
    for it in range(args.num_iters):
        t0 = time.perf_counter()
        for _ in range(args.num_batches_per_iter):
            variables, opt_state, loss = step(variables, opt_state, inputs, labels)
        float(loss)
        dt = time.perf_counter() - t0
        rates.append(tokens_per_batch * args.num_batches_per_iter / dt)
        if hvd.rank() == 0:
            print(f"Iter #{it}: {rates[-1]:.0f} tokens/sec")

    if hvd.rank() == 0:
        print(f"Mean: {np.mean(rates):.0f} +- {1.96 * np.std(rates):.0f} "
              f"tokens/sec; final loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
