"""Elastic MNIST — the reference's elastic training recipe.

Counterpart of ``examples/elastic/tensorflow2_mnist_elastic.py``: wrap
the training loop in ``@hvd.elastic.run``, keep everything that must
survive a host change inside a ``TpuState``, ``commit()`` between
batches.  Run under the elastic launcher::

    python -m horovod_tpu.runner.launch -np 2 --min-np 1 --max-np 4 \
        --host-discovery-script ./discover.sh -- python examples/mnist_elastic.py
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--batches-per-commit", type=int, default=10)
    p.add_argument("--platform", default=None)
    args = p.parse_args()
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    import flax.linen as nn

    import horovod_tpu as hvd

    hvd.init()

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = x.reshape(x.shape[0], -1)
            x = nn.relu(nn.Dense(128)(x))
            return nn.Dense(10)(x)

    model = Net()

    def loss_fn(params, batch):
        logits = model.apply(params, batch["x"])
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["y"]).mean()

    def make_step():
        # rebuilt after every reset: the mesh (and so the compiled step)
        # changes with the world
        return hvd.DistributedTrainStep(
            loss_fn, optax.adam(0.001 * hvd.size()))

    rng = np.random.RandomState(0)
    x = rng.rand(4096, 28, 28).astype(np.float32)
    y = rng.randint(0, 10, (4096,)).astype(np.int32)

    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 28, 28), jnp.float32))
    state = hvd.elastic.TpuState(params=params, opt_state=None,
                                 epoch=0, batch=0)

    @hvd.elastic.run
    def train(state):
        step = make_step()
        params = state.params
        opt_state = state.opt_state
        if opt_state is None:
            params, opt_state = step.init(params)
        global_bs = args.batch_size * hvd.size()
        nbatches = len(x) // global_bs
        while state.epoch < args.epochs:
            perm = np.random.RandomState(state.epoch).permutation(len(x))
            while state.batch < nbatches:
                b = state.batch
                idx = perm[b * global_bs:(b + 1) * global_bs]
                batch = step.shard_batch({"x": jnp.asarray(x[idx]),
                                          "y": jnp.asarray(y[idx])})
                params, opt_state, loss = step(params, opt_state, batch)
                state.params = params
                state.opt_state = opt_state
                state.batch = b + 1
                if (b + 1) % args.batches_per_commit == 0:
                    state.commit()     # snapshot + host-update check
            if hvd.rank() == 0:
                print(f"epoch {state.epoch}: loss={float(loss):.4f} "
                      f"on {hvd.size()} chips")
            state.epoch += 1
            state.batch = 0
            state.commit()

    train(state)


if __name__ == "__main__":
    main()
