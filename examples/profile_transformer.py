"""Per-op device profile of the transformer-LM bench step (PERF_NOTES).

The transformer counterpart of ``profile_resnet.py``: captures a
``jax.profiler`` trace of the exact ``bench.py`` transformer step on the
real chip and prints exclusive per-op device times ("XLA Ops" line,
nesting-aware — async spans overlap and double-count, so exclusive
self-time is the honest attribution).

Usage::

    python examples/profile_transformer.py --layers 12 --d-model 1024 \
        [--batch-size 8] [--seq-len 1024] [--steps-per-call 4] [--remat]
"""

import argparse
import collections
import os
import sys
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from profile_resnet import exclusive_op_times, op_kind  # noqa: E402


def build_step(args):
    import horovod_tpu as hvd
    from horovod_tpu.models import TransformerConfig, TransformerLM

    hvd.init()
    cfg = TransformerConfig(
        vocab_size=32_000, num_layers=args.layers, num_heads=args.heads,
        d_model=args.d_model, d_ff=4 * args.d_model,
        max_seq_len=args.seq_len, dtype=jnp.bfloat16,
        attention_impl=args.attention, remat=args.remat)
    model = TransformerLM(cfg)

    def loss_fn(params, batch):
        logits = model.apply(params, batch["inputs"])
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["labels"]).mean()

    opts = None if args.no_lhs else \
        {"xla_tpu_enable_latency_hiding_scheduler": "true"}
    step = hvd.DistributedTrainStep(
        loss_fn, optax.adamw(3e-4), steps_per_call=args.steps_per_call,
        compiler_options=opts)
    tokens0 = jnp.zeros((1, args.seq_len), jnp.int32)
    variables = jax.jit(model.init)(jax.random.PRNGKey(0), tokens0)
    nparams = sum(x.size for x in jax.tree_util.tree_leaves(variables))
    params, opt_state = step.init(variables)
    rng = np.random.RandomState(0)
    raw = rng.randint(0, cfg.vocab_size, (args.batch_size,
                                          args.seq_len + 1))
    batch = step.shard_batch({
        "inputs": jnp.asarray(raw[:, :-1], jnp.int32),
        "labels": jnp.asarray(raw[:, 1:], jnp.int32),
    })
    return step, params, opt_state, batch, nparams


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--layers", type=int, default=12)
    ap.add_argument("--d-model", type=int, default=1024)
    ap.add_argument("--heads", type=int, default=16)
    ap.add_argument("--seq-len", type=int, default=1024)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--attention", default="flash")
    ap.add_argument("--remat", action="store_true")
    ap.add_argument("--steps-per-call", type=int, default=4)
    ap.add_argument("--top", type=int, default=30)
    ap.add_argument("--no-lhs", action="store_true")
    ap.add_argument("--trace-dir", default=None)
    args = ap.parse_args()

    step, params, opt_state, batch, nparams = build_step(args)
    p, o, loss = step(params, opt_state, batch)        # compile + warm
    float(loss)

    trace_dir = args.trace_dir or tempfile.mkdtemp(prefix="tfprof_")
    with jax.profiler.trace(trace_dir):
        p, o, loss = step(p, o, batch)
        float(loss)
    print(f"trace: {trace_dir}  ({nparams / 1e6:.1f}M params)")

    self_ps = exclusive_op_times(trace_dir)
    nsteps = args.steps_per_call
    total_ms = sum(self_ps.values()) / 1e9 / nsteps
    print(f"device exclusive op time: {total_ms:.2f} ms/step "
          f"({len(self_ps)} distinct ops, {nsteps} steps traced)")
    tokens = args.batch_size * args.seq_len
    flops_per_token = 6 * nparams + 6 * args.layers * args.seq_len \
        * args.d_model
    print(f"implied: {tokens / total_ms * 1000:.0f} tok/s, "
          f"{tokens / total_ms * 1000 * flops_per_token / 1e12:.1f} TF/s")

    by_kind = collections.defaultdict(float)
    for name, ps in self_ps.items():
        by_kind[op_kind(name)] += ps
    print("\n-- by op class (ms/step) --")
    for k, v in sorted(by_kind.items(), key=lambda kv: -kv[1])[:14]:
        ms = v / 1e9 / nsteps
        if ms >= 0.005:
            print(f"{k:36s} {ms:8.2f}  {ms / total_ms * 100:5.1f}%")

    print(f"\n-- top {args.top} ops (self ms/step) --")
    ranked = sorted(self_ps.items(), key=lambda kv: -kv[1])
    for name, ps in ranked[:args.top]:
        ms = ps / 1e9 / nsteps
        print(f"{name[:84]:84s} {ms:7.3f}")


if __name__ == "__main__":
    main()
