"""Native (C++) runtime components, loaded via ctypes.

Reference: the C++ core in ``horovod/common/`` — here the pieces where
native code still earns its keep on TPU: the lock-free timeline writer
(``timeline.{h,cc}``) and the rendezvous KV store
(``gloo/http_store.{h,cc}`` + ``runner/http/http_server.py``).

The shared library builds lazily with g++ on first use and caches next
to the source; every consumer has a pure-Python fallback, so missing
toolchains degrade gracefully (the reference's optional-extension
pattern, ``setup.py`` capability probes).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

from horovod_tpu.utils import logging as hvd_logging

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "hvd_native.cc")
_LIB = os.path.join(_HERE, "libhvd_tpu_native.so")

_lib = None
_lib_lock = threading.Lock()
_build_failed = False


def _build() -> bool:
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
           _SRC, "-o", _LIB]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (subprocess.SubprocessError, FileNotFoundError) as e:
        hvd_logging.debug("native build failed (%s); using Python fallbacks",
                          e)
        return False


def load() -> Optional[ctypes.CDLL]:
    """The shared library, building it on first call; None if unavailable."""
    global _lib, _build_failed
    with _lib_lock:
        if _lib is not None or _build_failed:
            return _lib
        if not os.path.exists(_LIB) or \
                os.path.getmtime(_LIB) < os.path.getmtime(_SRC):
            if not _build():
                _build_failed = True
                return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError as e:
            hvd_logging.debug("native load failed: %s", e)
            _build_failed = True
            return None
        lib.hvdtl_create.restype = ctypes.c_void_p
        lib.hvdtl_create.argtypes = [ctypes.c_char_p, ctypes.c_uint32]
        lib.hvdtl_intern.restype = ctypes.c_int32
        lib.hvdtl_intern.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.hvdtl_event.argtypes = [ctypes.c_void_p, ctypes.c_int32,
                                    ctypes.c_int32, ctypes.c_char]
        lib.hvdtl_dropped.restype = ctypes.c_uint64
        lib.hvdtl_dropped.argtypes = [ctypes.c_void_p]
        lib.hvdtl_close.argtypes = [ctypes.c_void_p]
        lib.hvdkv_start.restype = ctypes.c_void_p
        lib.hvdkv_start.argtypes = [ctypes.c_int]
        lib.hvdkv_port.restype = ctypes.c_int
        lib.hvdkv_port.argtypes = [ctypes.c_void_p]
        lib.hvdkv_stop.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def native_built() -> bool:
    """Capability probe (reference ``horovod_nccl_built`` style)."""
    return load() is not None


class NativeTimeline:
    """ctypes wrapper matching :class:`horovod_tpu.utils.timeline.Timeline`'s
    event API; producers pay one atomic + two stores per event."""

    def __init__(self, filename: str, mark_cycles: bool = False,
                 capacity: int = 65536):
        lib = load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._mark_cycles = mark_cycles
        self.filename = filename
        # native ts are µs since writer start; wall time at construction
        # is the rebase origin for cross-process aggregation
        import time as _time

        self.wall_origin_us = _time.time_ns() / 1e3
        self._handle = lib.hvdtl_create(filename.encode(), capacity)
        self._intern_cache: dict = {}
        self._cycle_id = self._intern("CYCLE_START")
        self._closed = False
        from horovod_tpu.utils.timeline import TraceAnnotationBridge

        self._annotations = TraceAnnotationBridge()

    def _intern(self, s: str) -> int:
        i = self._intern_cache.get(s)
        if i is None:
            i = self._lib.hvdtl_intern(self._handle, s.encode())
            self._intern_cache[s] = i
        return i

    def start_activity(self, tensor_name: str, activity: str) -> None:
        # the _closed guards make post-close events no-ops (dropped, as
        # the Python writer's dead queue drops them): deferred span closes
        # (eager handles' _tl_neg) may legally outlive stop_timeline, and
        # hvdtl_close frees the native writer
        if self._closed:
            return
        self._lib.hvdtl_event(self._handle, self._intern(activity),
                              self._intern(tensor_name), b"B")
        self._annotations.start(tensor_name, activity)

    def end_activity(self, tensor_name: str) -> None:
        if self._closed:
            return
        self._lib.hvdtl_event(self._handle, -1,
                              self._intern(tensor_name), b"E")
        self._annotations.end(tensor_name)

    def instant(self, name: str, args=None) -> None:
        if self._closed:
            return
        self._lib.hvdtl_event(self._handle, self._intern(name), -1, b"i")

    def mark_cycle_start(self) -> None:
        if self._mark_cycles and not self._closed:
            self._lib.hvdtl_event(self._handle, self._cycle_id, -1, b"i")

    @property
    def dropped_events(self) -> int:
        return int(self._lib.hvdtl_dropped(self._handle))

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._annotations.clear()
            self._lib.hvdtl_close(self._handle)


class KvStoreServer:
    """Launcher-side rendezvous KV server (reference ``RendezvousServer``)."""

    def __init__(self, port: int = 0):
        lib = load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._handle = lib.hvdkv_start(port)
        if not self._handle:
            raise OSError(f"could not bind KV store on port {port}")

    @property
    def port(self) -> int:
        return self._lib.hvdkv_port(self._handle)

    def stop(self) -> None:
        if self._handle:
            self._lib.hvdkv_stop(self._handle)
            self._handle = None


class KvStoreClient:
    """Blocking client for :class:`KvStoreServer` (reference ``HTTPStore``
    worker side, ``gloo/http_store.cc``): ``get`` waits until the key is
    published — the rendezvous primitive."""

    def __init__(self, host: str, port: int):
        self._addr = (host, port)

    def _roundtrip(self, payload: bytes, read_reply) -> bytes:
        import socket

        with socket.create_connection(self._addr, timeout=60) as s:
            s.sendall(payload)
            return read_reply(s)

    @staticmethod
    def _read_exact(sock, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise EOFError("kv connection closed")
            buf += chunk
        return buf

    def set(self, key: str, value: bytes) -> None:
        k = key.encode()
        payload = (b"S" + len(k).to_bytes(4, "big") + k
                   + len(value).to_bytes(4, "big") + value)
        self._roundtrip(payload, lambda s: self._read_exact(s, 1))

    def get(self, key: str, timeout_ms: int = 60000) -> Optional[bytes]:
        k = key.encode()
        payload = (b"G" + len(k).to_bytes(4, "big") + k
                   + timeout_ms.to_bytes(4, "big"))

        def read(sock):
            vlen = int.from_bytes(self._read_exact(sock, 4), "big")
            if vlen == 0xFFFFFFFF:
                return None
            return self._read_exact(sock, vlen)

        return self._roundtrip(payload, read)

    def num_keys(self) -> int:
        payload = b"D" + (0).to_bytes(4, "big")
        return int.from_bytes(
            self._roundtrip(payload, lambda s: self._read_exact(s, 4)),
            "big")
