// Native runtime components for horovod_tpu.
//
// Two pieces where the reference implements C++ and Python-level latency
// actually matters:
//
// 1. Timeline writer — reference horovod/common/timeline.{h,cc}: producers
//    push fixed-size event records into a lock-free MPSC ring buffer
//    (reference uses a boost SPSC queue, timeline.h:47-75); a dedicated
//    thread drains records to Chrome-tracing JSON.  Event cost on the hot
//    path is one atomic fetch_add + a few stores (no GIL-held file IO).
//
// 2. Rendezvous KV store — reference horovod/common/gloo/http_store.{h,cc}
//    + runner/http/http_server.py (KVStoreHandler): workers rendezvous
//    through a launcher-side key-value service.  Here: a threaded TCP
//    server with blocking GET-until-set semantics (the HTTPStore wait
//    loop, gloo_context.cc:71-91) over a length-prefixed binary frame.
//
// Exposed as a plain C API for ctypes (no pybind11 in this image).
//
// Build: g++ -O2 -std=c++17 -shared -fPIC -pthread hvd_native.cc -o ...

#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

// ---------------------------------------------------------------------------
// Timeline writer
// ---------------------------------------------------------------------------

namespace {

struct Event {
  int32_t name_id;   // interned activity name
  int32_t tid_id;    // interned tensor/thread name
  int64_t ts_us;     // microseconds since writer start
  char phase;        // 'B', 'E', 'i'
  char _pad[7];
};

// Bounded MPSC slot (Vyukov scheme): seq == ticket means free for that
// ticket's producer; seq == ticket + 1 means committed, ready to drain.
struct Slot {
  std::atomic<uint64_t> seq;
  Event e;
};

struct TimelineWriter {
  explicit TimelineWriter(const char* path, uint32_t capacity)
      : capacity_(capacity), buf_(capacity), start_(now_us()) {
    for (uint32_t i = 0; i < capacity_; ++i)
      buf_[i].seq.store(i, std::memory_order_relaxed);
    file_ = std::fopen(path, "w");
    if (file_) std::fputs("[\n", file_);
    writer_ = std::thread([this] { DrainLoop(); });
  }

  ~TimelineWriter() { Close(); }

  int32_t Intern(const char* s) {
    std::lock_guard<std::mutex> lk(intern_mu_);
    auto it = intern_.find(s);
    if (it != intern_.end()) return it->second;
    int32_t id = static_cast<int32_t>(names_.size());
    names_.emplace_back(s);
    intern_.emplace(s, id);
    return id;
  }

  // Multi-producer push: claim a ticket, wait for the slot to be recycled
  // (consumer drains at disk speed, so the wait is bounded — the
  // reference's boost-lockfree push spins the same way on full), write,
  // publish by bumping the slot's per-slot sequence.  Per-slot sequences
  // make out-of-order producer commits safe: the drain only consumes a
  // slot whose own sequence says "committed".
  void Push(int32_t name_id, int32_t tid_id, char phase) {
    uint64_t ticket = head_.fetch_add(1, std::memory_order_acq_rel);
    Slot& s = buf_[ticket % capacity_];
    while (s.seq.load(std::memory_order_acquire) != ticket) {
      if (closing_.load(std::memory_order_acquire)) {
        dropped_.fetch_add(1, std::memory_order_relaxed);
        return;
      }
      std::this_thread::yield();
    }
    s.e.name_id = name_id;
    s.e.tid_id = tid_id;
    s.e.ts_us = now_us() - start_;
    s.e.phase = phase;
    s.seq.store(ticket + 1, std::memory_order_release);
  }

  void DrainLoop() {
    uint64_t t = 0;
    while (true) {
      Slot& s = buf_[t % capacity_];
      if (s.seq.load(std::memory_order_acquire) == t + 1) {
        WriteEvent(s.e);
        s.seq.store(t + capacity_, std::memory_order_release);
        ++t;
        continue;
      }
      if (closing_.load(std::memory_order_acquire)) {
        if (t >= head_.load(std::memory_order_acquire)) return;
        // claimed but uncommitted: grace-wait for a mid-write producer;
        // an abandoned slot (producer saw closing_) never commits
        bool committed = false;
        for (int i = 0; i < 1000; ++i) {
          if (s.seq.load(std::memory_order_acquire) == t + 1) {
            committed = true;
            break;
          }
          std::this_thread::sleep_for(std::chrono::microseconds(50));
        }
        if (!committed) return;
        continue;
      }
      std::this_thread::sleep_for(std::chrono::milliseconds(2));
    }
  }

  void WriteEvent(const Event& e) {
    if (!file_) return;
    if (!first_) std::fputs(",\n", file_);
    first_ = false;
    // Copy interned strings under intern_mu_: producers' Intern() may
    // emplace_back and reallocate names_ concurrently with this drain
    // thread, so an unlocked names_[id] read is a use-after-free race.
    std::string name_s, tid_s("runtime");
    {
      std::lock_guard<std::mutex> lk(intern_mu_);
      if (e.name_id >= 0) name_s = names_[e.name_id];
      if (e.tid_id >= 0) tid_s = names_[e.tid_id];
    }
    const char* name = name_s.c_str();
    const char* tid = tid_s.c_str();
    if (e.phase == 'E') {
      std::fprintf(file_, "{\"ph\":\"E\",\"tid\":\"%s\",\"pid\":1,"
                   "\"ts\":%lld}", tid, (long long)e.ts_us);
    } else if (e.phase == 'i') {
      std::fprintf(file_, "{\"ph\":\"i\",\"name\":\"%s\",\"s\":\"p\","
                   "\"tid\":\"%s\",\"pid\":1,\"ts\":%lld}",
                   name, tid, (long long)e.ts_us);
    } else {
      std::fprintf(file_, "{\"ph\":\"B\",\"name\":\"%s\",\"cat\":\"%s\","
                   "\"tid\":\"%s\",\"pid\":1,\"ts\":%lld}",
                   name, name, tid, (long long)e.ts_us);
    }
  }

  void Close() {
    bool expected = false;
    if (!closed_.compare_exchange_strong(expected, true)) return;
    closing_.store(true, std::memory_order_release);
    if (writer_.joinable()) writer_.join();
    if (file_) {
      uint64_t d = dropped_.load();
      if (d) {
        if (!first_) std::fputs(",\n", file_);
        std::fprintf(file_, "{\"ph\":\"i\",\"name\":\"DROPPED_%llu_EVENTS\","
                     "\"s\":\"g\",\"tid\":\"runtime\",\"pid\":1,\"ts\":0}",
                     (unsigned long long)d);
      }
      std::fputs("\n]\n", file_);
      std::fclose(file_);
      file_ = nullptr;
    }
  }

  static int64_t now_us() {
    return std::chrono::duration_cast<std::chrono::microseconds>(
               std::chrono::steady_clock::now().time_since_epoch())
        .count();
  }

  uint32_t capacity_;
  std::vector<Slot> buf_;
  int64_t start_;
  std::FILE* file_ = nullptr;
  bool first_ = true;
  std::thread writer_;
  std::atomic<uint64_t> head_{0}, dropped_{0};
  std::atomic<bool> closing_{false}, closed_{false};
  std::mutex intern_mu_;
  std::map<std::string, int32_t> intern_;
  std::vector<std::string> names_;
};

// ---------------------------------------------------------------------------
// KV store (rendezvous)
// ---------------------------------------------------------------------------

bool ReadExact(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool WriteExact(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t w = ::write(fd, p, n);
    if (w <= 0) return false;
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

struct KvStore {
  int listen_fd = -1;
  int port = 0;
  std::thread accept_thread;
  std::atomic<bool> stopping{false};
  std::mutex mu;
  std::condition_variable cv;
  std::map<std::string, std::string> data;
  std::vector<std::thread> workers;

  // frame: op(1) keylen(4,be) key [vallen(4,be) val]
  //   'S' set -> reply 1 byte 0x01
  //   'G' get, blocks until key exists or timeout(4, be, ms) -> reply
  //       vallen(4,be) + val; vallen = 0xFFFFFFFF on timeout
  //   'D' dump count -> reply count(4,be) of keys (diagnostics)
  void Serve(int fd) {
    for (;;) {
      char op;
      if (!ReadExact(fd, &op, 1)) break;
      uint32_t klen_be;
      if (!ReadExact(fd, &klen_be, 4)) break;
      uint32_t klen = ntohl(klen_be);
      if (klen > (1u << 20)) break;
      std::string key(klen, '\0');
      if (!ReadExact(fd, key.data(), klen)) break;
      if (op == 'S') {
        uint32_t vlen_be;
        if (!ReadExact(fd, &vlen_be, 4)) break;
        uint32_t vlen = ntohl(vlen_be);
        if (vlen > (1u << 26)) break;
        std::string val(vlen, '\0');
        if (!ReadExact(fd, val.data(), vlen)) break;
        {
          std::lock_guard<std::mutex> lk(mu);
          data[key] = std::move(val);
        }
        cv.notify_all();
        char ok = 1;
        if (!WriteExact(fd, &ok, 1)) break;
      } else if (op == 'G') {
        uint32_t to_be;
        if (!ReadExact(fd, &to_be, 4)) break;
        uint32_t timeout_ms = ntohl(to_be);
        std::string val;
        bool found = false;
        {
          std::unique_lock<std::mutex> lk(mu);
          found = cv.wait_for(
              lk, std::chrono::milliseconds(timeout_ms), [&] {
                return stopping.load() || data.count(key) > 0;
              }) && data.count(key) > 0;
          if (found) val = data[key];
        }
        uint32_t vlen_be = htonl(found ? (uint32_t)val.size() : 0xFFFFFFFFu);
        if (!WriteExact(fd, &vlen_be, 4)) break;
        if (found && !WriteExact(fd, val.data(), val.size())) break;
      } else if (op == 'D') {
        uint32_t n_be;
        {
          std::lock_guard<std::mutex> lk(mu);
          n_be = htonl((uint32_t)data.size());
        }
        if (!WriteExact(fd, &n_be, 4)) break;
      } else {
        break;
      }
    }
    ::close(fd);
  }

  bool Start(int requested_port) {
    listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd < 0) return false;
    int one = 1;
    ::setsockopt(listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(requested_port));
    if (::bind(listen_fd, reinterpret_cast<sockaddr*>(&addr),
               sizeof(addr)) != 0 ||
        ::listen(listen_fd, 128) != 0) {
      ::close(listen_fd);
      return false;
    }
    socklen_t len = sizeof(addr);
    ::getsockname(listen_fd, reinterpret_cast<sockaddr*>(&addr), &len);
    port = ntohs(addr.sin_port);
    accept_thread = std::thread([this] {
      for (;;) {
        int fd = ::accept(listen_fd, nullptr, nullptr);
        if (fd < 0) {
          if (stopping.load()) return;
          continue;
        }
        workers.emplace_back([this, fd] { Serve(fd); });
      }
    });
    return true;
  }

  void Stop() {
    bool expected = false;
    if (!stopping.compare_exchange_strong(expected, true)) return;
    cv.notify_all();
    ::shutdown(listen_fd, SHUT_RDWR);
    ::close(listen_fd);
    if (accept_thread.joinable()) accept_thread.join();
    for (auto& w : workers)
      if (w.joinable()) w.join();
  }
};

}  // namespace

// ---------------------------------------------------------------------------
// C API
// ---------------------------------------------------------------------------

extern "C" {

void* hvdtl_create(const char* path, uint32_t capacity) {
  return new TimelineWriter(path, capacity ? capacity : 65536);
}

int32_t hvdtl_intern(void* h, const char* s) {
  return static_cast<TimelineWriter*>(h)->Intern(s);
}

void hvdtl_event(void* h, int32_t name_id, int32_t tid_id, char phase) {
  static_cast<TimelineWriter*>(h)->Push(name_id, tid_id, phase);
}

uint64_t hvdtl_dropped(void* h) {
  return static_cast<TimelineWriter*>(h)->dropped_.load();
}

void hvdtl_close(void* h) {
  auto* w = static_cast<TimelineWriter*>(h);
  w->Close();
  delete w;
}

void* hvdkv_start(int port) {
  auto* s = new KvStore();
  if (!s->Start(port)) {
    delete s;
    return nullptr;
  }
  return s;
}

int hvdkv_port(void* h) { return static_cast<KvStore*>(h)->port; }

void hvdkv_stop(void* h) {
  auto* s = static_cast<KvStore*>(h);
  s->Stop();
  delete s;
}

}  // extern "C"
