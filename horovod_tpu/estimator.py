"""Estimator: the fit/transform high-level API.

Reference: ``horovod/spark/common/estimator.py`` (``HorovodEstimator``
fit/transform), ``spark/keras/estimator.py:105`` /
``spark/torch/estimator.py:84`` and their ``remote.py`` training loops —
the only place the reference owns a training loop.  Same shape here
over pandas/numpy data (Spark DataFrames reduce to the same arrays via
``toPandas`` on the caller's side): ``Estimator.fit(df) -> TpuModel``,
``TpuModel.transform(df) -> df + prediction column``.

The loop underneath is :class:`~horovod_tpu.optim.DistributedTrainStep`
— sharded batches, compiled step, callbacks, optional checkpoint store.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax


def _extract(df, cols: Sequence[str]) -> np.ndarray:
    """(n, len(cols)) float array from a DataFrame or dict of arrays;
    columns holding arrays (images) are stacked along feature dims."""
    parts = []
    for c in cols:
        col = np.asarray(list(df[c]) if not isinstance(df, dict) else df[c])
        parts.append(col.reshape(len(col), -1).astype(np.float32))
    return np.concatenate(parts, axis=1) if len(parts) > 1 else parts[0]


@dataclasses.dataclass
class _Loop:
    """Duck-typed loop object handed to callbacks."""

    params: Any = None
    opt_state: Any = None


class TpuModel:
    """Fitted model (reference ``HorovodModel`` Transformer)."""

    def __init__(self, apply_fn: Callable, params: Any,
                 feature_cols: Sequence[str], output_col: str = "prediction",
                 batch_size: int = 1024):
        self._apply = apply_fn
        self.params = params
        self._feature_cols = list(feature_cols)
        self._output_col = output_col
        self._batch_size = batch_size

    def transform(self, df):
        """Return ``df`` with the model output column appended (reference
        ``transform`` adds prediction columns to the DataFrame)."""
        x = _extract(df, self._feature_cols)
        outs = []
        apply = jax.jit(self._apply)
        for i in range(0, len(x), self._batch_size):
            outs.append(np.asarray(
                apply(self.params, jnp.asarray(x[i:i + self._batch_size]))))
        preds = np.concatenate(outs, axis=0)
        if isinstance(df, dict):
            out = dict(df)
            out[self._output_col] = preds
            return out
        out = df.copy()
        out[self._output_col] = list(preds)
        return out


class Estimator:
    """Fit a model to a DataFrame (reference ``HorovodEstimator``).

    ``model`` is a flax module or an ``apply(params, x) -> out`` callable
    paired with ``initial_params``.  ``loss`` maps (output, label batch)
    to a scalar; defaults to softmax cross-entropy on integer labels.
    """

    def __init__(self, model, feature_cols: Sequence[str], label_col: str,
                 optimizer: Optional[optax.GradientTransformation] = None,
                 loss: Optional[Callable] = None,
                 initial_params: Any = None,
                 batch_size: int = 32, epochs: int = 1,
                 callbacks: Optional[List] = None,
                 store_dir: Optional[str] = None,
                 validation_fraction: float = 0.0,
                 seed: int = 0):
        self._model = model
        self._feature_cols = list(feature_cols)
        self._label_col = label_col
        self._optimizer = optimizer or optax.adam(1e-3)
        self._loss = loss
        self._initial_params = initial_params
        self._batch_size = batch_size
        self._epochs = epochs
        self._callbacks = callbacks or []
        self._store_dir = store_dir
        self._validation_fraction = validation_fraction
        self._seed = seed

    def _apply_fn(self):
        if hasattr(self._model, "apply"):
            return lambda params, x: self._model.apply(params, x)
        return self._model

    def fit(self, df) -> TpuModel:
        import horovod_tpu as hvd
        from horovod_tpu.callbacks import CallbackList

        hvd.init()
        x = _extract(df, self._feature_cols)
        y = np.asarray(df[self._label_col])
        if y.dtype.kind == "f":
            y = y.astype(np.float32)
        else:
            y = y.astype(np.int32)

        n_val = int(len(x) * self._validation_fraction)
        if n_val:
            x, x_val = x[:-n_val], x[-n_val:]
            y, y_val = y[:-n_val], y[-n_val:]

        apply_fn = self._apply_fn()
        loss = self._loss or (
            lambda out, batch: optax.softmax_cross_entropy_with_integer_labels(
                out, batch["y"]).mean())

        def loss_fn(params, batch):
            return loss(apply_fn(params, batch["x"]), batch)

        step = hvd.DistributedTrainStep(loss_fn, self._optimizer)
        params = self._initial_params
        if params is None:
            if not hasattr(self._model, "init"):
                raise ValueError("pass initial_params for a bare apply fn")
            params = self._model.init(jax.random.PRNGKey(self._seed),
                                      jnp.asarray(x[:1]))
        params = hvd.broadcast_variables(params, root_rank=0)
        params, opt_state = step.init(params)

        ckpt = hvd.checkpoint.Checkpointer(self._store_dir) \
            if self._store_dir else None
        loop = _Loop(params, opt_state)
        cbs = CallbackList(self._callbacks)
        cbs.on_train_begin(loop)

        global_bs = self._batch_size * hvd.size()
        nbatches = max(len(x) // global_bs, 1)
        rng = np.random.RandomState(self._seed)
        logs: dict = {}
        for epoch in range(self._epochs):
            cbs.on_epoch_begin(epoch, loop, logs)
            perm = rng.permutation(len(x))
            for b in range(nbatches):
                cbs.on_batch_begin(b, loop, logs)
                idx = perm[b * global_bs:(b + 1) * global_bs]
                if len(idx) < global_bs:   # pad the ragged tail batch
                    # np.resize cycles perm, so even len(x) < global_bs/2
                    # still yields a full, device-divisible batch
                    idx = np.concatenate(
                        [idx, np.resize(perm, global_bs - len(idx))])
                batch = step.shard_batch({"x": jnp.asarray(x[idx]),
                                          "y": jnp.asarray(y[idx])})
                loop.params, loop.opt_state, train_loss = step(
                    loop.params, loop.opt_state, batch)
                cbs.on_batch_end(b, loop, logs)
            logs["loss"] = float(train_loss)
            if n_val:
                logs["val_loss"] = float(loss_fn(
                    loop.params, {"x": jnp.asarray(x_val),
                                  "y": jnp.asarray(y_val)}))
            cbs.on_epoch_end(epoch, loop, logs)
            if ckpt:
                ckpt.save(epoch, {"params": loop.params,
                                  "opt_state": loop.opt_state})
        cbs.on_train_end(loop, logs)
        return TpuModel(apply_fn, loop.params, self._feature_cols)
