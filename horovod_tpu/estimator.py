"""Estimator: the fit/transform high-level API.

Reference: ``horovod/spark/common/estimator.py`` (``HorovodEstimator``
fit/transform), ``spark/keras/estimator.py:105`` /
``spark/torch/estimator.py:84`` and their ``remote.py`` training loops —
the only place the reference owns a training loop.  Same shape here
over pandas/numpy data (Spark DataFrames reduce to the same arrays via
``toPandas`` on the caller's side): ``Estimator.fit(df) -> TpuModel``,
``TpuModel.transform(df) -> df + prediction column``.

The loop underneath is :class:`~horovod_tpu.optim.DistributedTrainStep`
— sharded batches, compiled step, callbacks, optional checkpoint store.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from horovod_tpu.spark.store import (
    ColSpec,
    Store,
    assemble_features,
    extract_columns,
    extract_typed,
    save_metadata,
)


def _features(df, cols: Sequence[str],
              specs: Optional[Sequence[ColSpec]] = None):
    """Typed feature assembly (reference petastorm feeds named, typed
    columns; round 1 flattened everything to float32 — ints and image
    shapes now survive, see ``spark/store.py``).  With known specs the
    columns are validated against them; otherwise a single-pass
    extract-and-infer avoids materializing every column twice."""
    if specs is not None:
        return assemble_features(extract_columns(df, specs), specs)
    columns, inferred = extract_typed(df, cols)
    return assemble_features(columns, inferred)


def _map_leaves(f, x):
    """Apply ``f`` to an array or to every array of a feature dict —
    one pytree map instead of scattered isinstance branches."""
    return jax.tree_util.tree_map(f, x)


@dataclasses.dataclass
class _Loop:
    """Duck-typed loop object handed to callbacks."""

    params: Any = None
    opt_state: Any = None


class TpuModel:
    """Fitted model (reference ``HorovodModel`` Transformer)."""

    def __init__(self, apply_fn: Callable, params: Any,
                 feature_cols: Sequence[str], output_col: str = "prediction",
                 batch_size: int = 1024,
                 feature_specs: Optional[Sequence[ColSpec]] = None):
        self._apply = apply_fn
        self.params = params
        self._feature_cols = list(feature_cols)
        self._specs = list(feature_specs) if feature_specs else None
        self._output_col = output_col
        self._batch_size = batch_size

    def transform(self, df):
        """Return ``df`` with the model output column appended (reference
        ``transform`` adds prediction columns to the DataFrame)."""
        x = _features(df, self._feature_cols, self._specs)
        outs = []
        apply = jax.jit(self._apply)
        n = len(jax.tree_util.tree_leaves(x)[0])
        for i in range(0, n, self._batch_size):
            xb = _map_leaves(
                lambda v: jnp.asarray(v[i:i + self._batch_size]), x)
            outs.append(np.asarray(apply(self.params, xb)))
        preds = np.concatenate(outs, axis=0)
        if isinstance(df, dict):
            out = dict(df)
            out[self._output_col] = preds
            return out
        out = df.copy()
        out[self._output_col] = list(preds)
        return out


class Estimator:
    """Fit a model to a DataFrame (reference ``HorovodEstimator``).

    ``model`` is a flax module or an ``apply(params, x) -> out`` callable
    paired with ``initial_params``.  ``loss`` maps (output, label batch)
    to a scalar; defaults to softmax cross-entropy on integer labels.
    """

    def __init__(self, model, feature_cols: Sequence[str], label_col: str,
                 optimizer: Optional[optax.GradientTransformation] = None,
                 loss: Optional[Callable] = None,
                 initial_params: Any = None,
                 batch_size: int = 32, epochs: int = 1,
                 callbacks: Optional[List] = None,
                 store: Optional[Any] = None,
                 store_dir: Optional[str] = None,
                 validation_fraction: float = 0.0,
                 seed: int = 0):
        self._model = model
        self._feature_cols = list(feature_cols)
        self._label_col = label_col
        self._optimizer = optimizer or optax.adam(1e-3)
        self._loss = loss
        self._initial_params = initial_params
        self._batch_size = batch_size
        self._epochs = epochs
        self._callbacks = callbacks or []
        # `store` is the reference Estimator's artifact manager
        # (spark/common/store.py: runs/<id>/{checkpoint,logs,metadata} +
        # intermediate parquet).  `store_dir` keeps its original, narrower
        # meaning — checkpoints written directly under that path, no run
        # layout, no data materialization — so existing tooling pointed
        # at a store_dir keeps finding its files.
        if isinstance(store, str):
            store = Store.create(store)
        self._store = store
        self._legacy_ckpt_dir = store_dir if store is None else None
        self._validation_fraction = validation_fraction
        self._seed = seed

    def _apply_fn(self):
        if hasattr(self._model, "apply"):
            return lambda params, x: self._model.apply(params, x)
        return self._model

    def fit(self, df) -> TpuModel:
        import horovod_tpu as hvd
        from horovod_tpu.callbacks import CallbackList

        hvd.init()
        cols_x, feature_specs = extract_typed(df, self._feature_cols)
        cols_y, (label_spec,) = extract_typed(df, [self._label_col])
        x = assemble_features(cols_x, feature_specs)
        y = cols_y[self._label_col]

        def take(data, sl):
            return _map_leaves(lambda v: v[sl], data)

        n_rows = len(y)
        n_val = int(n_rows * self._validation_fraction)
        if n_val:
            x, x_val = take(x, slice(None, -n_val)), take(x, slice(-n_val,
                                                                   None))
            y, y_val = y[:-n_val], y[-n_val:]

        run_id = None
        if self._store is not None:
            # reference run layout: runs/<run_id>/{checkpoint,logs,
            # metadata.json} + intermediate parquet data dirs (store.py
            # path contract, util.py materialization).  Writes happen on
            # rank 0 only — the repo's Checkpointer convention — and the
            # run id is broadcast so every rank agrees on the paths.
            run_id = hvd.broadcast_object(
                self._store.new_run_id() if hvd.rank() == 0 else None,
                root_rank=0)
            if hvd.rank() == 0:
                self._store.makedirs(self._store.get_logs_path(run_id))
                save_metadata(self._store, run_id, feature_specs,
                              label_spec)
                import pandas as pd

                if isinstance(df, pd.DataFrame):
                    split = len(df) - n_val
                    self._store.write_dataframe(
                        df.iloc[:split],
                        self._store.get_train_data_path())
                    if n_val:
                        self._store.write_dataframe(
                            df.iloc[split:],
                            self._store.get_val_data_path())

        apply_fn = self._apply_fn()
        loss = self._loss or (
            lambda out, batch: optax.softmax_cross_entropy_with_integer_labels(
                out, batch["y"]).mean())

        def to_dev(data):
            return _map_leaves(jnp.asarray, data)

        def loss_fn(params, batch):
            return loss(apply_fn(params, batch["x"]), batch)

        step = hvd.DistributedTrainStep(loss_fn, self._optimizer)
        params = self._initial_params
        if params is None:
            if not hasattr(self._model, "init"):
                raise ValueError("pass initial_params for a bare apply fn")
            params = self._model.init(jax.random.PRNGKey(self._seed),
                                      to_dev(take(x, slice(0, 1))))
        params = hvd.broadcast_variables(params, root_rank=0)
        params, opt_state = step.init(params)

        if self._store is not None:
            ckpt = hvd.checkpoint.Checkpointer(
                self._store.get_checkpoint_path(run_id))
        elif self._legacy_ckpt_dir:
            ckpt = hvd.checkpoint.Checkpointer(self._legacy_ckpt_dir)
        else:
            ckpt = None
        loop = _Loop(params, opt_state)
        cbs = CallbackList(self._callbacks)
        cbs.on_train_begin(loop)

        global_bs = self._batch_size * hvd.size()
        nbatches = max(len(y) // global_bs, 1)
        rng = np.random.RandomState(self._seed)
        logs: dict = {}
        for epoch in range(self._epochs):
            cbs.on_epoch_begin(epoch, loop, logs)
            perm = rng.permutation(len(y))
            for b in range(nbatches):
                cbs.on_batch_begin(b, loop, logs)
                idx = perm[b * global_bs:(b + 1) * global_bs]
                if len(idx) < global_bs:   # pad the ragged tail batch
                    # np.resize cycles perm, so even len(x) < global_bs/2
                    # still yields a full, device-divisible batch
                    idx = np.concatenate(
                        [idx, np.resize(perm, global_bs - len(idx))])
                # host arrays go straight in: shard_batch feeds each
                # process's addressable shards from the numpy buffers
                batch = step.shard_batch({"x": take(x, idx),
                                          "y": y[idx]})
                loop.params, loop.opt_state, train_loss = step(
                    loop.params, loop.opt_state, batch)
                cbs.on_batch_end(b, loop, logs)
            logs["loss"] = float(train_loss)
            if n_val:
                logs["val_loss"] = float(loss_fn(
                    loop.params, {"x": to_dev(x_val),
                                  "y": jnp.asarray(y_val)}))
            cbs.on_epoch_end(epoch, loop, logs)
            if ckpt:
                ckpt.save(epoch, {"params": loop.params,
                                  "opt_state": loop.opt_state})
        cbs.on_train_end(loop, logs)
        return TpuModel(apply_fn, loop.params, self._feature_cols,
                        feature_specs=feature_specs)
