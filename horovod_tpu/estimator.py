"""Estimator: the fit/transform high-level API.

Reference: ``horovod/spark/common/estimator.py`` (``HorovodEstimator``
fit/transform), ``spark/keras/estimator.py:105`` /
``spark/torch/estimator.py:84`` and their ``remote.py`` training loops —
the only place the reference owns a training loop.  Same shape here
over pandas/numpy data (Spark DataFrames reduce to the same arrays via
``toPandas`` on the caller's side): ``Estimator.fit(df) -> TpuModel``,
``TpuModel.transform(df) -> df + prediction column``.

The loop underneath is :class:`~horovod_tpu.optim.DistributedTrainStep`
— sharded batches, compiled step, callbacks, optional checkpoint store.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from horovod_tpu.spark.params import (
    HasParams,
    Param,
    ParamError,
    optional,
    to_bool,
    to_fraction,
    to_int,
    to_positive_int,
    to_str,
    to_str_list,
)
from horovod_tpu.spark.store import (
    ColSpec,
    FilesystemStore,
    RowGroupReader,
    Store,
    assemble_features,
    extract_columns,
    extract_typed,
    save_metadata,
)


def _features(df, cols: Sequence[str],
              specs: Optional[Sequence[ColSpec]] = None):
    """Typed feature assembly (reference petastorm feeds named, typed
    columns; round 1 flattened everything to float32 — ints and image
    shapes now survive, see ``spark/store.py``).  With known specs the
    columns are validated against them; otherwise a single-pass
    extract-and-infer avoids materializing every column twice."""
    if specs is not None:
        return assemble_features(extract_columns(df, specs), specs)
    columns, inferred = extract_typed(df, cols)
    return assemble_features(columns, inferred)


def _map_leaves(f, x):
    """Apply ``f`` to an array or to every array of a feature dict —
    one pytree map instead of scattered isinstance branches."""
    return jax.tree_util.tree_map(f, x)


def _tree_concat(a, b):
    return jax.tree_util.tree_map(
        lambda u, v: np.concatenate([u, v], axis=0), a, b)


def _slice_rows(df, sl: slice):
    """Row slice of a DataFrame or column dict — the one place the
    dict-vs-DataFrame branch lives."""
    if isinstance(df, dict):
        return {k: v[sl] for k, v in df.items()}
    return df.iloc[sl]


def _head(df, n: int = 1):
    """First ``n`` rows (schema probes)."""
    return _slice_rows(df, slice(None, n))


def _num_rows(df) -> int:
    if isinstance(df, dict):
        return len(next(iter(df.values()))) if df else 0
    return len(df)


_localized_cache: dict = {}   # remote URL -> local copy (per process)


def _localize_dataset(path: Optional[str]) -> Optional[str]:
    """Fetch a remote (fsspec URL) dataset directory to a local temp dir;
    local paths pass through.  RowGroupReader streams from local files,
    so remote fits download once per process, then shard locally.
    Downloads are cached per URL for the process lifetime (repeated fits
    must not re-transfer or accumulate copies) and removed at exit."""
    if not path or "://" not in path or path.startswith("file://"):
        return path[len("file://"):] if path and \
            path.startswith("file://") else path
    cached = _localized_cache.get(path)
    if cached is not None and os.path.isdir(cached):
        return cached
    import atexit
    import shutil
    import tempfile

    import fsspec

    fs, _ = fsspec.core.url_to_fs(path)
    local = tempfile.mkdtemp(prefix="hvd_dataset_")
    fs.get(path.rstrip("/") + "/", local + "/", recursive=True)
    _localized_cache[path] = local
    atexit.register(shutil.rmtree, local, ignore_errors=True)
    return local


def _evict_localized(*paths: Optional[str]) -> None:
    """Drop local copies of run-scoped remote paths.  Run-scoped
    intermediates use distinct URLs per fit, so without eviction every
    remote ``_fit_via_store`` fit leaves one more full dataset copy on
    each worker until exit (the remote source is deleted after the fit
    anyway, so the cache entry could never be reused)."""
    import shutil

    for path in paths:
        local = _localized_cache.pop(path, None) if path else None
        if local is not None:
            shutil.rmtree(local, ignore_errors=True)


class _SyncingCheckpointer:
    """Checkpointer that mirrors its staging dir to the remote store
    after every successful save — a crash mid-fit leaves the epochs
    already trained in the store (the reference estimator persists
    per-epoch), not zero checkpoints.

    The mirror is incremental per file: only files new or changed since
    the last sync are uploaded (not the whole retained-step set every
    epoch), and files the local retention gc pruned are deleted
    remotely, so the store honors ``max_to_keep`` instead of growing
    with epoch count."""

    def __init__(self, inner, store, staging: str, remote: str):
        self._inner, self._store = inner, store
        self._staging, self._remote = staging, remote
        self._mirrored: dict = {}     # relpath -> (mtime_ns, size)

    def save(self, step, state) -> bool:
        wrote = self._inner.save(step, state)
        if wrote:
            try:
                self.mirror()
            except Exception as exc:
                # a transient store blip must not abort the training
                # loop; _mirrored only advances on a fully successful
                # pass, so the next save (or the strict final sync)
                # retries everything still pending
                from horovod_tpu.utils import logging as hvd_logging

                hvd_logging.warning(
                    "checkpoint mirror to store failed (will retry on "
                    "the next save / final sync): %s", exc)
        return wrote

    def mirror(self) -> None:
        current = {}
        for root, _dirs, files in os.walk(self._staging):
            for fn in files:
                full = os.path.join(root, fn)
                st = os.stat(full)
                current[os.path.relpath(full, self._staging)] = \
                    (st.st_mtime_ns, st.st_size)
        base = self._remote.rstrip("/")
        # streamed per-file upload when the store offers it — reading
        # a multi-GB state.pkl into a bytes object per epoch is a host
        # OOM with large models
        upload = getattr(self._store, "upload_file", None)
        for rel, sig in current.items():
            if self._mirrored.get(rel) != sig:
                full = os.path.join(self._staging, rel)
                dest = base + "/" + rel.replace(os.sep, "/")
                if upload is not None:
                    upload(full, dest)
                else:
                    with open(full, "rb") as f:
                        self._store.write(dest, f.read())
        for rel in set(self._mirrored) - set(current):
            self._store.delete(base + "/" + rel.replace(os.sep, "/"))
        self._mirrored = current

    def __getattr__(self, name):
        return getattr(self._inner, name)


def _checkpointer_for(store, run_id: str):
    """Checkpointer bound to a store run.  Remote stores stage locally
    (the checkpoint writers are filesystem code) and mirror to the store
    per save via :class:`_SyncingCheckpointer` — a raw remote URL handed
    to the local writer would silently land under ``$CWD/<scheme>:/...``.
    Staging dirs (every rank creates one; only rank 0's gets writes) are
    removed at process exit."""
    import atexit
    import shutil
    import tempfile

    from horovod_tpu import checkpoint as _checkpoint

    remote = store.get_checkpoint_path(run_id)
    # async_save=False: the estimator's contract is per-epoch
    # durability — the store mirror walks the directory right after
    # save(), and fit() may return (worker process exit included)
    # immediately after the last epoch, so the background-writer
    # deferral the training-loop Checkpointer defaults to would race
    # both.  The per-epoch save already sits between epochs, off the
    # step hot path.
    if not getattr(store, "is_remote", False):
        return _checkpoint.Checkpointer(remote, async_save=False), None
    staging = tempfile.mkdtemp(prefix="hvd_ckpt_stage_")
    atexit.register(shutil.rmtree, staging, ignore_errors=True)
    ckpt = _SyncingCheckpointer(
        _checkpoint.Checkpointer(staging, async_save=False),
        store, staging, remote)
    return ckpt, staging


def _sync_checkpoint_to_store(store, staging, ckpt) -> None:
    """Final strict mirror of the staging dir (incremental — a fit
    whose last save already mirrored uploads nothing; unlike the
    per-save mirror this one propagates store errors: a fit must not
    report success while the store silently lacks its checkpoints).
    The staging copy is then dropped — it is redundant once mirrored,
    and a long-lived driver otherwise accumulates one staging dir of
    full checkpoints per fit."""
    import shutil

    if staging is None:
        return
    ckpt.mirror()
    shutil.rmtree(staging, ignore_errors=True)


def _wrap_apply(model):
    """``apply(params, x)`` callable from a flax module or a bare apply
    fn — the one place the wrapping lives (fitted and loaded models must
    not diverge)."""
    if hasattr(model, "apply"):
        return lambda params, x: model.apply(params, x)
    return model


def _save_model_object(store, run_id: str, model) -> None:
    """Best-effort pickle of the model architecture into the run layout
    (reference estimators serialize the model so ``Model.load`` works
    without re-declaring it; flax modules are plain dataclasses and
    usually pickle fine).  Unpicklable models are skipped — load_model
    then needs the model passed explicitly."""
    import pickle

    try:
        payload = pickle.dumps(model)
    except Exception:
        return
    store.write(os.path.join(store.get_run_path(run_id), "model.pkl"),
                payload)


def load_model(store, run_id: Optional[str] = None, model=None,
               step: Optional[int] = None, batch_size: int = 1024,
               output_col: str = "prediction") -> TpuModel:
    """Reconstruct a fitted :class:`TpuModel` from a store run — the
    reference's ``Model.load`` round trip (``spark/common/estimator.py``
    model deserialization + checkpoint restore).

    ``run_id`` defaults to the newest run.  ``model`` overrides the
    pickled architecture (required if the fit-time model was not
    picklable).  ``step`` picks a checkpoint (default: latest).
    """
    import pickle

    from horovod_tpu.checkpoint import Checkpointer
    from horovod_tpu.spark.store import Store, load_metadata

    if isinstance(store, str):
        store = Store.create(store)
    if run_id is None:
        runs = store.list_runs(complete_only=True)
        if not runs:
            raise FileNotFoundError(
                f"no completed runs in {store.get_runs_path()}")
        run_id = runs[-1]
    feature_specs, _label = load_metadata(store, run_id)
    if model is None:
        pkl = os.path.join(store.get_run_path(run_id), "model.pkl")
        if not store.exists(pkl):
            raise FileNotFoundError(
                f"{pkl} missing (the fit-time model was not picklable); "
                f"pass model= explicitly")
        model = pickle.loads(store.read(pkl))
    apply_fn = _wrap_apply(model)
    ckpt_path = store.get_checkpoint_path(run_id)
    if getattr(store, "is_remote", False):
        import tempfile

        local = tempfile.mkdtemp(prefix="hvd_ckpt_fetch_")
        store.download_dir(ckpt_path, local)
        ckpt_path = local
    state = Checkpointer(ckpt_path).restore(None, step=step)
    params = state["params"] if isinstance(state, dict) and \
        "params" in state else state
    return TpuModel(apply_fn, params, [sp.name for sp in feature_specs],
                    output_col=output_col, batch_size=batch_size,
                    feature_specs=feature_specs)


@dataclasses.dataclass
class _Loop:
    """Duck-typed loop object handed to callbacks."""

    params: Any = None
    opt_state: Any = None


class TpuModel(HasParams):
    """Fitted model (reference ``HorovodModel`` Transformer).

    Config is a typed param surface (reference ``ModelParams``,
    ``spark/common/params.py:258``): misassignment raises
    :class:`~horovod_tpu.spark.params.ParamError` naming the parameter,
    and ``explain_params()`` lists everything.
    """

    feature_cols = Param(None, "feature column names", to_str_list)
    output_col = Param("prediction", "name of the appended output column",
                       to_str)
    batch_size = Param(1024, "transform micro-batch size (bounds peak "
                       "feature memory)", to_positive_int)

    def __init__(self, apply_fn: Callable, params: Any,
                 feature_cols: Sequence[str], output_col: str = "prediction",
                 batch_size: int = 1024,
                 feature_specs: Optional[Sequence[ColSpec]] = None):
        self._apply = apply_fn
        self.params = params
        self._specs = list(feature_specs) if feature_specs else None
        self.set_params(feature_cols=feature_cols, output_col=output_col,
                        batch_size=batch_size)

    def transform(self, df):
        """Return ``df`` with the model output column appended (reference
        ``transform`` adds prediction columns to the DataFrame).

        Features are extracted chunk by chunk, so peak memory is one
        ``batch_size`` chunk of assembled features plus the prediction
        column — not a second full copy of the input columns.
        """
        outs = []
        apply = jax.jit(self._apply)
        n = _num_rows(df)
        for i in range(0, n, self.batch_size):
            chunk = _slice_rows(df, slice(i, i + self.batch_size))
            xb = _features(chunk, self.feature_cols, self._specs)
            xb = _map_leaves(jnp.asarray, xb)
            outs.append(np.asarray(apply(self.params, xb)))
        preds = np.concatenate(outs, axis=0)
        if isinstance(df, dict):
            out = dict(df)
            out[self.output_col] = preds
            return out
        out = df.copy()
        out[self.output_col] = list(preds)
        return out


class Estimator(HasParams):
    """Fit a model to a DataFrame (reference ``HorovodEstimator``).

    ``model`` is a flax module or an ``apply(params, x) -> out`` callable
    paired with ``initial_params``.  ``loss`` maps (output, label batch)
    to a scalar; defaults to softmax cross-entropy on integer labels.

    Config is a typed, introspectable param surface (reference
    ``EstimatorParams``, ``spark/common/params.py:24``): every declared
    parameter carries a doc, default and converter; bad values raise
    :class:`~horovod_tpu.spark.params.ParamError` naming the parameter;
    ``explain_params()`` lists the full surface, ``set_params(**kw)``
    bulk-assigns with unknown-name suggestions.
    """

    feature_cols = Param(None, "feature column names", to_str_list)
    label_col = Param(None, "label column name", to_str)
    batch_size = Param(32, "per-chip training batch size",
                       to_positive_int)
    epochs = Param(1, "training epochs", to_positive_int)
    validation_fraction = Param(0.0, "trailing fraction of rows held out "
                                "for validation", to_fraction)
    streaming = Param(None, "train from row-group shards of the store's "
                      "parquet instead of in-memory arrays (default: on "
                      "whenever a store is set)", optional(to_bool))
    rows_per_group = Param(None, "parquet row-group size — the unit of "
                           "shard assignment and streaming IO",
                           optional(to_positive_int))
    seed = Param(0, "shuffling/init seed", to_int)

    def __init__(self, model, feature_cols: Sequence[str], label_col: str,
                 optimizer: Optional[optax.GradientTransformation] = None,
                 loss: Optional[Callable] = None,
                 initial_params: Any = None,
                 batch_size: int = 32, epochs: int = 1,
                 callbacks: Optional[List] = None,
                 store: Optional[Any] = None,
                 store_dir: Optional[str] = None,
                 validation_fraction: float = 0.0,
                 streaming: Optional[bool] = None,
                 rows_per_group: Optional[int] = None,
                 seed: int = 0):
        self._model = model
        self._optimizer = optimizer or optax.adam(1e-3)
        self._loss = loss
        self._initial_params = initial_params
        self._callbacks = callbacks or []
        # `store` is the reference Estimator's artifact manager
        # (spark/common/store.py: runs/<id>/{checkpoint,logs,metadata} +
        # intermediate parquet).  `store_dir` keeps its original, narrower
        # meaning — checkpoints written directly under that path, no run
        # layout, no data materialization — so existing tooling pointed
        # at a store_dir keeps finding its files.
        if isinstance(store, str):
            store = Store.create(store)
        self._store = store
        self._legacy_ckpt_dir = store_dir if store is None else None
        self.set_params(feature_cols=feature_cols, label_col=label_col,
                        batch_size=batch_size, epochs=epochs,
                        validation_fraction=validation_fraction,
                        streaming=streaming, rows_per_group=rows_per_group,
                        seed=seed)

    @property
    def _streaming(self) -> bool:
        # streaming defaults on whenever a store is present, matching the
        # reference: estimators always train from the store's parquet via
        # per-worker readers (``spark/keras/remote.py:336``)
        return self.streaming if self.streaming is not None \
            else self._store is not None

    def _apply_fn(self):
        return _wrap_apply(self._model)

    def fit(self, df) -> TpuModel:
        import horovod_tpu as hvd
        from horovod_tpu.callbacks import CallbackList
        from horovod_tpu.spark.store import PreparedData

        # store-prepared data streams straight from parquet — the
        # "prepare once on the driver, fit many times from the store"
        # flow (reference util.py:697 + keras/remote.py reader loop).
        # Remote (fsspec) datasets are fetched whole to a local temp dir
        # first: RowGroupReader streams from local files only.
        if isinstance(df, PreparedData):
            specs, label_spec = self._reconcile_prepared(df)
            return self.fit_on_parquet(
                _localize_dataset(df.train_path),
                _localize_dataset(df.val_path),
                specs, label_spec)
        if isinstance(df, str):
            prepared = FilesystemStore.load_schema(df)
            if prepared is not None:
                specs, label_spec = self._reconcile_prepared(prepared)
                return self.fit_on_parquet(
                    _localize_dataset(prepared.train_path),
                    _localize_dataset(prepared.val_path),
                    specs, label_spec)
            return self.fit_on_parquet(_localize_dataset(df))

        hvd.init()
        if self.streaming and self._store is None:
            raise ParamError(
                "streaming=True requires a store: the streamed shards "
                "are row groups of the store's parquet (pass store=, or "
                "use fit_on_parquet on existing parquet)")
        if self._store is not None and self._streaming:
            return self._fit_via_store(df, hvd)
        cols_x, feature_specs = extract_typed(df, self.feature_cols)
        cols_y, (label_spec,) = extract_typed(df, [self.label_col])
        x = assemble_features(cols_x, feature_specs)
        y = cols_y[self.label_col]

        def take(data, sl):
            return _map_leaves(lambda v: v[sl], data)

        n_rows = len(y)
        n_val = int(n_rows * self.validation_fraction)
        if n_val:
            x, x_val = take(x, slice(None, -n_val)), take(x, slice(-n_val,
                                                                   None))
            y, y_val = y[:-n_val], y[-n_val:]

        run_id = None
        if self._store is not None:
            # reference run layout: runs/<run_id>/{checkpoint,logs,
            # metadata.json} + intermediate parquet data dirs (store.py
            # path contract, util.py materialization).  Writes happen on
            # rank 0 only — the repo's Checkpointer convention — and the
            # run id is broadcast so every rank agrees on the paths.
            run_id = hvd.broadcast_object(
                self._store.new_run_id() if hvd.rank() == 0 else None,
                root_rank=0)
            if hvd.rank() == 0:
                self._store.makedirs(self._store.get_logs_path(run_id))
                save_metadata(self._store, run_id, feature_specs,
                              label_spec)
                _save_model_object(self._store, run_id, self._model)
                import pandas as pd

                if isinstance(df, pd.DataFrame):
                    split = len(df) - n_val
                    # intermediate data is keyed by run so two fits
                    # sharing one store never clobber each other
                    self._store.write_dataframe(
                        df.iloc[:split],
                        self._store.get_train_data_path(run_id))
                    if n_val:
                        self._store.write_dataframe(
                            df.iloc[split:],
                            self._store.get_val_data_path(run_id))

        apply_fn = self._apply_fn()
        loss = self._loss or (
            lambda out, batch: optax.softmax_cross_entropy_with_integer_labels(
                out, batch["y"]).mean())

        def to_dev(data):
            return _map_leaves(jnp.asarray, data)

        def loss_fn(params, batch):
            return loss(apply_fn(params, batch["x"]), batch)

        step = hvd.DistributedTrainStep(loss_fn, self._optimizer)
        params = self._initial_params
        if params is None:
            if not hasattr(self._model, "init"):
                raise ValueError("pass initial_params for a bare apply fn")
            params = self._model.init(jax.random.PRNGKey(self.seed),
                                      to_dev(take(x, slice(0, 1))))
        params = hvd.broadcast_variables(params, root_rank=0)
        params, opt_state = step.init(params)

        ckpt_staging = None
        if self._store is not None:
            ckpt, ckpt_staging = _checkpointer_for(self._store, run_id)
        elif self._legacy_ckpt_dir:
            ckpt = hvd.checkpoint.Checkpointer(self._legacy_ckpt_dir,
                                               async_save=False)
        else:
            ckpt = None
        loop = _Loop(params, opt_state)
        cbs = CallbackList(self._callbacks)
        cbs.on_train_begin(loop)

        from horovod_tpu.data import PrefetchIterator

        global_bs = self.batch_size * hvd.size()
        nbatches = max(len(y) // global_bs, 1)
        rng = np.random.RandomState(self.seed)
        logs: dict = {}
        for epoch in range(self.epochs):
            cbs.on_epoch_begin(epoch, loop, logs)
            perm = rng.permutation(len(y))

            def host_batches(perm=perm):
                for b in range(nbatches):
                    idx = perm[b * global_bs:(b + 1) * global_bs]
                    if len(idx) < global_bs:   # pad the ragged tail
                        # np.resize cycles perm, so even
                        # len(x) < global_bs/2 still yields a full,
                        # device-divisible batch
                        idx = np.concatenate(
                            [idx, np.resize(perm, global_bs - len(idx))])
                    yield {"x": take(x, idx), "y": y[idx]}

            # gather + device placement run ahead on the prefetcher's
            # threads (shard_batch feeds each process's addressable
            # shards straight from the numpy buffers), so batch k+1's
            # assembly and H2D overlap batch k's compute instead of
            # sitting between steps
            feed = PrefetchIterator(host_batches(),
                                    place=step.shard_batch,
                                    name="estimator")
            try:
                for b, batch in enumerate(feed):
                    cbs.on_batch_begin(b, loop, logs)
                    loop.params, loop.opt_state, train_loss = step(
                        loop.params, loop.opt_state, batch)
                    cbs.on_batch_end(b, loop, logs)
            finally:
                feed.close()
            logs["loss"] = float(train_loss)
            if n_val:
                logs["val_loss"] = float(loss_fn(
                    loop.params, {"x": to_dev(x_val),
                                  "y": jnp.asarray(y_val)}))
            cbs.on_epoch_end(epoch, loop, logs)
            if ckpt:
                ckpt.save(epoch, {"params": loop.params,
                                  "opt_state": loop.opt_state})
        cbs.on_train_end(loop, logs)
        if self._store is not None and hvd.rank() == 0:
            _sync_checkpoint_to_store(self._store, ckpt_staging, ckpt)
            # intermediate parquet copies are derived data; the run's
            # artifacts (checkpoints, metadata, logs) are what persists.
            # Cleanup happens on success only — a failed fit leaves them
            # for debugging.
            self._store.delete(self._store.get_train_data_path(run_id))
            self._store.delete(self._store.get_val_data_path(run_id))
        return TpuModel(apply_fn, loop.params, self.feature_cols,
                        feature_specs=feature_specs)

    # -- streaming path (petastorm-reader analogue) ---------------------

    def _fit_via_store(self, df, hvd) -> TpuModel:
        """``fit(df)`` with a store: materialize the DataFrame to
        multi-row-group parquet once (rank 0), then every process trains
        from its own row-group shard — the reference's flow, where
        estimators always train from store parquet through per-worker
        readers (``spark/keras/remote.py:336``,
        ``spark/common/util.py:697``), never from an in-memory copy of
        the full dataset per process."""
        # schema from a head probe; full-data validation happens
        # group-by-group at read time (extract_columns)
        _, feature_specs = extract_typed(_head(df), self.feature_cols)
        _, (label_spec,) = extract_typed(_head(df), [self.label_col])
        run_id = hvd.broadcast_object(
            self._store.new_run_id() if hvd.rank() == 0 else None,
            root_rank=0)
        n_rows = _num_rows(df)
        n_val = int(n_rows * self.validation_fraction)
        rpg = self.rows_per_group or max(self.batch_size, 1)
        if hvd.rank() == 0:
            self._store.makedirs(self._store.get_logs_path(run_id))
            save_metadata(self._store, run_id, feature_specs, label_spec)
            _save_model_object(self._store, run_id, self._model)
            split = n_rows - n_val

            # run-scoped intermediate paths: concurrent fits (or a second
            # fit while another run's readers are open) must not clobber
            # each other's training data (reference keys by idx)
            self._store.write_dataframe(
                _slice_rows(df, slice(None, split)),
                self._store.get_train_data_path(run_id), rows_per_group=rpg)
            if n_val:
                self._store.write_dataframe(
                    _slice_rows(df, slice(split, None)),
                    self._store.get_val_data_path(run_id), rows_per_group=rpg)
        hvd.barrier()     # readers must not open before the write lands
        # remote stores: RowGroupReader streams local files only, so
        # each process fetches the intermediates before reading
        model = self._fit_streaming(
            _localize_dataset(self._store.get_train_data_path(run_id)),
            _localize_dataset(self._store.get_val_data_path(run_id))
            if n_val else None,
            feature_specs, label_spec, hvd, run_id)
        hvd.barrier()     # every rank's readers are done
        # the localized copies are run-scoped (their source is deleted
        # below) — evict so repeated fits don't accumulate one dataset
        # copy per fit per worker
        _evict_localized(self._store.get_train_data_path(run_id),
                         self._store.get_val_data_path(run_id))
        if hvd.rank() == 0:
            # success: drop the run-scoped intermediate copies (a failed
            # fit leaves them for debugging); persistent prepared data is
            # the explicit store.prepare_data / fit_on_parquet path
            self._store.delete(self._store.get_train_data_path(run_id))
            self._store.delete(self._store.get_val_data_path(run_id))
        return model

    def _reconcile_prepared(self, prepared):
        """The Estimator's configured columns rule: prepared-schema specs
        are selected by ``feature_cols`` (subset training is legal) and a
        label mismatch fails loudly — silently training on the sidecar's
        column set would contradict the user's explicit configuration."""
        by_name = {s.name: s for s in prepared.feature_specs}
        missing = [c for c in self.feature_cols if c not in by_name]
        if missing:
            raise ParamError(
                f"feature_cols {missing} are not in the prepared "
                f"dataset's schema (has {sorted(by_name)}); re-prepare "
                f"with those columns or adjust feature_cols")
        if self.label_col != prepared.label_spec.name:
            raise ParamError(
                f"label_col '{self.label_col}' does not match the "
                f"prepared dataset's label "
                f"'{prepared.label_spec.name}'")
        return [by_name[c] for c in self.feature_cols], prepared.label_spec

    def fit_on_parquet(self, train_path: str, val_path: Optional[str] = None,
                       feature_specs: Optional[Sequence[ColSpec]] = None,
                       label_spec: Optional[ColSpec] = None) -> TpuModel:
        """Fit directly from parquet a Store wrote — the remote-worker
        entry, no DataFrame in sight.  Without explicit specs the schema
        is probed from this process's first shard group.  With a store
        configured, a run layout is still created (metadata +
        checkpoints); the parquet stays where it is."""
        import horovod_tpu as hvd

        hvd.init()
        if feature_specs is None or label_spec is None:
            probe = RowGroupReader(train_path)
            my = probe.shard_groups(hvd.process_rank(),
                                    hvd.process_count())
            head = _head(probe.read_group(my[0] if my else 0))
            if feature_specs is None:
                _, feature_specs = extract_typed(head, self.feature_cols)
            if label_spec is None:
                _, (label_spec,) = extract_typed(head, [self.label_col])
        run_id = None
        if self._store is not None:
            # the configured artifact store must not be silently dropped:
            # checkpoints + metadata get their run layout as in fit()
            run_id = hvd.broadcast_object(
                self._store.new_run_id() if hvd.rank() == 0 else None,
                root_rank=0)
            if hvd.rank() == 0:
                self._store.makedirs(self._store.get_logs_path(run_id))
                save_metadata(self._store, run_id, feature_specs,
                              label_spec)
                _save_model_object(self._store, run_id, self._model)
            hvd.barrier()
        return self._fit_streaming(train_path, val_path, feature_specs,
                                   label_spec, hvd, run_id)

    def _fit_streaming(self, train_path: str, val_path: Optional[str],
                       feature_specs, label_spec, hvd, run_id) -> TpuModel:
        from horovod_tpu.callbacks import CallbackList

        reader = RowGroupReader(train_path)
        # the reading/sharding unit is the *process* (each feeds all its
        # addressable devices), not the chip
        rank, size = hvd.process_rank(), hvd.process_count()
        if reader.num_row_groups < size:
            raise ValueError(
                f"train data at {train_path!r} has "
                f"{reader.num_row_groups} row group(s) for {size} "
                f"processes — rewrite with a smaller rows_per_group so "
                f"every process gets at least one shard group")
        my_groups = reader.shard_groups(rank, size)
        rows = reader.group_rows
        shard_rows = [sum(rows[g] for g in reader.shard_groups(p, size))
                      for p in range(size)]
        # batch_size is per-chip (matching the in-memory path's
        # global_bs = batch_size * hvd.size()); a process contributes one
        # slice per addressable device
        local_bs = self.batch_size * jax.local_device_count()
        # every process must run the same number of steps (the collective
        # cadence); footer metadata is identical everywhere, so this
        # needs no communication
        nbatches = max(min(shard_rows) // local_bs, 1)

        apply_fn = self._apply_fn()
        loss = self._loss or (
            lambda out, batch:
            optax.softmax_cross_entropy_with_integer_labels(
                out, batch["y"]).mean())

        def loss_fn(params, batch):
            return loss(apply_fn(params, batch["x"]), batch)

        step = hvd.DistributedTrainStep(loss_fn, self._optimizer)

        params = self._initial_params
        if params is None:
            if not hasattr(self._model, "init"):
                raise ValueError("pass initial_params for a bare apply fn")
            probe = _head(reader.read_group(my_groups[0]))
            x0 = assemble_features(
                extract_columns(probe, feature_specs), feature_specs)
            params = self._model.init(jax.random.PRNGKey(self.seed),
                                      _map_leaves(jnp.asarray, x0))
        params = hvd.broadcast_variables(params, root_rank=0)
        params, opt_state = step.init(params)

        ckpt_staging = None
        if run_id is not None:
            ckpt, ckpt_staging = _checkpointer_for(self._store, run_id)
        elif self._legacy_ckpt_dir:
            ckpt = hvd.checkpoint.Checkpointer(self._legacy_ckpt_dir,
                                               async_save=False)
        else:
            ckpt = None
        loop = _Loop(params, opt_state)
        cbs = CallbackList(self._callbacks)
        cbs.on_train_begin(loop)

        # the val data is immutable for the whole fit: open its footers
        # once, not per epoch
        val_reader = RowGroupReader(val_path) if val_path else None
        rng = np.random.RandomState(self.seed + rank * 10007)
        from horovod_tpu.data import PrefetchIterator

        logs: dict = {}
        for epoch in range(self.epochs):
            cbs.on_epoch_begin(epoch, loop, logs)
            # row-group reads, feature assembly and the per-process
            # device placement all run ahead on the prefetcher (one
            # feeder thread owns the reader+rng, so batch order is the
            # synchronous order); the step only ever waits when the
            # host can't keep up, not once per batch by construction
            feed = PrefetchIterator(
                ({"x": bx, "y": by} for bx, by in self._shard_batches(
                    reader, my_groups, feature_specs, label_spec,
                    local_bs, nbatches, rng)),
                place=step.shard_local_batch, name="estimator-stream")
            try:
                for b, batch in enumerate(feed):
                    cbs.on_batch_begin(b, loop, logs)
                    loop.params, loop.opt_state, train_loss = step(
                        loop.params, loop.opt_state, batch)
                    cbs.on_batch_end(b, loop, logs)
            finally:
                feed.close()
            logs["loss"] = float(train_loss)
            if val_reader is not None:
                logs["val_loss"] = self._streamed_val_loss(
                    val_reader, loss_fn, loop.params, feature_specs,
                    label_spec, hvd, epoch)
            cbs.on_epoch_end(epoch, loop, logs)
            if ckpt:
                ckpt.save(epoch, {"params": loop.params,
                                  "opt_state": loop.opt_state})
        cbs.on_train_end(loop, logs)
        if self._store is not None and hvd.rank() == 0:
            _sync_checkpoint_to_store(self._store, ckpt_staging, ckpt)
        # no cleanup here: _fit_via_store owns the run-scoped intermediate
        # data and deletes it behind a barrier once every rank's readers
        # are done; fit_on_parquet reads user-owned parquet
        return TpuModel(apply_fn, loop.params, self.feature_cols,
                        feature_specs=feature_specs)

    @staticmethod
    def _shard_batches(reader, groups, feature_specs, label_spec,
                       local_bs, nbatches, rng):
        """Yield ``nbatches`` local (x, y) batches of exactly
        ``local_bs`` rows, cycling this process's row groups in a
        shuffled order; at most one row group plus one batch is held in
        memory."""
        order = [groups[int(i)] for i in rng.permutation(len(groups))]
        pend_x, pend_y = None, None
        gi = 0
        for _ in range(nbatches):
            while pend_y is None or len(pend_y) < local_bs:
                df = reader.read_group(order[gi % len(order)])
                gi += 1
                x = assemble_features(
                    extract_columns(df, feature_specs), feature_specs)
                y = extract_columns(df, [label_spec])[label_spec.name]
                perm = rng.permutation(len(y))
                x = _map_leaves(lambda v: v[perm], x)
                y = y[perm]
                pend_x = x if pend_x is None else _tree_concat(pend_x, x)
                pend_y = y if pend_y is None else np.concatenate(
                    [pend_y, y])
            bx = _map_leaves(lambda v: v[:local_bs], pend_x)
            by = pend_y[:local_bs]
            pend_x = _map_leaves(lambda v: v[local_bs:], pend_x)
            pend_y = pend_y[local_bs:]
            yield bx, by

    @staticmethod
    def _streamed_val_loss(reader, loss_fn, params, feature_specs,
                           label_spec, hvd, epoch) -> float:
        """Group-streamed validation loss on this process's val shard,
        averaged across processes weighted by row count."""
        # params are replicated → every leaf is locally addressable
        host_params = jax.tree_util.tree_map(np.asarray, params)
        s, n = 0.0, 0
        for g in reader.shard_groups(hvd.process_rank(),
                                     hvd.process_count()):
            df = reader.read_group(g)
            x = assemble_features(
                extract_columns(df, feature_specs), feature_specs)
            y = extract_columns(df, [label_spec])[label_spec.name]
            s += float(loss_fn(host_params,
                               {"x": _map_leaves(jnp.asarray, x),
                                "y": jnp.asarray(y)})) * len(y)
            n += len(y)
        if hvd.process_count() > 1:
            tot = np.asarray(hvd.allreduce(
                jnp.asarray([s, float(n)]), op=hvd.Sum,
                name=f"estimator_val_{epoch}"))
            s, n = float(tot[0]), float(tot[1])
        return s / max(n, 1.0)
