"""HVD005-HVD006: runtime-contract rules.

HVD005 makes the ``HOROVOD_*`` env contract a *registry*, not a
convention: every knob the package reads or sets must be declared in
``runtime/config.py``'s ``KNOWN_KNOBS`` and documented under ``docs/``.
It subsumes the tier-1 doc-drift guard (``tests/test_env_knob_docs.py``
now delegates here) and extends it — a knob read somewhere deep in
``elastic/`` that never got registered is exactly how
``HOROVOD_EXCHANGE_HIERARCHY`` shipped undocumented twice.

HVD006 keeps the chaos plane honest: PR 5's fault-injection hooks are
only as good as their coverage, and a *new* thread run-loop or connect
path added without a ``faults.inject()`` site is invisible to every
chaos plan — the fault scenarios silently stop covering the code that
actually runs.  The rule requires every thread-target function
containing a loop, and every ``*connect*`` function, to carry an
inject site (directly or one call deep).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from horovod_tpu.analysis import astutil as A
from horovod_tpu.analysis.engine import Finding, Module, Project, Rule, \
    Severity

KNOB_RE = re.compile(r"^HOROVOD_[A-Z][A-Z0-9_]*$")
_ENV_READERS = {"os.environ.get", "environ.get", "os.getenv", "getenv"}
_CONFIG_MODULE = "runtime/config.py"


def parse_known_knobs(config_module: Optional[Module]) -> Optional[Set[str]]:
    """The ``KNOWN_KNOBS`` frozenset/sets literal in runtime/config.py,
    or None when the registry is missing."""
    if config_module is None or config_module.tree is None:
        return None
    for node in ast.walk(config_module.tree):
        if not isinstance(node, ast.Assign):
            continue
        names = {t.id for t in node.targets if isinstance(t, ast.Name)}
        if "KNOWN_KNOBS" not in names:
            continue
        knobs: Set[str] = set()
        for n in ast.walk(node.value):
            if isinstance(n, ast.Constant) and isinstance(n.value, str):
                knobs.add(n.value)
        return knobs
    return None


def referenced_knobs(project: Project
                     ) -> Dict[str, Tuple[Module, ast.Constant]]:
    """Every quoted ``HOROVOD_*`` literal in the analyzed set → first
    reference site.  String literals are the actual env contract: both
    reads and writes quote the name."""
    out: Dict[str, Tuple[Module, ast.Constant]] = {}
    for m in project.modules:
        if m.tree is None:
            continue
        for value, node in A.str_constants(m.tree):
            if KNOB_RE.match(value) and value not in out:
                out[value] = (m, node)
    return out


def undocumented_knobs(project: Project) -> Dict[str, str]:
    """knob → first-referencing relpath, for knobs missing from the doc
    corpus.  Public seam for ``tests/test_env_knob_docs.py``."""
    docs = project.docs_text()
    return {k: m.relpath
            for k, (m, _) in referenced_knobs(project).items()
            if k not in docs}


class EnvKnobRegistryRule(Rule):
    id = "HVD005"
    severity = Severity.P2
    name = "env-knob-registry"
    rationale = ("HOROVOD_* knobs read outside the registry or left "
                 "undocumented drift out of the env contract")

    def finalize(self, project: Project) -> Iterable[Finding]:
        config = project.module(_CONFIG_MODULE) \
            or _load_config_module(project)
        knobs = parse_known_knobs(config)
        refs = referenced_knobs(project)
        if knobs is None:
            # only demand a registry from trees that actually speak the
            # env contract — a scan with zero HOROVOD_* references
            # (e.g. --changed in an unrelated checkout) has nothing to
            # register
            if refs:
                yield Finding(
                    rule=self.id, severity=Severity.P1,
                    path=_CONFIG_MODULE, line=1, col=0,
                    message=("KNOWN_KNOBS registry not found in "
                             "runtime/config.py — declare every "
                             "HOROVOD_* knob name in one frozenset"),
                    context="")
            return
        # env *reads* outside config.py get the sharper message: those
        # are the sites that bypass the registry, not just mention it
        read_sites = {}
        for m in project.modules:
            if m.tree is None or m.relpath.endswith(_CONFIG_MODULE):
                continue
            for node in ast.walk(m.tree):
                name = _env_read_knob(node)
                if name is not None:
                    read_sites.setdefault(name, (m, node))
        docs = project.docs_text()
        for knob, (m, node) in sorted(refs.items()):
            if knob not in knobs:
                if knob in read_sites:
                    rm, rn = read_sites[knob]
                    yield self.finding(
                        rm, rn,
                        f"env knob '{knob}' is read here but not "
                        f"declared in runtime/config.py KNOWN_KNOBS — "
                        f"register it so the env contract stays "
                        f"greppable in one place",
                        severity=Severity.P1)
                else:
                    yield self.finding(
                        m, node,
                        f"env knob '{knob}' is referenced but not "
                        f"declared in runtime/config.py KNOWN_KNOBS")
            if docs and knob not in docs:
                yield self.finding(
                    m, node,
                    f"env knob '{knob}' is undocumented — add it to "
                    f"the docs/running.md 'Env-var reference' table",
                    severity=Severity.P1)
        # registry hygiene: a registered knob NOTHING in the whole
        # package references (outside the registry declaration itself)
        # is a rename that left its registration behind.  Checked
        # against the package on disk, not the scan scope — a --changed
        # run over two files must not call every other knob stale.
        if config is not None:
            pkg_refs = _package_references(project)
            if pkg_refs is not None:
                for knob in sorted(knobs - pkg_refs):
                    yield Finding(
                        rule=self.id, severity=Severity.P3,
                        path=config.relpath, line=1, col=0,
                        message=(f"KNOWN_KNOBS declares '{knob}' but "
                                 f"nothing in the package references "
                                 f"it — stale registration?"),
                        context="")


def _package_references(project: Project) -> Optional[Set[str]]:
    """Knob literals referenced anywhere in the on-disk package,
    EXCLUDING the KNOWN_KNOBS declaration itself (a registration is not
    a use — otherwise no registration could ever look stale)."""
    import os

    pkg = os.path.join(project.repo_root, "horovod_tpu")
    if not os.path.isdir(pkg):
        return None
    refs: Set[str] = set()
    for base, dirs, names in os.walk(pkg):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for n in sorted(names):
            if not n.endswith(".py"):
                continue
            path = os.path.join(base, n)
            with open(path, "r", errors="replace") as f:
                src = f.read()
            try:
                tree = ast.parse(src)
            except SyntaxError:
                continue
            registry_nodes: Set[int] = set()
            for node in ast.walk(tree):
                if isinstance(node, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == "KNOWN_KNOBS"
                        for t in node.targets):
                    registry_nodes = {id(x) for x in ast.walk(node)}
                    break
            for node in ast.walk(tree):
                if isinstance(node, ast.Constant) and \
                        isinstance(node.value, str) and \
                        KNOB_RE.match(node.value) and \
                        id(node) not in registry_nodes:
                    refs.add(node.value)
    return refs


def _load_config_module(project: Project) -> Optional[Module]:
    """When the scan scope (e.g. ``--changed``) does not include
    runtime/config.py, load it from disk so the registry is still the
    source of truth."""
    import os

    for cand in (os.path.join(project.repo_root, "horovod_tpu",
                              _CONFIG_MODULE.replace("/", os.sep)),):
        if os.path.exists(cand):
            with open(cand, "r", errors="replace") as f:
                rel = os.path.relpath(cand, project.root) \
                    .replace(os.sep, "/")
                return Module(cand, rel, f.read())
    return None


def _env_read_knob(node: ast.AST) -> Optional[str]:
    """The knob name when ``node`` is an env *read* of a HOROVOD_*
    literal: ``os.environ.get("X")`` / ``os.getenv("X")`` /
    ``os.environ["X"]``."""
    if isinstance(node, ast.Call):
        dotted = A.dotted_name(node.func) or ""
        if dotted in _ENV_READERS or dotted.endswith(".environ.get"):
            if node.args and isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str) and \
                    KNOB_RE.match(node.args[0].value):
                return node.args[0].value
    if isinstance(node, ast.Subscript):
        dotted = A.dotted_name(node.value) or ""
        if dotted.endswith("environ"):
            sl = node.slice
            if isinstance(sl, ast.Constant) and \
                    isinstance(sl.value, str) and KNOB_RE.match(sl.value):
                return sl.value
    return None


# -- HVD006 -----------------------------------------------------------------

def _has_inject(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            d = A.dotted_name(node.func) or ""
            if d.endswith("faults.inject") or d == "inject":
                return True
    return False


def _has_loop(fn: ast.AST) -> bool:
    # a run-loop is a `while` (poll/serve until told to stop); a one-shot
    # thread body iterating a worklist with `for` is not chaos surface
    for node in ast.walk(fn):
        if isinstance(node, ast.While):
            return True
    return False


class FaultHookCoverageRule(Rule):
    id = "HVD006"
    severity = Severity.P2
    name = "fault-hook-coverage"
    rationale = ("thread run-loops and connect paths without a "
                 "faults.inject() site are invisible to chaos plans — "
                 "the fault scenarios rot as the runtime grows")

    def check(self, module: Module, project: Project) -> Iterable[Finding]:
        if module.tree is None:
            return
        checked: Set[int] = set()
        funcs_by_name: Dict[str, ast.FunctionDef] = {}
        for node in ast.walk(module.tree):
            if isinstance(node, ast.FunctionDef):
                funcs_by_name.setdefault(node.name, node)

        def covered(fn: ast.AST) -> bool:
            if _has_inject(fn):
                return True
            # one call hop within the module: the loop body may delegate
            # (e.g. _watch -> check) and the hook may live in the callee
            for name in A.called_names(fn):
                tail = name.rsplit(".", 1)[-1]
                callee = funcs_by_name.get(tail)
                if callee is not None and _has_inject(callee):
                    return True
            return False

        # thread-target run loops
        for cls in ast.walk(module.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            from horovod_tpu.analysis.rules_threads import (
                _thread_entry_functions,
            )

            for key, fn in _thread_entry_functions(cls).items():
                if id(fn) in checked:
                    continue
                checked.add(id(fn))
                if key.startswith("method:") and \
                        key.split(":", 1)[1].startswith("__"):
                    continue
                if not _has_loop(fn):
                    continue    # one-shot targets aren't run-loops
                if not covered(fn):
                    fname = getattr(fn, "name", "<lambda>")
                    yield self.finding(
                        module, fn,
                        f"thread run-loop '{cls.name}.{fname}' has no "
                        f"faults.inject() site — chaos plans cannot "
                        f"exercise this thread; add a named site and "
                        f"document it in docs/faults.md")
        # module-level thread targets (driver-style local closures are
        # covered through the class scan; plain functions here)
        for fn in ast.walk(module.tree):
            if not isinstance(fn, ast.FunctionDef) or id(fn) in checked:
                continue
            lowname = fn.name.lower()
            if "connect" in lowname and "disconnect" not in lowname:
                checked.add(id(fn))
                if not covered(fn):
                    yield self.finding(
                        module, fn,
                        f"connect path '{fn.name}' has no "
                        f"faults.inject() site — transient-connect "
                        f"chaos scenarios cannot reach it; add a named "
                        f"site and document it in docs/faults.md")


RULES: List[Rule] = [EnvKnobRegistryRule, FaultHookCoverageRule]
