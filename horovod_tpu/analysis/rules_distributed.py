"""HVD001-HVD003: the SPMD/tracing correctness rules.

These three rules police the failure classes the paper's runtime
controller policed dynamically (SURVEY §"collective negotiation"): the
reference's rank-0 controller *detects* a rank-divergent collective at
runtime by matching per-rank submissions; an SPMD program has no
controller, so a divergent collective simply deadlocks the pod.  The
compile-time answer is lexical: a collective call must never be
guarded by rank-dependent control flow (HVD001).  HVD002/HVD003 guard
the two tracing-level costs with no runtime guard at all — host syncs
inside the jitted step (a dispatch stall the overlap probe measures but
cannot attribute) and unstable AOT cache keys / tracer branching
(silent warm-start misses, recompiles).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set

from horovod_tpu.analysis import astutil as A
from horovod_tpu.analysis.engine import Finding, Module, Project, Rule, \
    Severity

# The package's collective surface (ops/collectives.py + ops/eager.py
# public API) plus the jax.lax collective primitives they lower to.
COLLECTIVE_NAMES: Set[str] = {
    # ops/collectives.py
    "allreduce", "grouped_allreduce", "quantized_allreduce",
    "quantized_reducescatter", "grouped_reducescatter",
    "hierarchical_reducescatter", "hierarchical_allgather",
    "grouped_allgather", "sparse_allreduce", "allgather", "allgather_v",
    "broadcast", "reducescatter", "alltoall", "alltoall_v", "barrier",
    "bitwise_and", "bitwise_or",
    # functions.py frontends
    "broadcast_variables", "broadcast_optimizer_state", "allreduce_",
    # jax.lax primitives
    "psum", "pmean", "pmax", "pmin", "ppermute", "pshuffle",
    "all_gather", "all_to_all", "psum_scatter", "axis_index_groups",
}

# names whose *value* differs per rank: branching on them forks the SPMD
# program across the pod
_RANK_VALUE_NAMES = {"rank", "local_rank", "cross_rank", "node_rank",
                     "process_index", "axis_index", "local_rank_id"}
_RANK_BOOL_NAMES = {"is_root", "_is_root", "is_master", "is_chief",
                    "is_coordinator"}
# names that look rank-ish but are uniform across the world
_UNIFORM_NAMES = {"process_count", "size", "world_size", "num_ranks",
                  "local_size", "cross_size", "axis_size", "shard_count"}


def _is_rank_dependent(test: ast.AST) -> Optional[str]:
    """The offending name when ``test`` references a per-rank value."""
    for node in ast.walk(test):
        if isinstance(node, (ast.Name, ast.Attribute)):
            tail = A.name_tail(node)
            if tail is None or tail in _UNIFORM_NAMES:
                continue
            if tail in _RANK_VALUE_NAMES or tail in _RANK_BOOL_NAMES \
                    or tail.endswith("_rank"):
                return tail
    return None


def _is_collective_call(node: ast.Call) -> Optional[str]:
    tail = A.name_tail(node.func)
    if tail in COLLECTIVE_NAMES:
        return tail
    return None


def _contains_exit(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, (ast.Return, ast.Raise, ast.Break, ast.Continue)):
            return True
        if isinstance(n, ast.Call):
            d = A.dotted_name(n.func)
            if d in ("sys.exit", "os._exit", "exit"):
                return True
    return False


class CollectiveDivergenceRule(Rule):
    """HVD001: a collective call reachable under rank-dependent control
    flow.  Ranks that skip (or double) a collective desynchronize the
    pod's collective schedule — the remaining ranks block in the op
    forever.  The reference caught this at runtime via controller
    negotiation (its ``NegotiateResponse`` mismatch error); SPMD has no
    negotiation, so the guard must be lexical."""

    id = "HVD001"
    severity = Severity.P0
    name = "collective-divergence"
    rationale = ("collective under rank-dependent control flow → "
                 "a subset of ranks enters the op → pod deadlock")

    def check(self, module: Module, project: Project) -> Iterable[Finding]:
        if module.tree is None:
            return
        parents = A.ParentMap(module.tree)
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            coll = _is_collective_call(node)
            if coll is None:
                continue
            fn = parents.enclosing_function(node)
            # (a) the collective sits inside a rank-dependent branch
            guard = self._rank_guard(node, fn, parents)
            if guard is not None:
                yield self.finding(
                    module, node,
                    f"collective '{coll}' is guarded by "
                    f"rank-dependent control flow (branches on "
                    f"'{guard}') — ranks that skip it deadlock the "
                    f"rest of the pod in the collective")
                continue
            # (b) the collective follows a rank-dependent early exit
            # in the same function: `if rank() != 0: return` above a
            # broadcast means only rank 0 ever reaches the op
            if fn is None:
                continue
            for stmt in ast.walk(fn):
                if not isinstance(stmt, ast.If):
                    continue
                if stmt.end_lineno is None or \
                        stmt.end_lineno >= node.lineno:
                    continue
                if parents.enclosing_function(stmt) is not fn:
                    continue
                # the exit must be in the rank-guarded suite itself,
                # not in an else branch
                dep = _is_rank_dependent(stmt.test)
                if dep is not None and \
                        any(_contains_exit(s) for s in stmt.body):
                    yield self.finding(
                        module, node,
                        f"collective '{coll}' follows a "
                        f"rank-dependent early exit at line "
                        f"{stmt.lineno} (branches on '{dep}') — "
                        f"only a subset of ranks reaches the op")
                    break

    @staticmethod
    def _rank_guard(node: ast.AST, fn: Optional[ast.AST],
                    parents: A.ParentMap) -> Optional[str]:
        for anc in parents.ancestors(node):
            if anc is fn:
                return None
            test = None
            if isinstance(anc, (ast.If, ast.While, ast.IfExp)):
                test = anc.test
            elif isinstance(anc, ast.Assert):
                test = anc.test
            if test is None:
                continue
            dep = _is_rank_dependent(test)
            if dep is not None:
                return dep
        return None


# -- HVD002 -----------------------------------------------------------------

_JIT_WRAPPERS = {"jit", "pjit", "pmap", "shard_map", "smap",
                 "checkpoint", "remat"}
_SYNC_METHODS = {"item", "block_until_ready"}
_SYNC_CALLS = {"float", "int", "bool"}
_SYNC_DOTTED_TAILS = {"asarray", "array", "device_get"}
_SYNC_DOTTED_PREFIXES = ("np.", "numpy.", "jax.")


def jit_compiled_functions(tree: ast.AST) -> Dict[str, ast.FunctionDef]:
    """Functions that end up traced: decorated with a jit-family
    transform, or referenced by name inside a ``jax.jit(...)`` /
    ``shard_map(...)`` call chain."""
    defs: Dict[str, ast.FunctionDef] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            defs.setdefault(node.name, node)
    out: Dict[str, ast.FunctionDef] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                d = A.name_tail(dec)
                if d in _JIT_WRAPPERS:
                    out[node.name] = node
                elif isinstance(dec, ast.Call) and \
                        A.name_tail(dec.func) == "partial" and dec.args \
                        and A.name_tail(dec.args[0]) in _JIT_WRAPPERS:
                    out[node.name] = node
        if isinstance(node, ast.Call) and \
                A.name_tail(node.func) in _JIT_WRAPPERS:
            # jit(f) / jit(shard_map(f, ...)): any plain-name argument
            # that resolves to a local def is traced
            stack = list(node.args)
            while stack:
                a = stack.pop()
                if isinstance(a, ast.Name) and a.id in defs:
                    out[a.id] = defs[a.id]
                elif isinstance(a, ast.Call):
                    stack.extend(a.args)
    return out


def _static_argnames(fn: ast.FunctionDef) -> Set[str]:
    """Names listed in ``static_argnames=`` of a jit decorator — those
    parameters are Python values, free to branch on."""
    names: Set[str] = set()
    for dec in fn.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        for kw in dec.keywords:
            if kw.arg == "static_argnames":
                for n in ast.walk(kw.value):
                    if isinstance(n, ast.Constant) and \
                            isinstance(n.value, str):
                        names.add(n.value)
    return names


class HostSyncInHotPathRule(Rule):
    """HVD002: ``float()``/``.item()``/``np.asarray``/
    ``block_until_ready`` on traced values inside jit/train-step
    bodies.  Each one forces a device→host transfer and a dispatch
    fence; inside the steady-state step it serializes the pipeline the
    async dispatch exists to keep full — a stall the overlap probe
    measures but cannot attribute to a line of code.  (At trace time it
    is outright hostile: it concretizes the tracer or fails.)"""

    id = "HVD002"
    severity = Severity.P1
    name = "host-sync-in-hot-path"
    rationale = ("host synchronization inside a jitted body → "
                 "dispatch stall / tracer concretization error")

    def check(self, module: Module, project: Project) -> Iterable[Finding]:
        if module.tree is None:
            return
        jitted = jit_compiled_functions(module.tree)
        seen: Set[int] = set()
        for fn in jitted.values():
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call) or id(node) in seen:
                    continue
                hit = self._sync_kind(node)
                if hit is None:
                    continue
                seen.add(id(node))
                yield self.finding(
                    module, node,
                    f"host sync '{hit}' inside jit-compiled "
                    f"'{fn.name}' — forces a device fence in the hot "
                    f"path (or a tracer concretization error); move it "
                    f"outside the compiled region")

    @staticmethod
    def _sync_kind(node: ast.Call) -> Optional[str]:
        tail = A.name_tail(node.func)
        if isinstance(node.func, ast.Name) and tail in _SYNC_CALLS:
            # float(3.0) / float("inf") are static Python, not a sync
            if node.args and isinstance(node.args[0], ast.Constant):
                return None
            return f"{tail}()"
        if isinstance(node.func, ast.Attribute):
            if tail in _SYNC_METHODS:
                return f".{tail}()"
            dotted = A.dotted_name(node.func) or ""
            if tail in _SYNC_DOTTED_TAILS and \
                    dotted.startswith(_SYNC_DOTTED_PREFIXES):
                return dotted
        return None


# -- HVD003 -----------------------------------------------------------------

_UNSTABLE_BUILTINS = {"hash", "id"}
_KEYISH = ("key", "cache", "fingerprint", "digest")


class RetraceHazardRule(Rule):
    """HVD003: retrace / warm-start-miss hazards.

    (a) Python ``if``/``while`` on a *traced* parameter inside a jitted
    body — either a concretization error or, with weak types, a silent
    per-value retrace.  (b) process-unstable values (builtin ``hash``
    — salted per process — ``id``, and ``repr`` of arbitrary objects,
    which embeds ``0x...`` addresses) flowing into cache-key
    construction: the AOT store (``runtime/compile_cache.py``) then
    computes a different key every process start and every warm start
    silently misses, re-paying the 40-50 s compile."""

    id = "HVD003"
    severity = Severity.P1
    name = "retrace-hazard"
    rationale = ("tracer branching / process-unstable cache-key input "
                 "→ recompiles and silent AOT warm-start misses")

    def check(self, module: Module, project: Project) -> Iterable[Finding]:
        if module.tree is None:
            return
        yield from self._tracer_branches(module)
        yield from self._unstable_keys(module)

    def _tracer_branches(self, module: Module) -> Iterable[Finding]:
        jitted = jit_compiled_functions(module.tree)
        for fn in jitted.values():
            params = {a.arg for a in fn.args.args + fn.args.kwonlyargs
                      if a.arg not in ("self", "cls")}
            params -= _static_argnames(fn)
            for node in ast.walk(fn):
                if not isinstance(node, (ast.If, ast.While, ast.IfExp)):
                    continue
                name = self._traced_param_in_test(node.test, params)
                if name is None:
                    continue
                yield self.finding(
                    module, node,
                    f"Python branch on traced parameter '{name}' "
                    f"inside jit-compiled '{fn.name}' — concretization "
                    f"error or a silent retrace per value; use "
                    f"lax.cond/jnp.where or mark it static")

    @staticmethod
    def _traced_param_in_test(test: ast.AST,
                              params: Set[str]) -> Optional[str]:
        # `x is None` / `x is not None` / isinstance(x, ...) are static
        # trace-time dispatch on the *Python* value, not tracer branching
        if isinstance(test, ast.Compare) and \
                all(isinstance(op, (ast.Is, ast.IsNot))
                    for op in test.ops):
            return None
        if isinstance(test, ast.Call) and \
                A.name_tail(test.func) in ("isinstance", "len", "hasattr",
                                           "callable"):
            return None
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            return RetraceHazardRule._traced_param_in_test(test.operand,
                                                           params)
        if isinstance(test, ast.BoolOp):
            for v in test.values:
                n = RetraceHazardRule._traced_param_in_test(v, params)
                if n is not None:
                    return n
            return None
        if isinstance(test, ast.Name):
            return test.id if test.id in params else None
        if isinstance(test, ast.Compare):
            for side in [test.left] + list(test.comparators):
                if isinstance(side, ast.Name) and side.id in params:
                    # comparisons against None are trace-static
                    others = [s for s in [test.left] + list(test.comparators)
                              if s is not side]
                    if any(isinstance(o, ast.Constant) and o.value is None
                           for o in others):
                        return None
                    return side.id
        return None

    def _unstable_keys(self, module: Module) -> Iterable[Finding]:
        in_cache_module = module.relpath.endswith("compile_cache.py")
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.FunctionDef):
                continue
            keyish = in_cache_module or \
                any(k in node.name.lower() for k in _KEYISH)
            if not keyish:
                continue
            for call in ast.walk(node):
                if not isinstance(call, ast.Call):
                    continue
                tail = A.name_tail(call.func)
                if isinstance(call.func, ast.Name) and \
                        tail in _UNSTABLE_BUILTINS:
                    yield self.finding(
                        module, call,
                        f"'{tail}()' in cache-key path '{node.name}' — "
                        f"builtin {tail}() is not stable across "
                        f"processes (PYTHONHASHSEED / address reuse); "
                        f"the AOT key changes every start and the warm "
                        f"start silently misses")
                for kw in call.keywords:
                    if kw.arg == "default" and \
                            A.name_tail(kw.value) == "repr":
                        yield self.finding(
                            module, call,
                            f"'default=repr' serializing the cache key "
                            f"in '{node.name}' — repr of arbitrary "
                            f"objects embeds '0x...' addresses, so the "
                            f"key differs every process and warm "
                            f"starts silently miss")


RULES: List[Rule] = [CollectiveDivergenceRule, HostSyncInHotPathRule,
                     RetraceHazardRule]
