"""``python -m horovod_tpu.analysis`` — the hvdlint CLI.

Usage::

    python -m horovod_tpu.analysis horovod_tpu/          # full scan
    python -m horovod_tpu.analysis --changed             # git-diff scope
    python -m horovod_tpu.analysis --json horovod_tpu/   # machine output
    python -m horovod_tpu.analysis --hlo dump.txt        # HLO rule pack
    python -m horovod_tpu.analysis --artifact BENCH.json # bench artifact
    python -m horovod_tpu.analysis --write-baseline ...  # accept findings
    python -m horovod_tpu.analysis perf-gate --candidate new.json
    python -m horovod_tpu.analysis ci                    # lint+artifacts+gate
    python -m horovod_tpu.analysis metrics-check run.metrics.jsonl

Exit codes: 0 clean, 1 findings, 2 usage/environment error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional

from horovod_tpu.analysis import engine
from horovod_tpu.analysis import hlo_lint

DEFAULT_BASELINE = "analysis_baseline.json"


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m horovod_tpu.analysis",
        description="hvdlint: distributed-correctness static analysis "
                    "for horovod_tpu (rules HVD001-HVD006; see "
                    "docs/analysis.md)")
    p.add_argument("paths", nargs="*",
                   help="files or directories to lint (default: the "
                        "horovod_tpu package next to the repo root)")
    p.add_argument("--changed", action="store_true",
                   help="lint only files changed vs HEAD (staged + "
                        "unstaged + untracked)")
    p.add_argument("--json", action="store_true", dest="json_out",
                   help="emit one JSON object instead of text")
    p.add_argument("--select", default=None, metavar="RULES",
                   help="comma-separated rule ids to run (e.g. "
                        "HVD001,HVD004)")
    p.add_argument("--baseline", default=None, metavar="PATH",
                   help=f"baseline file (default: <repo>/"
                        f"{DEFAULT_BASELINE} when present)")
    p.add_argument("--write-baseline", action="store_true",
                   help="write the current findings to the baseline "
                        "file and exit 0")
    p.add_argument("--hlo", action="append", default=[], metavar="PATH",
                   help="lint an HLO text dump with the HLO rule pack "
                        "(repeatable)")
    p.add_argument("--artifact", action="append", default=[],
                   metavar="PATH",
                   help="lint a bench --json-out artifact with the HLO "
                        "rule pack (repeatable)")
    p.add_argument("--expect-hierarchy", default=None,
                   choices=("flat", "two_level"),
                   help="assert the exchange topology when linting "
                        "--hlo dumps")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule table and exit")
    return p


def _metrics_check(argv: List[str]) -> int:
    """Validate hvdtel metric artifacts (docs/metrics.md): a
    ``HOROVOD_METRICS_LOG`` JSONL snapshot log, or a BENCH artifact's
    embedded ``metrics`` block (``.json`` files)."""
    from horovod_tpu.analysis import metrics_schema

    p = argparse.ArgumentParser(
        prog="python -m horovod_tpu.analysis metrics-check",
        description="validate metrics snapshot logs / BENCH metrics "
                    "blocks against the hvdtel schema")
    p.add_argument("paths", nargs="+",
                   help=".jsonl snapshot logs or BENCH .json artifacts")
    args = p.parse_args(argv)
    errors: List[str] = []
    for path in args.paths:
        try:
            if path.endswith(".jsonl"):
                errors.extend(f"{path}: {e}"
                              for e in metrics_schema.validate_jsonl_path(
                                  path))
            else:
                with open(path) as f:
                    blob = json.load(f)
                errors.extend(
                    f"{path}: {e}" for e in
                    metrics_schema.validate_artifact_metrics(blob))
        except (OSError, json.JSONDecodeError) as e:
            print(f"metrics-check: cannot read {path}: {e}",
                  file=sys.stderr)
            return 2
    for e in errors:
        print(f"metrics-check: {e}")
    print(f"metrics-check: {len(args.paths)} artifact(s), "
          f"{len(errors)} error(s) — {'FAIL' if errors else 'ok'}")
    return 1 if errors else 0


def _list_rules() -> int:
    for rule in engine.default_rules():
        print(f"{rule.id}  [{rule.severity}]  {rule.name}")
        print(f"        {rule.rationale}")
    print("HLO001-HLO004  (offline HLO/artifact rule pack; "
          "--hlo/--artifact)")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:]) if argv is None else list(argv)
    # subcommands ride the same CLI (and the hvdlint console script):
    # dispatch BEFORE argparse so "perf-gate" is never mistaken for a
    # lint path
    if argv and argv[0] == "perf-gate":
        from horovod_tpu.analysis import perf_gate

        return perf_gate.main(argv[1:])
    if argv and argv[0] == "ci":
        from horovod_tpu.analysis import ci

        return ci.main(argv[1:])
    if argv and argv[0] == "metrics-check":
        return _metrics_check(argv[1:])
    args = _build_parser().parse_args(argv)
    if args.list_rules:
        return _list_rules()

    t0 = time.perf_counter()
    hlo_findings = []
    try:
        if args.hlo:
            hlo_findings.extend(hlo_lint.lint_paths(
                args.hlo, expect_hierarchy=args.expect_hierarchy))
        if args.artifact:
            for p in args.artifact:
                hlo_findings.extend(hlo_lint.lint_artifact_path(p))
    except (OSError, json.JSONDecodeError) as e:
        print(f"hvdlint: cannot read artifact: {e}", file=sys.stderr)
        return 2

    report = None
    if args.paths or args.changed or not (args.hlo or args.artifact):
        paths = list(args.paths)
        repo_root = engine.find_repo_root(
            paths[0] if paths else os.getcwd()) or os.getcwd()
        if args.changed:
            try:
                changed = engine.changed_files(repo_root)
            except Exception as e:     # noqa: BLE001 — not a git tree
                print(f"hvdlint: --changed needs a git checkout: {e}",
                      file=sys.stderr)
                return 2
            scope = [os.path.abspath(p) for p in paths] if paths else None
            paths = [f for f in changed
                     if scope is None
                     or any(os.path.abspath(f).startswith(s + os.sep)
                            or os.path.abspath(f) == s for s in scope)]
            if not paths:
                print("hvdlint: no changed Python files in scope")
        elif not paths:
            default_pkg = os.path.join(repo_root, "horovod_tpu")
            if not os.path.isdir(default_pkg):
                print("hvdlint: no paths given and no horovod_tpu/ "
                      "package found", file=sys.stderr)
                return 2
            paths = [default_pkg]
        baseline = args.baseline or os.path.join(repo_root,
                                                 DEFAULT_BASELINE)
        select = {r.strip() for r in args.select.split(",")} \
            if args.select else None
        report = engine.run_analysis(paths, select=select,
                                     baseline_path=baseline,
                                     root=repo_root)
        if args.write_baseline:
            engine.write_baseline(baseline, report.findings)
            print(f"hvdlint: wrote {len(report.findings)} finding(s) to "
                  f"{baseline}")
            return 0

    elapsed = time.perf_counter() - t0
    if args.json_out:
        out = report.as_json() if report is not None else \
            {"files_scanned": 0, "findings": [], "suppressed": [],
             "baselined": []}
        out["hlo_findings"] = [f.as_json() for f in hlo_findings]
        out["elapsed_s"] = round(elapsed, 3)
        print(json.dumps(out, indent=2))
    else:
        for f in hlo_findings:
            print(f.format())
        if report is not None:
            for f in report.findings:
                print(f.format())
            print(f"hvdlint: {report.files_scanned} file(s), "
                  f"{len(report.findings)} finding(s), "
                  f"{len(report.suppressed)} suppressed, "
                  f"{len(report.baselined)} baselined "
                  f"in {elapsed:.2f}s")

    failed = bool(hlo_findings) or \
        (report is not None and report.exit_code != 0)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
