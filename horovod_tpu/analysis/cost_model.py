"""Static HLO cost model: FLOPs, wire bytes per fabric level, memory
high-water and a roofline step-time prediction — all without hardware.

ROADMAP item 5 asks for a predictive cluster-scale model the autotuner
and the (future) sharding-plan compiler can query before touching a
chip.  Three layers, each usable alone:

1. **Module accounting** — :func:`module_cost` parses a lowered
   StableHLO / compiled-HLO dump (``utils/hlo.py`` parser) into
   countable FLOPs (dot/convolution, fusion bodies included), collective
   wire bytes attributed to the ICI vs DCN fabric level from the
   replica-group structure, and a buffer-lifetime memory high-water
   estimate per device.

2. **Exchange model** — :func:`exchange_wire_bytes` prices the gradient
   exchange per level from the mesh factorization alone: the two-level
   path reduce-scatters the full payload over ICI but crosses DCN with
   only the ``1/n_ici`` partial-sum shard at the (default int8) wire
   width — the quantity ``utils/scaling.py`` now routes through here
   instead of assuming a flat fp32 ring (the MULTICHIP v5e-64
   projections overstated DCN traffic by ``4·n_ici×`` before this).

3. **Calibrated roofline** — :func:`calibrate` fits per-workload-family
   efficiency constants from the checked-in ``BENCH_r0*`` trajectory
   (measured rate ÷ roofline ceiling, most recent artifact wins);
   :func:`predict_rate` / :func:`predict_step_time_s` then predict new
   configurations.  The perf gate (``analysis/perf_gate.py``) and the
   autotune ``predict=`` path (``utils/autotune.py``) consume this.

The module is stdlib-only (plus ``utils/hlo.py``, itself stdlib-only)
so the analysis CLI stays importable without JAX.  Calibration
procedure, roofline assumptions and their failure modes are documented
in ``docs/perf_gate.md``.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
from typing import Dict, List, Optional, Sequence, Tuple, Union

from horovod_tpu.utils import hlo as H

# -- hardware ---------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class HardwareModel:
    """Per-chip roofline constants for one accelerator generation."""

    name: str
    peak_flops_per_s: float     # bf16 matmul peak
    hbm_bytes_per_s: float      # achievable HBM bandwidth
    ici_bytes_per_s: float      # per-chip ICI link budget
    dcn_bytes_per_s: float      # per-host DCN budget
    #: per-chip HBM capacity — the default feasibility ceiling when
    #: HOROVOD_HBM_BUDGET_BYTES is unset; None = unconstrained (the
    #: pre-memory-plane behavior, docs/memory.md)
    hbm_capacity_bytes: Optional[float] = None


#: v5e figures: 197 bf16 TFLOP/s, ~810 GB/s measured HBM
#: (PERF_NOTES.md hardware-envelope round), 1,600 Gbps ICI per chip,
#: ~200 Gbps DCN per host — the same constants docs/scaling.md tables
#: use.  16 GB HBM per chip.
V5E = HardwareModel(name="v5e", peak_flops_per_s=197e12,
                    hbm_bytes_per_s=810e9, ici_bytes_per_s=200e9,
                    dcn_bytes_per_s=25e9, hbm_capacity_bytes=16e9)


# -- exchange wire bytes per level ------------------------------------------


def _ring_factor(n: int) -> float:
    return (n - 1) / n if n > 1 else 0.0


@dataclasses.dataclass(frozen=True)
class WireBytes:
    """Per-chip bytes on each fabric level for one gradient exchange
    (reduce-scatter + allgather, i.e. one logical allreduce)."""

    ici: float
    dcn: float

    @property
    def total(self) -> float:
        return self.ici + self.dcn


def exchange_wire_bytes(payload_bytes: float,
                        n_dcn: int = 1,
                        n_ici: int = 1,
                        hierarchy: str = "flat",
                        wire_bits_dcn: int = 8,
                        elem_bits: int = 32) -> WireBytes:
    """Price one full gradient exchange per fabric level.

    Both modes decompose hierarchically (XLA lowers multi-slice
    collectives that way; the guards' ``[2,4]<=[8]`` replica groups are
    exactly these two levels): a ring over ``n_ici`` chips inside the
    slice and a ring over ``n_dcn`` slices across hosts, each costing
    ``2·(n−1)/n·(bytes carried)`` per chip.

    * ``flat``: the DCN hop carries the **full** payload at the element
      width — ``2·(n_dcn−1)/n_dcn·B``.
    * ``two_level``: the intra-slice reduce-scatter leaves only the
      ``1/n_ici`` partial-sum shard to cross DCN, quantized to
      ``wire_bits_dcn`` (int8 by default — the PR 2 DCN codec):
      ``2·(n_dcn−1)/n_dcn·(B/n_ici)·(wire/elem)``.  This is the
      correction :mod:`~horovod_tpu.utils.scaling` routes through.
    """
    if hierarchy not in ("flat", "two_level"):
        raise ValueError(f"hierarchy must be flat|two_level, got "
                         f"{hierarchy!r}")
    n_dcn, n_ici = max(1, int(n_dcn)), max(1, int(n_ici))
    ici = 2.0 * _ring_factor(n_ici) * payload_bytes
    if hierarchy == "flat":
        dcn = 2.0 * _ring_factor(n_dcn) * payload_bytes
    else:
        dcn = 2.0 * _ring_factor(n_dcn) * (payload_bytes / n_ici) \
            * (wire_bits_dcn / elem_bits)
    return WireBytes(ici=ici, dcn=dcn)


def exchange_time_s(wire: WireBytes, hw: HardwareModel = V5E) -> float:
    """Serial wire time of one exchange: each level at its own fabric
    bandwidth (the levels cannot overlap each other — the DCN phase
    consumes the ICI phase's output)."""
    return wire.ici / hw.ici_bytes_per_s + wire.dcn / hw.dcn_bytes_per_s


#: Tile count of the tile-fused exchange schedule — mirrors
#: ``ops.collectives.FUSED_TAIL_TILES`` (this module stays stdlib-only,
#: so the constant is duplicated by value; docs/fused_kernels.md).
FUSED_TILE_COUNT = 4


def fused_tail_exchange_s(wire_s: float, compute_s: float,
                          n_tiles: int = FUSED_TILE_COUNT) -> float:
    """Overlap-aware roofline of the tile-fused exchange
    (docs/fused_kernels.md): with the wire split into ``n_tiles``
    sub-exchanges interleaved with per-tile compute, tile *k*'s
    transfer hides under tile *k+1*'s work — only the FIRST tile's
    share (``wire/n_tiles``, nothing precedes it) plus whatever wire
    exceeds the available compute stays exposed.  ``n_tiles <= 1`` is
    the unfused serial tail: the whole ``wire_s`` exposed.  This is
    the ceiling the autotuner uses to prune the
    ``fused_collectives`` axis without hardware
    (:func:`score_exchange_schedule`)."""
    wire_s = max(0.0, float(wire_s))
    if n_tiles <= 1 or wire_s == 0.0:
        return wire_s
    startup = wire_s / n_tiles
    return startup + max(0.0, wire_s - max(0.0, float(compute_s)))


# -- parallelism-plan pricing -----------------------------------------------


#: Plan-grammar keys — mirrors ``parallel/plan.PLAN_KEYS`` (this module
#: stays stdlib-only, so the grammar is duplicated by value like
#: :data:`FUSED_TILE_COUNT`; ``v`` is the interleaved-1F1B
#: virtual-stage count).
PLAN_GRAMMAR_KEYS = ("dp", "pp", "fsdp", "ep", "sp", "tp", "v")

#: Microbatch count the plan scorer assumes when the caller does not
#: pin one — matches the bench pipeline probe's default depth.
PLAN_SCORE_MICROBATCHES = 8

#: Wire bits per ``HOROVOD_EXCHANGE_WIRE_DTYPE`` value — the
#: ``wire_dtype`` autotune axis's pricing table (fp32 = no wire
#: compression; int8 and fp8_e4m3 both move one byte per element, so
#: the model ranks them identically and the measurement breaks the
#: tie).
WIRE_DTYPE_BITS = {"fp32": 32, "int8": 8, "fp8_e4m3": 8}


def parse_plan(plan: Union[str, Dict]) -> Dict[str, int]:
    """Parse the ``HOROVOD_PLAN`` grammar into a full extent dict
    (every :data:`PLAN_GRAMMAR_KEYS` key, absent axes at 1).  The
    stdlib mirror of ``parallel/plan.ShardingPlan.from_string`` for the
    analysis layer; ``dp=?`` (an unresolved plan string) prices as
    ``dp=1``."""
    if isinstance(plan, dict):
        ext = dict(plan)
    else:
        ext = {}
        for item in str(plan).split(","):
            item = item.strip()
            if not item:
                continue
            key, sep, val = item.partition("=")
            key = key.strip()
            if not sep or key not in PLAN_GRAMMAR_KEYS:
                raise ValueError(
                    f"bad plan term {item!r}: expected axis=extent "
                    f"with axis in {', '.join(PLAN_GRAMMAR_KEYS)}")
            if key in ext:
                raise ValueError(f"duplicate plan axis {key!r} in "
                                 f"{plan!r}")
            v = val.strip()
            ext[key] = 1 if v == "?" else int(v)
    out = {}
    for k in PLAN_GRAMMAR_KEYS:
        raw = ext.get(k)
        v = 1 if raw is None else int(raw)
        if v < 1:
            raise ValueError(f"plan axis {k} must be >= 1, got {v}")
        out[k] = v
    return out


def pipeline_bubble_fraction(stages: int, microbatches: int,
                             virtual_stages: int = 1) -> float:
    """Idle share of the pipeline schedule, ``(s-1)/(v*m+s-1)`` —
    mirrors ``parallel/pipeline.bubble_fraction`` by value (GPipe at
    ``v=1``, interleaved-1F1B at ``v>1``; docs/parallelism.md)."""
    stages, microbatches = max(1, int(stages)), max(1, int(microbatches))
    virtual_stages = max(1, int(virtual_stages))
    return (stages - 1) / (virtual_stages * microbatches + stages - 1)


def plan_exchange_wire_bytes(plan: Union[str, Dict],
                             payload_bytes: float,
                             n_dcn: int = 1,
                             n_ici: int = 1,
                             wire_bits_dcn: int = 8) -> WireBytes:
    """Gradient-exchange wire bytes under a parallelism plan.

    The model axes (pp/ep/sp/tp) shard the parameters, so each data
    replica exchanges only ``payload / model_extent`` bytes.  The data
    axes (dp × fsdp) then map onto the fabric DCN-outer/ICI-inner
    (the ``AXIS_ORDER`` layout ``parallel/mesh.py`` realizes): ``dp``
    absorbs the DCN extent first, the remainder rides ICI, and the
    exchange goes two-level exactly when both derived extents exceed
    1 — the same decision ``resolve_hierarchy`` makes at trace time.
    """
    ext = parse_plan(plan)
    model = ext["pp"] * ext["ep"] * ext["sp"] * ext["tp"]
    per_replica = float(payload_bytes) / max(1, model)
    data_world = ext["dp"] * ext["fsdp"]
    d_dcn = min(ext["dp"], max(1, int(n_dcn)))
    while data_world % d_dcn:
        d_dcn -= 1
    d_ici = max(1, data_world // d_dcn)
    hierarchy = "two_level" if d_dcn > 1 and d_ici > 1 else "flat"
    return exchange_wire_bytes(per_replica, n_dcn=d_dcn, n_ici=d_ici,
                               hierarchy=hierarchy,
                               wire_bits_dcn=wire_bits_dcn)


def plan_cost_s(plan: Union[str, Dict],
                payload_bytes: float,
                n_dcn: int = 1,
                n_ici: int = 1,
                compute_s: float = 0.0,
                microbatches: int = PLAN_SCORE_MICROBATCHES,
                hw: HardwareModel = V5E,
                wire_bits_dcn: int = 8) -> float:
    """Predicted per-step seconds of one plan: compute stretched by the
    pipeline bubble (``t / (1 - bubble)`` — the idle ticks are pure
    loss) plus the serial wire time of the plan-scoped gradient
    exchange.  The quantity ``ThroughputAutotuner(predict=)`` ranks the
    ``plan`` axis with (:func:`score_exchange_schedule`), and the
    1F1B-beats-GPipe acceptance check reads straight off: same plan
    with ``v>1`` has a strictly smaller bubble term."""
    ext = parse_plan(plan)
    bubble = 0.0
    if ext["pp"] > 1:
        bubble = pipeline_bubble_fraction(ext["pp"], microbatches,
                                          ext["v"])
    wire = plan_exchange_wire_bytes(plan, payload_bytes, n_dcn=n_dcn,
                                    n_ici=n_ici,
                                    wire_bits_dcn=wire_bits_dcn)
    return float(compute_s) / (1.0 - bubble) + exchange_time_s(wire, hw)


def rank_plans(plans: Sequence[Union[str, Dict]],
               payload_bytes: float,
               n_dcn: int = 1,
               n_ici: int = 1,
               compute_s: float = 0.0,
               microbatches: int = PLAN_SCORE_MICROBATCHES,
               hw: HardwareModel = V5E,
               wire_bits_dcn: int = 8
               ) -> List[Tuple[float, Union[str, Dict]]]:
    """Score each plan with :func:`plan_cost_s` and return
    ``(cost_s, plan)`` pairs sorted cheapest-first.  The sort is
    stable, so a caller that pre-orders its candidates by preference
    (``ShardingPlan.degrade_candidates`` puts dp-shrink before
    fsdp-shrink at equal world size) gets that preference as the
    tie-break for free."""
    scored = [(plan_cost_s(p, payload_bytes, n_dcn=n_dcn, n_ici=n_ici,
                           compute_s=compute_s,
                           microbatches=microbatches, hw=hw,
                           wire_bits_dcn=wire_bits_dcn), p)
              for p in plans]
    scored.sort(key=lambda cp: cp[0])
    return scored


# -- plan memory: the HBM roofline ------------------------------------------


#: Remat policy vocabulary — mirrors ``memory/remat.REMAT_POLICIES`` by
#: value (this module stays stdlib-only, like :data:`PLAN_GRAMMAR_KEYS`).
REMAT_POLICIES = ("none", "dots", "full", "offload")

#: Share of the remat-none activation footprint still resident in HBM
#: under each policy.  Calibrated against the linear-scan
#: ``utils/hlo.memory_high_water`` estimate of the CPU-twin
#: transformer's compiled dumps (the same estimator
#: :func:`plan_memory_bytes` is validated against, so model and
#: measurement share one definition of "activation bytes"): ``full``
#: keeps the per-block backward-recompute peak plus the saved block
#: inputs; ``dots`` additionally keeps every matmul output; ``offload``
#: streams the dots residuals to pinned host memory, leaving roughly
#: the ``full`` residency on-device.
REMAT_ACTIVATION_FRACTION = {
    "none": 1.0, "dots": 0.82, "full": 0.31, "offload": 0.41,
}

#: Fractional step-time penalty of the policy's recomputation (plus,
#: for ``offload``, the un-hidden share of the D2H/H2D stream):
#: ``dots`` replays the cheap elementwise ops only, ``full`` replays
#: each block's forward (~1/3 of the fwd+bwd budget).
REMAT_RECOMPUTE_OVERHEAD = {
    "none": 0.0, "dots": 0.12, "full": 0.30, "offload": 0.34,
}

#: Resident share of an offloaded optimizer shard **during the step
#: window**: 1.0 — no high-water credit.  The streaming engine
#: (``memory/offload.py``) retains the device reference until
#: ``fetch()`` (the degrade contract) and ``fetch()`` restores the
#: whole shard to device *before* the step that consumes it, so the
#: per-step HBM high-water still holds the full shard; the host
#: round-trip only parks it between steps.  Charging less would let
#: the budgeted planner call configs feasible that OOM in practice —
#: ``bench.py --hbm-budget`` validates the offload=True prediction
#: against the measured high-water to keep this honest.  An engine
#: that streamed slot *buckets* through the update phase could earn a
#: fraction < 1 here; until one exists, offload is HBM-neutral in the
#: roofline and the planner never profits from it.
OFFLOAD_RESIDENT_FRACTION = 1.0


@dataclasses.dataclass(frozen=True)
class MemoryBytes:
    """Per-device HBM high-water decomposition of one plan — the four
    components the budget trades against each other, plus the exchange
    staging.  ``tightest`` names the dominant component, the axis an
    infeasibility error points at (``memory/planner.py``).

    MoE plans add two components (both 0.0 for dense models):
    ``expert_params`` — the per-device expert-parameter shard (their
    grads/optimizer slots fold into ``grads``/``optimizer``) — and
    ``moe_buffers``, the static ``(E, C, d)`` dispatch + combine
    capacity buckets, which are ``ep``-invariant per device (each chip
    always stages ``E·C·d`` slots: all experts' slots before the
    exchange, or ``ep`` source tiles of its ``E/ep`` experts after)."""

    params: float
    grads: float
    optimizer: float
    activations: float
    exchange: float
    expert_params: float = 0.0
    moe_buffers: float = 0.0

    @property
    def total(self) -> float:
        return (self.params + self.grads + self.optimizer
                + self.activations + self.exchange
                + self.expert_params + self.moe_buffers)

    @property
    def tightest(self) -> str:
        """Name of the largest component (deterministic field-order
        tie-break)."""
        return max(dataclasses.asdict(self).items(),
                   key=lambda kv: (kv[1], kv[0]))[0]


def plan_memory_bytes(plan: Union[str, Dict], *,
                      param_bytes: float,
                      activation_bytes: float,
                      remat_policy: str = "none",
                      microbatches: int = 1,
                      optimizer_slots: int = 2,
                      shard_optimizer_states: bool = False,
                      offload_optimizer: bool = False,
                      exchange_bucket_bytes: Optional[float] = None,
                      expert_param_bytes: float = 0.0,
                      moe_capacity_buffer_bytes: float = 0.0
                      ) -> MemoryBytes:
    """Predicted per-device HBM high-water of one plan — the memory
    twin of :func:`plan_cost_s`, and the quantity the feasibility
    predicate (:func:`plan_fits`) holds under ``HOROVOD_HBM_BUDGET_BYTES``.

    Inputs are *unsharded single-replica* quantities: ``param_bytes``
    the whole model's parameters, ``activation_bytes`` the whole
    network's activation footprint for one device's batch shard at
    ``remat_policy="none"`` and ``microbatches=1``.  The plan then
    shards them:

    * params/grads divide over the parameter-sharding axes
      (``tp·pp·ep·fsdp`` — ``ep`` idealized as sharding every layer,
      ``sp`` replicates parameters);
    * optimizer state is ``optimizer_slots`` × the param shard,
      further ÷ ``dp`` under the ZeRO sharded exchange;
      ``offload_optimizer`` charges
      :data:`OFFLOAD_RESIDENT_FRACTION` = 1.0 of it — host streaming
      parks the shard *between* steps but restores it whole before the
      step (``memory/offload.py``), so it buys no step-window
      high-water;
    * activations scale by the policy's residency fraction
      (:data:`REMAT_ACTIVATION_FRACTION`), divide over ``sp`` and the
      microbatch count, and a pipeline holds ``min(pp, m)`` in-flight
      microbatches of its ``1/pp`` layer slice (the 1F1B steady
      state);
    * exchange staging is the double-buffered bucket pair when the
      bucketed exchange is on, else one grad-shard-sized fused buffer
      whenever a data axis exists;
    * ``expert_param_bytes`` (MoE plans: the expert FFN weights, which
      ``ep`` *actually* shards — pass the dense remainder as
      ``param_bytes``) divides over the same ``tp·pp·ep·fsdp`` axes,
      with grads and optimizer slots folded into those components;
      ``moe_capacity_buffer_bytes`` (the static dispatch + combine
      ``(E, C, d)`` buckets, already per-device and ``ep``-invariant:
      ``2·E·C·d·elem_bytes``) is charged as-is.

    Validated against ``utils/hlo.memory_high_water`` on compiled
    CPU-twin dumps by ``bench.py --hbm-budget`` (within 25%;
    docs/memory.md lists the approximations).
    """
    if remat_policy not in REMAT_POLICIES:
        raise ValueError(
            f"unknown remat policy {remat_policy!r}: expected one of "
            f"{', '.join(REMAT_POLICIES)}")
    ext = parse_plan(plan)
    microbatches = max(1, int(microbatches))
    param_shard_axes = ext["tp"] * ext["pp"] * ext["ep"] * ext["fsdp"]
    params = float(param_bytes) / param_shard_axes
    expert_params = float(expert_param_bytes) / param_shard_axes
    grads = params + expert_params
    optimizer = max(0, int(optimizer_slots)) * (params + expert_params)
    if shard_optimizer_states:
        optimizer /= ext["dp"]
    if offload_optimizer:
        optimizer *= OFFLOAD_RESIDENT_FRACTION
    frac = REMAT_ACTIVATION_FRACTION[remat_policy]
    act_per_mb = float(activation_bytes) * frac \
        / (microbatches * ext["sp"])
    in_flight = min(ext["pp"], microbatches)
    activations = act_per_mb / ext["pp"] * in_flight
    data_world = ext["dp"] * ext["fsdp"]
    if exchange_bucket_bytes:
        exchange = 2.0 * float(exchange_bucket_bytes)
    else:
        exchange = grads if data_world > 1 else 0.0
    return MemoryBytes(params=params, grads=grads, optimizer=optimizer,
                       activations=activations, exchange=exchange,
                       expert_params=expert_params,
                       moe_buffers=float(moe_capacity_buffer_bytes))


def plan_fits(mem: Union[MemoryBytes, float],
              budget_bytes: Optional[float] = None,
              hw: HardwareModel = V5E) -> bool:
    """Feasibility predicate: does the predicted high-water fit the
    budget?  ``budget_bytes`` (the HOROVOD_HBM_BUDGET_BYTES knob) rules
    when given; otherwise the hardware model's capacity; no capacity
    anywhere = everything fits (the pre-memory-plane behavior)."""
    total = mem.total if isinstance(mem, MemoryBytes) else float(mem)
    cap = budget_bytes if budget_bytes is not None \
        else hw.hbm_capacity_bytes
    if cap is None:
        return True
    return total <= float(cap)


def score_exchange_schedule(point: Dict,
                            payload_bytes: float,
                            n_dcn: int = 1,
                            n_ici: int = 1,
                            compute_s: float = 0.0,
                            hw: HardwareModel = V5E,
                            n_tiles: int = FUSED_TILE_COUNT,
                            sp_attn_wire_s: float = 0.0,
                            sp_attn_compute_s: float = 0.0
                            ) -> Optional[float]:
    """Rank one autotune sample point by its predicted *exposed*
    exchange seconds (negated — higher is better, matching the
    measured-rate objective).  ``point`` is a bench-autotuner sample
    (``{"hierarchy": ..., "fused_collectives": ..., "wire_dtype": ...,
    "plan": ..., ...}``); knobs the exchange model does not price
    (steps_per_call, flash_block, bucket cap) leave the score
    unchanged, so per-axis scans of those knobs see constant scores
    and stay fully measured.  ``wire_dtype`` prices the codec width
    (:data:`WIRE_DTYPE_BITS`): the DCN hop in two_level, the whole
    single-scope wire in flat (the flat quantized path compresses ICI
    too).  A ``plan`` knob reprices the exchange under that plan's
    factorization and adds the pipeline bubble penalty
    (:func:`plan_cost_s`); a plan with ``sp>1`` additionally charges
    the attention K/V ring — ``sp_attn_wire_s``/``sp_attn_compute_s``
    (from :func:`sp_ring_wire_bytes` / :func:`sp_attention_compute_s`,
    priced for sp=1 by the caller and rescaled here to the sampled
    extent) exposed per :func:`sp_ring_exposed_s`, fused when the
    point's ``fused_collectives`` is ``"on"`` — the fused-vs-unfused
    ring the dp×sp autotune prunes on.  Returns ``None`` when the
    point carries no
    exchange knob at all — the caller then skips pruning entirely (the
    ParameterManager ``predict=`` contract: a predictor that cannot
    rank must not narrow the grid)."""
    hierarchy = point.get("hierarchy")
    fused = point.get("fused_collectives")
    wire_dtype = point.get("wire_dtype")
    plan = point.get("plan")
    if hierarchy is None and fused is None and wire_dtype is None \
            and plan is None:
        return None
    wire_bits = WIRE_DTYPE_BITS.get(wire_dtype, 8)
    if plan is not None:
        ext = parse_plan(plan)
        bubble = 0.0
        if ext["pp"] > 1:
            bubble = pipeline_bubble_fraction(
                ext["pp"], PLAN_SCORE_MICROBATCHES, ext["v"])
        wire = plan_exchange_wire_bytes(plan, float(payload_bytes),
                                        n_dcn=n_dcn, n_ici=n_ici,
                                        wire_bits_dcn=wire_bits)
        exch = exchange_time_s(wire, hw)
        if fused == "on":
            exch = fused_tail_exchange_s(exch, compute_s, n_tiles)
        sp_cost = 0.0
        if ext["sp"] > 1 and (sp_attn_wire_s or sp_attn_compute_s):
            # inputs are the sp=1 (whole-sequence, one-chip) quantities:
            # wire = seconds to move the full K+V once at ICI rate,
            # compute = the full t_global² attention; the sampled sp
            # extent rescales them — per-chip ring wire is the
            # (sp−1)/sp ring factor of the full volume, per-chip
            # compute divides by sp (each rank owns t_global/sp queries)
            sp_w = float(sp_attn_wire_s) * _ring_factor(ext["sp"])
            sp_c = float(sp_attn_compute_s) / ext["sp"]
            sp_cost = sp_c + sp_ring_exposed_s(
                sp_w, sp_c, ext["sp"], fused=(fused == "on"))
        # penalty form of the bubble stretch: the constant compute_s
        # offset cancels in the ranking
        return -(float(compute_s) * bubble / (1.0 - bubble) + exch
                 + sp_cost)
    hierarchy = hierarchy if hierarchy in ("flat", "two_level") else "flat"
    wire = exchange_wire_bytes(float(payload_bytes), n_dcn=n_dcn,
                               n_ici=n_ici, hierarchy=hierarchy,
                               wire_bits_dcn=wire_bits)
    if hierarchy == "flat" and wire_dtype in ("int8", "fp8_e4m3"):
        # flat quantization compresses the single-scope wire everywhere
        wire = WireBytes(ici=wire.ici * wire_bits / 32.0,
                         dcn=wire.dcn * wire_bits / 32.0)
    serial = exchange_time_s(wire, hw)
    if fused == "on":
        return -fused_tail_exchange_s(serial, compute_s, n_tiles)
    return -serial


# -- sequence-parallel (sp ring) pricing ------------------------------------


def sp_ring_wire_bytes(seq_local: int, heads: int, head_dim: int,
                       sp: int, batch: int = 1,
                       elem_bits: int = 32) -> float:
    """Per-chip K/V ring wire bytes of one sp attention forward.

    Each of the ``sp−1`` ring hops moves this chip's K *and* V block
    (``b·t_local·h·d`` elements each):
    ``2·(sp−1)·b·t_local·h·d·elem_bytes``.  The fused ring-flash path
    moves exactly the same bytes as the jnp formulation — fusion
    changes the *exposure* (:func:`sp_ring_exposed_s`), never the
    volume — so this is the honest wire gauge for both schedules.
    ``sp <= 1`` prices 0 (the sequence is local, nothing crosses the
    wire)."""
    sp = max(1, int(sp))
    if sp == 1:
        return 0.0
    block = (max(1, int(batch)) * int(seq_local) * int(heads)
             * int(head_dim) * (elem_bits / 8.0))
    return 2.0 * (sp - 1) * block


def sp_attention_compute_s(seq_global: int, heads: int, head_dim: int,
                           sp: int, batch: int = 1,
                           causal: bool = False,
                           hw: HardwareModel = V5E) -> float:
    """Per-chip attention forward seconds under ``sp``-way sequence
    parallelism: the full ``4·b·t_global²·h·d`` FLOPs (QKᵀ + PV, two
    FLOPs per MAC) divide evenly over the sp ranks — each rank's
    ``t_global/sp`` queries visit every K/V block exactly once around
    the ring.  ``causal`` halves the live score area (under the zigzag
    layout the halving is per-rank exact; under the contiguous layout
    it holds in aggregate while the per-rank work skews — see
    ``ops.pallas_kernels.ring_step_schedule``)."""
    flops = (4.0 * max(1, int(batch)) * float(seq_global) ** 2
             * int(heads) * int(head_dim)) / max(1, int(sp))
    if causal:
        flops *= 0.5
    return flops / hw.peak_flops_per_s


def sp_ring_exposed_s(wire_s: float, compute_s: float, sp: int,
                      fused: bool = True) -> float:
    """Exposed (un-overlapped) seconds of the sp K/V ring: the fused
    ring-flash path pre-issues the next block's ``ppermute`` before
    the current block's flash kernel, so hop *k* hides under block
    *k*'s compute — the serial-tail credit is exactly
    :func:`fused_tail_exchange_s` with the ring's ``sp`` steps as
    tiles; unfused (the jnp scan), every hop sits serially between
    steps and the whole wire is exposed."""
    if not fused:
        return max(0.0, float(wire_s))
    return fused_tail_exchange_s(wire_s, compute_s,
                                 n_tiles=max(1, int(sp)))


# -- MoE expert-dispatch pricing --------------------------------------------


def moe_capacity(tokens: int, num_experts: int,
                 capacity_factor: float = 1.25) -> int:
    """Per-expert capacity bucket, ``max(1, ceil(cf·tokens/E))`` —
    mirrors ``parallel/expert.expert_parallel_ffn`` by value (this
    module stays stdlib-only, like :data:`PLAN_GRAMMAR_KEYS`)."""
    tokens, num_experts = max(1, int(tokens)), max(1, int(num_experts))
    return int(max(1, -(-float(capacity_factor) * tokens
                        // num_experts)))


def moe_dispatch_wire_bytes(tokens: int, d_model: int, num_experts: int,
                            ep: int, capacity_factor: float = 1.25,
                            elem_bits: int = 32,
                            capacity: Optional[int] = None) -> float:
    """Per-chip wire bytes of one MoE dispatch + combine exchange.

    Each of the ``ep−1`` ring hops moves one ``(E/ep, C, d)`` source
    tile, in both directions (route → expert, expert output → origin):
    ``2·(ep−1)·(E/ep)·C·d·elem_bytes``.  The boundary-wide
    ``all_to_all`` moves exactly the same bytes (each chip ships
    ``ep−1`` of its ``ep`` tiles, twice) — the fused ring changes the
    *exposure* (:func:`moe_dispatch_exposed_s`), never the volume, so
    this is the honest ``hvd_moe_ep_wire_bytes`` gauge for both
    schedules.  ``tokens`` is the per-chip token count; ``ep <= 1``
    prices 0 (local experts, nothing crosses the wire)."""
    ep = max(1, int(ep))
    if ep == 1:
        return 0.0
    if capacity is None:
        capacity = moe_capacity(tokens, num_experts, capacity_factor)
    e_local = max(1, int(num_experts) // ep)
    tile = e_local * int(capacity) * int(d_model) * (elem_bits / 8.0)
    return 2.0 * (ep - 1) * tile


def moe_expert_compute_s(tokens: int, d_model: int, d_ff: int,
                         num_experts: int, ep: int,
                         capacity_factor: float = 1.25,
                         hw: HardwareModel = V5E,
                         capacity: Optional[int] = None) -> float:
    """Per-chip expert-FFN forward seconds: ``E/ep`` local experts each
    process up to ``ep·C`` routed slots through the two ``d×d_ff``
    matmuls (``4·d·d_ff`` FLOPs per slot).  The compute the fused ring
    hides hops under — and the term that grows linearly with the
    ``capacity_factor`` autotune axis."""
    ep = max(1, int(ep))
    if capacity is None:
        capacity = moe_capacity(tokens, num_experts, capacity_factor)
    e_local = max(1, int(num_experts) // ep)
    flops = e_local * ep * int(capacity) * 4.0 * int(d_model) * int(d_ff)
    return flops / hw.peak_flops_per_s


def moe_dispatch_exposed_s(wire_s: float, compute_s: float, ep: int,
                           fused: bool = True) -> float:
    """Exposed (un-overlapped) seconds of the dispatch + combine
    exchange: the fused ``a2a ⊗ expert-matmul`` ring streams one tile
    per hop while the previous tile's expert matmul computes, so the
    serial-tail credit is exactly :func:`fused_tail_exchange_s` with
    the ring's ``ep`` tiles; unfused, the whole boundary-wide
    ``all_to_all`` wire is exposed (nothing overlaps it)."""
    if not fused:
        return max(0.0, float(wire_s))
    return fused_tail_exchange_s(wire_s, compute_s,
                                 n_tiles=max(1, int(ep)))


def score_moe_schedule(point: Dict, *,
                       tokens: int,
                       d_model: int,
                       d_ff: int,
                       num_experts: int,
                       ep: int = 1,
                       fused: bool = True,
                       hw: HardwareModel = V5E,
                       elem_bits: int = 32) -> Optional[float]:
    """Rank one MoE autotune sample point (``{"capacity_factor": ...}``
    and/or ``{"tokens_per_expert": ...}``) by its predicted per-step
    MoE seconds, negated — the ``bench --autotune`` pruning twin of
    :func:`score_exchange_schedule` for the routing axes.
    ``tokens_per_expert`` sets the nominal per-expert workload (scaled
    by ``capacity_factor`` slack when both are sampled);
    ``capacity_factor`` alone derives it via :func:`moe_capacity`.
    Returns
    ``None`` when the point carries neither knob (the ``predict=``
    contract: a predictor that cannot rank must not narrow the
    grid)."""
    cf = point.get("capacity_factor")
    tpe = point.get("tokens_per_expert")
    if cf is None and tpe is None:
        return None
    if tpe is not None:
        # cf composes with tpe when both knobs land in one point: tpe
        # is the nominal per-expert workload, cf the slack multiplier —
        # pinning capacity to tpe alone would score a cf scan flat and
        # prune nothing
        slack = float(cf) if cf is not None else 1.0
        capacity = int(max(1, -(-slack * int(tpe) // 1)))
    else:
        capacity = moe_capacity(tokens, num_experts, float(cf))
    wire_bytes = moe_dispatch_wire_bytes(
        tokens, d_model, num_experts, ep, elem_bits=elem_bits,
        capacity=capacity)
    wire_s = wire_bytes / hw.ici_bytes_per_s
    compute_s = moe_expert_compute_s(
        tokens, d_model, d_ff, num_experts, ep, hw=hw,
        capacity=capacity)
    exposed = moe_dispatch_exposed_s(wire_s, compute_s, ep, fused=fused)
    return -(compute_s + exposed)


def _op_wire_bytes(op: H.CollectiveOp, world: int) -> float:
    """Per-chip wire bytes of one compiled collective from its result
    size: RS results are per-shard (input = bytes·g), AR/AG results are
    the full payload."""
    g = op.group_size or world
    if g <= 1:
        return 0.0
    if op.kind == "all-reduce":
        return 2.0 * _ring_factor(g) * op.bytes
    if op.kind == "reduce-scatter":
        return (g - 1) * op.bytes
    if op.kind in ("all-gather", "all-to-all"):
        return _ring_factor(g) * op.bytes
    # permute / broadcast: the payload crosses once
    return float(op.bytes)


def collective_wire_by_level(ops: Sequence[H.CollectiveOp],
                             n_dcn: int = 1,
                             n_ici: int = 1) -> Dict[str, float]:
    """Attribute each compiled collective's wire bytes to a fabric
    level: an op whose replica-group size equals the DCN extent (on a
    factored mesh) runs the cross-slice hop; everything else — the
    intra-slice scopes and world-sized flat collectives — rides ICI.
    This is the per-level measurement the overlap probe embeds in bench
    artifacts (``exchange_wire_bytes_ici``/``_dcn``) for the perf gate
    to diff."""
    n_dcn, n_ici = max(1, int(n_dcn)), max(1, int(n_ici))
    world = n_dcn * n_ici
    out = {"ici": 0.0, "dcn": 0.0}
    for op in ops:
        level = "dcn" if n_dcn > 1 and op.group_size == n_dcn else "ici"
        out[level] += _op_wire_bytes(op, world)
    return out


# -- whole-module static cost -----------------------------------------------


@dataclasses.dataclass(frozen=True)
class ModuleCost:
    """Static accounting of one lowered module."""

    flops: int                        # countable matmul-class FLOPs
    wire_bytes: Dict[str, float]      # per-level collective bytes
    memory_high_water_bytes: int      # buffer-lifetime peak estimate

    def predicted_step_time_s(self, hw: HardwareModel = V5E,
                              overlap_fraction: float = 0.0,
                              efficiency: float = 1.0) -> float:
        """Roofline step time: compute at ``efficiency × peak`` plus the
        exposed share of the wire time.  ``efficiency`` comes from
        :func:`calibrate` when a trajectory exists; 1.0 is the
        theoretical floor."""
        compute = self.flops / (hw.peak_flops_per_s * max(efficiency,
                                                          1e-9))
        wire = (self.wire_bytes.get("ici", 0.0) / hw.ici_bytes_per_s
                + self.wire_bytes.get("dcn", 0.0) / hw.dcn_bytes_per_s)
        return compute + wire * (1.0 - overlap_fraction)


def module_cost(hlo_text: str, n_dcn: int = 1,
                n_ici: int = 1) -> ModuleCost:
    """Parse one HLO dump into the three static quantities the roofline
    needs: FLOPs (:func:`~horovod_tpu.utils.hlo.module_flops`), wire
    bytes per level, and the memory high-water estimate
    (:func:`~horovod_tpu.utils.hlo.memory_high_water`)."""
    ops = H.collective_ops(hlo_text)
    return ModuleCost(
        flops=H.module_flops(hlo_text),
        wire_bytes=collective_wire_by_level(ops, n_dcn=n_dcn,
                                            n_ici=n_ici),
        memory_high_water_bytes=H.memory_high_water(hlo_text))


# -- workload models + calibrated roofline ----------------------------------


@dataclasses.dataclass(frozen=True)
class WorkloadModel:
    """Analytic per-unit costs of one bench family — the same FLOP
    accounting ``bench.py`` prints (so model and measurement cannot
    disagree about what a unit costs)."""

    family: str                  # "resnet" | "transformer" | ...
    rate_field: str              # the BENCH-JSON throughput field
    unit: str                    # "img" | "token"
    flops_per_unit: float
    hbm_bytes_per_unit: float
    units_per_step: float        # per-chip batch units in one step


#: ResNet-50 HBM traffic per image at 224px: PERF_NOTES derives the
#: per-op-fusion ceiling of ~4,100 img/s from ~810 GB/s of achievable
#: bandwidth — i.e. ≈198 MB moved per image.  This is what makes the
#: model HBM-bound on v5e (mfu ceiling ≈26%), which the roofline must
#: know or it would predict 16,000 img/s from FLOPs alone.
RESNET_HBM_BYTES_PER_IMG = 810e9 / 4100.0

#: Parameter-traffic passes per step for the transformer HBM term:
#: forward read + backward read + optimizer write (activations are
#: small next to 871M params at batch 6).
_PARAM_PASSES = 3


def resnet_workload(image_size: int = 224,
                    batch: int = 128) -> WorkloadModel:
    scale = (image_size / 224.0) ** 2
    return WorkloadModel(
        family="resnet", rate_field="value", unit="img",
        flops_per_unit=3 * 4.1e9 * scale,            # bench.py accounting
        hbm_bytes_per_unit=RESNET_HBM_BYTES_PER_IMG * scale,
        units_per_step=batch)


def transformer_workload(params: float, layers: int = 16,
                         d_model: int = 2048, seq: int = 1024,
                         batch: int = 6,
                         param_bytes: int = 2) -> WorkloadModel:
    tokens_per_step = batch * seq
    return WorkloadModel(
        family="transformer", rate_field="transformer_tokens_per_sec",
        unit="token",
        flops_per_unit=6 * params + 6 * layers * seq * d_model,
        hbm_bytes_per_unit=_PARAM_PASSES * param_bytes * params
        / tokens_per_step,
        units_per_step=tokens_per_step)


def roofline_rate(w: WorkloadModel, hw: HardwareModel = V5E) -> float:
    """units/sec ceiling: the binding one of the compute and HBM
    rooflines.  ResNet-50 binds on HBM (~4,100 img/s on v5e), the
    flagship transformer on compute (~36,300 tok/s)."""
    return min(hw.peak_flops_per_s / w.flops_per_unit,
               hw.hbm_bytes_per_s / w.hbm_bytes_per_unit)


def workloads_from_artifact(artifact: Dict) -> List[WorkloadModel]:
    """The workload models a bench artifact carries evidence for.
    Transformer shape is keyed off ``transformer_params_m`` (the
    flagship layer/seq defaults otherwise match every checked-in
    round); artifacts without a family's fields contribute nothing."""
    out: List[WorkloadModel] = []
    if artifact.get("metric") == "resnet50_img_sec_per_chip" \
            and artifact.get("value") is not None:
        out.append(resnet_workload())
    params_m = artifact.get("transformer_params_m")
    if params_m is not None \
            and artifact.get("transformer_tokens_per_sec") is not None:
        out.append(transformer_workload(params=float(params_m) * 1e6))
    return out


@dataclasses.dataclass
class Calibration:
    """Fitted per-family efficiency constants (measured rate ÷ roofline
    ceiling).  ``efficiency`` keeps the most recent fit — the newest
    hardware measurement is the prediction anchor — while ``samples``
    retains the whole trajectory for drift inspection."""

    hw: HardwareModel
    efficiency: Dict[str, float]
    samples: Dict[str, List[Tuple[str, float]]]   # family → (src, eff)


ArtifactLike = Union[str, os.PathLike, Dict]


def _load_artifact(artifact: ArtifactLike) -> Tuple[str, Dict]:
    if isinstance(artifact, dict):
        data = artifact
        name = str(data.get("metric", "<dict>"))
    else:
        name = os.path.basename(os.fspath(artifact))
        with open(artifact) as f:
            data = json.load(f)
    if isinstance(data.get("parsed"), dict):     # MULTICHIP/driver wrapper
        data = dict(data, **data["parsed"])
    return name, data


def calibrate(artifacts: Sequence[ArtifactLike],
              hw: HardwareModel = V5E) -> Calibration:
    """Fit the roofline's per-family efficiency from a BENCH trajectory.

    For every artifact (in the given order — pass them oldest→newest)
    and every workload family it measures, the sample is
    ``measured_rate / roofline_rate``; the calibrated constant is the
    LAST sample per family.  Deterministic: same inputs, same
    calibration — the perf gate's two-run identity check relies on it.
    """
    eff: Dict[str, float] = {}
    samples: Dict[str, List[Tuple[str, float]]] = {}
    for art in artifacts:
        name, data = _load_artifact(art)
        for w in workloads_from_artifact(data):
            rate = data.get(w.rate_field)
            if rate is None:
                continue
            ceiling = roofline_rate(w, hw)
            e = float(rate) / ceiling
            eff[w.family] = e
            samples.setdefault(w.family, []).append((name, e))
    return Calibration(hw=hw, efficiency=eff, samples=samples)


def predict_rate(cal: Calibration, w: WorkloadModel) -> Optional[float]:
    """Calibrated units/sec prediction, or None for an unseen family."""
    e = cal.efficiency.get(w.family)
    if e is None:
        return None
    return e * roofline_rate(w, cal.hw)


def predict_step_time_s(cal: Calibration, w: WorkloadModel,
                        exposed_comm_s: float = 0.0) -> Optional[float]:
    """Predicted per-step wall time: batch units at the calibrated rate
    plus whatever exchange time is left exposed (0 on one chip;
    :func:`exchange_time_s` × (1 − overlap) on a mesh)."""
    rate = predict_rate(cal, w)
    if rate is None or rate <= 0:
        return None
    return w.units_per_step / rate + exposed_comm_s


# -- autotune predictor ------------------------------------------------------


def make_fusion_predictor(payload_bytes: float, n_leaves: int,
                          world: int = 8, hw: HardwareModel = V5E,
                          dispatch_latency_s: float = 1e-3):
    """Score function for the eager-plane autotune grid
    (``utils/autotune.py`` ``predict=``): predicted bytes/sec of one
    gradient exchange under a ``(fusion_threshold_bytes,
    cycle_time_ms)`` point.

    Model: a threshold of T splits the payload into ``ceil(B/T)``
    flushes (T = 0 flushes per tensor), each paying one dispatch
    latency; the wire itself is the flat ring ``2·(N−1)/N·B`` at ICI
    bandwidth; the flush interval adds half a cycle of expected queue
    wait.  Crude on purpose — it only needs to RANK the warm-up grid so
    the manager measures the plausible half instead of all of it (the
    measurement, not the model, still picks the winner)."""
    def predict(point) -> float:
        threshold, cycle_ms = point
        if threshold and threshold > 0:
            flushes = max(1, math.ceil(payload_bytes / threshold))
        else:
            flushes = max(1, int(n_leaves))
        wire_s = 2.0 * _ring_factor(max(1, world)) * payload_bytes \
            / hw.ici_bytes_per_s
        t = flushes * dispatch_latency_s + wire_s \
            + (float(cycle_ms) / 1e3) / 2.0
        return payload_bytes / t

    return predict
