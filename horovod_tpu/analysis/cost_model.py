"""Static HLO cost model: FLOPs, wire bytes per fabric level, memory
high-water and a roofline step-time prediction — all without hardware.

ROADMAP item 5 asks for a predictive cluster-scale model the autotuner
and the (future) sharding-plan compiler can query before touching a
chip.  Three layers, each usable alone:

1. **Module accounting** — :func:`module_cost` parses a lowered
   StableHLO / compiled-HLO dump (``utils/hlo.py`` parser) into
   countable FLOPs (dot/convolution, fusion bodies included), collective
   wire bytes attributed to the ICI vs DCN fabric level from the
   replica-group structure, and a buffer-lifetime memory high-water
   estimate per device.

2. **Exchange model** — :func:`exchange_wire_bytes` prices the gradient
   exchange per level from the mesh factorization alone: the two-level
   path reduce-scatters the full payload over ICI but crosses DCN with
   only the ``1/n_ici`` partial-sum shard at the (default int8) wire
   width — the quantity ``utils/scaling.py`` now routes through here
   instead of assuming a flat fp32 ring (the MULTICHIP v5e-64
   projections overstated DCN traffic by ``4·n_ici×`` before this).

3. **Calibrated roofline** — :func:`calibrate` fits per-workload-family
   efficiency constants from the checked-in ``BENCH_r0*`` trajectory
   (measured rate ÷ roofline ceiling, most recent artifact wins);
   :func:`predict_rate` / :func:`predict_step_time_s` then predict new
   configurations.  The perf gate (``analysis/perf_gate.py``) and the
   autotune ``predict=`` path (``utils/autotune.py``) consume this.

The module is stdlib-only (plus ``utils/hlo.py``, itself stdlib-only)
so the analysis CLI stays importable without JAX.  Calibration
procedure, roofline assumptions and their failure modes are documented
in ``docs/perf_gate.md``.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import warnings
from typing import Dict, List, Optional, Sequence, Tuple, Union

from horovod_tpu.utils import hlo as H

# -- hardware ---------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class HardwareModel:
    """Per-chip roofline constants for one accelerator generation."""

    name: str
    peak_flops_per_s: float     # bf16 matmul peak
    hbm_bytes_per_s: float      # achievable HBM bandwidth
    ici_bytes_per_s: float      # per-chip ICI link budget
    dcn_bytes_per_s: float      # per-host DCN budget
    #: per-chip HBM capacity — the default feasibility ceiling when
    #: HOROVOD_HBM_BUDGET_BYTES is unset; None = unconstrained (the
    #: pre-memory-plane behavior, docs/memory.md)
    hbm_capacity_bytes: Optional[float] = None

    @staticmethod
    def from_calibration(artifact: Union["os.PathLike", str, Dict]
                         ) -> "HardwareModel":
        """Build a hardware model from a ``bench --calibrate`` artifact
        (path or already-loaded dict; schema in docs/calibration.md).

        The roofline constants come from the *measured* fits: the
        matmul FLOP rate and HBM stream rate directly, the fabric
        bandwidths from the innermost/outermost level's fitted
        reduce-scatter beta (the collective the exchange is built
        from).  HBM capacity cannot be microbenchmarked safely, so it
        is inherited from the builtin preset of the calibrated
        ``device_kind`` (None when the kind is unknown — feasibility
        then falls back to the explicit budget knob)."""
        if not isinstance(artifact, dict):
            with open(os.fspath(artifact)) as f:
                artifact = json.load(f)
        errs = _calibration_schema_errors(artifact)
        if errs:
            raise ValueError(
                "bad calibration artifact: " + "; ".join(errs))
        bw = calibration_level_bandwidths(artifact)
        order = list(artifact["level_order"])
        kind = str(artifact.get("device_kind", ""))
        preset = preset_for_device_kind(kind, warn=False)
        return HardwareModel(
            name=f"calibrated:{kind or 'unknown'}",
            peak_flops_per_s=float(artifact["matmul_flops_per_s"]),
            hbm_bytes_per_s=float(artifact["hbm_bytes_per_s"]),
            ici_bytes_per_s=bw[order[0]],
            dcn_bytes_per_s=bw[order[-1]],
            hbm_capacity_bytes=(preset.hbm_capacity_bytes
                                if preset is not None else None))


#: v5e figures: 197 bf16 TFLOP/s, ~810 GB/s measured HBM
#: (PERF_NOTES.md hardware-envelope round), 1,600 Gbps ICI per chip,
#: ~200 Gbps DCN per host — the same constants docs/scaling.md tables
#: use.  16 GB HBM per chip.
V5E = HardwareModel(name="v5e", peak_flops_per_s=197e12,
                    hbm_bytes_per_s=810e9, ici_bytes_per_s=200e9,
                    dcn_bytes_per_s=25e9, hbm_capacity_bytes=16e9)

#: v5p: 459 bf16 TFLOP/s, ~2.77 TB/s HBM3, 4,800 Gbps ICI per chip,
#: same ~200 Gbps DCN class; 95 GB HBM per chip.
V5P = HardwareModel(name="v5p", peak_flops_per_s=459e12,
                    hbm_bytes_per_s=2765e9, ici_bytes_per_s=600e9,
                    dcn_bytes_per_s=25e9, hbm_capacity_bytes=95e9)

#: v4: 275 bf16 TFLOP/s, ~1.23 TB/s HBM2, 2,400 Gbps ICI per chip;
#: 32 GB HBM per chip.
V4 = HardwareModel(name="v4", peak_flops_per_s=275e12,
                   hbm_bytes_per_s=1228e9, ici_bytes_per_s=300e9,
                   dcn_bytes_per_s=25e9, hbm_capacity_bytes=32e9)

#: The CPU twin: honest-order-of-magnitude figures for the
#: 8-virtual-device host the tier-1 suite runs on.  It exists so
#: ``device_kind``-keyed selection has somewhere loud-warning-free to
#: land off-TPU; pricing paths that *model the target chip* (bench
#: autotune pruning, the perf gate roofline) still default to
#: :data:`V5E` — see :func:`resolve_hardware_model`.
CPU_TWIN = HardwareModel(name="cpu-twin", peak_flops_per_s=1e12,
                         hbm_bytes_per_s=50e9, ici_bytes_per_s=10e9,
                         dcn_bytes_per_s=1e9, hbm_capacity_bytes=None)

#: Builtin presets by name — the ``HOROVOD_HW_PRESET`` vocabulary.
HW_PRESETS: Dict[str, HardwareModel] = {
    "v5e": V5E, "v5p": V5P, "v4": V4, "cpu-twin": CPU_TWIN,
}

#: ``device_kind`` substrings → preset name, checked in order (the
#: first match wins; jax spells v5e as "TPU v5 lite" / "TPU v5e").
_DEVICE_KIND_PRESETS: Tuple[Tuple[str, str], ...] = (
    ("v5 lite", "v5e"), ("v5litepod", "v5e"), ("v5e", "v5e"),
    ("v5p", "v5p"), ("v5", "v5p"),
    ("v4", "v4"),
    ("cpu", "cpu-twin"),
)


def preset_for_device_kind(device_kind: Optional[str],
                           warn: bool = True
                           ) -> Optional[HardwareModel]:
    """The builtin :class:`HardwareModel` for one jax ``device_kind``
    string, or ``None`` for an unrecognized chip — after a loud
    :class:`UserWarning` (``warn=True``): an unknown generation must
    not silently price as v5e (calibrate it instead;
    docs/calibration.md)."""
    kind = (device_kind or "").lower()
    for needle, name in _DEVICE_KIND_PRESETS:
        if needle in kind:
            return HW_PRESETS[name]
    if warn and device_kind:
        warnings.warn(
            f"unrecognized device_kind {device_kind!r}: no builtin "
            f"HardwareModel preset — run `bench --calibrate` and set "
            f"HOROVOD_CALIBRATION_PATH (or force one of "
            f"{sorted(HW_PRESETS)} via HOROVOD_HW_PRESET); pricing "
            f"falls back to v5e constants until then",
            UserWarning, stacklevel=2)
    return None


def resolve_hardware_model(calibration_path: Optional[str] = None,
                           preset: Optional[str] = None,
                           device_kind: Optional[str] = None,
                           default: HardwareModel = V5E
                           ) -> HardwareModel:
    """Resolve THE hardware model every pricing consumer should use,
    with explicit precedence (docs/calibration.md):

    1. a calibration artifact — ``calibration_path`` arg, else the
       ``HOROVOD_CALIBRATION_PATH`` knob (an unreadable/invalid
       explicit artifact raises: measured constants were promised, a
       silent fallback to guesses would un-promise them);
    2. a named preset — ``preset`` arg, else ``HOROVOD_HW_PRESET``
       (unknown names raise, same reasoning);
    3. the builtin preset matching ``device_kind`` (unrecognized kinds
       warn loudly via :func:`preset_for_device_kind` and fall through);
    4. ``default`` (v5e — the historical constants).
    """
    path = calibration_path or os.environ.get("HOROVOD_CALIBRATION_PATH")
    if path:
        try:
            return HardwareModel.from_calibration(path)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            raise ValueError(
                f"HOROVOD_CALIBRATION_PATH={path!r} does not load as a "
                f"calibration artifact: {e}") from e
    name = preset or os.environ.get("HOROVOD_HW_PRESET")
    if name:
        hw = HW_PRESETS.get(name.strip().lower())
        if hw is None:
            raise ValueError(
                f"unknown HOROVOD_HW_PRESET {name!r}: expected one of "
                f"{sorted(HW_PRESETS)}")
        return hw
    if device_kind:
        hw = preset_for_device_kind(device_kind)
        if hw is not None:
            return hw
    return default


# -- calibration-artifact plumbing (the fit side lives in
#    analysis/calibration.py; the consumers here read artifacts
#    directly so the import stays one-way) ----------------------------------


#: Fields every calibration artifact must carry (docs/calibration.md).
CALIBRATION_SCHEMA_VERSION = 1
_CALIBRATION_REQUIRED = (
    "schema_version", "kind", "device_kind", "platform", "n_devices",
    "mesh_shape", "level_order", "levels", "matmul_flops_per_s",
    "hbm_bytes_per_s", "source",
)
#: Identity fields whose digest is the cross-hardware refusal key
#: (perf_gate.check_comparable): two artifacts calibrated on different
#: hardware must never be diffed against each other.
CALIBRATION_IDENTITY_FIELDS = (
    "device_kind", "platform", "n_devices", "mesh_shape",
)


def _calibration_schema_errors(data: Dict) -> List[str]:
    """Schema errors of one calibration-artifact dict ([] = valid).
    The full check (per-level fit fields) lives in
    ``analysis/calibration.validate_calibration``; this is the subset
    the consumers need before trusting the numbers."""
    errs = []
    if not isinstance(data, dict):
        return ["artifact is not a JSON object"]
    for f in _CALIBRATION_REQUIRED:
        if f not in data:
            errs.append(f"missing field {f!r}")
    if errs:
        return errs
    if data["kind"] != "horovod_calibration":
        errs.append(f"kind must be 'horovod_calibration', got "
                    f"{data['kind']!r}")
    if int(data["schema_version"]) > CALIBRATION_SCHEMA_VERSION:
        errs.append(
            f"schema_version {data['schema_version']} is newer than "
            f"this reader ({CALIBRATION_SCHEMA_VERSION})")
    order = data["level_order"]
    if not order or not isinstance(order, (list, tuple)):
        errs.append("level_order must be a non-empty list "
                    "(innermost level first)")
    elif set(order) != set(data["levels"].keys()):
        errs.append(f"level_order {list(order)} does not match levels "
                    f"{sorted(data['levels'])}")
    for val in ("matmul_flops_per_s", "hbm_bytes_per_s"):
        try:
            if float(data[val]) <= 0:
                errs.append(f"{val} must be > 0")
        except (TypeError, ValueError):
            errs.append(f"{val} is not a number")
    return errs


def calibration_fingerprint(data: Dict) -> str:
    """Stable identity digest of one calibration artifact — the value
    bench stamps into ``calibration_fingerprint`` and the perf gate
    refuses to diff across (:data:`CALIBRATION_IDENTITY_FIELDS`)."""
    import hashlib

    ident = {f: data.get(f) for f in CALIBRATION_IDENTITY_FIELDS}
    blob = json.dumps(ident, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def calibration_level_bandwidths(data: Dict) -> Dict[str, float]:
    """Fitted bytes/s per topology level from one calibration artifact:
    the reduce-scatter beta when present (the collective the exchange
    composes), else the first fitted collective at that level."""
    out: Dict[str, float] = {}
    for name in data["level_order"]:
        fits = data["levels"][name].get("collectives", {})
        fit = fits.get("reduce_scatter") or next(iter(fits.values()), None)
        if fit is None:
            raise ValueError(f"calibration level {name!r} carries no "
                             f"collective fits")
        out[name] = float(fit["beta_bytes_per_s"])
    return out


# -- exchange wire bytes per level ------------------------------------------


def _ring_factor(n: int) -> float:
    return (n - 1) / n if n > 1 else 0.0


@dataclasses.dataclass(frozen=True)
class WireBytes:
    """Per-chip bytes on each fabric level for one gradient exchange
    (reduce-scatter + allgather, i.e. one logical allreduce)."""

    ici: float
    dcn: float

    @property
    def total(self) -> float:
        return self.ici + self.dcn


def exchange_wire_bytes(payload_bytes: float,
                        n_dcn: int = 1,
                        n_ici: int = 1,
                        hierarchy: str = "flat",
                        wire_bits_dcn: int = 8,
                        elem_bits: int = 32) -> WireBytes:
    """Price one full gradient exchange per fabric level.

    Both modes decompose hierarchically (XLA lowers multi-slice
    collectives that way; the guards' ``[2,4]<=[8]`` replica groups are
    exactly these two levels): a ring over ``n_ici`` chips inside the
    slice and a ring over ``n_dcn`` slices across hosts, each costing
    ``2·(n−1)/n·(bytes carried)`` per chip.

    * ``flat``: the DCN hop carries the **full** payload at the element
      width — ``2·(n_dcn−1)/n_dcn·B``.
    * ``two_level``: the intra-slice reduce-scatter leaves only the
      ``1/n_ici`` partial-sum shard to cross DCN, quantized to
      ``wire_bits_dcn`` (int8 by default — the PR 2 DCN codec):
      ``2·(n_dcn−1)/n_dcn·(B/n_ici)·(wire/elem)``.  This is the
      correction :mod:`~horovod_tpu.utils.scaling` routes through.
    """
    if hierarchy not in ("flat", "two_level"):
        raise ValueError(f"hierarchy must be flat|two_level, got "
                         f"{hierarchy!r}")
    n_dcn, n_ici = max(1, int(n_dcn)), max(1, int(n_ici))
    if hierarchy == "flat":
        # single scope, decomposed per fabric with the FULL payload on
        # both hops — NOT a hierarchical tree (no per-level shrink)
        return WireBytes(
            ici=2.0 * _ring_factor(n_ici) * payload_bytes,
            dcn=2.0 * _ring_factor(n_dcn) * payload_bytes)
    # two_level IS the 2-deep degenerate tree: full precision inside,
    # the wire codec on the outermost (slowest) hop
    by_level = exchange_wire_by_level(
        payload_bytes,
        (("ici", n_ici, None), ("dcn", n_dcn, wire_bits_dcn)),
        elem_bits=elem_bits)
    return WireBytes(ici=by_level["ici"], dcn=by_level["dcn"])


#: Level spec accepted by the tree pricers: ``(name, extent)`` or
#: ``(name, extent, wire_bits|None)`` tuples, innermost level FIRST
#: (chip < slice < pod < cluster) — or any object with ``.name`` /
#: ``.extent`` / ``.wire_bits`` attributes (``runtime/topology.
#: TopologyLevel`` duck-types in without this module importing JAX).
LevelSpec = Sequence


def _level_triples(levels: LevelSpec
                   ) -> List[Tuple[str, int, Optional[int]]]:
    out = []
    for lv in levels:
        if hasattr(lv, "name") and hasattr(lv, "extent"):
            out.append((str(lv.name), max(1, int(lv.extent)),
                        getattr(lv, "wire_bits", None)))
        else:
            name, extent = lv[0], lv[1]
            bits = lv[2] if len(lv) > 2 else None
            out.append((str(name), max(1, int(extent)), bits))
    if not out:
        raise ValueError("level tree must have at least one level")
    return out


def exchange_wire_by_level(payload_bytes: float,
                           levels: LevelSpec,
                           elem_bits: int = 32) -> Dict[str, float]:
    """Price one hierarchical gradient exchange over an arbitrary
    N-level topology tree — per-level per-chip bytes, keyed by level
    name.

    ``levels`` is innermost-first (:data:`LevelSpec`).  Level ℓ
    reduce-scatters (and later all-gathers) the block surviving the
    inner levels — ``payload / ∏ inner extents`` — around its own ring
    at its configured wire width:
    ``2·(nℓ−1)/nℓ·(B/∏inner)·(bitsℓ/elem)``.  A 2-level
    ``(ici, dcn)`` tree reproduces :func:`exchange_wire_bytes`'s
    ``two_level`` numbers exactly (the degenerate-tree pin
    ``tests/test_calibration.py`` holds)."""
    out: Dict[str, float] = {}
    inner = 1
    for name, extent, bits in _level_triples(levels):
        width = (bits if bits else elem_bits) / elem_bits
        out[name] = (2.0 * _ring_factor(extent)
                     * (float(payload_bytes) / inner) * width)
        inner *= extent
    return out


def level_bandwidths(levels: LevelSpec,
                     hw: HardwareModel = V5E) -> Dict[str, float]:
    """Default bytes/s per level when no calibration artifact supplies
    measured ones: the innermost level rides ICI, every outer hop the
    DCN budget (the conservative choice — a middle fabric is at least
    as fast as the slowest one).  A calibrated model replaces this via
    :func:`calibration_level_bandwidths`."""
    triples = _level_triples(levels)
    return {name: (hw.ici_bytes_per_s if i == 0 else hw.dcn_bytes_per_s)
            for i, (name, _, _) in enumerate(triples)}


def exchange_time_by_level(wire_by_level: Dict[str, float],
                           bandwidths: Dict[str, float]) -> float:
    """Serial wire seconds of an N-level exchange: each level at its
    own fabric bandwidth (levels cannot overlap each other — level
    ℓ+1 consumes level ℓ's output, exactly like
    :func:`exchange_time_s`).  ``bandwidths`` maps level name →
    bytes/s (:func:`level_bandwidths` or a calibration artifact's
    :func:`calibration_level_bandwidths`)."""
    t = 0.0
    for name, b in wire_by_level.items():
        bw = bandwidths.get(name)
        if bw is None or bw <= 0:
            raise ValueError(f"no bandwidth for level {name!r}")
        t += b / bw
    return t


def exchange_time_s(wire: WireBytes, hw: HardwareModel = V5E) -> float:
    """Serial wire time of one exchange: each level at its own fabric
    bandwidth (the levels cannot overlap each other — the DCN phase
    consumes the ICI phase's output)."""
    return wire.ici / hw.ici_bytes_per_s + wire.dcn / hw.dcn_bytes_per_s


#: Tile count of the tile-fused exchange schedule — mirrors
#: ``ops.collectives.FUSED_TAIL_TILES`` (this module stays stdlib-only,
#: so the constant is duplicated by value; docs/fused_kernels.md).
FUSED_TILE_COUNT = 4


def fused_tail_exchange_s(wire_s: float, compute_s: float,
                          n_tiles: int = FUSED_TILE_COUNT) -> float:
    """Overlap-aware roofline of the tile-fused exchange
    (docs/fused_kernels.md): with the wire split into ``n_tiles``
    sub-exchanges interleaved with per-tile compute, tile *k*'s
    transfer hides under tile *k+1*'s work — only the FIRST tile's
    share (``wire/n_tiles``, nothing precedes it) plus whatever wire
    exceeds the available compute stays exposed.  ``n_tiles <= 1`` is
    the unfused serial tail: the whole ``wire_s`` exposed.  This is
    the ceiling the autotuner uses to prune the
    ``fused_collectives`` axis without hardware
    (:func:`score_exchange_schedule`)."""
    wire_s = max(0.0, float(wire_s))
    if n_tiles <= 1 or wire_s == 0.0:
        return wire_s
    startup = wire_s / n_tiles
    return startup + max(0.0, wire_s - max(0.0, float(compute_s)))


# -- adasum reduction-operator pricing --------------------------------------


#: Statistical-efficiency credit of the adasum operator, as a fraction
#: of per-step compute seconds.  AdaSum buys nothing at a fixed batch —
#: it strictly *adds* wire (the dot/norm pairwise exchange below) — its
#: value is that it holds the loss trajectory at 2–4× the global batch
#: where plain sum degrades (docs/adasum.md, the pinned convergence
#: test).  The autotuner's objective is throughput at the sampled
#: batch, so the model books the batch-scaling headroom as a credit
#: proportional to compute seconds: compute_s grows linearly with the
#: per-chip batch while the exchange wire does not, which is exactly
#: what makes the ``reduction`` axis flip to adasum only above a batch
#: crossover — small batches never pay the extra DCN round.
ADASUM_COMPUTE_CREDIT_FRACTION = 0.05


def adasum_extra_wire_bytes(payload_bytes: float,
                            n_dcn: int = 1,
                            n_ici: int = 1) -> float:
    """Extra per-chip DCN bytes the adasum outer-level exchange moves
    *beyond* the plain ring reduce-scatter it replaces.

    The operator is pairwise and order-sensitive, so the outer level
    cannot ring-RS 1/n-sized shards: it runs a recursive-halving
    doubling schedule (``ops.collectives._adasum_psum_scatter``) that
    ppermutes the **full** inner-reduced block every round —
    ``⌈log2(n_dcn)⌉ · (payload/n_ici)`` per chip, each round carrying
    the operands the per-pair fp32 dot/norms are computed from (the
    "extra dot/norm round" is this full-block traffic; the scalar
    coefficients themselves ride along for free).  The ring RS it
    displaces would have moved ``(n_dcn−1)/n_dcn`` of the same block,
    so the extra is the difference, floored at 0.  ``n_dcn <= 1``
    prices 0: a single-slice world degenerates adasum to plain sum
    bit-for-bit and the schedule never engages."""
    n_dcn, n_ici = max(1, int(n_dcn)), max(1, int(n_ici))
    if n_dcn <= 1:
        return 0.0
    block = float(payload_bytes) / n_ici
    rounds = math.ceil(math.log2(n_dcn))
    return max(0.0, (rounds - _ring_factor(n_dcn)) * block)


# -- parallelism-plan pricing -----------------------------------------------


#: Plan-grammar keys — mirrors ``parallel/plan.PLAN_KEYS`` (this module
#: stays stdlib-only, so the grammar is duplicated by value like
#: :data:`FUSED_TILE_COUNT`; ``v`` is the interleaved-1F1B
#: virtual-stage count).
PLAN_GRAMMAR_KEYS = ("dp", "pp", "fsdp", "ep", "sp", "tp", "v")

#: Microbatch count the plan scorer assumes when the caller does not
#: pin one — matches the bench pipeline probe's default depth.
PLAN_SCORE_MICROBATCHES = 8

#: Wire bits per ``HOROVOD_EXCHANGE_WIRE_DTYPE`` value — the
#: ``wire_dtype`` autotune axis's pricing table (fp32 = no wire
#: compression; int8 and fp8_e4m3 both move one byte per element, so
#: the model ranks them identically and the measurement breaks the
#: tie).
WIRE_DTYPE_BITS = {"fp32": 32, "int8": 8, "fp8_e4m3": 8}


def parse_plan(plan: Union[str, Dict]) -> Dict[str, int]:
    """Parse the ``HOROVOD_PLAN`` grammar into a full extent dict
    (every :data:`PLAN_GRAMMAR_KEYS` key, absent axes at 1).  The
    stdlib mirror of ``parallel/plan.ShardingPlan.from_string`` for the
    analysis layer; ``dp=?`` (an unresolved plan string) prices as
    ``dp=1``."""
    if isinstance(plan, dict):
        ext = dict(plan)
    else:
        ext = {}
        for item in str(plan).split(","):
            item = item.strip()
            if not item:
                continue
            key, sep, val = item.partition("=")
            key = key.strip()
            if not sep or key not in PLAN_GRAMMAR_KEYS:
                raise ValueError(
                    f"bad plan term {item!r}: expected axis=extent "
                    f"with axis in {', '.join(PLAN_GRAMMAR_KEYS)}")
            if key in ext:
                raise ValueError(f"duplicate plan axis {key!r} in "
                                 f"{plan!r}")
            v = val.strip()
            ext[key] = 1 if v == "?" else int(v)
    out = {}
    for k in PLAN_GRAMMAR_KEYS:
        raw = ext.get(k)
        v = 1 if raw is None else int(raw)
        if v < 1:
            raise ValueError(f"plan axis {k} must be >= 1, got {v}")
        out[k] = v
    return out


def pipeline_bubble_fraction(stages: int, microbatches: int,
                             virtual_stages: int = 1) -> float:
    """Idle share of the pipeline schedule, ``(s-1)/(v*m+s-1)`` —
    mirrors ``parallel/pipeline.bubble_fraction`` by value (GPipe at
    ``v=1``, interleaved-1F1B at ``v>1``; docs/parallelism.md)."""
    stages, microbatches = max(1, int(stages)), max(1, int(microbatches))
    virtual_stages = max(1, int(virtual_stages))
    return (stages - 1) / (virtual_stages * microbatches + stages - 1)


def plan_exchange_wire_bytes(plan: Union[str, Dict],
                             payload_bytes: float,
                             n_dcn: int = 1,
                             n_ici: int = 1,
                             wire_bits_dcn: int = 8,
                             topology: Optional[LevelSpec] = None
                             ) -> Union[WireBytes, Dict[str, float]]:
    """Gradient-exchange wire bytes under a parallelism plan.

    The model axes (pp/ep/sp/tp) shard the parameters, so each data
    replica exchanges only ``payload / model_extent`` bytes.  The data
    axes (dp × fsdp) then map onto the fabric DCN-outer/ICI-inner
    (the ``AXIS_ORDER`` layout ``parallel/mesh.py`` realizes): ``dp``
    absorbs the DCN extent first, the remainder rides ICI, and the
    exchange goes two-level exactly when both derived extents exceed
    1 — the same decision ``resolve_hierarchy`` makes at trace time.

    ``topology`` (an innermost-first :data:`LevelSpec` whose extents
    factor the plan's data world) prices the exchange over that
    N-level tree instead and changes the return to the per-level dict
    of :func:`exchange_wire_by_level` — the pricing the N-level
    resolved topology (``runtime/topology.resolve_topology``) feeds
    in; the 2-level default keeps the :class:`WireBytes` contract.
    """
    ext = parse_plan(plan)
    model = ext["pp"] * ext["ep"] * ext["sp"] * ext["tp"]
    per_replica = float(payload_bytes) / max(1, model)
    data_world = ext["dp"] * ext["fsdp"]
    if topology is not None:
        triples = _level_triples(topology)
        tree_world = 1
        for _, extent, _ in triples:
            tree_world *= extent
        if tree_world != data_world:
            raise ValueError(
                f"topology world {tree_world} does not factor the "
                f"plan's data world {data_world} "
                f"(dp={ext['dp']}, fsdp={ext['fsdp']})")
        return exchange_wire_by_level(per_replica, triples)
    d_dcn = min(ext["dp"], max(1, int(n_dcn)))
    while data_world % d_dcn:
        d_dcn -= 1
    d_ici = max(1, data_world // d_dcn)
    hierarchy = "two_level" if d_dcn > 1 and d_ici > 1 else "flat"
    return exchange_wire_bytes(per_replica, n_dcn=d_dcn, n_ici=d_ici,
                               hierarchy=hierarchy,
                               wire_bits_dcn=wire_bits_dcn)


def plan_cost_s(plan: Union[str, Dict],
                payload_bytes: float,
                n_dcn: int = 1,
                n_ici: int = 1,
                compute_s: float = 0.0,
                microbatches: int = PLAN_SCORE_MICROBATCHES,
                hw: HardwareModel = V5E,
                wire_bits_dcn: int = 8,
                reduction: str = "sum") -> float:
    """Predicted per-step seconds of one plan: compute stretched by the
    pipeline bubble (``t / (1 - bubble)`` — the idle ticks are pure
    loss) plus the serial wire time of the plan-scoped gradient
    exchange.  The quantity ``ThroughputAutotuner(predict=)`` ranks the
    ``plan`` axis with (:func:`score_exchange_schedule`), and the
    1F1B-beats-GPipe acceptance check reads straight off: same plan
    with ``v>1`` has a strictly smaller bubble term.
    ``reduction="adasum"`` adds the outer-level dot/norm round's extra
    DCN wire time (:func:`adasum_extra_wire_bytes`, priced under the
    plan's derived dp factorization) — a pure step-time penalty here;
    the batch-scaling *credit* lives in the ranking-side
    :func:`score_exchange_schedule`, not in the honest per-step
    clock."""
    ext = parse_plan(plan)
    bubble = 0.0
    if ext["pp"] > 1:
        bubble = pipeline_bubble_fraction(ext["pp"], microbatches,
                                          ext["v"])
    wire = plan_exchange_wire_bytes(plan, payload_bytes, n_dcn=n_dcn,
                                    n_ici=n_ici,
                                    wire_bits_dcn=wire_bits_dcn)
    t = float(compute_s) / (1.0 - bubble) + exchange_time_s(wire, hw)
    if reduction == "adasum":
        model = ext["pp"] * ext["ep"] * ext["sp"] * ext["tp"]
        per_replica = float(payload_bytes) / max(1, model)
        data_world = ext["dp"] * ext["fsdp"]
        d_dcn = min(ext["dp"], max(1, int(n_dcn)))
        while data_world % d_dcn:
            d_dcn -= 1
        d_ici = max(1, data_world // d_dcn)
        t += adasum_extra_wire_bytes(per_replica, n_dcn=d_dcn,
                                     n_ici=d_ici) / hw.dcn_bytes_per_s
    return t


def rank_plans(plans: Sequence[Union[str, Dict]],
               payload_bytes: float,
               n_dcn: int = 1,
               n_ici: int = 1,
               compute_s: float = 0.0,
               microbatches: int = PLAN_SCORE_MICROBATCHES,
               hw: HardwareModel = V5E,
               wire_bits_dcn: int = 8
               ) -> List[Tuple[float, Union[str, Dict]]]:
    """Score each plan with :func:`plan_cost_s` and return
    ``(cost_s, plan)`` pairs sorted cheapest-first.  The sort is
    stable, so a caller that pre-orders its candidates by preference
    (``ShardingPlan.degrade_candidates`` puts dp-shrink before
    fsdp-shrink at equal world size) gets that preference as the
    tie-break for free."""
    scored = [(plan_cost_s(p, payload_bytes, n_dcn=n_dcn, n_ici=n_ici,
                           compute_s=compute_s,
                           microbatches=microbatches, hw=hw,
                           wire_bits_dcn=wire_bits_dcn), p)
              for p in plans]
    scored.sort(key=lambda cp: cp[0])
    return scored


# -- plan memory: the HBM roofline ------------------------------------------


#: Remat policy vocabulary — mirrors ``memory/remat.REMAT_POLICIES`` by
#: value (this module stays stdlib-only, like :data:`PLAN_GRAMMAR_KEYS`).
REMAT_POLICIES = ("none", "dots", "full", "offload")

#: Share of the remat-none activation footprint still resident in HBM
#: under each policy.  Calibrated against the linear-scan
#: ``utils/hlo.memory_high_water`` estimate of the CPU-twin
#: transformer's compiled dumps (the same estimator
#: :func:`plan_memory_bytes` is validated against, so model and
#: measurement share one definition of "activation bytes"): ``full``
#: keeps the per-block backward-recompute peak plus the saved block
#: inputs; ``dots`` additionally keeps every matmul output; ``offload``
#: streams the dots residuals to pinned host memory, leaving roughly
#: the ``full`` residency on-device.
REMAT_ACTIVATION_FRACTION = {
    "none": 1.0, "dots": 0.82, "full": 0.31, "offload": 0.41,
}

#: Fractional step-time penalty of the policy's recomputation (plus,
#: for ``offload``, the un-hidden share of the D2H/H2D stream):
#: ``dots`` replays the cheap elementwise ops only, ``full`` replays
#: each block's forward (~1/3 of the fwd+bwd budget).
REMAT_RECOMPUTE_OVERHEAD = {
    "none": 0.0, "dots": 0.12, "full": 0.30, "offload": 0.34,
}

#: Resident share of an offloaded optimizer shard **during the step
#: window**: 1.0 — no high-water credit.  The streaming engine
#: (``memory/offload.py``) retains the device reference until
#: ``fetch()`` (the degrade contract) and ``fetch()`` restores the
#: whole shard to device *before* the step that consumes it, so the
#: per-step HBM high-water still holds the full shard; the host
#: round-trip only parks it between steps.  Charging less would let
#: the budgeted planner call configs feasible that OOM in practice —
#: ``bench.py --hbm-budget`` validates the offload=True prediction
#: against the measured high-water to keep this honest.  An engine
#: that streamed slot *buckets* through the update phase could earn a
#: fraction < 1 here; until one exists, offload is HBM-neutral in the
#: roofline and the planner never profits from it.
OFFLOAD_RESIDENT_FRACTION = 1.0


@dataclasses.dataclass(frozen=True)
class MemoryBytes:
    """Per-device HBM high-water decomposition of one plan — the four
    components the budget trades against each other, plus the exchange
    staging.  ``tightest`` names the dominant component, the axis an
    infeasibility error points at (``memory/planner.py``).

    MoE plans add two components (both 0.0 for dense models):
    ``expert_params`` — the per-device expert-parameter shard (their
    grads/optimizer slots fold into ``grads``/``optimizer``) — and
    ``moe_buffers``, the static ``(E, C, d)`` dispatch + combine
    capacity buckets, which are ``ep``-invariant per device (each chip
    always stages ``E·C·d`` slots: all experts' slots before the
    exchange, or ``ep`` source tiles of its ``E/ep`` experts after)."""

    params: float
    grads: float
    optimizer: float
    activations: float
    exchange: float
    expert_params: float = 0.0
    moe_buffers: float = 0.0

    @property
    def total(self) -> float:
        return (self.params + self.grads + self.optimizer
                + self.activations + self.exchange
                + self.expert_params + self.moe_buffers)

    @property
    def tightest(self) -> str:
        """Name of the largest component (deterministic field-order
        tie-break)."""
        return max(dataclasses.asdict(self).items(),
                   key=lambda kv: (kv[1], kv[0]))[0]


def plan_memory_bytes(plan: Union[str, Dict], *,
                      param_bytes: float,
                      activation_bytes: float,
                      remat_policy: str = "none",
                      microbatches: int = 1,
                      optimizer_slots: int = 2,
                      shard_optimizer_states: bool = False,
                      offload_optimizer: bool = False,
                      exchange_bucket_bytes: Optional[float] = None,
                      expert_param_bytes: float = 0.0,
                      moe_capacity_buffer_bytes: float = 0.0
                      ) -> MemoryBytes:
    """Predicted per-device HBM high-water of one plan — the memory
    twin of :func:`plan_cost_s`, and the quantity the feasibility
    predicate (:func:`plan_fits`) holds under ``HOROVOD_HBM_BUDGET_BYTES``.

    Inputs are *unsharded single-replica* quantities: ``param_bytes``
    the whole model's parameters, ``activation_bytes`` the whole
    network's activation footprint for one device's batch shard at
    ``remat_policy="none"`` and ``microbatches=1``.  The plan then
    shards them:

    * params/grads divide over the parameter-sharding axes
      (``tp·pp·ep·fsdp`` — ``ep`` idealized as sharding every layer,
      ``sp`` replicates parameters);
    * optimizer state is ``optimizer_slots`` × the param shard,
      further ÷ ``dp`` under the ZeRO sharded exchange;
      ``offload_optimizer`` charges
      :data:`OFFLOAD_RESIDENT_FRACTION` = 1.0 of it — host streaming
      parks the shard *between* steps but restores it whole before the
      step (``memory/offload.py``), so it buys no step-window
      high-water;
    * activations scale by the policy's residency fraction
      (:data:`REMAT_ACTIVATION_FRACTION`), divide over ``sp`` and the
      microbatch count, and a pipeline holds ``min(pp, m)`` in-flight
      microbatches of its ``1/pp`` layer slice (the 1F1B steady
      state);
    * exchange staging is the double-buffered bucket pair when the
      bucketed exchange is on, else one grad-shard-sized fused buffer
      whenever a data axis exists;
    * ``expert_param_bytes`` (MoE plans: the expert FFN weights, which
      ``ep`` *actually* shards — pass the dense remainder as
      ``param_bytes``) divides over the same ``tp·pp·ep·fsdp`` axes,
      with grads and optimizer slots folded into those components;
      ``moe_capacity_buffer_bytes`` (the static dispatch + combine
      ``(E, C, d)`` buckets, already per-device and ``ep``-invariant:
      ``2·E·C·d·elem_bytes``) is charged as-is.

    Validated against ``utils/hlo.memory_high_water`` on compiled
    CPU-twin dumps by ``bench.py --hbm-budget`` (within 25%;
    docs/memory.md lists the approximations).
    """
    if remat_policy not in REMAT_POLICIES:
        raise ValueError(
            f"unknown remat policy {remat_policy!r}: expected one of "
            f"{', '.join(REMAT_POLICIES)}")
    ext = parse_plan(plan)
    microbatches = max(1, int(microbatches))
    param_shard_axes = ext["tp"] * ext["pp"] * ext["ep"] * ext["fsdp"]
    params = float(param_bytes) / param_shard_axes
    expert_params = float(expert_param_bytes) / param_shard_axes
    grads = params + expert_params
    optimizer = max(0, int(optimizer_slots)) * (params + expert_params)
    if shard_optimizer_states:
        optimizer /= ext["dp"]
    if offload_optimizer:
        optimizer *= OFFLOAD_RESIDENT_FRACTION
    frac = REMAT_ACTIVATION_FRACTION[remat_policy]
    act_per_mb = float(activation_bytes) * frac \
        / (microbatches * ext["sp"])
    in_flight = min(ext["pp"], microbatches)
    activations = act_per_mb / ext["pp"] * in_flight
    data_world = ext["dp"] * ext["fsdp"]
    if exchange_bucket_bytes:
        exchange = 2.0 * float(exchange_bucket_bytes)
    else:
        exchange = grads if data_world > 1 else 0.0
    return MemoryBytes(params=params, grads=grads, optimizer=optimizer,
                       activations=activations, exchange=exchange,
                       expert_params=expert_params,
                       moe_buffers=float(moe_capacity_buffer_bytes))


def plan_fits(mem: Union[MemoryBytes, float],
              budget_bytes: Optional[float] = None,
              hw: HardwareModel = V5E) -> bool:
    """Feasibility predicate: does the predicted high-water fit the
    budget?  ``budget_bytes`` (the HOROVOD_HBM_BUDGET_BYTES knob) rules
    when given; otherwise the hardware model's capacity; no capacity
    anywhere = everything fits (the pre-memory-plane behavior)."""
    total = mem.total if isinstance(mem, MemoryBytes) else float(mem)
    cap = budget_bytes if budget_bytes is not None \
        else hw.hbm_capacity_bytes
    if cap is None:
        return True
    return total <= float(cap)


def score_exchange_schedule(point: Dict,
                            payload_bytes: float,
                            n_dcn: int = 1,
                            n_ici: int = 1,
                            compute_s: float = 0.0,
                            hw: HardwareModel = V5E,
                            n_tiles: int = FUSED_TILE_COUNT,
                            sp_attn_wire_s: float = 0.0,
                            sp_attn_compute_s: float = 0.0
                            ) -> Optional[float]:
    """Rank one autotune sample point by its predicted *exposed*
    exchange seconds (negated — higher is better, matching the
    measured-rate objective).  ``point`` is a bench-autotuner sample
    (``{"hierarchy": ..., "fused_collectives": ..., "wire_dtype": ...,
    "plan": ..., ...}``); knobs the exchange model does not price
    (steps_per_call, flash_block, bucket cap) leave the score
    unchanged, so per-axis scans of those knobs see constant scores
    and stay fully measured.  ``wire_dtype`` prices the codec width
    (:data:`WIRE_DTYPE_BITS`): the DCN hop in two_level, the whole
    single-scope wire in flat (the flat quantized path compresses ICI
    too).  A ``plan`` knob reprices the exchange under that plan's
    factorization and adds the pipeline bubble penalty
    (:func:`plan_cost_s`); a plan with ``sp>1`` additionally charges
    the attention K/V ring — ``sp_attn_wire_s``/``sp_attn_compute_s``
    (from :func:`sp_ring_wire_bytes` / :func:`sp_attention_compute_s`,
    priced for sp=1 by the caller and rescaled here to the sampled
    extent) exposed per :func:`sp_ring_exposed_s`, fused when the
    point's ``fused_collectives`` is ``"on"`` — the fused-vs-unfused
    ring the dp×sp autotune prunes on.  A ``reduction`` knob
    (``"sum"`` | ``"adasum"``) charges the adasum outer-level exchange
    its extra DCN wire (:func:`adasum_extra_wire_bytes`) and credits
    its batch-scaling headroom
    (:data:`ADASUM_COMPUTE_CREDIT_FRACTION` × ``compute_s``) — since
    ``compute_s`` grows with the per-chip batch and the wire penalty
    does not, the axis flips to adasum only above a batch crossover.
    Returns ``None`` when the
    point carries no
    exchange knob at all — the caller then skips pruning entirely (the
    ParameterManager ``predict=`` contract: a predictor that cannot
    rank must not narrow the grid)."""
    hierarchy = point.get("hierarchy")
    fused = point.get("fused_collectives")
    wire_dtype = point.get("wire_dtype")
    plan = point.get("plan")
    reduction = point.get("reduction")
    if hierarchy is None and fused is None and wire_dtype is None \
            and plan is None and reduction is None:
        return None

    def _with_reduction(score: float) -> float:
        if reduction != "adasum":
            return score
        extra_s = adasum_extra_wire_bytes(
            float(payload_bytes), n_dcn=n_dcn, n_ici=n_ici) \
            / hw.dcn_bytes_per_s
        return (score - extra_s
                + ADASUM_COMPUTE_CREDIT_FRACTION * float(compute_s))

    wire_bits = WIRE_DTYPE_BITS.get(wire_dtype, 8)
    if plan is not None:
        ext = parse_plan(plan)
        bubble = 0.0
        if ext["pp"] > 1:
            bubble = pipeline_bubble_fraction(
                ext["pp"], PLAN_SCORE_MICROBATCHES, ext["v"])
        wire = plan_exchange_wire_bytes(plan, float(payload_bytes),
                                        n_dcn=n_dcn, n_ici=n_ici,
                                        wire_bits_dcn=wire_bits)
        exch = exchange_time_s(wire, hw)
        if fused == "on":
            exch = fused_tail_exchange_s(exch, compute_s, n_tiles)
        sp_cost = 0.0
        if ext["sp"] > 1 and (sp_attn_wire_s or sp_attn_compute_s):
            # inputs are the sp=1 (whole-sequence, one-chip) quantities:
            # wire = seconds to move the full K+V once at ICI rate,
            # compute = the full t_global² attention; the sampled sp
            # extent rescales them — per-chip ring wire is the
            # (sp−1)/sp ring factor of the full volume, per-chip
            # compute divides by sp (each rank owns t_global/sp queries)
            sp_w = float(sp_attn_wire_s) * _ring_factor(ext["sp"])
            sp_c = float(sp_attn_compute_s) / ext["sp"]
            sp_cost = sp_c + sp_ring_exposed_s(
                sp_w, sp_c, ext["sp"], fused=(fused == "on"))
        # penalty form of the bubble stretch: the constant compute_s
        # offset cancels in the ranking
        return _with_reduction(
            -(float(compute_s) * bubble / (1.0 - bubble) + exch
              + sp_cost))
    hierarchy = hierarchy if hierarchy in ("flat", "two_level") else "flat"
    wire = exchange_wire_bytes(float(payload_bytes), n_dcn=n_dcn,
                               n_ici=n_ici, hierarchy=hierarchy,
                               wire_bits_dcn=wire_bits)
    if hierarchy == "flat" and wire_dtype in ("int8", "fp8_e4m3"):
        # flat quantization compresses the single-scope wire everywhere
        wire = WireBytes(ici=wire.ici * wire_bits / 32.0,
                         dcn=wire.dcn * wire_bits / 32.0)
    serial = exchange_time_s(wire, hw)
    if fused == "on":
        return _with_reduction(
            -fused_tail_exchange_s(serial, compute_s, n_tiles))
    return _with_reduction(-serial)


# -- sequence-parallel (sp ring) pricing ------------------------------------


def sp_ring_wire_bytes(seq_local: int, heads: int, head_dim: int,
                       sp: int, batch: int = 1,
                       elem_bits: int = 32) -> float:
    """Per-chip K/V ring wire bytes of one sp attention forward.

    Each of the ``sp−1`` ring hops moves this chip's K *and* V block
    (``b·t_local·h·d`` elements each):
    ``2·(sp−1)·b·t_local·h·d·elem_bytes``.  The fused ring-flash path
    moves exactly the same bytes as the jnp formulation — fusion
    changes the *exposure* (:func:`sp_ring_exposed_s`), never the
    volume — so this is the honest wire gauge for both schedules.
    ``sp <= 1`` prices 0 (the sequence is local, nothing crosses the
    wire)."""
    sp = max(1, int(sp))
    if sp == 1:
        return 0.0
    block = (max(1, int(batch)) * int(seq_local) * int(heads)
             * int(head_dim) * (elem_bits / 8.0))
    return 2.0 * (sp - 1) * block


def sp_attention_compute_s(seq_global: int, heads: int, head_dim: int,
                           sp: int, batch: int = 1,
                           causal: bool = False,
                           hw: HardwareModel = V5E) -> float:
    """Per-chip attention forward seconds under ``sp``-way sequence
    parallelism: the full ``4·b·t_global²·h·d`` FLOPs (QKᵀ + PV, two
    FLOPs per MAC) divide evenly over the sp ranks — each rank's
    ``t_global/sp`` queries visit every K/V block exactly once around
    the ring.  ``causal`` halves the live score area (under the zigzag
    layout the halving is per-rank exact; under the contiguous layout
    it holds in aggregate while the per-rank work skews — see
    ``ops.pallas_kernels.ring_step_schedule``)."""
    flops = (4.0 * max(1, int(batch)) * float(seq_global) ** 2
             * int(heads) * int(head_dim)) / max(1, int(sp))
    if causal:
        flops *= 0.5
    return flops / hw.peak_flops_per_s


def sp_ring_exposed_s(wire_s: float, compute_s: float, sp: int,
                      fused: bool = True) -> float:
    """Exposed (un-overlapped) seconds of the sp K/V ring: the fused
    ring-flash path pre-issues the next block's ``ppermute`` before
    the current block's flash kernel, so hop *k* hides under block
    *k*'s compute — the serial-tail credit is exactly
    :func:`fused_tail_exchange_s` with the ring's ``sp`` steps as
    tiles; unfused (the jnp scan), every hop sits serially between
    steps and the whole wire is exposed."""
    if not fused:
        return max(0.0, float(wire_s))
    return fused_tail_exchange_s(wire_s, compute_s,
                                 n_tiles=max(1, int(sp)))


# -- MoE expert-dispatch pricing --------------------------------------------


def moe_capacity(tokens: int, num_experts: int,
                 capacity_factor: float = 1.25) -> int:
    """Per-expert capacity bucket, ``max(1, ceil(cf·tokens/E))`` —
    mirrors ``parallel/expert.expert_parallel_ffn`` by value (this
    module stays stdlib-only, like :data:`PLAN_GRAMMAR_KEYS`)."""
    tokens, num_experts = max(1, int(tokens)), max(1, int(num_experts))
    return int(max(1, -(-float(capacity_factor) * tokens
                        // num_experts)))


def moe_dispatch_wire_bytes(tokens: int, d_model: int, num_experts: int,
                            ep: int, capacity_factor: float = 1.25,
                            elem_bits: int = 32,
                            capacity: Optional[int] = None) -> float:
    """Per-chip wire bytes of one MoE dispatch + combine exchange.

    Each of the ``ep−1`` ring hops moves one ``(E/ep, C, d)`` source
    tile, in both directions (route → expert, expert output → origin):
    ``2·(ep−1)·(E/ep)·C·d·elem_bytes``.  The boundary-wide
    ``all_to_all`` moves exactly the same bytes (each chip ships
    ``ep−1`` of its ``ep`` tiles, twice) — the fused ring changes the
    *exposure* (:func:`moe_dispatch_exposed_s`), never the volume, so
    this is the honest ``hvd_moe_ep_wire_bytes`` gauge for both
    schedules.  ``tokens`` is the per-chip token count; ``ep <= 1``
    prices 0 (local experts, nothing crosses the wire)."""
    ep = max(1, int(ep))
    if ep == 1:
        return 0.0
    if capacity is None:
        capacity = moe_capacity(tokens, num_experts, capacity_factor)
    e_local = max(1, int(num_experts) // ep)
    tile = e_local * int(capacity) * int(d_model) * (elem_bits / 8.0)
    return 2.0 * (ep - 1) * tile


def moe_expert_compute_s(tokens: int, d_model: int, d_ff: int,
                         num_experts: int, ep: int,
                         capacity_factor: float = 1.25,
                         hw: HardwareModel = V5E,
                         capacity: Optional[int] = None) -> float:
    """Per-chip expert-FFN forward seconds: ``E/ep`` local experts each
    process up to ``ep·C`` routed slots through the two ``d×d_ff``
    matmuls (``4·d·d_ff`` FLOPs per slot).  The compute the fused ring
    hides hops under — and the term that grows linearly with the
    ``capacity_factor`` autotune axis."""
    ep = max(1, int(ep))
    if capacity is None:
        capacity = moe_capacity(tokens, num_experts, capacity_factor)
    e_local = max(1, int(num_experts) // ep)
    flops = e_local * ep * int(capacity) * 4.0 * int(d_model) * int(d_ff)
    return flops / hw.peak_flops_per_s


def moe_dispatch_exposed_s(wire_s: float, compute_s: float, ep: int,
                           fused: bool = True) -> float:
    """Exposed (un-overlapped) seconds of the dispatch + combine
    exchange: the fused ``a2a ⊗ expert-matmul`` ring streams one tile
    per hop while the previous tile's expert matmul computes, so the
    serial-tail credit is exactly :func:`fused_tail_exchange_s` with
    the ring's ``ep`` tiles; unfused, the whole boundary-wide
    ``all_to_all`` wire is exposed (nothing overlaps it)."""
    if not fused:
        return max(0.0, float(wire_s))
    return fused_tail_exchange_s(wire_s, compute_s,
                                 n_tiles=max(1, int(ep)))


def score_moe_schedule(point: Dict, *,
                       tokens: int,
                       d_model: int,
                       d_ff: int,
                       num_experts: int,
                       ep: int = 1,
                       fused: bool = True,
                       hw: HardwareModel = V5E,
                       elem_bits: int = 32) -> Optional[float]:
    """Rank one MoE autotune sample point (``{"capacity_factor": ...}``
    and/or ``{"tokens_per_expert": ...}``) by its predicted per-step
    MoE seconds, negated — the ``bench --autotune`` pruning twin of
    :func:`score_exchange_schedule` for the routing axes.
    ``tokens_per_expert`` sets the nominal per-expert workload (scaled
    by ``capacity_factor`` slack when both are sampled);
    ``capacity_factor`` alone derives it via :func:`moe_capacity`.
    Returns
    ``None`` when the point carries neither knob (the ``predict=``
    contract: a predictor that cannot rank must not narrow the
    grid)."""
    cf = point.get("capacity_factor")
    tpe = point.get("tokens_per_expert")
    if cf is None and tpe is None:
        return None
    if tpe is not None:
        # cf composes with tpe when both knobs land in one point: tpe
        # is the nominal per-expert workload, cf the slack multiplier —
        # pinning capacity to tpe alone would score a cf scan flat and
        # prune nothing
        slack = float(cf) if cf is not None else 1.0
        capacity = int(max(1, -(-slack * int(tpe) // 1)))
    else:
        capacity = moe_capacity(tokens, num_experts, float(cf))
    wire_bytes = moe_dispatch_wire_bytes(
        tokens, d_model, num_experts, ep, elem_bits=elem_bits,
        capacity=capacity)
    wire_s = wire_bytes / hw.ici_bytes_per_s
    compute_s = moe_expert_compute_s(
        tokens, d_model, d_ff, num_experts, ep, hw=hw,
        capacity=capacity)
    exposed = moe_dispatch_exposed_s(wire_s, compute_s, ep, fused=fused)
    return -(compute_s + exposed)


def _op_wire_bytes(op: H.CollectiveOp, world: int) -> float:
    """Per-chip wire bytes of one compiled collective from its result
    size: RS results are per-shard (input = bytes·g), AR/AG results are
    the full payload."""
    g = op.group_size or world
    if g <= 1:
        return 0.0
    if op.kind == "all-reduce":
        return 2.0 * _ring_factor(g) * op.bytes
    if op.kind == "reduce-scatter":
        return (g - 1) * op.bytes
    if op.kind in ("all-gather", "all-to-all"):
        return _ring_factor(g) * op.bytes
    # permute / broadcast: the payload crosses once
    return float(op.bytes)


def collective_wire_by_level(ops: Sequence[H.CollectiveOp],
                             n_dcn: int = 1,
                             n_ici: int = 1,
                             topology: Optional[LevelSpec] = None
                             ) -> Dict[str, float]:
    """Attribute each compiled collective's wire bytes to a fabric
    level of the resolved topology tree.  ``topology`` is an
    innermost-first :data:`LevelSpec`; the default is the 2-level
    ``(ici, dcn)`` runtime mesh, keeping the historical
    ``{"ici": ..., "dcn": ...}`` keys the overlap probe embeds in
    bench artifacts (``exchange_wire_bytes_ici``/``_dcn``) for the
    perf gate to diff.

    Attribution consults BOTH the replica-group size and the group
    *stride* (``utils/hlo.replica_group_stride``): level ℓ of a
    row-major mesh produces groups of size ``extentℓ`` whose members
    step by ``∏ inner extents`` device ids, so two levels with equal
    extents no longer alias (the former size-only rule booked every
    ``n_dcn``-sized group — including intra-slice ones on an
    ``n_ici == n_dcn`` mesh — to the DCN hop).  Ops matching no level
    (world-sized flat collectives, scopeless spellings) ride the
    innermost fabric, as before."""
    if topology is None:
        n_dcn, n_ici = max(1, int(n_dcn)), max(1, int(n_ici))
        topology = (("ici", n_ici, None), ("dcn", n_dcn, None))
    triples = _level_triples(topology)
    # level ℓ's replica groups on a row-major device order: size =
    # extentℓ, member stride = product of the extents inside it
    level_sig: List[Tuple[str, int, int]] = []   # (name, size, stride)
    world = 1
    for name, extent, _ in triples:
        level_sig.append((name, extent, world))
        world *= extent
    innermost = triples[0][0]
    out: Dict[str, float] = {name: 0.0 for name, _, _ in triples}
    for op in ops:
        stride = H.replica_group_stride(op.replica_groups)
        candidates = [(name, sz, st) for name, sz, st in level_sig
                      if sz > 1 and op.group_size == sz]
        level = innermost
        if len(candidates) == 1 and (
                stride is None or candidates[0][2] == stride):
            level = candidates[0][0]
        elif len(candidates) > 1:
            # equal extents at different levels: the stride decides;
            # a stride matching no level (or unknown) books innermost —
            # the conservative fabric, same as the no-candidate case
            for name, _, st in candidates:
                if stride == st:
                    level = name
                    break
        out[level] += _op_wire_bytes(op, world)
    return out


# -- whole-module static cost -----------------------------------------------


@dataclasses.dataclass(frozen=True)
class ModuleCost:
    """Static accounting of one lowered module."""

    flops: int                        # countable matmul-class FLOPs
    wire_bytes: Dict[str, float]      # per-level collective bytes
    memory_high_water_bytes: int      # buffer-lifetime peak estimate

    def predicted_step_time_s(self, hw: HardwareModel = V5E,
                              overlap_fraction: float = 0.0,
                              efficiency: float = 1.0) -> float:
        """Roofline step time: compute at ``efficiency × peak`` plus the
        exposed share of the wire time.  ``efficiency`` comes from
        :func:`calibrate` when a trajectory exists; 1.0 is the
        theoretical floor."""
        compute = self.flops / (hw.peak_flops_per_s * max(efficiency,
                                                          1e-9))
        wire = (self.wire_bytes.get("ici", 0.0) / hw.ici_bytes_per_s
                + self.wire_bytes.get("dcn", 0.0) / hw.dcn_bytes_per_s)
        return compute + wire * (1.0 - overlap_fraction)


def module_cost(hlo_text: str, n_dcn: int = 1,
                n_ici: int = 1) -> ModuleCost:
    """Parse one HLO dump into the three static quantities the roofline
    needs: FLOPs (:func:`~horovod_tpu.utils.hlo.module_flops`), wire
    bytes per level, and the memory high-water estimate
    (:func:`~horovod_tpu.utils.hlo.memory_high_water`)."""
    ops = H.collective_ops(hlo_text)
    return ModuleCost(
        flops=H.module_flops(hlo_text),
        wire_bytes=collective_wire_by_level(ops, n_dcn=n_dcn,
                                            n_ici=n_ici),
        memory_high_water_bytes=H.memory_high_water(hlo_text))


# -- workload models + calibrated roofline ----------------------------------


@dataclasses.dataclass(frozen=True)
class WorkloadModel:
    """Analytic per-unit costs of one bench family — the same FLOP
    accounting ``bench.py`` prints (so model and measurement cannot
    disagree about what a unit costs)."""

    family: str                  # "resnet" | "transformer" | ...
    rate_field: str              # the BENCH-JSON throughput field
    unit: str                    # "img" | "token"
    flops_per_unit: float
    hbm_bytes_per_unit: float
    units_per_step: float        # per-chip batch units in one step


#: ResNet-50 HBM traffic per image at 224px: PERF_NOTES derives the
#: per-op-fusion ceiling of ~4,100 img/s from ~810 GB/s of achievable
#: bandwidth — i.e. ≈198 MB moved per image.  This is what makes the
#: model HBM-bound on v5e (mfu ceiling ≈26%), which the roofline must
#: know or it would predict 16,000 img/s from FLOPs alone.
RESNET_HBM_BYTES_PER_IMG = 810e9 / 4100.0

#: Parameter-traffic passes per step for the transformer HBM term:
#: forward read + backward read + optimizer write (activations are
#: small next to 871M params at batch 6).
_PARAM_PASSES = 3


def resnet_workload(image_size: int = 224,
                    batch: int = 128) -> WorkloadModel:
    scale = (image_size / 224.0) ** 2
    return WorkloadModel(
        family="resnet", rate_field="value", unit="img",
        flops_per_unit=3 * 4.1e9 * scale,            # bench.py accounting
        hbm_bytes_per_unit=RESNET_HBM_BYTES_PER_IMG * scale,
        units_per_step=batch)


def transformer_workload(params: float, layers: int = 16,
                         d_model: int = 2048, seq: int = 1024,
                         batch: int = 6,
                         param_bytes: int = 2) -> WorkloadModel:
    tokens_per_step = batch * seq
    return WorkloadModel(
        family="transformer", rate_field="transformer_tokens_per_sec",
        unit="token",
        flops_per_unit=6 * params + 6 * layers * seq * d_model,
        hbm_bytes_per_unit=_PARAM_PASSES * param_bytes * params
        / tokens_per_step,
        units_per_step=tokens_per_step)


def roofline_rate(w: WorkloadModel, hw: HardwareModel = V5E) -> float:
    """units/sec ceiling: the binding one of the compute and HBM
    rooflines.  ResNet-50 binds on HBM (~4,100 img/s on v5e), the
    flagship transformer on compute (~36,300 tok/s)."""
    return min(hw.peak_flops_per_s / w.flops_per_unit,
               hw.hbm_bytes_per_s / w.hbm_bytes_per_unit)


def workloads_from_artifact(artifact: Dict) -> List[WorkloadModel]:
    """The workload models a bench artifact carries evidence for.
    Transformer shape is keyed off ``transformer_params_m`` (the
    flagship layer/seq defaults otherwise match every checked-in
    round); artifacts without a family's fields contribute nothing."""
    out: List[WorkloadModel] = []
    if artifact.get("metric") == "resnet50_img_sec_per_chip" \
            and artifact.get("value") is not None:
        out.append(resnet_workload())
    params_m = artifact.get("transformer_params_m")
    if params_m is not None \
            and artifact.get("transformer_tokens_per_sec") is not None:
        out.append(transformer_workload(params=float(params_m) * 1e6))
    return out


@dataclasses.dataclass
class Calibration:
    """Fitted per-family efficiency constants (measured rate ÷ roofline
    ceiling).  ``efficiency`` keeps the most recent fit — the newest
    hardware measurement is the prediction anchor — while ``samples``
    retains the whole trajectory for drift inspection."""

    hw: HardwareModel
    efficiency: Dict[str, float]
    samples: Dict[str, List[Tuple[str, float]]]   # family → (src, eff)


ArtifactLike = Union[str, os.PathLike, Dict]


def _load_artifact(artifact: ArtifactLike) -> Tuple[str, Dict]:
    if isinstance(artifact, dict):
        data = artifact
        name = str(data.get("metric", "<dict>"))
    else:
        name = os.path.basename(os.fspath(artifact))
        with open(artifact) as f:
            data = json.load(f)
    if isinstance(data.get("parsed"), dict):     # MULTICHIP/driver wrapper
        data = dict(data, **data["parsed"])
    return name, data


def calibrate(artifacts: Sequence[ArtifactLike],
              hw: HardwareModel = V5E) -> Calibration:
    """Fit the roofline's per-family efficiency from a BENCH trajectory.

    For every artifact (in the given order — pass them oldest→newest)
    and every workload family it measures, the sample is
    ``measured_rate / roofline_rate``; the calibrated constant is the
    LAST sample per family.  Deterministic: same inputs, same
    calibration — the perf gate's two-run identity check relies on it.
    """
    eff: Dict[str, float] = {}
    samples: Dict[str, List[Tuple[str, float]]] = {}
    for art in artifacts:
        name, data = _load_artifact(art)
        for w in workloads_from_artifact(data):
            rate = data.get(w.rate_field)
            if rate is None:
                continue
            ceiling = roofline_rate(w, hw)
            e = float(rate) / ceiling
            eff[w.family] = e
            samples.setdefault(w.family, []).append((name, e))
    return Calibration(hw=hw, efficiency=eff, samples=samples)


def predict_rate(cal: Calibration, w: WorkloadModel) -> Optional[float]:
    """Calibrated units/sec prediction, or None for an unseen family."""
    e = cal.efficiency.get(w.family)
    if e is None:
        return None
    return e * roofline_rate(w, cal.hw)


def predict_step_time_s(cal: Calibration, w: WorkloadModel,
                        exposed_comm_s: float = 0.0) -> Optional[float]:
    """Predicted per-step wall time: batch units at the calibrated rate
    plus whatever exchange time is left exposed (0 on one chip;
    :func:`exchange_time_s` × (1 − overlap) on a mesh)."""
    rate = predict_rate(cal, w)
    if rate is None or rate <= 0:
        return None
    return w.units_per_step / rate + exposed_comm_s


# -- autotune predictor ------------------------------------------------------


def make_fusion_predictor(payload_bytes: float, n_leaves: int,
                          world: int = 8, hw: HardwareModel = V5E,
                          dispatch_latency_s: float = 1e-3):
    """Score function for the eager-plane autotune grid
    (``utils/autotune.py`` ``predict=``): predicted bytes/sec of one
    gradient exchange under a ``(fusion_threshold_bytes,
    cycle_time_ms)`` point.

    Model: a threshold of T splits the payload into ``ceil(B/T)``
    flushes (T = 0 flushes per tensor), each paying one dispatch
    latency; the wire itself is the flat ring ``2·(N−1)/N·B`` at ICI
    bandwidth; the flush interval adds half a cycle of expected queue
    wait.  Crude on purpose — it only needs to RANK the warm-up grid so
    the manager measures the plausible half instead of all of it (the
    measurement, not the model, still picks the winner)."""
    def predict(point) -> float:
        threshold, cycle_ms = point
        if threshold and threshold > 0:
            flushes = max(1, math.ceil(payload_bytes / threshold))
        else:
            flushes = max(1, int(n_leaves))
        wire_s = 2.0 * _ring_factor(max(1, world)) * payload_bytes \
            / hw.ici_bytes_per_s
        t = flushes * dispatch_latency_s + wire_s \
            + (float(cycle_ms) / 1e3) / 2.0
        return payload_bytes / t

    return predict
