"""AdaSum operator smoke: hvdci gate 10 (docs/adasum.md).

The convergence story the AdaSum reduction operator ships
(``ops/collectives.adasum_pair`` + the outer-level pairwise exchange)
is a *numerical* claim — orthogonal gradients add, parallel gradients
average, antiparallel gradients damp — and the CI gate pins it
without hardware: seeded pure-sim gradient-pair fixtures plus a
sub-second two-slice convergence loop where

* plain sum at the base batch converges (the reference trajectory),
* adasum at 2× the global batch tracks that reference, and
* plain *summation* at 2× (the naive scale-out: N× the mean step,
  exactly what an untuned learning rate sees) demonstrably degrades,

run twice and required bit-identical (the same determinism contract
every smoke in ``analysis/ci.py`` holds).

The module is stdlib-only like the rest of the analysis layer — the
pair rule is mirrored here in pure python (float64) and cross-checked
against the real ``ops.collectives.adasum_pair`` (fp32) whenever JAX
imports, so the gate exercises the shipped operator in the test image
while ``python -m horovod_tpu.analysis`` stays importable without it.
``bench --adasum`` reuses :func:`simulate_convergence` for its
trajectory fields, so the BENCH artifact and the CI gate share one
definition of the twin.
"""

from __future__ import annotations

import json
import math
import random
from typing import List, Optional, Sequence

#: Zero-norm guard threshold — mirrors ``ops.collectives.adasum_pair``
#: by value (this module stays stdlib-only).
ZERO_NORM_EPS = 1e-30


def _dot(a: Sequence[float], b: Sequence[float]) -> float:
    return sum(x * y for x, y in zip(a, b))


def adasum_pair(a: Sequence[float], b: Sequence[float]) -> List[float]:
    """Pure-python mirror of the pairwise rule
    ``a·(1 − ⟨a,b⟩/2‖a‖²) + b·(1 − ⟨a,b⟩/2‖b‖²)`` with the zero-norm →
    plain-sum guard, in float64 (the shipped operator accumulates in
    fp32; the cross-check below bounds the drift)."""
    dot, an, bn = _dot(a, b), _dot(a, a), _dot(b, b)
    ac = 1.0 - dot / (2.0 * an + ZERO_NORM_EPS) \
        if an >= ZERO_NORM_EPS else 1.0
    bc = 1.0 - dot / (2.0 * bn + ZERO_NORM_EPS) \
        if bn >= ZERO_NORM_EPS else 1.0
    return [ac * x + bc * y for x, y in zip(a, b)]


def adasum_reduce(grads: Sequence[Sequence[float]]) -> List[float]:
    """Binary adasum tree over a replica list — the same adjacent-pair
    order as ``ops.collectives._adasum_psum_scatter``'s replicated
    tree (the pair rule is symmetric, so the pow2 recursive-doubling
    schedule combines in the same bracketing)."""
    vals = [list(g) for g in grads]
    while len(vals) > 1:
        nxt = [adasum_pair(vals[i], vals[i + 1])
               for i in range(0, len(vals) - 1, 2)]
        if len(vals) % 2:
            nxt.append(vals[-1])
        vals = nxt
    return vals[0]


def simulate_convergence(n_replicas: int,
                         reduction: str,
                         steps: int = 40,
                         seed: int = 42,
                         lr: float = 0.75,
                         dim: int = 8,
                         noise: float = 0.01) -> List[float]:
    """Seeded quadratic twin: per-step loss trajectory of ``steps``
    SGD updates where each of ``n_replicas`` slices contributes a
    noisy gradient of the same diagonal quadratic and the slices are
    combined by ``reduction`` ("sum" = plain summation, the naive
    scale-out that multiplies the effective step by N; "adasum" = the
    binary pairwise tree).

    The curvature spectrum is a fixed ``[0.5, 1.5]`` spread chosen so
    the base step is stable (``lr·h_max < 2``) while the summed
    2-replica step is not (``2·lr·h_max > 2``) — the textbook
    large-batch blow-up adasum's damping absorbs.  Pure stdlib floats,
    bit-deterministic for one seed."""
    if dim < 2:
        raise ValueError(f"dim must be >= 2, got {dim}")
    h = [0.5 + i / (dim - 1) for i in range(dim)]
    rng = random.Random(seed)
    wstar = [rng.uniform(-1.0, 1.0) for _ in range(dim)]
    w = [0.0] * dim
    losses: List[float] = []
    for _ in range(steps):
        grads = [[h[i] * (w[i] - wstar[i]) + noise * rng.gauss(0.0, 1.0)
                  for i in range(dim)]
                 for _r in range(n_replicas)]
        if reduction == "adasum":
            g = adasum_reduce(grads)
        else:
            g = [sum(gr[i] for gr in grads) for i in range(dim)]
        w = [w[i] - lr * g[i] for i in range(dim)]
        losses.append(0.5 * sum(h[i] * (w[i] - wstar[i]) ** 2
                                for i in range(dim)))
    return losses


#: The gradient-pair fixtures the gate pins (docs/adasum.md):
#: identical pair → itself (parallel average), orthogonal pair →
#: plain sum, antiparallel pair → damped below the plain sum,
#: zero-norm operand → plain-sum guard.
_PAIR_FIXTURES = (
    ("parallel", [1.0, 2.0, -3.0, 0.5], [1.0, 2.0, -3.0, 0.5]),
    ("orthogonal", [1.0, 0.0, 2.0, 0.0], [0.0, -1.0, 0.0, 3.0]),
    ("antiparallel", [1.0, 2.0, -3.0, 0.5], [-2.0, -4.0, 6.0, -1.0]),
    ("zero-norm", [0.0, 0.0, 0.0, 0.0], [1.0, 2.0, -3.0, 0.5]),
)


def _close(a: Sequence[float], b: Sequence[float],
           rtol: float = 1e-9) -> bool:
    return all(abs(x - y) <= rtol * max(1.0, abs(x), abs(y))
               for x, y in zip(a, b))


def run_smoke(root: Optional[str] = None) -> List[str]:
    """hvdci gate 10: the seeded adasum fixtures + two-slice
    convergence loop, run twice and required bit-identical.  Returns
    the error list ([] = pass); sub-second, stdlib-only (the real
    fp32 operator is cross-checked when JAX imports)."""
    del root  # same signature as the other smokes; nothing on disk
    errors: List[str] = []

    fix = {name: adasum_pair(a, b) for name, a, b in _PAIR_FIXTURES}
    g = _PAIR_FIXTURES[0][1]
    if not _close(fix["parallel"], g):
        errors.append(
            f"adasum(g, g) must return g (parallel average), got "
            f"{fix['parallel']}")
    a, b = _PAIR_FIXTURES[1][1], _PAIR_FIXTURES[1][2]
    if not _close(fix["orthogonal"], [x + y for x, y in zip(a, b)]):
        errors.append(
            f"adasum of an orthogonal pair must equal the plain sum, "
            f"got {fix['orthogonal']}")
    a, b = _PAIR_FIXTURES[2][1], _PAIR_FIXTURES[2][2]
    # b = -2a: coefficients 2 and 1.25, combine = -a/2 — damped to
    # half the plain sum's norm
    if not _close(fix["antiparallel"], [-0.5 * x for x in a]):
        errors.append(
            f"adasum of the antiparallel fixture must damp to -a/2, "
            f"got {fix['antiparallel']}")
    if math.sqrt(_dot(fix["antiparallel"], fix["antiparallel"])) \
            >= math.sqrt(_dot(a, a)):
        errors.append("antiparallel combine is not damped below the "
                      "operand norm")
    if not _close(fix["zero-norm"], _PAIR_FIXTURES[3][2]):
        errors.append(
            f"zero-norm operand must fall back to the plain sum, got "
            f"{fix['zero-norm']}")

    # cross-check the pure-python mirror against the shipped fp32
    # operator (ops/collectives.py) whenever JAX is importable — the
    # CI image always has it; a JAX-less analysis install skips this
    # arm without weakening the stdlib fixtures above
    try:
        import numpy as np

        from horovod_tpu.ops.collectives import adasum_pair as real_pair
    except ImportError:
        pass
    else:
        for name, x, y in _PAIR_FIXTURES:
            got = real_pair(np.asarray(x, np.float32),
                            np.asarray(y, np.float32), xp=np)
            if not _close([float(v) for v in got], fix[name],
                          rtol=1e-5):
                errors.append(
                    f"ops.collectives.adasum_pair diverges from the "
                    f"smoke mirror on the {name} fixture: "
                    f"{[float(v) for v in got]} vs {fix[name]}")

    runs = []
    for _ in range(2):
        base = simulate_convergence(1, "sum", seed=42)
        ada = simulate_convergence(2, "adasum", seed=42)
        summed = simulate_convergence(2, "sum", seed=42)
        runs.append(json.dumps({"base": base, "adasum": ada,
                                "sum2x": summed}))
    if runs[0] != runs[1]:
        errors.append(
            "adasum convergence twin is not deterministic: two seeded "
            "runs serialized differently")
    base = simulate_convergence(1, "sum", seed=42)
    ada = simulate_convergence(2, "adasum", seed=42)
    summed = simulate_convergence(2, "sum", seed=42)
    if not all(math.isfinite(x) for x in base) \
            or base[-1] >= 0.01 * base[0]:
        errors.append(
            f"base sum trajectory failed to converge: "
            f"{base[0]:.4g} -> {base[-1]:.4g}")
    if not all(math.isfinite(x) for x in ada) \
            or ada[-1] >= 0.01 * ada[0]:
        errors.append(
            f"adasum-at-2x trajectory failed to converge: "
            f"{ada[0]:.4g} -> {ada[-1]:.4g}")
    if ada[-1] > 10.0 * max(base[-1], 1e-6):
        errors.append(
            f"adasum-at-2x final loss {ada[-1]:.4g} does not track "
            f"the base trajectory's {base[-1]:.4g}")
    if summed[-1] < 100.0 * max(ada[-1], base[-1]):
        errors.append(
            f"sum-at-2x was expected to degrade (effective step "
            f"doubled past the stability edge) but reached "
            f"{summed[-1]:.4g} vs adasum {ada[-1]:.4g}")
    return errors
