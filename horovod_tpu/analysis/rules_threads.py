"""HVD004: thread/lock discipline.

The runtime now runs five background threads sharing mutable state with
the main thread — heartbeat sender, checkpoint writer, prefetch feeder,
discovery loop, progress watchdog — and the PR 3/5 reviews each found
an unlocked cross-thread mutation by hand (the ``last_recovery_s``
log-under-lock fix, the sticky writer error).  This rule mechanizes
that review:

* **unlocked shared mutation** — within a class, an attribute assigned
  both from a thread-entry function (a ``threading.Thread``/``Timer``
  target or an executor ``submit`` callee, plus the class methods it
  reaches) and from any other method must be assigned under a ``with
  <lock>:`` block on *both* sides (``__init__`` is exempt: construction
  happens-before the thread starts).
* **lock-order inversion** — a directed graph of "acquired lock B while
  holding lock A" edges, including one call-hop through attributes
  whose class is known from ``__init__`` (``self._registry =
  WorkerStateRegistry(...)``); any cycle is a potential deadlock and is
  reported once per cycle.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from horovod_tpu.analysis import astutil as A
from horovod_tpu.analysis.engine import Finding, Module, Project, Rule, \
    Severity

_THREAD_CTORS = {"Thread", "Timer"}


def _thread_entry_functions(cls: ast.ClassDef) -> Dict[str, ast.AST]:
    """Functions of ``cls`` that run on another thread: ``target=`` of a
    Thread/Timer construction and first args of ``submit`` calls —
    resolved to class methods (``self.m``) or to local ``def``s of the
    constructing method."""
    methods = {n.name: n for n in cls.body
               if isinstance(n, ast.FunctionDef)}
    entries: Dict[str, ast.AST] = {}

    def resolve(ref: ast.AST, locals_: Dict[str, ast.FunctionDef]) -> None:
        attr = A.self_attr(ref)
        if attr is not None and attr in methods:
            entries[f"method:{attr}"] = methods[attr]
        elif isinstance(ref, ast.Name) and ref.id in locals_:
            entries[f"local:{ref.id}"] = locals_[ref.id]

    for m in methods.values():
        locals_ = A.local_functions(m)
        for node in ast.walk(m):
            if not isinstance(node, ast.Call):
                continue
            tail = A.name_tail(node.func)
            if tail in _THREAD_CTORS:
                for kw in node.keywords:
                    if kw.arg == "target":
                        resolve(kw.value, locals_)
                # Timer(interval, fn) / Thread positional target
                if len(node.args) >= 2 and tail == "Timer":
                    resolve(node.args[1], locals_)
            elif tail == "submit" and node.args:
                resolve(node.args[0], locals_)
    return entries


def _reachable_methods(cls: ast.ClassDef, roots: List[ast.AST]
                       ) -> Set[str]:
    """Names of class methods reachable from ``roots`` via ``self.m()``
    calls (the thread's footprint inside the class)."""
    methods = {n.name: n for n in cls.body
               if isinstance(n, ast.FunctionDef)}
    seen: Set[str] = set()
    stack = list(roots)
    while stack:
        fn = stack.pop()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                attr = A.self_attr(node.func)
                if attr in methods and attr not in seen:
                    seen.add(attr)
                    stack.append(methods[attr])
    return seen


def _mutations(fn: ast.AST, parents: A.ParentMap
               ) -> Iterable[Tuple[str, ast.AST, bool]]:
    """``(attr, node, locked)`` for every ``self.attr = ...`` in ``fn``
    (including nested defs — the checkpoint writer closure pattern)."""
    for attr, node in A.iter_self_attr_stores(fn):
        yield attr, node, A.under_lock(node, parents)


class ThreadLockDisciplineRule(Rule):
    id = "HVD004"
    severity = Severity.P1
    name = "thread-lock-discipline"
    rationale = ("attribute mutated from a thread and a method without "
                 "the class's lock → torn state/lost updates; "
                 "lock-order cycles → deadlock")

    def check(self, module: Module, project: Project) -> Iterable[Finding]:
        if module.tree is None:
            return
        parents = A.ParentMap(module.tree)
        for cls in ast.walk(module.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            yield from self._check_class(module, cls, parents)

    def _check_class(self, module: Module, cls: ast.ClassDef,
                     parents: A.ParentMap) -> Iterable[Finding]:
        entries = _thread_entry_functions(cls)
        if not entries:
            return
        methods = {n.name: n for n in cls.body
                   if isinstance(n, ast.FunctionDef)}
        thread_roots = list(entries.values())
        thread_method_names = {k.split(":", 1)[1]
                               for k in entries if k.startswith("method:")}
        thread_method_names |= _reachable_methods(cls, thread_roots)

        # thread-side mutations: the entry functions themselves (incl.
        # local closures) + reachable methods
        thread_mut: Dict[str, List[Tuple[ast.AST, bool]]] = {}
        for fn in thread_roots:
            for attr, node, locked in _mutations(fn, parents):
                thread_mut.setdefault(attr, []).append((node, locked))
        for name in thread_method_names:
            fn = methods.get(name)
            if fn is None:
                continue
            for attr, node, locked in _mutations(fn, parents):
                thread_mut.setdefault(attr, []).append((node, locked))

        # main-side mutations: every *other* method except __init__
        # (construction happens-before thread start).  A _private method
        # reachable only from the thread entries is thread-local by
        # within-class evidence and stays off the main side; a *public*
        # thread-reachable method is callable from anywhere and counts
        # on both sides (shared footprint).
        main_mut: Dict[str, List[Tuple[str, ast.AST, bool]]] = {}
        spawning = {n for n in methods
                    if any(e is methods.get(n) for e in thread_roots)}
        for name, fn in methods.items():
            if name == "__init__" or name in spawning:
                continue
            if name in thread_method_names and name.startswith("_"):
                continue
            own_locals = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.FunctionDef) and node is not fn:
                    own_locals.add(node)
            for attr, node, locked in _mutations(fn, parents):
                # skip stores inside nested defs already counted as
                # thread entries (the writer-closure pattern)
                if any(node in set(ast.walk(loc)) for loc in own_locals
                       if loc in thread_roots):
                    continue
                main_mut.setdefault(attr, []).append((name, node, locked))

        for attr in sorted(set(thread_mut) & set(main_mut)):
            t_sites = thread_mut[attr]
            m_sites = main_mut[attr]
            unlocked = [(n, "thread") for n, lk in t_sites if not lk] + \
                       [(n, f"method '{m}'") for m, n, lk in m_sites
                        if not lk]
            if not unlocked:
                continue
            node, side = unlocked[0]
            other = "a background thread" if side != "thread" \
                else "other methods"
            yield self.finding(
                module, node,
                f"'{cls.name}.{attr}' is mutated from {side} without "
                f"the class's lock, but is also mutated from {other} "
                f"({len(t_sites)} thread-side / {len(m_sites)} "
                f"method-side sites) — guard every store with the "
                f"class lock or document the happens-before edge")

    # -- lock-order graph ---------------------------------------------------

    def finalize(self, project: Project) -> Iterable[Finding]:
        # lock identity: (ClassName, attrname) — coarse but stable.
        # attr_types: (ClassName, attr) -> ClassName for `self.x = Cls(...)`
        classes: Dict[str, ast.ClassDef] = {}
        class_module: Dict[str, Module] = {}
        for m in project.modules:
            if m.tree is None:
                continue
            for node in ast.walk(m.tree):
                if isinstance(node, ast.ClassDef):
                    classes.setdefault(node.name, node)
                    class_module.setdefault(node.name, m)
        attr_types: Dict[Tuple[str, str], str] = {}

        def init_of(cls: ast.ClassDef) -> Optional[ast.FunctionDef]:
            for fn in cls.body:
                if isinstance(fn, ast.FunctionDef) and \
                        fn.name == "__init__":
                    return fn
            return None

        for cname, cls in classes.items():
            init = init_of(cls)
            if init is None:
                continue
            for node in ast.walk(init):
                if not (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)):
                    continue
                tgt_attr = None
                for t in node.targets:
                    tgt_attr = A.self_attr(t) or tgt_attr
                callee = A.name_tail(node.value.func)
                if not tgt_attr or callee not in classes:
                    continue
                attr_types[(cname, tgt_attr)] = callee
                # ctor-argument flow: `self.x = Other(self, ...)` hands
                # THIS object to Other.__init__; whatever attribute
                # Other stores that parameter under has OUR type — the
                # registry/driver back-reference pattern the elastic
                # inversion hid behind
                callee_init = init_of(classes[callee])
                if callee_init is None:
                    continue
                params = [a.arg for a in callee_init.args.args]
                for i, arg in enumerate(node.value.args):
                    if not (isinstance(arg, ast.Name)
                            and arg.id == "self"):
                        continue
                    if i + 1 >= len(params):
                        continue
                    pname = params[i + 1]
                    for st in ast.walk(callee_init):
                        if isinstance(st, ast.Assign) and \
                                isinstance(st.value, ast.Name) and \
                                st.value.id == pname:
                            for t in st.targets:
                                back = A.self_attr(t)
                                if back is not None:
                                    attr_types[(callee, back)] = cname

        # per-method top-level lock acquisitions, per class
        def method_locks(cname: str, mname: str) -> Set[Tuple[str, str]]:
            cls = classes.get(cname)
            if cls is None:
                return set()
            for fn in cls.body:
                if isinstance(fn, ast.FunctionDef) and fn.name == mname:
                    out = set()
                    for node in ast.walk(fn):
                        if isinstance(node, ast.With):
                            for ln in A.with_lock_names(node):
                                out.add((cname, ln))
                    return out
            return set()

        edges: Dict[Tuple[Tuple[str, str], Tuple[str, str]],
                    Tuple[str, int]] = {}

        for m in project.modules:
            if m.tree is None:
                continue
            parents = A.ParentMap(m.tree)
            for node in ast.walk(m.tree):
                if not isinstance(node, ast.With):
                    continue
                held_names = A.with_lock_names(node)
                if not held_names:
                    continue
                cls = parents.enclosing_class(node)
                cname = cls.name if cls is not None else m.relpath
                held = [(cname, n) for n in held_names]
                for inner in ast.walk(node):
                    if inner is node:
                        continue
                    # direct nesting: with A: ... with B:
                    if isinstance(inner, ast.With):
                        for n2 in A.with_lock_names(inner):
                            tgt = (cname, n2)
                            for h in held:
                                if h != tgt and (h, tgt) not in edges:
                                    edges[(h, tgt)] = (m.relpath,
                                                       inner.lineno)
                    # one call-hop: with A: self._other.m() where
                    # self._other's class is known and m() takes a lock
                    if isinstance(inner, ast.Call) and \
                            isinstance(inner.func, ast.Attribute):
                        recv_attr = A.self_attr(inner.func.value)
                        if recv_attr is None:
                            continue
                        tcls = attr_types.get((cname, recv_attr))
                        if tcls is None:
                            continue
                        for tgt in method_locks(tcls, inner.func.attr):
                            for h in held:
                                if h != tgt and (h, tgt) not in edges:
                                    edges[(h, tgt)] = (m.relpath,
                                                       inner.lineno)

        # cycle detection over the edge set
        graph: Dict[Tuple[str, str], Set[Tuple[str, str]]] = {}
        for (a, b) in edges:
            graph.setdefault(a, set()).add(b)
        reported: Set[frozenset] = set()
        for start in sorted(graph):
            cyc = _find_cycle(graph, start)
            if cyc is None:
                continue
            key = frozenset(cyc)
            if key in reported:
                continue
            reported.add(key)
            # anchor the report at the first edge of the cycle we know
            a, b = cyc[0], cyc[1 % len(cyc)]
            relpath, lineno = edges.get((a, b), ("", 1))
            mod = None
            for pm in project.modules:
                if pm.relpath == relpath:
                    mod = pm
                    break
            order = " -> ".join(f"{c}.{n}" for c, n in cyc + [cyc[0]])
            f = Finding(
                rule=self.id, severity=Severity.P1,
                path=relpath or (project.modules[0].relpath
                                 if project.modules else ""),
                line=lineno, col=0,
                message=(f"lock-acquisition-order cycle: {order} — two "
                         f"threads taking these locks in opposite order "
                         f"deadlock; impose a single global order or "
                         f"drop one lock before acquiring the next"),
                context=mod.context_line(lineno) if mod else "")
            yield f


def _find_cycle(graph: Dict, start) -> Optional[List]:
    path: List = []
    on_path: Set = set()
    visited: Set = set()

    def dfs(node) -> Optional[List]:
        if node in on_path:
            i = path.index(node)
            return path[i:]
        if node in visited:
            return None
        visited.add(node)
        path.append(node)
        on_path.add(node)
        for nxt in sorted(graph.get(node, ())):
            cyc = dfs(nxt)
            if cyc is not None:
                return cyc
        path.pop()
        on_path.discard(node)
        return None

    return dfs(start)
