"""Perf regression gate: diff bench artifacts against the checked-in
trajectory and fail on throughput/overlap/wire-byte regressions.

The repo carries a five-round BENCH/MULTICHIP trajectory, but until
this gate nothing stopped a regression from merging — the BENCH_r05
final-iteration collapse (25,364→3,061 tok/s) is exactly the anomaly
class that should fail a merge, not decorate a log.  The gate runs two
ways:

* **trajectory walk** (no candidate): every checked-in artifact is
  diffed against the best comparable value among its predecessors —
  the tier-1 self-check that the history itself is regression-free;
* **candidate diff** (``--candidate new.json``): a fresh
  ``bench.py --json-out`` artifact is diffed against the best
  comparable value anywhere in the trajectory.

"Comparable" is load-bearing: the transformer grew 183.8M→870.9M
params between r03 and r04, so tokens/sec across that boundary is not
a regression, it's a different model — throughput fields carry a
comparability key (``transformer_params_m`` etc.) and only matching
artifacts are diffed.  Schema-versioned artifacts
(``bench.py`` ``schema_version`` ≥ 1) additionally pin device/mesh
identity, and the gate REFUSES to diff mismatched identities with a
clear error instead of producing a nonsense verdict (or a KeyError).
Calibration provenance is identity too: artifacts stamped with
differing ``calibration_fingerprint`` (the run consumed a measured
hardware model via ``HOROVOD_CALIBRATION_PATH``; docs/calibration.md)
were priced against different machines and are likewise refused.

Rules (ids continue the HLO00x pack; docs/perf_gate.md):

=========  ==============================================================
PERF001    throughput field dropped more than the tolerance vs the best
           comparable trajectory value
PERF002    measured ``overlap_fraction`` dropped more than the overlap
           tolerance (absolute)
PERF003    per-level exchange wire bytes grew more than the wire
           tolerance at the same hierarchy (de-fusion/de-quantization
           shows up here before a pod does)
PERF004    candidate artifact reports a failed run (``rc``/``ok``)
PERF006    measured HBM high-water grew more than the memory tolerance
           at the same remat policy + plan (a remat or donation
           regression shows up here before an OOM does)
=========  ==============================================================

Tolerances come from ``HOROVOD_PERF_GATE_TOLERANCE`` (relative
throughput drop, default 0.10), ``HOROVOD_PERF_GATE_OVERLAP_TOLERANCE``
(absolute overlap drop, default 0.10),
``HOROVOD_PERF_GATE_WIRE_TOLERANCE`` (relative wire growth, default
0.10) and ``HOROVOD_PERF_GATE_MEMORY_TOLERANCE`` (relative HBM
high-water growth, default 0.10) — registered knobs
(docs/running.md).  Blessing an intentional
regression = updating the trajectory the gate reads
(docs/perf_gate.md walks the procedure).
"""

from __future__ import annotations

import argparse
import dataclasses
import glob as _glob
import json
import os
import sys
from typing import Dict, List, Optional, Sequence, Tuple

from horovod_tpu.analysis import cost_model as CM
from horovod_tpu.analysis import engine

#: Highest bench-artifact schema this gate understands.
SCHEMA_VERSION = 1

#: v1 provenance fields bench.py stamps (artifact_metadata()).
_V1_REQUIRED = ("jax_version", "platform", "device_kind", "n_devices",
                "mesh_shape")
#: identity fields that must MATCH for two v1 artifacts to be diffable
_V1_IDENTITY = ("platform", "device_kind", "n_devices", "mesh_shape")

#: throughput fields and the comparability key guarding each — only
#: artifacts agreeing on the key's value are diffed (None key field on
#: both sides also matches).  ``plan`` guards every field: a dp=8 run
#: against a dp=4,fsdp=2 run measures two different exchange
#: schedules, not a regression (bench.py --plan; docs/parallelism.md).
#: ``reduction`` guards them the same way: a sum→adasum switch moves
#: the outer exchange level onto the pairwise full-block schedule —
#: a schedule change, never a throughput regression (bench.py
#: --reduction; docs/adasum.md); legacy artifacts without the field
#: keep gating via the None-matches-None rule
THROUGHPUT_FIELDS: Tuple[Tuple[str, Tuple[str, ...]], ...] = (
    ("value", ("metric", "plan", "reduction")),
    # sp extent + sequence length guard the transformer diff: an
    # sp=2 seq-4096 long-context run against an sp=1 seq-512 one
    # measures a different attention schedule and a t²-different
    # FLOP mix, never a regression (bench.py --plan dp×sp)
    ("transformer_tokens_per_sec",
     ("transformer_params_m", "plan", "sp", "transformer_seq_len",
      "reduction")),
    # routing config guards the MoE diff: a capacity-factor or ep-extent
    # change is a schedule change (different dispatch geometry + drop
    # behavior), never a throughput regression
    ("moe_tokens_per_sec",
     ("moe_params_m", "plan", "moe_capacity_factor", "moe_ep",
      "reduction")),
    ("vit_img_sec_per_chip", ("vit_params_m", "plan", "reduction")),
    # model count + tenant-class mix guard the serving diff: a fleet
    # artifact (3 tenants behind weighted-fair scheduling) measures a
    # different arbitration/hot-swap schedule than a single-model one,
    # never a regression; legacy single-model artifacts carry neither
    # key and stay comparable with each other (None matches None)
    ("serve_throughput_rps",
     ("serve_offered_rps", "plan", "serve_models",
      "serve_tenant_mix")),
)

#: latency (lower-is-better) fields and their comparability keys —
#: PERF005 fails on *growth* beyond the throughput tolerance, so
#: ``bench.py --serve`` tail latency is gateable like throughput
LATENCY_FIELDS: Tuple[Tuple[str, Tuple[str, ...]], ...] = (
    ("serve_p50_latency_s",
     ("serve_offered_rps", "plan", "serve_models",
      "serve_tenant_mix")),
    ("serve_p99_latency_s",
     ("serve_offered_rps", "plan", "serve_models",
      "serve_tenant_mix")),
)

#: memory (lower-is-better) fields and their comparability keys —
#: PERF006 fails on growth beyond the memory tolerance.  ``remat_policy``
#: guards the diff: a none-vs-full comparison measures two different
#: recompute trades, not a leak (bench.py --hbm-budget; docs/memory.md)
MEMORY_FIELDS: Tuple[Tuple[str, Tuple[str, ...]], ...] = (
    ("hbm_high_water_bytes", ("remat_policy", "plan")),
)


class GateError(Exception):
    """Artifact unusable (unreadable, unknown schema, identity
    mismatch) — the gate refuses with this instead of guessing."""


@dataclasses.dataclass(frozen=True)
class GateFinding:
    rule: str
    message: str
    detail: str = ""

    def format(self) -> str:
        d = f" ({self.detail})" if self.detail else ""
        return f"{self.rule}: {self.message}{d}"

    def as_json(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class Tolerances:
    throughput: float = 0.10     # relative drop allowed
    overlap: float = 0.10        # absolute overlap_fraction drop
    wire: float = 0.10           # relative wire-byte growth allowed
    memory: float = 0.10         # relative HBM high-water growth allowed

    @staticmethod
    def from_env(throughput: Optional[float] = None,
                 overlap: Optional[float] = None,
                 wire: Optional[float] = None,
                 memory: Optional[float] = None) -> "Tolerances":
        def knob(name: str, override: Optional[float],
                 default: float) -> float:
            if override is not None:
                return float(override)
            raw = os.environ.get(name)
            if raw in (None, ""):
                return default
            try:
                return float(raw)
            except ValueError:
                raise GateError(f"{name} must be a float, got {raw!r}")

        return Tolerances(
            throughput=knob("HOROVOD_PERF_GATE_TOLERANCE",
                            throughput, 0.10),
            overlap=knob("HOROVOD_PERF_GATE_OVERLAP_TOLERANCE",
                         overlap, 0.10),
            wire=knob("HOROVOD_PERF_GATE_WIRE_TOLERANCE", wire, 0.10),
            memory=knob("HOROVOD_PERF_GATE_MEMORY_TOLERANCE",
                        memory, 0.10))


@dataclasses.dataclass(frozen=True)
class Artifact:
    """One normalized bench artifact: flattened fields + provenance."""

    name: str
    fields: Dict
    schema_version: int

    def get(self, key, default=None):
        return self.fields.get(key, default)


def load_artifact(path: str) -> Artifact:
    """Read + normalize one artifact file.

    Accepts the raw ``bench.py --json-out`` object, the driver wrapper
    (``{"parsed": {...}, "rc": ...}`` — the checked-in ``BENCH_r0*``
    layout) and the metric-less ``MULTICHIP_r0*`` health stubs.  Raises
    :class:`GateError` with a pointed message on anything unreadable or
    schema-invalid — never a KeyError."""
    try:
        with open(path) as f:
            data = json.load(f)
    except OSError as e:
        raise GateError(f"{path}: cannot read artifact: {e}")
    except json.JSONDecodeError as e:
        raise GateError(f"{path}: not valid JSON: {e}")
    if not isinstance(data, dict):
        raise GateError(f"{path}: artifact must be a JSON object, got "
                        f"{type(data).__name__}")
    if isinstance(data.get("parsed"), dict):
        data = dict(data, **data["parsed"])
    return _validate(os.path.basename(path), data)


def _validate(name: str, data: Dict) -> Artifact:
    version = data.get("schema_version", 0)
    if not isinstance(version, int) or version < 0:
        raise GateError(f"{name}: schema_version must be a non-negative "
                        f"int, got {version!r}")
    if version > SCHEMA_VERSION:
        raise GateError(
            f"{name}: schema_version {version} is newer than this "
            f"gate understands (≤ {SCHEMA_VERSION}) — upgrade "
            f"horovod_tpu before diffing this artifact")
    if version >= 1:
        missing = [k for k in _V1_REQUIRED if data.get(k) is None]
        if missing:
            raise GateError(
                f"{name}: schema_version {version} artifact is missing "
                f"required provenance field(s) {missing} — it was not "
                f"written by bench.py --json-out; refusing to diff it")
    return Artifact(name=name, fields=data, schema_version=version)


def _identity(art: Artifact) -> Optional[Tuple]:
    if art.schema_version < 1:
        return None
    return tuple(json.dumps(art.get(k), sort_keys=True)
                 for k in _V1_IDENTITY)


def check_comparable(baseline: Sequence[Artifact],
                     candidate: Artifact) -> None:
    """Refuse (GateError) when the candidate's device/mesh identity
    contradicts a schema-versioned baseline artifact.  Legacy (v0)
    artifacts carry no identity and are accepted — the checked-in
    trajectory predates the schema."""
    cand_id = _identity(candidate)
    if cand_id is None:
        return
    for base in baseline:
        base_id = _identity(base)
        if base_id is not None and base_id != cand_id:
            diffs = [f"{k}: {base.get(k)!r} vs {candidate.get(k)!r}"
                     for k in _V1_IDENTITY
                     if base.get(k) != candidate.get(k)]
            raise GateError(
                f"{candidate.name}: not comparable with "
                f"{base.name} — {'; '.join(diffs)}; a perf diff "
                f"across different hardware/mesh identities is "
                f"meaningless, refusing")
        # calibration provenance: two artifacts priced/pruned against
        # measured hardware models fitted on DIFFERENT hardware are not
        # a perf diff, they are a hardware change (docs/calibration.md)
        base_fp = base.get("calibration_fingerprint")
        cand_fp = candidate.get("calibration_fingerprint")
        if base_fp is not None and cand_fp is not None \
                and base_fp != cand_fp:
            raise GateError(
                f"{candidate.name}: not comparable with {base.name} — "
                f"calibration_fingerprint {base_fp!r} vs {cand_fp!r} "
                f"(calibrated on "
                f"{base.get('calibration_device_kind')!r} vs "
                f"{candidate.get('calibration_device_kind')!r}); a "
                f"perf diff across different measured hardware models "
                f"is meaningless — recalibrate on one machine "
                f"(bench --calibrate) or drop the stale artifact, "
                f"refusing")


def _keys_match(a: Artifact, b: Artifact, keys: Tuple[str, ...]) -> bool:
    for k in keys:
        va, vb = a.get(k), b.get(k)
        if isinstance(va, float) or isinstance(vb, float):
            if va is None or vb is None:
                if va is not vb:
                    return False
            elif abs(float(va) - float(vb)) > 1e-3 * max(
                    abs(float(va)), abs(float(vb)), 1e-12):
                return False
        elif va != vb:
            return False
    return True


def _numeric(v) -> Optional[float]:
    return float(v) if isinstance(v, (int, float)) \
        and not isinstance(v, bool) else None


def diff(baseline: Sequence[Artifact], candidate: Artifact,
         tol: Tolerances) -> List[GateFinding]:
    """All regressions of ``candidate`` vs the best comparable baseline
    values.  Pure function of its inputs — the gate's two-run
    determinism contract."""
    findings: List[GateFinding] = []

    # PERF004 — a failed run can't vouch for anything
    if candidate.get("rc", 0) not in (0, None) \
            or candidate.get("ok") is False:
        findings.append(GateFinding(
            "PERF004",
            f"{candidate.name}: artifact reports a failed run "
            f"(rc={candidate.get('rc')!r}, ok={candidate.get('ok')!r}) "
            f"— fix the run before gating on its numbers"))

    # PERF001 — throughput
    for field, keys in THROUGHPUT_FIELDS:
        cand_v = _numeric(candidate.get(field))
        if cand_v is None:
            continue
        best: Optional[Tuple[float, str]] = None
        for base in baseline:
            base_v = _numeric(base.get(field))
            if base_v is None or not _keys_match(base, candidate, keys):
                continue
            if best is None or base_v > best[0]:
                best = (base_v, base.name)
        if best is None:
            continue
        ref, ref_name = best
        if ref > 0 and cand_v < (1.0 - tol.throughput) * ref:
            drop = (ref - cand_v) / ref
            findings.append(GateFinding(
                "PERF001",
                f"{candidate.name}: {field} regressed "
                f"{drop * 100:.1f}% ({cand_v:g} vs {ref:g} in "
                f"{ref_name}; tolerance "
                f"{tol.throughput * 100:.0f}%)"))

    # PERF005 — latency (lower is better): growth beyond the
    # throughput tolerance vs the best (lowest) comparable baseline
    for field, keys in LATENCY_FIELDS:
        cand_v = _numeric(candidate.get(field))
        if cand_v is None:
            continue
        best = None
        for base in baseline:
            base_v = _numeric(base.get(field))
            if base_v is None or not _keys_match(base, candidate, keys):
                continue
            if best is None or base_v < best[0]:
                best = (base_v, base.name)
        if best is None:
            continue
        ref, ref_name = best
        if ref > 0 and cand_v > (1.0 + tol.throughput) * ref:
            growth = (cand_v - ref) / ref
            findings.append(GateFinding(
                "PERF005",
                f"{candidate.name}: {field} inflated "
                f"{growth * 100:.1f}% ({cand_v:g} vs {ref:g} in "
                f"{ref_name}; tolerance "
                f"{tol.throughput * 100:.0f}%) — tail latency "
                f"regressed under the same offered load"))

    # PERF006 — HBM high-water (lower is better): growth beyond the
    # memory tolerance vs the best (lowest) comparable baseline
    for field, keys in MEMORY_FIELDS:
        cand_v = _numeric(candidate.get(field))
        if cand_v is None:
            continue
        best = None
        for base in baseline:
            base_v = _numeric(base.get(field))
            if base_v is None or not _keys_match(base, candidate, keys):
                continue
            if best is None or base_v < best[0]:
                best = (base_v, base.name)
        if best is None:
            continue
        ref, ref_name = best
        if ref > 0 and cand_v > (1.0 + tol.memory) * ref:
            growth = (cand_v - ref) / ref
            findings.append(GateFinding(
                "PERF006",
                f"{candidate.name}: {field} grew "
                f"{growth * 100:.1f}% ({cand_v:g} vs {ref:g} in "
                f"{ref_name}; tolerance {tol.memory * 100:.0f}%) — "
                f"more HBM at the same remat policy and plan"))

    # PERF002 — measured overlap
    for key in sorted(candidate.fields):
        if not key.endswith("overlap_fraction") \
                or key.endswith("h2d_overlap_fraction"):
            continue
        cand_v = _numeric(candidate.get(key))
        if cand_v is None:
            continue
        refs = [(v, b.name) for b in baseline
                if (v := _numeric(b.get(key))) is not None]
        if not refs:
            continue
        ref, ref_name = max(refs)
        if ref - cand_v > tol.overlap:
            findings.append(GateFinding(
                "PERF002",
                f"{candidate.name}: {key} dropped {ref - cand_v:.2f} "
                f"({cand_v:.2f} vs {ref:.2f} in {ref_name}; tolerance "
                f"{tol.overlap:.2f} absolute) — the exchange lost its "
                f"compute overlap"))

    # PERF003 — wire bytes per level, comparable only at the same
    # hierarchy (two_level vs flat is a topology change, not a leak)
    for key in sorted(candidate.fields):
        if not (key.endswith("exchange_wire_bytes_ici")
                or key.endswith("exchange_wire_bytes_dcn")):
            continue
        cand_v = _numeric(candidate.get(key))
        if cand_v is None:
            continue
        prefix = key[: -len("exchange_wire_bytes_ici")] \
            if key.endswith("_ici") else \
            key[: -len("exchange_wire_bytes_dcn")]
        hier_key = f"{prefix}exchange_hierarchy"
        refs = [(v, b.name) for b in baseline
                if b.get(hier_key) == candidate.get(hier_key)
                and (v := _numeric(b.get(key))) is not None]
        if not refs:
            continue
        ref, ref_name = min(refs)
        if ref >= 0 and cand_v > (1.0 + tol.wire) * max(ref, 1.0):
            growth = (cand_v - ref) / max(ref, 1.0)
            findings.append(GateFinding(
                "PERF003",
                f"{candidate.name}: {key} grew {growth * 100:.1f}% "
                f"({cand_v:g} vs {ref:g} in {ref_name}; tolerance "
                f"{tol.wire * 100:.0f}%) — more bytes on the wire for "
                f"the same exchange"))
    return findings


@dataclasses.dataclass
class GateReport:
    findings: List[GateFinding]
    artifacts: List[str]
    candidate: Optional[str]
    predictions: List[Dict]      # cost-model context, informational

    @property
    def exit_code(self) -> int:
        return 1 if self.findings else 0

    def as_json(self) -> dict:
        return {"findings": [f.as_json() for f in self.findings],
                "artifacts": self.artifacts,
                "candidate": self.candidate,
                "predictions": self.predictions}


def _predictions(trajectory: Sequence[Artifact],
                 target: Artifact) -> List[Dict]:
    """Calibrated-roofline context for the report: predicted vs
    measured rate per family, calibrated on the trajectory *excluding*
    the target.  Informational — the gate's verdict comes from the
    direct diffs; this line is what tells a reader whether a failure
    is 'model drifted' or 'run collapsed'."""
    out: List[Dict] = []
    # the roofline is calibrated on TPU rounds; predicting a known
    # non-TPU artifact (CPU twin runs) with v5e constants is noise
    platform = target.get("platform")
    if platform is not None and platform != "tpu":
        return out
    # calibration artifact > preset knob > device_kind preset > v5e;
    # device_kind only steers the preset on real TPU artifacts — the
    # precedence chain of docs/calibration.md
    hw = CM.resolve_hardware_model(
        device_kind=target.get("device_kind")
        if platform == "tpu" else None)
    cal = CM.calibrate([t.fields for t in trajectory
                        if t.name != target.name], hw=hw)
    for w in CM.workloads_from_artifact(target.fields):
        pred = CM.predict_rate(cal, w)
        measured = _numeric(target.get(w.rate_field))
        if pred is None or measured is None:
            continue
        out.append({
            "family": w.family, "field": w.rate_field,
            "predicted": round(pred, 1), "measured": measured,
            "error": round(abs(pred - measured) / measured, 4)
            if measured else None})
    return out


def run_gate(trajectory_paths: Sequence[str],
             candidate_path: Optional[str] = None,
             tolerances: Optional[Tolerances] = None) -> GateReport:
    """Run the gate: candidate-vs-trajectory when ``candidate_path`` is
    given, else the trajectory self-walk (each artifact vs its
    predecessors).  Deterministic for fixed inputs + env."""
    tol = tolerances or Tolerances.from_env()
    trajectory = [load_artifact(p) for p in trajectory_paths]
    if not trajectory:
        raise GateError("perf gate needs at least one trajectory "
                        "artifact (BENCH_r0*.json)")
    findings: List[GateFinding] = []
    if candidate_path is not None:
        candidate = load_artifact(candidate_path)
        check_comparable(trajectory, candidate)
        findings = diff(trajectory, candidate, tol)
        predictions = _predictions(trajectory, candidate)
        cand_name = candidate.name
    else:
        for i in range(1, len(trajectory)):
            check_comparable(trajectory[:i], trajectory[i])
            findings.extend(diff(trajectory[:i], trajectory[i], tol))
        # prediction context anchors on the newest artifact that
        # actually measures a workload (MULTICHIP stubs carry none)
        target = next((t for t in reversed(trajectory)
                       if CM.workloads_from_artifact(t.fields)),
                      trajectory[-1])
        predictions = _predictions(trajectory, target)
        cand_name = None
    return GateReport(findings=findings,
                      artifacts=[t.name for t in trajectory],
                      candidate=cand_name, predictions=predictions)


# -- CLI (python -m horovod_tpu.analysis perf-gate / hvdlint perf-gate) -----


def default_trajectory(root: Optional[str] = None) -> List[str]:
    """The checked-in trajectory: ``BENCH_r0*.json`` +
    ``MULTICHIP_r0*.json`` at the repo root, oldest→newest."""
    root = root or engine.find_repo_root(os.getcwd()) or os.getcwd()
    return (sorted(_glob.glob(os.path.join(root, "BENCH_r0*.json")))
            + sorted(_glob.glob(os.path.join(root,
                                             "MULTICHIP_r0*.json"))))


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m horovod_tpu.analysis perf-gate",
        description="perf regression gate: diff bench artifacts "
                    "against the checked-in trajectory "
                    "(docs/perf_gate.md)")
    p.add_argument("--trajectory", action="append", default=[],
                   metavar="PATH",
                   help="baseline artifact path or glob (repeatable; "
                        "default: <repo>/BENCH_r0*.json + "
                        "MULTICHIP_r0*.json)")
    p.add_argument("--candidate", default=None, metavar="PATH",
                   help="new bench --json-out artifact to gate; "
                        "without it the trajectory self-walk runs")
    p.add_argument("--tolerance", type=float, default=None,
                   help="relative throughput-drop tolerance (overrides "
                        "HOROVOD_PERF_GATE_TOLERANCE; default 0.10)")
    p.add_argument("--json", action="store_true", dest="json_out")
    args = p.parse_args(argv)

    try:
        paths: List[str] = []
        for pat in args.trajectory:
            hits = sorted(_glob.glob(pat))
            if not hits and os.path.exists(pat):
                hits = [pat]
            if not hits:
                raise GateError(f"--trajectory {pat}: no artifacts "
                                f"match")
            paths.extend(hits)
        if not paths:
            paths = default_trajectory()
        report = run_gate(paths, candidate_path=args.candidate,
                          tolerances=Tolerances.from_env(
                              throughput=args.tolerance))
    except GateError as e:
        print(f"perf-gate: {e}", file=sys.stderr)
        return 2

    if args.json_out:
        print(json.dumps(report.as_json(), indent=2))
    else:
        for f in report.findings:
            print(f.format())
        for pr in report.predictions:
            print(f"perf-gate: cost model [{pr['family']}] predicted "
                  f"{pr['predicted']:g} {pr['field']}, measured "
                  f"{pr['measured']:g} ({pr['error'] * 100:.1f}% off)")
        verdict = "FAIL" if report.findings else "ok"
        target = report.candidate or "trajectory self-walk"
        print(f"perf-gate: {target} vs {len(report.artifacts)} "
              f"artifact(s): {len(report.findings)} finding(s) — "
              f"{verdict}")
    return report.exit_code


if __name__ == "__main__":
    sys.exit(main())
