"""``hvdlint`` core: finding/severity model, suppressions, baseline,
module loading and the rule-driver loop.

The analyzer is the compile-time half of the correctness contract the
runtime guards (HLO tests, chaos plans) enforce dynamically: every rule
is grounded in a failure class this repo has already paid for at least
once — a rank-divergent collective deadlocks a pod, a host sync inside
the jitted step stalls dispatch, an unstable AOT key silently re-pays
the 40-50 s compile, an unlocked cross-thread mutation corrupts the
elastic bookkeeping.  Rules are AST-based (no imports of the analyzed
code, so a broken module can still be linted) and cheap enough that the
package-wide self-run is a tier-1 test.

Model:

* :class:`Finding` — one violation: rule id, severity (P0 worst → P3),
  location, message, and the stripped source line (``context``) that
  doubles as its line-shift-stable baseline identity.
* suppression — ``# hvd: disable=HVD001 -- <reason>`` on the flagged
  line or on a comment line directly above it.  The reason is
  mandatory: a reasonless disable is itself a finding (``HVD000``), so
  a suppression always documents *why* the rule is wrong here.
* baseline — a checked-in JSON of accepted findings, matched by
  ``(rule, path, context)``; new code cannot hide behind it because any
  new finding has a context line the baseline has never seen.
"""

from __future__ import annotations

import ast
import dataclasses
import enum
import json
import os
import re
import subprocess
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple


class Severity(enum.IntEnum):
    """P0 (pod-deadlock class) is the worst; P3 is advisory."""

    P0 = 0
    P1 = 1
    P2 = 2
    P3 = 3

    def __str__(self) -> str:  # noqa: D105
        return self.name


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    severity: Severity
    path: str              # posix path relative to the scan root
    line: int
    col: int
    message: str
    context: str = ""      # stripped source line (baseline identity)

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.context)

    def format(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule} [{self.severity}] {self.message}")

    def as_json(self) -> dict:
        return {"rule": self.rule, "severity": str(self.severity),
                "path": self.path, "line": self.line, "col": self.col,
                "message": self.message, "context": self.context}


# ``# hvd: disable=HVD001[,HVD004] -- reason`` (reason mandatory; the
# engine turns a missing one into an HVD000 finding)
_SUPPRESS_RE = re.compile(
    r"#\s*hvd:\s*disable=([A-Za-z0-9_,\s\*]+?)\s*(?:--\s*(.*?))?\s*$")


@dataclasses.dataclass
class Suppression:
    rules: Set[str]        # rule ids, or {"*"}
    reason: str
    line: int

    def covers(self, rule: str) -> bool:
        return "*" in self.rules or rule in self.rules


class Module:
    """One parsed source file plus its per-line suppression table."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[SyntaxError] = None
        try:
            self.tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            self.parse_error = e
        self.suppressions: Dict[int, Suppression] = {}
        self.bad_suppressions: List[int] = []
        for i, text in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            reason = (m.group(2) or "").strip()
            if not reason:
                self.bad_suppressions.append(i)
                continue
            self.suppressions[i] = Suppression(rules, reason, i)

    def context_line(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def suppression_for(self, finding: Finding) -> Optional[Suppression]:
        """Inline on the finding's line, or a comment-only line directly
        above it."""
        s = self.suppressions.get(finding.line)
        if s is not None and s.covers(finding.rule):
            return s
        prev = finding.line - 1
        s = self.suppressions.get(prev)
        if s is not None and s.covers(finding.rule) and \
                self.context_line(prev).startswith("#"):
            return s
        return None


class Project:
    """The full analyzed file set plus repo-level context shared by the
    cross-module rules (docs text for HVD005, the knob registry, the
    lock graph for HVD004)."""

    def __init__(self, modules: Sequence[Module], root: str,
                 repo_root: Optional[str] = None):
        self.modules = list(modules)
        self.root = root
        self.repo_root = repo_root or find_repo_root(root) or root
        self._docs_text: Optional[str] = None

    def module(self, relpath_suffix: str) -> Optional[Module]:
        for m in self.modules:
            if m.relpath.endswith(relpath_suffix):
                return m
        return None

    def docs_text(self) -> str:
        """Concatenated documentation the HVD005 doc-drift check scans —
        the same corpus ``tests/test_env_knob_docs.py`` used before it
        delegated here."""
        if self._docs_text is not None:
            return self._docs_text
        texts = []
        docs = os.path.join(self.repo_root, "docs")
        if os.path.isdir(docs):
            for base, _, names in sorted(os.walk(docs)):
                for n in sorted(names):
                    if n.endswith(".md"):
                        texts.append(_read(os.path.join(base, n)))
        for name in ("README.md", "PERF_NOTES.md"):
            p = os.path.join(self.repo_root, name)
            if os.path.exists(p):
                texts.append(_read(p))
        self._docs_text = "\n".join(texts)
        return self._docs_text


class Rule:
    """One lint rule.  ``check`` runs per module; ``finalize`` runs once
    with the whole project (cross-module invariants)."""

    id: str = "HVD000"
    severity: Severity = Severity.P2
    name: str = ""
    rationale: str = ""

    def check(self, module: Module, project: Project) -> Iterable[Finding]:
        return ()

    def finalize(self, project: Project) -> Iterable[Finding]:
        return ()

    def finding(self, module: Module, node, message: str,
                severity: Optional[Severity] = None) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(rule=self.id,
                       severity=severity or self.severity,
                       path=module.relpath, line=line, col=col,
                       message=message,
                       context=module.context_line(line))


def _read(path: str) -> str:
    with open(path, "r", errors="replace") as f:
        return f.read()


def find_repo_root(start: str) -> Optional[str]:
    cur = os.path.abspath(start)
    if os.path.isfile(cur):
        cur = os.path.dirname(cur)
    while True:
        if os.path.exists(os.path.join(cur, "pyproject.toml")) or \
                os.path.isdir(os.path.join(cur, ".git")):
            return cur
        parent = os.path.dirname(cur)
        if parent == cur:
            return None
        cur = parent


def collect_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for base, dirs, names in os.walk(p):
                dirs[:] = [d for d in dirs if d != "__pycache__"]
                for n in sorted(names):
                    if n.endswith(".py"):
                        out.append(os.path.join(base, n))
        elif p.endswith(".py"):
            out.append(p)
    return sorted(dict.fromkeys(out))


def changed_files(repo_root: str) -> List[str]:
    """``--changed`` scope: files touched vs HEAD (staged + unstaged)
    plus untracked — the pre-commit view of the working tree."""
    def git(*args: str) -> List[str]:
        res = subprocess.run(["git", "-C", repo_root, *args],
                             capture_output=True, text=True, check=True)
        return [ln for ln in res.stdout.splitlines() if ln.strip()]

    names = set(git("diff", "--name-only", "HEAD"))
    names.update(git("ls-files", "--others", "--exclude-standard"))
    return sorted(os.path.join(repo_root, n) for n in names
                  if n.endswith(".py") and
                  os.path.exists(os.path.join(repo_root, n)))


# -- baseline ---------------------------------------------------------------

BASELINE_VERSION = 1


def load_baseline(path: str) -> Set[Tuple[str, str, str]]:
    with open(path, "r") as f:
        data = json.load(f)
    return {(f_["rule"], f_["path"], f_.get("context", ""))
            for f_ in data.get("findings", [])}


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    entries = sorted({f.key() for f in findings})
    data = {"version": BASELINE_VERSION,
            "findings": [{"rule": r, "path": p, "context": c}
                         for (r, p, c) in entries]}
    with open(path, "w") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")


# -- the driver loop --------------------------------------------------------

@dataclasses.dataclass
class Report:
    findings: List[Finding]                       # live (actionable)
    suppressed: List[Tuple[Finding, str]]         # (finding, reason)
    baselined: List[Finding]
    files_scanned: int = 0

    @property
    def exit_code(self) -> int:
        return 1 if self.findings else 0

    def as_json(self) -> dict:
        return {
            "files_scanned": self.files_scanned,
            "findings": [f.as_json() for f in self.findings],
            "suppressed": [dict(f.as_json(), reason=r)
                           for f, r in self.suppressed],
            "baselined": [f.as_json() for f in self.baselined],
        }


def default_rules() -> List[Rule]:
    from horovod_tpu.analysis.rules_distributed import (
        CollectiveDivergenceRule,
        HostSyncInHotPathRule,
        RetraceHazardRule,
    )
    from horovod_tpu.analysis.rules_runtime import (
        EnvKnobRegistryRule,
        FaultHookCoverageRule,
    )
    from horovod_tpu.analysis.rules_threads import ThreadLockDisciplineRule

    return [CollectiveDivergenceRule(), HostSyncInHotPathRule(),
            RetraceHazardRule(), ThreadLockDisciplineRule(),
            EnvKnobRegistryRule(), FaultHookCoverageRule()]


def load_modules(files: Sequence[str], root: str) -> List[Module]:
    modules = []
    for path in files:
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        modules.append(Module(path, rel, _read(path)))
    return modules


def run_analysis(paths: Sequence[str],
                 select: Optional[Set[str]] = None,
                 baseline_path: Optional[str] = None,
                 rules: Optional[Sequence[Rule]] = None,
                 root: Optional[str] = None) -> Report:
    """Lint ``paths`` (files or directories) and return the report.

    ``select`` restricts to a set of rule ids; ``baseline_path`` (when
    it exists) removes previously-accepted findings; ``root`` anchors
    the relative paths findings/baselines use (default: the repo root
    above the first path, so baselines are stable no matter where the
    CLI is invoked from)."""
    files = collect_files(paths)
    if root is None:
        root = find_repo_root(paths[0] if files else os.getcwd()) \
            or os.getcwd()
    modules = load_modules(files, root)
    project = Project(modules, root=root)
    active = [r for r in (rules if rules is not None else default_rules())
              if select is None or r.id in select]

    raw: List[Finding] = []
    for m in modules:
        if m.parse_error is not None:
            raw.append(Finding(
                rule="HVD000", severity=Severity.P1, path=m.relpath,
                line=m.parse_error.lineno or 1, col=0,
                message=f"syntax error: {m.parse_error.msg}",
                context=m.context_line(m.parse_error.lineno or 1)))
            continue
        for line in m.bad_suppressions:
            raw.append(Finding(
                rule="HVD000", severity=Severity.P1, path=m.relpath,
                line=line, col=0,
                message="suppression without a reason — write "
                        "'# hvd: disable=RULE -- why this is a false "
                        "positive here'",
                context=m.context_line(line)))
        for rule in active:
            raw.extend(rule.check(m, project))
    for rule in active:
        raw.extend(rule.finalize(project))

    by_path = {m.relpath: m for m in modules}
    baseline = load_baseline(baseline_path) \
        if baseline_path and os.path.exists(baseline_path) else set()

    live: List[Finding] = []
    suppressed: List[Tuple[Finding, str]] = []
    baselined: List[Finding] = []
    for f in sorted(raw, key=lambda f: (f.severity, f.path, f.line)):
        m = by_path.get(f.path)
        sup = m.suppression_for(f) if m is not None else None
        # HVD000 (engine hygiene) cannot be suppressed or baselined —
        # otherwise a reasonless disable could disable the rule that
        # flags reasonless disables
        if f.rule != "HVD000":
            if sup is not None:
                suppressed.append((f, sup.reason))
                continue
            if f.key() in baseline:
                baselined.append(f)
                continue
        live.append(f)
    return Report(findings=live, suppressed=suppressed,
                  baselined=baselined, files_scanned=len(files))
