"""hvdlint: distributed-correctness static analysis for horovod_tpu.

The compile-time half of the repo's correctness tooling (docs/
analysis.md): an AST-based rule engine that finds the bug classes the
paper's runtime controller policed dynamically — rank-divergent
collectives (HVD001), host syncs in jitted bodies (HVD002), retrace/
warm-start-miss hazards (HVD003), unlocked cross-thread mutations and
lock-order inversions (HVD004), undeclared/undocumented env knobs
(HVD005), chaos-hook coverage rot (HVD006) — plus an offline HLO/
bench-artifact rule pack (:mod:`~horovod_tpu.analysis.hlo_lint`), the
static HLO cost model (:mod:`~horovod_tpu.analysis.cost_model`:
per-op FLOPs, per-level wire bytes, memory high-water, calibrated
roofline) and the perf regression gate
(:mod:`~horovod_tpu.analysis.perf_gate`, PERF001-PERF004).

The package self-run is a tier-1 test (``tests/test_analysis.py``),
and so are the perf gate's trajectory walk and the combined CI entry
point (``tests/test_perf_gate.py``)::

    python -m horovod_tpu.analysis horovod_tpu/
    python -m horovod_tpu.analysis --changed --json
    python -m horovod_tpu.analysis --artifact BENCH_r05.json
    python -m horovod_tpu.analysis perf-gate --candidate new.json
    python -m horovod_tpu.analysis ci

The rule engine is AST-only and never imports the analyzed code, so a
module that cannot import (missing optional dep, syntax error) can
still be linted.
"""

from horovod_tpu.analysis.engine import (
    Finding,
    Report,
    Rule,
    Severity,
    default_rules,
    run_analysis,
    write_baseline,
)
from horovod_tpu.analysis.perf_gate import (
    GateError,
    GateFinding,
    Tolerances,
    run_gate,
)

__all__ = [
    "Finding",
    "GateError",
    "GateFinding",
    "Report",
    "Rule",
    "Severity",
    "Tolerances",
    "default_rules",
    "run_analysis",
    "run_gate",
    "write_baseline",
]
