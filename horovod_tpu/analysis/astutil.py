"""Shared AST plumbing for the ``hvdlint`` rules.

Every rule needs the same three capabilities: resolving a call/decorator
to a dotted name (``jax.jit``, ``threading.Thread``), walking *upward*
(is this collective call inside a rank-dependent branch? is this
mutation under ``with self._lock``?), and mapping functions to the
functions they reference (thread targets, jit-wrapped defs).  They live
here so the rule modules stay readable statements of their invariant.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Tuple


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains; for a Call, the callee's
    dotted name.  ``None`` for anything dynamic (subscripts, calls in
    the middle of the chain)."""
    if isinstance(node, ast.Call):
        return dotted_name(node.func)
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted_name(node.value)
        return None if base is None else f"{base}.{node.attr}"
    return None


def name_tail(node: ast.AST) -> Optional[str]:
    """The last segment of a dotted name (``jit`` for ``jax.jit``)."""
    d = dotted_name(node)
    return None if d is None else d.rsplit(".", 1)[-1]


class ParentMap:
    """child → parent links for one module tree, so rules can walk
    upward (ancestor If/With/FunctionDef) from any node."""

    def __init__(self, tree: ast.AST):
        self._parent: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self._parent[child] = parent

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parent.get(node)

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self._parent.get(node)
        while cur is not None:
            yield cur
            cur = self._parent.get(cur)

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        for a in self.ancestors(node):
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return a
        return None

    def enclosing_class(self, node: ast.AST) -> Optional[ast.ClassDef]:
        for a in self.ancestors(node):
            if isinstance(a, ast.ClassDef):
                return a
        return None


def self_attr(node: ast.AST) -> Optional[str]:
    """``x`` when ``node`` is exactly ``self.x``, else ``None``."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def assign_targets(stmt: ast.AST) -> List[ast.AST]:
    """The target expressions a statement writes to (Assign/AugAssign/
    AnnAssign, tuple targets flattened)."""
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    else:
        return []
    flat: List[ast.AST] = []
    for t in targets:
        if isinstance(t, (ast.Tuple, ast.List)):
            flat.extend(t.elts)
        else:
            flat.append(t)
    return flat


def iter_self_attr_stores(fn: ast.AST) -> Iterator[Tuple[str, ast.AST]]:
    """``(attr, node)`` for every ``self.attr = ...`` (or aug/ann
    assign) anywhere inside ``fn``, including nested functions."""
    for node in ast.walk(fn):
        for target in assign_targets(node):
            attr = self_attr(target)
            if attr is not None:
                yield attr, node


_LOCKISH = ("lock", "mutex", "cond")


def is_lock_expr(expr: ast.AST) -> Optional[str]:
    """A ``with`` context expression that is a lock by naming
    convention: ``self._lock`` / a bare ``lock`` name / ``x.lock`` —
    returns the lock's attribute/bare name, else None."""
    name = self_attr(expr)
    if name is None:
        if isinstance(expr, ast.Name):
            name = expr.id
        elif isinstance(expr, ast.Attribute):
            name = expr.attr
        elif isinstance(expr, ast.Call):
            # ``with self._lock:`` is the idiom; ``with self._lock.acquire()``
            # style does not exist here, but ``with lock_for(x):`` might
            return is_lock_expr(expr.func)
    if name is None:
        return None
    low = name.lower()
    return name if any(k in low for k in _LOCKISH) else None


def with_lock_names(node: ast.With) -> List[str]:
    out = []
    for item in node.items:
        n = is_lock_expr(item.context_expr)
        if n is not None:
            out.append(n)
    return out


def under_lock(node: ast.AST, parents: ParentMap) -> bool:
    """Is ``node`` lexically inside a ``with <lock>:`` block (within its
    own function — a lock held by a *caller* is invisible here, which is
    exactly the discipline the rule wants to enforce: mutations of
    shared state should sit visibly under the class's declared lock)."""
    fn = parents.enclosing_function(node)
    for a in parents.ancestors(node):
        if a is fn:
            break
        if isinstance(a, ast.With) and with_lock_names(a):
            return True
    return False


def local_functions(fn: ast.AST) -> Dict[str, ast.FunctionDef]:
    """Nested ``def``s of a function body, by name (one level)."""
    out = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.FunctionDef) and node is not fn:
            out.setdefault(node.name, node)
    return out


def called_names(fn: ast.AST) -> List[str]:
    """Dotted names of every call inside ``fn``."""
    out = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            d = dotted_name(node.func)
            if d is not None:
                out.append(d)
    return out


def str_constants(tree: ast.AST) -> Iterator[Tuple[str, ast.Constant]]:
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            yield node.value, node
