"""Schema validation for hvdtel metric snapshots (docs/metrics.md).

Two artifact shapes share one contract:

* a **JSONL snapshot log** (``HOROVOD_METRICS_LOG``): one
  ``schema_version``-stamped object per line, written by
  ``telemetry.MetricsSnapshotWriter``;
* the **BENCH-embedded block**: ``bench.py`` folds the final counters
  into BENCH JSON under the ``"metrics"`` key.

``hvdci`` (``analysis/ci.py``) validates the embedded block of every
checked-in BENCH artifact, and ``python -m horovod_tpu.analysis
metrics-check PATH`` validates either shape from the command line — so
a telemetry schema change that would break a scraper or the perf-gate
diff fails tier-1, not a dashboard at 3 a.m.

Validators return a list of error strings (empty = valid) rather than
raising: callers decide severity.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

SCHEMA_VERSION = 1
SNAPSHOT_KIND = "hvdtel_snapshot"

_NUM = (int, float)

# the guard plane's closed series vocabulary (docs/guardian.md): any
# series in the hvd_guard_* namespace must be one of these base names,
# so a typo'd or ad-hoc guard metric fails tier-1 instead of silently
# forking the dashboard contract
GUARD_SERIES = frozenset({
    "hvd_guard_checks_total",
    "hvd_guard_checksum_seconds",
    "hvd_guard_anomalies_total",
    "hvd_guard_skipped_steps_total",
    "hvd_guard_grad_norm",
    "hvd_guard_rollbacks_total",
    "hvd_guard_steps_replayed",
    "hvd_guard_last_good_step",
    "hvd_guard_divergence_rank",
    "hvd_guard_preempt_departures_total",
    "hvd_guard_preempt_drains_total",
})

# the serving plane's closed series vocabulary (docs/serving.md): same
# contract as GUARD_SERIES for the hvd_serve_* namespace
SERVE_SERIES = frozenset({
    "hvd_serve_queue_depth",
    "hvd_serve_admitted_total",
    "hvd_serve_shed_total",
    "hvd_serve_completed_total",
    "hvd_serve_requeued_total",
    "hvd_serve_batches_total",
    "hvd_serve_batch_occupancy",
    "hvd_serve_latency_seconds",
    "hvd_serve_replicas",
    "hvd_serve_replica_deaths_total",
    "hvd_serve_drains_total",
    "hvd_serve_drain_timeouts_total",
    "hvd_serve_scale_events_total",
    # hvdfleet (ISSUE 20): tenancy / live refresh / closed-loop
    # autoscale — serve/tenancy.py, serve/refresh.py, serve/autoscale.py
    "hvd_serve_tenant_admitted_total",
    "hvd_serve_tenant_shed_total",
    "hvd_serve_tenant_picks_total",
    "hvd_serve_tenant_share",
    "hvd_serve_refresh_staged_total",
    "hvd_serve_refresh_flips_total",
    "hvd_serve_refresh_rollbacks_total",
    "hvd_serve_refresh_superseded_total",
    "hvd_serve_scale_ups_total",
    "hvd_serve_scale_downs_total",
    "hvd_serve_scale_suppressed_total",
    "hvd_serve_scale_target",
})

# the elastic plane's closed series vocabulary (docs/elastic.md,
# docs/faults.md): generation lifecycle, commit/restore bookkeeping and
# per-worker health verdicts in the hvd_elastic_* namespace
ELASTIC_SERIES = frozenset({
    "hvd_elastic_generations_ready_total",
    "hvd_elastic_recovery_seconds",
    "hvd_elastic_generation_detect_seconds",
    "hvd_elastic_generation_steps_lost",
    "hvd_elastic_generation",
    "hvd_elastic_world_size",
    "hvd_elastic_commits_total",
    "hvd_elastic_steps_committed",
    "hvd_elastic_restore_seconds",
    "hvd_elastic_restored_step",
    "hvd_elastic_steps_lost",
    "hvd_elastic_worker_suspect_total",
    "hvd_elastic_worker_deaths_total",
    "hvd_elastic_detect_seconds",
    "hvd_elastic_straggler_ratio",
})

# the graceful-degradation plane's closed series vocabulary
# (docs/elastic.md "Degraded mode"): plan transitions, wait verdicts and
# the degraded-world gauges in the hvd_degrade_* namespace
DEGRADE_SERIES = frozenset({
    "hvd_degrade_transitions_total",
    "hvd_degrade_waits_total",
    "hvd_degrade_active",
    "hvd_degrade_data_extent",
    "hvd_degrade_grad_accum",
    "hvd_degrade_transition_seconds",
    "hvd_degrade_promoted_step",
})

# the memory plane's closed series vocabulary (docs/memory.md): host
# offload traffic/stalls/degrades plus the HBM accounting gauges the
# budget autotuner reports, in the hvd_memory_* namespace
MEMORY_SERIES = frozenset({
    "hvd_memory_offload_bytes_total",
    "hvd_memory_offload_stall_seconds",
    "hvd_memory_offload_inflight",
    "hvd_memory_offload_fallbacks_total",
    "hvd_memory_hbm_high_water_bytes",
    "hvd_memory_plan_bytes",
})

# the MoE expert-dispatch plane's closed series vocabulary
# (docs/fused_kernels.md "Expert-parallel dispatch", docs/moe.md):
# routing quality (drop fraction, per-expert utilization) and the
# ep-ring wire gauge in the hvd_moe_* namespace.  The fused-launch
# counter lives in the hvd_pallas namespace
# (hvd_pallas_fused_launches_total{kernel="a2a_matmul"}) and is open
# by design — new fused kernels add label values, not series.
MOE_SERIES = frozenset({
    "hvd_moe_drop_fraction",
    "hvd_moe_expert_utilization",
    "hvd_moe_ep_wire_bytes",
})

# the sequence-parallel (sp ring) plane's closed series vocabulary
# (docs/fused_kernels.md "Ring-flash attention"): the K/V ring wire
# gauge and the causal launch schedule counters in the hvd_sp_*
# namespace.  As with MoE, the fused-launch counter lives in the open
# hvd_pallas namespace
# (hvd_pallas_fused_launches_total{kernel="ring_flash_attention"})
SP_SERIES = frozenset({
    "hvd_sp_ring_wire_bytes",
    "hvd_sp_ring_steps",
    "hvd_sp_skipped_ring_steps",
})

# the hardware-calibration plane's closed series vocabulary
# (docs/calibration.md): sweep volume, fitted curves and the worst
# per-curve RMS residual ``bench --calibrate`` reports, in the
# hvd_calibration_* namespace
CALIBRATION_SERIES = frozenset({
    "hvd_calibration_points_total",
    "hvd_calibration_fits_total",
    "hvd_calibration_fit_residual_max",
})

# the adasum reduction-operator plane's closed series vocabulary
# (docs/adasum.md): outer-level exchange constructions (trace-time,
# labelled by the level's mesh axis), the cost-model-priced extra DCN
# bytes of the pairwise dot/norm round, and the zero-norm → plain-sum
# guard activations, in the hvd_adasum_* namespace
ADASUM_SERIES = frozenset({
    "hvd_adasum_steps_total",
    "hvd_adasum_dot_wire_bytes",
    "hvd_adasum_zero_norm_fallbacks_total",
})


def _check_adasum_series(errors: List[str], obj, field: str) -> None:
    if not isinstance(obj, dict):
        return      # shape error already reported by _check_series_map
    for k in obj:
        if isinstance(k, str) and k.startswith("hvd_adasum"):
            base = k.split("{", 1)[0]
            if base not in ADASUM_SERIES:
                errors.append(
                    f"{field}[{k!r}]: unknown adasum series {base!r} — "
                    f"not in metrics_schema.ADASUM_SERIES")


def _check_guard_series(errors: List[str], obj, field: str) -> None:
    if not isinstance(obj, dict):
        return      # shape error already reported by _check_series_map
    for k in obj:
        if isinstance(k, str) and k.startswith("hvd_guard"):
            base = k.split("{", 1)[0]
            if base not in GUARD_SERIES:
                errors.append(
                    f"{field}[{k!r}]: unknown guard series {base!r} — "
                    f"not in metrics_schema.GUARD_SERIES")


def _check_serve_series(errors: List[str], obj, field: str) -> None:
    if not isinstance(obj, dict):
        return      # shape error already reported by _check_series_map
    for k in obj:
        if isinstance(k, str) and k.startswith("hvd_serve"):
            base = k.split("{", 1)[0]
            if base not in SERVE_SERIES:
                errors.append(
                    f"{field}[{k!r}]: unknown serve series {base!r} — "
                    f"not in metrics_schema.SERVE_SERIES")


def _check_elastic_series(errors: List[str], obj, field: str) -> None:
    if not isinstance(obj, dict):
        return      # shape error already reported by _check_series_map
    for k in obj:
        if isinstance(k, str) and k.startswith("hvd_elastic"):
            base = k.split("{", 1)[0]
            if base not in ELASTIC_SERIES:
                errors.append(
                    f"{field}[{k!r}]: unknown elastic series {base!r} — "
                    f"not in metrics_schema.ELASTIC_SERIES")


def _check_degrade_series(errors: List[str], obj, field: str) -> None:
    if not isinstance(obj, dict):
        return      # shape error already reported by _check_series_map
    for k in obj:
        if isinstance(k, str) and k.startswith("hvd_degrade"):
            base = k.split("{", 1)[0]
            if base not in DEGRADE_SERIES:
                errors.append(
                    f"{field}[{k!r}]: unknown degrade series {base!r} — "
                    f"not in metrics_schema.DEGRADE_SERIES")


def _check_memory_series(errors: List[str], obj, field: str) -> None:
    if not isinstance(obj, dict):
        return      # shape error already reported by _check_series_map
    for k in obj:
        if isinstance(k, str) and k.startswith("hvd_memory"):
            base = k.split("{", 1)[0]
            if base not in MEMORY_SERIES:
                errors.append(
                    f"{field}[{k!r}]: unknown memory series {base!r} — "
                    f"not in metrics_schema.MEMORY_SERIES")


def _check_moe_series(errors: List[str], obj, field: str) -> None:
    if not isinstance(obj, dict):
        return      # shape error already reported by _check_series_map
    for k in obj:
        if isinstance(k, str) and k.startswith("hvd_moe"):
            base = k.split("{", 1)[0]
            if base not in MOE_SERIES:
                errors.append(
                    f"{field}[{k!r}]: unknown moe series {base!r} — "
                    f"not in metrics_schema.MOE_SERIES")


def _check_sp_series(errors: List[str], obj, field: str) -> None:
    if not isinstance(obj, dict):
        return      # shape error already reported by _check_series_map
    for k in obj:
        if isinstance(k, str) and k.startswith("hvd_sp_"):
            base = k.split("{", 1)[0]
            if base not in SP_SERIES:
                errors.append(
                    f"{field}[{k!r}]: unknown sp series {base!r} — "
                    f"not in metrics_schema.SP_SERIES")


def _check_calibration_series(errors: List[str], obj,
                              field: str) -> None:
    if not isinstance(obj, dict):
        return      # shape error already reported by _check_series_map
    for k in obj:
        if isinstance(k, str) and k.startswith("hvd_calibration"):
            base = k.split("{", 1)[0]
            if base not in CALIBRATION_SERIES:
                errors.append(
                    f"{field}[{k!r}]: unknown calibration series "
                    f"{base!r} — not in "
                    f"metrics_schema.CALIBRATION_SERIES")


def _check_series_map(errors: List[str], obj, field: str) -> None:
    if not isinstance(obj, dict):
        errors.append(f"{field}: expected object, got "
                      f"{type(obj).__name__}")
        return
    for k, v in obj.items():
        if not isinstance(k, str):
            errors.append(f"{field}: non-string series key {k!r}")
        if not isinstance(v, _NUM) or isinstance(v, bool):
            errors.append(f"{field}[{k!r}]: non-numeric value {v!r}")


def _check_histograms(errors: List[str], obj) -> None:
    if not isinstance(obj, dict):
        errors.append(f"histograms: expected object, got "
                      f"{type(obj).__name__}")
        return
    for key, h in obj.items():
        if not isinstance(h, dict):
            errors.append(f"histograms[{key!r}]: expected object")
            continue
        bounds = h.get("bounds")
        counts = h.get("counts")
        if not isinstance(bounds, list) or not isinstance(counts, list):
            errors.append(f"histograms[{key!r}]: bounds/counts must be "
                          f"arrays")
            continue
        if len(counts) != len(bounds) + 1:
            errors.append(
                f"histograms[{key!r}]: {len(counts)} counts for "
                f"{len(bounds)} bounds (need bounds+1 — the overflow "
                f"bucket)")
        if list(bounds) != sorted(float(b) for b in bounds):
            errors.append(f"histograms[{key!r}]: bounds not sorted")
        if any((not isinstance(c, int)) or c < 0 for c in counts):
            errors.append(f"histograms[{key!r}]: counts must be "
                          f"non-negative integers")
        count = h.get("count")
        if isinstance(count, int) and sum(c for c in counts
                                          if isinstance(c, int)) != count:
            errors.append(
                f"histograms[{key!r}]: count {count} != sum of bucket "
                f"counts — a merge or a torn write")


def validate_snapshot(obj: Dict) -> List[str]:
    """One JSONL snapshot record (the ``MetricsSnapshotWriter`` line)."""
    errors: List[str] = []
    if not isinstance(obj, dict):
        return [f"snapshot: expected object, got {type(obj).__name__}"]
    sv = obj.get("schema_version")
    if sv != SCHEMA_VERSION:
        errors.append(f"schema_version: expected {SCHEMA_VERSION}, "
                      f"got {sv!r}")
    if obj.get("kind") != SNAPSHOT_KIND:
        errors.append(f"kind: expected {SNAPSHOT_KIND!r}, "
                      f"got {obj.get('kind')!r}")
    for field in ("run_id",):
        if not isinstance(obj.get(field), str):
            errors.append(f"{field}: expected string")
    for field in ("generation", "step"):
        if not isinstance(obj.get(field), int):
            errors.append(f"{field}: expected integer")
    _check_series_map(errors, obj.get("counters", {}), "counters")
    _check_series_map(errors, obj.get("gauges", {}), "gauges")
    _check_histograms(errors, obj.get("histograms", {}))
    _check_guard_series(errors, obj.get("counters", {}), "counters")
    _check_guard_series(errors, obj.get("gauges", {}), "gauges")
    _check_guard_series(errors, obj.get("histograms", {}), "histograms")
    _check_serve_series(errors, obj.get("counters", {}), "counters")
    _check_serve_series(errors, obj.get("gauges", {}), "gauges")
    _check_serve_series(errors, obj.get("histograms", {}), "histograms")
    _check_elastic_series(errors, obj.get("counters", {}), "counters")
    _check_elastic_series(errors, obj.get("gauges", {}), "gauges")
    _check_elastic_series(errors, obj.get("histograms", {}), "histograms")
    _check_degrade_series(errors, obj.get("counters", {}), "counters")
    _check_degrade_series(errors, obj.get("gauges", {}), "gauges")
    _check_degrade_series(errors, obj.get("histograms", {}), "histograms")
    _check_memory_series(errors, obj.get("counters", {}), "counters")
    _check_memory_series(errors, obj.get("gauges", {}), "gauges")
    _check_memory_series(errors, obj.get("histograms", {}), "histograms")
    _check_moe_series(errors, obj.get("counters", {}), "counters")
    _check_moe_series(errors, obj.get("gauges", {}), "gauges")
    _check_moe_series(errors, obj.get("histograms", {}), "histograms")
    _check_sp_series(errors, obj.get("counters", {}), "counters")
    _check_sp_series(errors, obj.get("gauges", {}), "gauges")
    _check_sp_series(errors, obj.get("histograms", {}), "histograms")
    _check_calibration_series(errors, obj.get("counters", {}),
                              "counters")
    _check_calibration_series(errors, obj.get("gauges", {}), "gauges")
    _check_calibration_series(errors, obj.get("histograms", {}),
                              "histograms")
    _check_adasum_series(errors, obj.get("counters", {}), "counters")
    _check_adasum_series(errors, obj.get("gauges", {}), "gauges")
    _check_adasum_series(errors, obj.get("histograms", {}),
                         "histograms")
    return errors


def validate_bench_metrics(obj: Dict) -> List[str]:
    """The ``"metrics"`` block bench.py embeds in BENCH JSON: schema
    stamp + final counters (the deterministic slice)."""
    errors: List[str] = []
    if not isinstance(obj, dict):
        return [f"metrics: expected object, got {type(obj).__name__}"]
    if obj.get("schema_version") != SCHEMA_VERSION:
        errors.append(f"metrics.schema_version: expected "
                      f"{SCHEMA_VERSION}, got {obj.get('schema_version')!r}")
    _check_series_map(errors, obj.get("counters", {}), "metrics.counters")
    _check_guard_series(errors, obj.get("counters", {}), "metrics.counters")
    _check_serve_series(errors, obj.get("counters", {}), "metrics.counters")
    _check_elastic_series(errors, obj.get("counters", {}), "metrics.counters")
    _check_degrade_series(errors, obj.get("counters", {}), "metrics.counters")
    _check_memory_series(errors, obj.get("counters", {}), "metrics.counters")
    _check_moe_series(errors, obj.get("counters", {}), "metrics.counters")
    _check_sp_series(errors, obj.get("counters", {}), "metrics.counters")
    _check_calibration_series(errors, obj.get("counters", {}),
                              "metrics.counters")
    _check_adasum_series(errors, obj.get("counters", {}),
                         "metrics.counters")
    return errors


def validate_jsonl_path(path: str) -> List[str]:
    """Every line of a snapshot log; line numbers prefixed."""
    errors: List[str] = []
    n = 0
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            n += 1
            try:
                obj = json.loads(line)
            except json.JSONDecodeError as e:
                errors.append(f"line {i}: not JSON ({e})")
                continue
            errors.extend(f"line {i}: {e}"
                          for e in validate_snapshot(obj))
    if not n:
        errors.append("empty snapshot log")
    return errors


def validate_artifact_metrics(artifact: Dict) -> List[str]:
    """The hvdci hook: validate a BENCH artifact's embedded metrics
    block when present (legacy artifacts without one pass trivially).
    Handles the MULTICHIP ``parsed`` wrapper the way hlo_lint does."""
    if "parsed" in artifact and isinstance(artifact["parsed"], dict):
        artifact = artifact["parsed"]
    block = artifact.get("metrics")
    if block is None:
        return []
    return validate_bench_metrics(block)


def counters_delta(a: Optional[Dict], b: Optional[Dict]
                   ) -> Dict[str, float]:
    """Per-series counter difference between two metrics blocks (b − a)
    — the diff seam ``perf_gate``/operators use to compare runs (e.g.
    retry or writer-error counts that should stay flat)."""
    ca = (a or {}).get("counters", {}) or {}
    cb = (b or {}).get("counters", {}) or {}
    out: Dict[str, float] = {}
    for k in sorted(set(ca) | set(cb)):
        d = float(cb.get(k, 0.0)) - float(ca.get(k, 0.0))
        if d:
            out[k] = d
    return out
