"""Measured hardware model: collective microbenchmark fitting.

ROADMAP item 5's characterize-then-design loop (the method of
PAPERS.md arXiv 1810.11112): instead of trusting the hand-coded
:data:`~horovod_tpu.analysis.cost_model.V5E` constants, ``bench
--calibrate`` measures every collective the exchange is built from
(allreduce / reduce-scatter / all-gather / ppermute / all-to-all) per
fabric level across a message-size sweep, plus the matmul FLOP rate
and the HBM stream rate, and this module fits the classic alpha-beta
model per (level, collective):

    t(n) = alpha + n / beta            # latency + bytes/bandwidth

by closed-form least squares (:func:`fit_alpha_beta`).  The fits are
persisted as a versioned JSON artifact (:func:`build_artifact`,
schema in docs/calibration.md) that
``HardwareModel.from_calibration`` turns back into roofline
constants — the cost model, perf gate, memory planner and
``ThroughputAutotuner(predict=)`` then consume measured numbers with
the precedence chain ``calibration artifact > HOROVOD_HW_PRESET >
builtin preset`` (:func:`~horovod_tpu.analysis.cost_model.
resolve_hardware_model`).

The module is stdlib-only (plus :mod:`~horovod_tpu.analysis.
cost_model`, itself stdlib-only): the measurement side lives in
``bench.py`` (it needs JAX); everything here — fitting, artifact
schema, the seeded pure-sim smoke hvdci gate 9 runs — works without
hardware.  Artifacts carry NO wall-clock fields: the same sweep on
the same seed must serialize bit-identically (the run-twice CI
determinism contract every smoke in ``analysis/ci.py`` holds).
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import random
from typing import Dict, List, Optional, Sequence, Tuple

from horovod_tpu.analysis import cost_model as CM

#: The collectives the sweep measures per level — the exchange's
#: building blocks (docs/calibration.md "Sweep design").
CALIBRATED_COLLECTIVES = ("allreduce", "reduce_scatter", "all_gather",
                         "ppermute", "all_to_all")

#: Default message-size sweep (bytes): 8 log-spaced points from 64 KiB
#: to 128 MiB — small enough to expose alpha, large enough to pin beta.
DEFAULT_SWEEP_BYTES = tuple(2 ** p for p in range(16, 28, 2)) + \
    (2 ** 27,)


@dataclasses.dataclass(frozen=True)
class LevelFit:
    """One fitted alpha-beta curve: ``t(n) = alpha_s + n /
    beta_bytes_per_s``.  ``residual`` is the RMS relative error of the
    fit over its own points — the staleness/quality signal the
    artifact carries per curve."""

    collective: str
    alpha_s: float
    beta_bytes_per_s: float
    residual: float
    n_points: int

    def predict_s(self, nbytes: float) -> float:
        return self.alpha_s + float(nbytes) / self.beta_bytes_per_s

    def as_json(self) -> Dict:
        return {"alpha_s": self.alpha_s,
                "beta_bytes_per_s": self.beta_bytes_per_s,
                "residual": self.residual,
                "n_points": self.n_points}


def fit_alpha_beta(sizes_bytes: Sequence[float],
                   times_s: Sequence[float]) -> Tuple[float, float, float]:
    """Closed-form least-squares fit of ``t(n) = alpha + n/beta``.

    Returns ``(alpha_s, beta_bytes_per_s, rms_relative_residual)``.
    The slope of the ``t``-on-``n`` regression is ``1/beta``, the
    intercept ``alpha`` (clamped at 0 — a negative latency is noise,
    not physics).  Degenerate inputs raise: a sweep needs >= 2
    distinct sizes to separate latency from bandwidth."""
    xs = [float(x) for x in sizes_bytes]
    ys = [float(y) for y in times_s]
    if len(xs) != len(ys) or len(xs) < 2:
        raise ValueError("fit needs >= 2 (size, time) pairs")
    xbar = sum(xs) / len(xs)
    ybar = sum(ys) / len(ys)
    sxx = sum((x - xbar) ** 2 for x in xs)
    if sxx == 0:
        raise ValueError("fit needs >= 2 distinct sizes")
    slope = sum((x - xbar) * (y - ybar)
                for x, y in zip(xs, ys)) / sxx
    if slope <= 0:
        raise ValueError(
            "non-positive time-vs-bytes slope: the sweep did not "
            "resolve a bandwidth (measure larger messages)")
    alpha = max(0.0, ybar - slope * xbar)
    beta = 1.0 / slope
    sq = 0.0
    for x, y in zip(xs, ys):
        pred = alpha + x * slope
        sq += ((pred - y) / y) ** 2 if y > 0 else 0.0
    residual = math.sqrt(sq / len(xs))
    return alpha, beta, residual


def fit_level(collective: str,
              sizes_bytes: Sequence[float],
              times_s: Sequence[float]) -> LevelFit:
    alpha, beta, residual = fit_alpha_beta(sizes_bytes, times_s)
    return LevelFit(collective=collective, alpha_s=alpha,
                    beta_bytes_per_s=beta, residual=residual,
                    n_points=len(list(sizes_bytes)))


# -- simulated measurements (the deterministic CI path) ---------------------


#: Per-collective latency/bandwidth scale relative to the fabric's
#: reduce-scatter curve — the shape the simulator gives synthetic
#: sweeps (an allreduce moves ~2x the RS wire; a ppermute has no
#: reduction tree, so less latency).
_SIM_COLLECTIVE_SHAPE = {
    "allreduce": (1.5, 0.5), "reduce_scatter": (1.0, 1.0),
    "all_gather": (1.0, 1.0), "ppermute": (0.5, 1.2),
    "all_to_all": (1.2, 0.8),
}


def simulate_sweep(alpha_s: float, beta_bytes_per_s: float,
                   sizes_bytes: Sequence[float], seed: int,
                   rel_noise: float = 5e-4) -> List[float]:
    """Synthetic measured times for a known alpha-beta truth, with
    seeded multiplicative noise — the pure-sim calibration source
    (``bench --calibrate --calibrate-sim`` and hvdci gate 9).
    Deterministic: same ``(alpha, beta, sizes, seed, rel_noise)`` →
    bit-identical floats."""
    rng = random.Random(seed)
    out = []
    for n in sizes_bytes:
        t = alpha_s + float(n) / beta_bytes_per_s
        out.append(t * (1.0 + rng.uniform(-rel_noise, rel_noise)))
    return out


def simulate_level_measurements(level_bw_bytes_per_s: float,
                                level_alpha_s: float,
                                sizes_bytes: Sequence[float],
                                seed: int,
                                rel_noise: float = 5e-4
                                ) -> Dict[str, Tuple[List[float],
                                                     List[float]]]:
    """One level's full collective sweep from its fabric truth:
    ``{collective: (sizes, times)}``, each collective's curve shaped
    by :data:`_SIM_COLLECTIVE_SHAPE` and independently seeded."""
    out = {}
    for i, coll in enumerate(CALIBRATED_COLLECTIVES):
        a_scale, b_scale = _SIM_COLLECTIVE_SHAPE[coll]
        times = simulate_sweep(level_alpha_s * a_scale,
                               level_bw_bytes_per_s * b_scale,
                               sizes_bytes, seed=seed * 1000 + i,
                               rel_noise=rel_noise)
        out[coll] = (list(float(s) for s in sizes_bytes), times)
    return out


# -- the artifact -----------------------------------------------------------


def build_artifact(*,
                   device_kind: str,
                   platform: str,
                   n_devices: int,
                   mesh_shape: Sequence[int],
                   level_order: Sequence[str],
                   level_fits: Dict[str, Sequence[LevelFit]],
                   level_extents: Dict[str, int],
                   matmul_flops_per_s: float,
                   hbm_bytes_per_s: float,
                   source: str,
                   seed: Optional[int] = None,
                   jax_version: Optional[str] = None,
                   jaxlib_version: Optional[str] = None) -> Dict:
    """Assemble one versioned calibration artifact (docs/calibration.md
    "Artifact schema").  ``level_order`` is innermost-first; ``source``
    is ``"measured"`` or ``"simulated"``.  No wall-clock fields — the
    artifact of a seeded sim run is bit-reproducible."""
    if source not in ("measured", "simulated"):
        raise ValueError(f"source must be measured|simulated, got "
                         f"{source!r}")
    levels = {}
    residual_max = 0.0
    for name in level_order:
        fits = {f.collective: f.as_json() for f in level_fits[name]}
        residual_max = max(
            [residual_max] + [f.residual for f in level_fits[name]])
        levels[name] = {"extent": int(level_extents[name]),
                        "collectives": fits}
    art = {
        "schema_version": CM.CALIBRATION_SCHEMA_VERSION,
        "kind": "horovod_calibration",
        "device_kind": str(device_kind),
        "platform": str(platform),
        "n_devices": int(n_devices),
        "mesh_shape": [int(s) for s in mesh_shape],
        "level_order": [str(n) for n in level_order],
        "levels": levels,
        "matmul_flops_per_s": float(matmul_flops_per_s),
        "hbm_bytes_per_s": float(hbm_bytes_per_s),
        "fit_residual_max": residual_max,
        "source": source,
        "seed": seed,
        "jax_version": jax_version,
        "jaxlib_version": jaxlib_version,
    }
    art["calibration_fingerprint"] = CM.calibration_fingerprint(art)
    return art


def validate_calibration(data: Dict) -> List[str]:
    """Full schema check of one calibration artifact — the consumer
    subset (:func:`cost_model._calibration_schema_errors`) plus the
    per-level fit fields hvdci gate 9 verifies.  Returns the error
    list ([] = valid)."""
    errs = CM._calibration_schema_errors(data)
    if errs:
        return errs
    for name in data["level_order"]:
        lv = data["levels"][name]
        if int(lv.get("extent", 0)) < 1:
            errs.append(f"level {name!r}: extent must be >= 1")
        colls = lv.get("collectives", {})
        if not colls:
            errs.append(f"level {name!r}: no collective fits")
        for coll, fit in colls.items():
            for field in ("alpha_s", "beta_bytes_per_s", "residual",
                          "n_points"):
                if field not in fit:
                    errs.append(
                        f"level {name!r} {coll}: missing {field!r}")
            try:
                if float(fit.get("beta_bytes_per_s", 0)) <= 0:
                    errs.append(
                        f"level {name!r} {coll}: beta must be > 0")
                if float(fit.get("alpha_s", 0)) < 0:
                    errs.append(
                        f"level {name!r} {coll}: alpha must be >= 0")
            except (TypeError, ValueError):
                errs.append(f"level {name!r} {coll}: non-numeric fit")
    fp = data.get("calibration_fingerprint")
    if fp is not None and fp != CM.calibration_fingerprint(data):
        errs.append("calibration_fingerprint does not match the "
                    "identity fields")
    return errs


def save_artifact(data: Dict, path: str) -> None:
    """Atomic JSON write (tmp + rename), sorted keys — byte-stable."""
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


def load_artifact(path: str) -> Dict:
    with open(path) as f:
        data = json.load(f)
    errs = validate_calibration(data)
    if errs:
        raise ValueError(f"{path}: " + "; ".join(errs))
    return data


# -- the pure-sim calibrate→fit→price pipeline (gate 9 substrate) -----------


def simulated_calibration(hw: CM.HardwareModel = CM.V5E,
                          level_order: Sequence[str] = ("ici", "dcn"),
                          level_extents: Optional[Dict[str, int]] = None,
                          seed: int = 17,
                          sizes_bytes: Sequence[float] =
                          DEFAULT_SWEEP_BYTES,
                          rel_noise: float = 5e-4) -> Dict:
    """The whole pipeline without hardware: simulate each level's sweep
    from a preset's truth, fit, assemble the artifact.  Innermost
    level takes the preset's ICI figures, every outer level the DCN
    figures (matching :func:`cost_model.level_bandwidths`)."""
    level_extents = dict(level_extents or
                         {n: 2 for n in level_order})
    n_devices = 1
    for n in level_order:
        n_devices *= level_extents[n]
    level_fits: Dict[str, List[LevelFit]] = {}
    for li, name in enumerate(level_order):
        bw = hw.ici_bytes_per_s if li == 0 else hw.dcn_bytes_per_s
        alpha = 2e-6 if li == 0 else 50e-6   # ICI ~µs, DCN ~tens of µs
        sweeps = simulate_level_measurements(
            bw, alpha, sizes_bytes, seed=seed + li,
            rel_noise=rel_noise)
        level_fits[name] = [fit_level(coll, sizes, times)
                            for coll, (sizes, times) in sweeps.items()]
    return build_artifact(
        device_kind=f"simulated:{hw.name}", platform="sim",
        n_devices=n_devices,
        mesh_shape=[level_extents[n] for n in reversed(level_order)],
        level_order=level_order, level_fits=level_fits,
        level_extents=level_extents,
        matmul_flops_per_s=hw.peak_flops_per_s,
        hbm_bytes_per_s=hw.hbm_bytes_per_s,
        source="simulated", seed=seed)


def run_smoke(root: Optional[str] = None) -> List[str]:
    """hvdci gate 9: the seeded pure-sim calibrate→fit→price loop, run
    twice and required bit-identical, plus the artifact schema check —
    and, when a ``CALIBRATION*.json`` is checked in at the repo root,
    its schema too.  Returns the error list ([] = pass); sub-second,
    no JAX."""
    errors: List[str] = []
    runs = []
    for _ in range(2):
        art = simulated_calibration(seed=17)
        errs = validate_calibration(art)
        if errs:
            errors.extend(f"sim artifact: {e}" for e in errs)
            break
        hw = CM.HardwareModel.from_calibration(art)
        bw = CM.calibration_level_bandwidths(art)
        levels = tuple(
            (name, art["levels"][name]["extent"],
             8 if name == art["level_order"][-1] else None)
            for name in art["level_order"])
        wire = CM.exchange_wire_by_level(1e9, levels)
        price = CM.exchange_time_by_level(wire, bw)
        runs.append(json.dumps(
            {"artifact": art, "hw": dataclasses.asdict(hw),
             "wire": wire, "price": price}, sort_keys=True))
    if not errors:
        if len(runs) != 2 or runs[0] != runs[1]:
            errors.append(
                "calibrate→fit→price is not deterministic: two seeded "
                "sim runs serialized differently")
        art = simulated_calibration(seed=17)
        hw = CM.HardwareModel.from_calibration(art)
        # the sim truth must round-trip through the fit: fitted RS beta
        # within 1% of the preset bandwidth it was simulated from
        if abs(hw.ici_bytes_per_s - CM.V5E.ici_bytes_per_s) \
                > 0.01 * CM.V5E.ici_bytes_per_s:
            errors.append(
                f"fitted ICI bandwidth {hw.ici_bytes_per_s:.3e} is "
                f">1% off the simulated truth "
                f"{CM.V5E.ici_bytes_per_s:.3e}")
    if root:
        import glob

        for path in sorted(glob.glob(os.path.join(root,
                                                  "CALIBRATION*.json"))):
            try:
                with open(path) as f:
                    data = json.load(f)
            except (OSError, json.JSONDecodeError) as e:
                errors.append(f"{os.path.basename(path)}: unreadable: "
                              f"{e}")
                continue
            errors.extend(f"{os.path.basename(path)}: {e}"
                          for e in validate_calibration(data))
    return errors
