"""Offline HLO/artifact lint: the compiled-collective guards as a rule
pack.

``tests/test_hlo_guards.py`` pins the exchange structure by lowering
the real train step — which needs a JAX install and a compile.  This
module promotes the *invariants* those guards assert into rules that
run against artifacts that already exist on disk:

* an **HLO text dump** (``step.compiled_text(...)`` saved to a file,
  or any ``--xla_dump_to`` module): full structural checks;
* a **bench JSON artifact** (``bench.py --json-out``): the collective
  structure fields the overlap probe embeds (``exchange_rs_scopes``,
  ``exchange_hierarchy``, ``*_grad_sized_allreduces``), so a
  MULTICHIP/BENCH artifact from a real pod can be linted on a laptop
  without recompiling anything.

Rules (shared ids with the docs table):

=========  ==============================================================
HLO001     gradient-sized all-reduce in a sharded-exchange module (the
           silent de-fusion/regression-to-allreduce the ZeRO path bans)
HLO002     async ``-start`` without matching ``-done`` (broken pairing
           loses the latency hiding the scheduler provides)
HLO003     two-level exchange without a low-precision (s8/u8/fp8) DCN
           hop — the cross-slice phase is paying full-width wire bytes
HLO004     artifact structure: hierarchy says two_level but the scope
           set isn't two distinct scopes (or flat with >1 scope)
HLO005     serial exchange tail: the final RS/AG start..done pair has
           no compute scheduled between it (HLO text), or an artifact
           claims ``fused_collectives=on`` yet still reports a serial
           tail — the exposure the tile-fused exchange exists to
           remove (docs/fused_kernels.md)
HLO006     serial boundary-wide MoE dispatch: an ``all-to-all``
           start..done window with no compute inside it (HLO text), or
           an artifact claiming the fused expert dispatch is on for an
           ``ep>1`` plan yet still reporting serial all-to-alls — the
           a2a ⊗ expert-matmul ring's mirror of HLO005
           (docs/fused_kernels.md "Expert-parallel dispatch")
HLO007     serial/de-fused sp attention ring: a ``collective-permute``
           start..done window with no compute inside it (HLO text — a
           K/V hop the flash compute should be hiding), or an artifact
           claiming ``sp>1`` fused ring attention yet reporting serial
           tail permutes, any full-sequence attention all-gather, or
           fewer than ``2·(sp−1)`` ring permutes — the ring-flash
           mirror of HLO005/HLO006 (docs/fused_kernels.md "Ring-flash
           attention")
=========  ==============================================================
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Sequence

from horovod_tpu.utils import hlo as H

_SCALAR_MAX_BYTES = 256     # "gradient-sized" = anything bigger than this
_LOW_PRECISION = {"s8", "u8", "f8e4m3fn", "f8e5m2"}


@dataclasses.dataclass(frozen=True)
class HloFinding:
    rule: str
    message: str
    detail: str = ""

    def format(self) -> str:
        d = f" ({self.detail})" if self.detail else ""
        return f"{self.rule}: {self.message}{d}"

    def as_json(self) -> dict:
        return dataclasses.asdict(self)


def lint_hlo_text(text: str,
                  expect_hierarchy: Optional[str] = None,
                  grad_bytes: Optional[int] = None) -> List[HloFinding]:
    """Structural lint of one optimized-HLO module dump.

    ``grad_bytes`` (when known) sharpens HLO001 to "all-reduce >= the
    gradient payload"; without it any non-scalar all-reduce in a module
    that also reduce-scatters counts.  ``expect_hierarchy`` enables the
    two-level checks (scope count, int8 DCN hop)."""
    findings: List[HloFinding] = []
    ops = H.collective_ops(text)
    kinds = H.count_by_kind(ops)

    # HLO001 — the sharded exchange must never fall back to a
    # gradient-sized all-reduce (same math, 2x optimizer FLOPs + N x
    # state memory on a real pod; invisible to numerics tests)
    if kinds.get("reduce-scatter", 0) >= 1:
        threshold = grad_bytes if grad_bytes is not None \
            else _SCALAR_MAX_BYTES
        offenders = [o for o in ops if o.kind == "all-reduce"
                     and o.bytes >= threshold]
        if grad_bytes is None:
            offenders = [o for o in offenders
                         if o.bytes > _SCALAR_MAX_BYTES]
        for o in offenders:
            findings.append(HloFinding(
                "HLO001",
                f"gradient-sized all-reduce ({o.bytes} bytes) in a "
                f"module that reduce-scatters — the sharded exchange "
                f"regressed to allreduce",
                detail=o.line[:160]))

    # HLO002 — every -start must close with a -done
    for kind in ("all-reduce", "reduce-scatter", "all-gather",
                 "collective-permute", "all-to-all"):
        starts = text.count(f"{kind}-start(")
        dones = text.count(f"{kind}-done(")
        if starts != dones:
            findings.append(HloFinding(
                "HLO002",
                f"async pairing broken for {kind}: {starts} -start vs "
                f"{dones} -done"))

    # HLO003 — the two-level exchange's cross-slice hop must be
    # low-precision (the int8 DCN wire PR 2 introduced)
    scopes = H.scopes_by_kind(ops)
    rs_scopes = scopes.get("reduce-scatter", ())
    if expect_hierarchy == "two_level":
        distinct = [s for s in rs_scopes if s is not None]
        if len(distinct) < 2:
            findings.append(HloFinding(
                "HLO004",
                f"hierarchy=two_level but reduce-scatter scopes are "
                f"{rs_scopes} — expected two distinct scopes (ici + "
                f"dcn); the exchange compiled flat"))
        else:
            low = {o.group_size for o in ops
                   if o.dtypes & _LOW_PRECISION}
            if not low:
                findings.append(HloFinding(
                    "HLO003",
                    "two-level exchange carries no low-precision "
                    "(s8/u8/fp8) collective — the DCN hop is paying "
                    "full-width wire bytes"))
    elif expect_hierarchy == "flat":
        distinct = [s for s in rs_scopes if s is not None]
        if len(distinct) > 1:
            findings.append(HloFinding(
                "HLO004",
                f"hierarchy=flat but reduce-scatter runs {len(distinct)} "
                f"distinct scopes {rs_scopes} — expected one"))

    # HLO005 — serial exchange tail: the module's FINAL async RS/AG
    # pair has no compute op scheduled inside its start..done window,
    # i.e. the last bucket's exchange sits fully exposed on the step's
    # critical path (the tile-fused exchange removes exactly this;
    # synchronous dumps with no async pairs are not judged)
    if H.serial_tail_collectives(text):
        findings.append(HloFinding(
            "HLO005",
            "serial exchange tail: the final reduce-scatter/all-gather "
            "start..done pair has no compute scheduled between it — "
            "the last bucket's wire is fully exposed (enable "
            "fused_collectives, docs/fused_kernels.md)"))

    # HLO006 — serial boundary-wide MoE dispatch: an all-to-all whose
    # start..done window holds no compute is the exposure the fused
    # a2a ⊗ expert-matmul ring removes (same judgment rule as HLO005,
    # pointed at the expert-dispatch collective)
    if H.serial_tail_collectives(text, kinds=("all-to-all",)):
        findings.append(HloFinding(
            "HLO006",
            "serial MoE dispatch: the final all-to-all start..done "
            "window has no compute scheduled inside it — the expert "
            "exchange is fully exposed (enable the fused a2a ⊗ "
            "expert-matmul dispatch, docs/fused_kernels.md)"))

    # HLO007 — serial sp attention ring hop: a collective-permute whose
    # start..done window holds no compute is a K/V hop the flash
    # kernel's compute should be hiding (the double-buffered ring-flash
    # schedule issues the next hop before the current block's kernel —
    # same judgment rule as HLO005/HLO006, pointed at the ring wire)
    if H.serial_tail_collectives(text, kinds=("collective-permute",)):
        findings.append(HloFinding(
            "HLO007",
            "serial sp ring hop: the final collective-permute "
            "start..done window has no compute scheduled inside it — "
            "the K/V exchange is fully exposed (enable the fused "
            "ring-flash attention, docs/fused_kernels.md)"))
    return findings


def _prefixes(artifact: Dict) -> List[str]:
    """Field prefixes present in a bench artifact (PR 3-5 emit
    ``transformer_*`` alongside unprefixed resnet fields)."""
    out = {""}
    for k in artifact:
        for marker in ("exchange_hierarchy", "overlap_fraction",
                       "exchange_rs_scopes"):
            if k.endswith(marker) and k != marker:
                out.add(k[: -len(marker)])
    return sorted(out)


def lint_artifact(artifact: Dict) -> List[HloFinding]:
    """Lint the collective-structure fields of one ``--json-out`` bench
    artifact (no JAX, no compile — pure dict checks)."""
    findings: List[HloFinding] = []
    for prefix in _prefixes(artifact):
        hierarchy = artifact.get(f"{prefix}exchange_hierarchy")
        rs_scopes = artifact.get(f"{prefix}exchange_rs_scopes")
        grad_ars = artifact.get(f"{prefix}exchange_grad_sized_allreduces")
        label = prefix.rstrip("_") or "default"
        if grad_ars:
            findings.append(HloFinding(
                "HLO001",
                f"[{label}] artifact reports "
                f"{grad_ars} gradient-sized all-reduce(s) — the "
                f"sharded exchange regressed to allreduce on the wire"))
        if hierarchy == "two_level" and rs_scopes is not None:
            distinct = [s for s in rs_scopes if s is not None]
            if len(distinct) < 2:
                findings.append(HloFinding(
                    "HLO004",
                    f"[{label}] exchange_hierarchy=two_level but "
                    f"rs scopes are {rs_scopes} — expected two distinct "
                    f"scopes (ici + dcn)"))
        if hierarchy == "flat" and rs_scopes is not None:
            distinct = [s for s in rs_scopes if s is not None]
            if len(distinct) > 1:
                findings.append(HloFinding(
                    "HLO004",
                    f"[{label}] exchange_hierarchy=flat but rs scopes "
                    f"are {rs_scopes} — expected a single scope"))
        frac = artifact.get(f"{prefix}overlap_fraction")
        if frac is not None and not 0.0 <= float(frac) <= 1.0:
            findings.append(HloFinding(
                "HLO004",
                f"[{label}] overlap_fraction={frac} out of [0, 1] — "
                f"corrupt probe output"))
        # HLO005 — a run that claims the fused tail is ON must not
        # still report a serial final RS/AG pair in its probe scan
        # (legacy artifacts without the fields pass vacuously; with
        # fused off a serial tail is the expected unfused schedule)
        serial = artifact.get(
            f"{prefix}exchange_serial_tail_collectives")
        fused = artifact.get(f"{prefix}fused_collectives")
        if fused == "on" and serial:
            findings.append(HloFinding(
                "HLO005",
                f"[{label}] fused_collectives=on but the probe still "
                f"found {serial} serial final RS/AG pair(s) — the "
                f"tile-fused exchange is not reaching the wire"))
        # HLO006 — an ep>1 run that claims the fused expert dispatch
        # is ON must not still report serial boundary-wide all-to-alls
        # (legacy artifacts without the fields pass vacuously; ep<=1
        # or fused off is the expected unfused/local schedule)
        moe_serial = artifact.get(
            f"{prefix}moe_serial_tail_alltoalls")
        moe_fused = artifact.get(f"{prefix}moe_fused_collectives")
        moe_ep = artifact.get(f"{prefix}moe_ep")
        if moe_fused == "on" and moe_ep and int(moe_ep) > 1 \
                and moe_serial:
            findings.append(HloFinding(
                "HLO006",
                f"[{label}] moe_fused_collectives=on for an "
                f"ep={moe_ep} plan but the probe still found "
                f"{moe_serial} serial boundary-wide all-to-all(s) — "
                f"the a2a ⊗ expert-matmul ring is not reaching the "
                f"wire"))
        # HLO007 — an sp>1 run that claims the fused ring-flash
        # attention is ON must show a clean ring: zero full-sequence
        # attention all-gathers, zero serial tail permutes, and at
        # least 2·(sp−1) collective-permutes when the probe counted
        # them (K and V each hop sp−1 times; legacy artifacts without
        # the fields pass vacuously, sp<=1 or fused off is the
        # expected jnp/unfused schedule)
        sp_fused = artifact.get(f"{prefix}sp_fused_collectives")
        sp_ext = artifact.get(f"{prefix}sp")
        if sp_fused == "on" and sp_ext and int(sp_ext) > 1:
            sp_serial = artifact.get(
                f"{prefix}sp_serial_tail_permutes")
            sp_ag = artifact.get(f"{prefix}sp_attention_allgathers")
            sp_perms = artifact.get(f"{prefix}sp_collective_permutes")
            if sp_serial:
                findings.append(HloFinding(
                    "HLO007",
                    f"[{label}] sp_fused_collectives=on for an "
                    f"sp={sp_ext} plan but the probe still found "
                    f"{sp_serial} serial collective-permute window(s) "
                    f"— the K/V ring is not hiding under the flash "
                    f"compute"))
            if sp_ag:
                findings.append(HloFinding(
                    "HLO007",
                    f"[{label}] sp={sp_ext} fused ring attention but "
                    f"the probe found {sp_ag} full-sequence "
                    f"all-gather(s) on the attention path — the ring "
                    f"degenerated to gather-everything"))
            if sp_perms is not None and \
                    int(sp_perms) < 2 * (int(sp_ext) - 1):
                findings.append(HloFinding(
                    "HLO007",
                    f"[{label}] sp={sp_ext} fused ring attention "
                    f"compiled only {sp_perms} collective-permute(s) — "
                    f"expected >= 2·(sp−1) = {2 * (int(sp_ext) - 1)} "
                    f"(K and V each hop sp−1 times)"))
    return findings


def lint_artifact_path(path: str) -> List[HloFinding]:
    with open(path, "r") as f:
        data = json.load(f)
    # MULTICHIP_r0*.json wraps the bench line under "parsed"
    if isinstance(data.get("parsed"), dict):
        data = dict(data, **data["parsed"])
    return lint_artifact(data)


def lint_paths(paths: Sequence[str],
               expect_hierarchy: Optional[str] = None
               ) -> List[HloFinding]:
    """Dispatch on suffix: ``.json`` → bench artifact, anything else →
    raw HLO text dump."""
    findings: List[HloFinding] = []
    for p in paths:
        if p.endswith(".json"):
            findings.extend(lint_artifact_path(p))
        else:
            with open(p, "r", errors="replace") as f:
                findings.extend(lint_hlo_text(
                    f.read(), expect_hierarchy=expect_hierarchy))
    return findings
