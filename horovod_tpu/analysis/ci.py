"""``python -m horovod_tpu.analysis ci`` / ``hvdci`` — the one-shot CI
entry point.

Eleven gates, one invocation, one exit code (docs/perf_gate.md):

1. **hvdlint** over the pre-commit scope (``--changed``: staged +
   unstaged + untracked files under ``horovod_tpu/``; falls back to the
   full package scan outside a git checkout — an sdist CI job still
   gets linted, just wider);
2. the **HLO/artifact rule pack** over every checked-in
   ``BENCH_r0*.json`` / ``MULTICHIP_r0*.json``;
3. the **perf gate** trajectory self-walk;
4. the **guard-chaos smoke** (``guard/smoke.py``): a seeded silent-
   corruption → detect → rollback → replay round trip, run twice and
   required bit-identical (docs/guardian.md);
5. the **serve-chaos smoke** (``serve/smoke.py``): the serving plane's
   enqueue → batch → kill-replica → requeue → drain loop, seeded, run
   twice and required bit-identical (docs/serving.md);
6. the **plan smoke** (``parallel/smoke.py``): a seeded dp×tp×pp
   virtual-device walk of the sharding-plan compiler — tensor shards,
   data-extent exchange and the interleaved-1F1B tick schedule, run
   twice and required bit-identical (docs/parallelism.md);
7. the **degrade smoke** (``elastic/smoke.py``): the plan-aware
   degradation loop — seeded kill → dp-shrink reshard → replay →
   promote at the next checkpoint boundary, bit-exact against a
   never-degraded run, run twice and required bit-identical
   (docs/elastic.md "Degraded mode");
8. the **memory smoke** (``memory/smoke.py``): the HBM-budgeted
   planner — unconstrained vs budgeted search must pick different
   feasible winners, an infeasible budget must raise naming the
   tightest axis, run twice and required bit-identical
   (docs/memory.md);
9. the **calibration smoke** (``analysis/calibration.py``): a seeded
   pure-sim calibrate → fit → ``HardwareModel.from_calibration`` →
   price round trip, run twice and required bit-identical, plus the
   artifact schema check over any checked-in ``CALIBRATION*.json``
   (docs/calibration.md);
10. the **adasum smoke** (``analysis/adasum_smoke.py``): seeded
    gradient-pair fixtures of the pairwise reduction operator
    (parallel/orthogonal/antiparallel/zero-norm) plus a two-slice
    convergence loop — adasum at 2× tracks the base-batch sum
    trajectory while plain sum at 2× degrades — run twice and
    required bit-identical (docs/adasum.md);
11. the **fleet smoke** (``serve/fleet_smoke.py``): the hvdfleet
    story — 3-model weighted-fair enqueue → live weight refresh
    mid-load (fingerprint-verified flip) → kill-replica →
    autoscale-up → drain, seeded, run twice and required
    bit-identical (docs/serving.md).

The whole run is a tier-1 test with the same <30 s budget as the
hvdlint self-run, so "CI passed" and "the analysis suite passed" are
the same fact.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional

from horovod_tpu.analysis import engine, hlo_lint, metrics_schema, perf_gate


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m horovod_tpu.analysis ci",
        description="run hvdlint (--changed scope), the artifact rule "
                    "pack and the perf gate in one invocation")
    p.add_argument("--full", action="store_true",
                   help="lint the whole package instead of the "
                        "--changed scope")
    p.add_argument("--root", default=None,
                   help="repo root (default: auto-detected)")
    p.add_argument("--json", action="store_true", dest="json_out")
    args = p.parse_args(argv)

    t0 = time.perf_counter()
    root = args.root or engine.find_repo_root(os.getcwd()) or os.getcwd()
    pkg = os.path.join(root, "horovod_tpu")

    # 1 — hvdlint
    scope = "full"
    paths: List[str] = [pkg]
    if not args.full:
        try:
            changed = [f for f in engine.changed_files(root)
                       if os.path.abspath(f).startswith(pkg + os.sep)]
            paths, scope = changed, "--changed"
        except Exception:          # noqa: BLE001 — not a git checkout
            pass
    baseline = os.path.join(root, "analysis_baseline.json")
    if paths:
        lint = engine.run_analysis(
            paths, root=root,
            baseline_path=baseline if os.path.exists(baseline) else None)
    else:
        lint = engine.Report(findings=[], suppressed=[], baselined=[])

    # 2 — artifact rule pack (HLO001-HLO004 over the checked-in runs)
    # plus the hvdtel metrics-snapshot schema check: any embedded
    # "metrics" block must parse against the telemetry contract
    # (analysis/metrics_schema.py; legacy artifacts without one pass)
    artifacts = perf_gate.default_trajectory(root)
    art_findings = []
    metrics_errors = []
    art_error = None
    for art in artifacts:
        try:
            art_findings.extend(hlo_lint.lint_artifact_path(art))
            with open(art) as f:
                blob = json.load(f)
            metrics_errors.extend(
                f"{os.path.basename(art)}: {e}"
                for e in metrics_schema.validate_artifact_metrics(blob))
        except (OSError, json.JSONDecodeError) as e:
            art_error = f"cannot read {art}: {e}"
            break

    # 3 — perf gate trajectory self-walk
    gate_error = None
    gate = None
    if artifacts and art_error is None:
        try:
            gate = perf_gate.run_gate(artifacts)
        except perf_gate.GateError as e:
            gate_error = str(e)

    # 4 — guard-chaos smoke: the integrity plane's detect→rollback→
    # replay loop, seeded and deterministic (sub-second, CPU-only)
    try:
        from horovod_tpu.guard.smoke import run_smoke

        guard_errors = run_smoke()
    except Exception as e:          # noqa: BLE001 — a crash IS a failure
        guard_errors = [f"guard-smoke crashed: {type(e).__name__}: {e}"]

    # 5 — serve-chaos smoke: the serving plane's crash→requeue→drain
    # loop, seeded and deterministic (sub-second, CPU-only)
    try:
        from horovod_tpu.serve.smoke import run_smoke as run_serve_smoke

        serve_errors = run_serve_smoke()
    except Exception as e:          # noqa: BLE001 — a crash IS a failure
        serve_errors = [f"serve-smoke crashed: {type(e).__name__}: {e}"]

    # 6 — plan smoke: the sharding-plan compiler's dp×tp×pp virtual-
    # device walk, seeded and deterministic (sub-second, CPU-only)
    try:
        from horovod_tpu.parallel.smoke import run_smoke as run_plan_smoke

        plan_errors = run_plan_smoke()
    except Exception as e:          # noqa: BLE001 — a crash IS a failure
        plan_errors = [f"plan-smoke crashed: {type(e).__name__}: {e}"]

    # 7 — degrade smoke: the plan-aware degradation loop's kill →
    # shrink → replay → promote round trip, seeded and deterministic
    try:
        from horovod_tpu.elastic.smoke import run_smoke as \
            run_degrade_smoke

        degrade_errors = run_degrade_smoke()
    except Exception as e:          # noqa: BLE001 — a crash IS a failure
        degrade_errors = [f"degrade-smoke crashed: "
                          f"{type(e).__name__}: {e}"]

    # 8 — memory smoke: the HBM-budgeted planner's free → budgeted →
    # infeasible walk, seeded and deterministic (sub-second, no JAX)
    try:
        from horovod_tpu.memory.smoke import run_smoke as \
            run_memory_smoke

        memory_errors = run_memory_smoke()
    except Exception as e:          # noqa: BLE001 — a crash IS a failure
        memory_errors = [f"memory-smoke crashed: "
                         f"{type(e).__name__}: {e}"]

    # 9 — calibration smoke: seeded sim calibrate→fit→price, run twice
    # bit-identical, + schema check over checked-in CALIBRATION*.json
    try:
        from horovod_tpu.analysis.calibration import run_smoke as \
            run_calibration_smoke

        calibration_errors = run_calibration_smoke(root)
    except Exception as e:          # noqa: BLE001 — a crash IS a failure
        calibration_errors = [f"calibration-smoke crashed: "
                              f"{type(e).__name__}: {e}"]

    # 10 — adasum smoke: seeded pair fixtures + the two-slice
    # convergence loop, run twice bit-identical (sub-second, stdlib)
    try:
        from horovod_tpu.analysis.adasum_smoke import run_smoke as \
            run_adasum_smoke

        adasum_errors = run_adasum_smoke(root)
    except Exception as e:          # noqa: BLE001 — a crash IS a failure
        adasum_errors = [f"adasum-smoke crashed: "
                         f"{type(e).__name__}: {e}"]

    # 11 — fleet smoke: the multi-tenant serving plane's weighted-fair
    # enqueue → refresh-mid-load → kill → scale-up → drain loop,
    # seeded and deterministic (sub-second, CPU-only)
    try:
        from horovod_tpu.serve.fleet_smoke import run_smoke as \
            run_fleet_smoke

        fleet_errors = run_fleet_smoke()
    except Exception as e:          # noqa: BLE001 — a crash IS a failure
        fleet_errors = [f"fleet-smoke crashed: "
                        f"{type(e).__name__}: {e}"]

    elapsed = time.perf_counter() - t0
    gate_findings = gate.findings if gate is not None else []
    rc = 2 if (art_error or gate_error) else (
        1 if (lint.findings or art_findings or gate_findings
              or metrics_errors or guard_errors or serve_errors
              or plan_errors or degrade_errors or memory_errors
              or calibration_errors or adasum_errors or fleet_errors)
        else 0)

    if args.json_out:
        print(json.dumps({
            "lint": dict(lint.as_json(), scope=scope),
            "artifact_findings": [f.as_json() for f in art_findings],
            "metrics_schema_errors": metrics_errors,
            "guard_smoke_errors": guard_errors,
            "serve_smoke_errors": serve_errors,
            "plan_smoke_errors": plan_errors,
            "degrade_smoke_errors": degrade_errors,
            "memory_smoke_errors": memory_errors,
            "calibration_smoke_errors": calibration_errors,
            "adasum_smoke_errors": adasum_errors,
            "fleet_smoke_errors": fleet_errors,
            "perf_gate": gate.as_json() if gate is not None else None,
            "errors": [e for e in (art_error, gate_error) if e],
            "elapsed_s": round(elapsed, 3),
            "exit_code": rc,
        }, indent=2))
        return rc

    for f in lint.findings:
        print(f.format())
    for f in art_findings:
        print(f.format())
    for e in metrics_errors:
        print(f"hvdci: metrics-schema: {e}")
    for e in guard_errors:
        print(f"hvdci: guard-smoke: {e}")
    for e in serve_errors:
        print(f"hvdci: serve-smoke: {e}")
    for e in plan_errors:
        print(f"hvdci: plan-smoke: {e}")
    for e in degrade_errors:
        print(f"hvdci: degrade-smoke: {e}")
    for e in memory_errors:
        print(f"hvdci: memory-smoke: {e}")
    for e in calibration_errors:
        print(f"hvdci: calibration-smoke: {e}")
    for e in adasum_errors:
        print(f"hvdci: adasum-smoke: {e}")
    for e in fleet_errors:
        print(f"hvdci: fleet-smoke: {e}")
    for f in gate_findings:
        print(f.format())
    for err in (art_error, gate_error):
        if err:
            print(f"hvdci: ERROR {err}", file=sys.stderr)
    print(f"hvdci: lint[{scope}] {len(lint.findings)} · "
          f"artifacts[{len(artifacts)}] "
          f"{len(art_findings) + len(metrics_errors)} · "
          f"perf-gate {len(gate_findings)} · "
          f"guard-smoke {len(guard_errors)} · "
          f"serve-smoke {len(serve_errors)} · "
          f"plan-smoke {len(plan_errors)} · "
          f"degrade-smoke {len(degrade_errors)} · "
          f"memory-smoke {len(memory_errors)} · "
          f"calibration-smoke {len(calibration_errors)} · "
          f"adasum-smoke {len(adasum_errors)} · "
          f"fleet-smoke {len(fleet_errors)} finding(s) "
          f"in {elapsed:.2f}s — {'FAIL' if rc else 'ok'}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
