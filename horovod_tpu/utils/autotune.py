"""Online autotuning of runtime knobs (reference ``parameter_manager.{h,cc}``).

The reference tunes fusion-buffer size, cycle time, cache and hierarchy
flags during the first training batches: a categorical warm-up grid, then
Bayesian optimization (GP + expected improvement, ``optim/``), scoring each
sample by negotiated bytes/sec and broadcasting the winner from rank 0
(``controller.cc:34-48``).

On TPU the jit data plane leaves two meaningful knobs: the eager-bucket
fusion threshold and flush cycle time.  This manager keeps the same
lifecycle — ``record_bytes()`` each step, sample scoring over fixed windows,
readback of the best point — with a grid + golden-section refinement, which
converges in fewer samples than GP for 1–2 smooth dims.  Knobs the user set
explicitly (``fixed_knobs``) are never touched (reference ``operations.cc:436``).
"""

from __future__ import annotations

import csv
import time
from typing import List, Optional, Tuple

from horovod_tpu.utils import logging as hvd_logging

MiB = 1024 * 1024

# categorical warm-up grid: (fusion_threshold_bytes, cycle_time_ms),
# same spirit as parameter_manager.cc's initial grid
_WARMUP_GRID: List[Tuple[int, float]] = [
    (0, 1.0),
    (8 * MiB, 2.5),
    (32 * MiB, 5.0),
    (64 * MiB, 5.0),
    (128 * MiB, 10.0),
]


#: Bayesian-refinement search box: log2(fusion bytes) in [1 MiB, 256 MiB],
#: cycle time in [1, 20] ms (reference tunable ranges,
#: parameter_manager.h:58-78)
_BO_BOUNDS = [(20.0, 28.0), (1.0, 20.0)]
_BO_SAMPLES = 8

#: warm-up points still MEASURED after cost-model pruning (predict=):
#: the model ranks, the measurement decides — two survivors keep the
#: decision empirical while skipping the predicted-hopeless majority
_PREDICT_KEEP = 2


class ParameterManager:
    """Online knob tuner; ``predict=`` (a scorer like
    ``analysis.cost_model.make_fusion_predictor``) pre-prunes the
    categorical warm-up grid by predicted bytes/sec so only the
    plausible points pay for measurement steps — the ISSUE-7 path that
    queries the static cost model before touching hardware."""

    def __init__(self, config, log_path: Optional[str] = None,
                 predict=None):
        self._config = config
        self._tunable = [k for k in ("fusion_threshold_bytes", "cycle_time_ms")
                         if k not in config.fixed_knobs]
        self._samples_per_point = config.autotune_steps_per_sample
        self._points = list(_WARMUP_GRID)
        if predict is not None:
            self._points = self._prune_by_prediction(predict)
        self._scores: List[Tuple[float, Tuple[int, float]]] = []
        self._point_idx = 0
        self._bytes_this_point = 0
        self._steps_this_point = 0
        self._point_start = time.monotonic()
        self._done = not self._tunable
        self._log_path = log_path
        self._log_rows: List[dict] = []
        self._bo = None
        self._bo_samples_left = getattr(
            config, 'autotune_bayes_opt_max_samples', _BO_SAMPLES)
        self._gp_noise = getattr(
            config, 'autotune_gaussian_process_noise', 0.8)
        if not self._done:
            self._apply(self._points[0])

    def _prune_by_prediction(self, predict) -> List[Tuple[int, float]]:
        """Rank the warm-up grid by the cost model's predicted score
        and keep the top ``_PREDICT_KEEP`` points (grid order
        preserved).  A predictor that throws falls back to the full
        grid — a broken model must cost tuning time, never correctness.
        The Bayesian refinement after the warm-up is untouched: it can
        still walk back into pruned territory if the measurements
        disagree with the model."""
        try:
            scored = sorted(((float(predict(p)), p)
                             for p in self._points),
                            key=lambda s: -s[0])
        except Exception as e:  # noqa: BLE001 — prediction is advisory
            hvd_logging.warning(
                "autotune: predict scorer failed (%s); measuring the "
                "full warm-up grid", e)
            return list(self._points)
        top = {p for _, p in scored[:_PREDICT_KEEP]}
        kept = [p for p in self._points if p in top]
        hvd_logging.info(
            "autotune: cost model pruned the warm-up grid %d -> %d "
            "points (%s)", len(self._points), len(kept), kept)
        return kept

    @property
    def active(self) -> bool:
        return not self._done

    def _apply(self, point: Tuple[int, float]) -> None:
        if "fusion_threshold_bytes" in self._tunable:
            self._config.fusion_threshold_bytes = point[0]
        if "cycle_time_ms" in self._tunable:
            self._config.cycle_time_ms = point[1]

    def record_bytes(self, nbytes: int) -> None:
        """Called by the bucketing layer after each flushed collective."""
        if self._done:
            return
        self._bytes_this_point += nbytes
        self._steps_this_point += 1
        if self._steps_this_point >= self._samples_per_point:
            self._finish_point()

    def _finish_point(self) -> None:
        elapsed = max(time.monotonic() - self._point_start, 1e-9)
        score = self._score_across_processes(self._bytes_this_point, elapsed)
        point = self._points[self._point_idx]
        self._scores.append((score, point))
        self._log_rows.append({
            "fusion_threshold": point[0], "cycle_time_ms": point[1],
            "bytes_per_sec": score})
        hvd_logging.debug("autotune: point %s scored %.3e B/s", point, score)

        self._point_idx += 1
        if self._point_idx < len(self._points):
            self._apply(self._points[self._point_idx])
            self._reset_window()
            return

        # Bayesian refinement after the categorical warm-up (reference
        # parameter_manager.cc: grid warm-up, then GP+EI).  Deterministic
        # seed + synced scores keep every process proposing the same point.
        import math

        from horovod_tpu.utils.bayesian import BayesianOptimizer

        if self._bo is None:
            self._bo = BayesianOptimizer(_BO_BOUNDS, seed=0,
                                         noise=self._gp_noise)
            for sc, (thr, cyc) in self._scores:
                self._bo.observe(
                    [math.log2(max(thr, 1 * MiB)), cyc], sc)
        else:
            self._bo.observe([math.log2(max(point[0], 1 * MiB)), point[1]],
                             score)

        if self._bo_samples_left > 0:
            self._bo_samples_left -= 1
            log_thr, cyc = self._bo.suggest()
            nxt = (int(2 ** log_thr), round(float(cyc), 2))
            self._points.append(nxt)
            self._apply(nxt)
            self._reset_window()
            return

        best = max(self._scores, key=lambda s: s[0])[1]
        self._apply(best)
        self._done = True
        hvd_logging.info(
            "autotune converged: fusion_threshold=%d cycle_time=%.1fms",
            self._config.fusion_threshold_bytes, self._config.cycle_time_ms)
        self._write_log()

    def _reset_window(self) -> None:
        self._bytes_this_point = 0
        self._steps_this_point = 0
        self._point_start = time.monotonic()

    def _score_across_processes(self, nbytes: int, elapsed: float) -> float:
        """Agree on one score for this sample point across all processes.

        Locally-timed scores differ per process; applying per-process
        winners would set divergent fusion thresholds and break the
        bucketer's every-process-fuses-the-same-set invariant.  The
        reference solves this by rank-0 tuning + parameter broadcast
        (``controller.cc:34-48``); here every process derives the identical
        score from a metadata allgather — total bytes over the slowest
        process's elapsed time.  All processes reach this exchange at the
        same flush index because flush decisions follow program order.
        """
        import numpy as np

        from horovod_tpu.ops import eager

        if eager.process_mesh().devices.size == 1:
            return nbytes / elapsed
        sample = np.asarray([nbytes, int(elapsed * 1e9)], np.int64)
        gathered = eager._allgather_host_metadata(sample)
        total_bytes = float(gathered[:, 0].sum())
        slowest_s = max(float(gathered[:, 1].max()) / 1e9, 1e-9)
        return total_bytes / slowest_s

    def _write_log(self) -> None:
        if not self._log_path or not self._log_rows:
            return
        with open(self._log_path, "w", newline="") as f:
            w = csv.DictWriter(f, fieldnames=list(self._log_rows[0]))
            w.writeheader()
            w.writerows(self._log_rows)
