"""Auxiliary subsystems: logging, timeline tracing, stall detection, autotune."""
