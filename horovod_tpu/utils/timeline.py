"""Chrome-tracing timeline, the reference's profiling subsystem rebuilt.

The reference's ``Timeline`` (``horovod/common/timeline.{h,cc}``) writes
Chrome ``chrome://tracing`` JSON from a dedicated writer thread fed by a
lock-free SPSC queue (``timeline.h:47-75``); every tensor walks a
NEGOTIATING → TOP_LEVEL → ACTIVITY state machine (``timeline.h:77``) with
activity names from ``common.h:32-62`` (QUEUE, MEMCPY_IN_FUSION_BUFFER,
NCCL_ALLREDUCE, ...).

TPU version: negotiation does not exist, so the per-tensor states collapse
to QUEUE (bucketed, waiting for flush) → COLLECTIVE (dispatched into XLA).
Device-side timing comes from ``jax.profiler`` (Perfetto) — this timeline
records the host-side orchestration view, which is what the reference's
timeline showed too (GPU activities were event-drained estimates,
``gpu_operations.h:110-119``).  Enabled by ``HOROVOD_TIMELINE=file.json``
(``operations.cc:417-424``).
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
from typing import Optional

# Activity names mirroring common.h:32-62
QUEUE = "QUEUE"
NEGOTIATE = "NEGOTIATE"            # NEGOTIATE_ALLREDUCE/... analogue
FUSE = "FUSE"                      # MEMCPY_IN_FUSION_BUFFER analogue
COLLECTIVE = "COLLECTIVE"          # NCCL_ALLREDUCE etc. analogue
XLA_ALLREDUCE = "XLA_ALLREDUCE"
XLA_ALLGATHER = "XLA_ALLGATHER"
XLA_BROADCAST = "XLA_BROADCAST"
XLA_ALLTOALL = "XLA_ALLTOALL"
XLA_REDUCESCATTER = "XLA_REDUCESCATTER"
XLA_BARRIER = "XLA_BARRIER"
COMPILE = "COMPILE"
UNFUSE = "UNFUSE"                  # MEMCPY_OUT_FUSION_BUFFER analogue


class Timeline:
    """Asynchronous Chrome-trace writer (reference ``TimelineWriter``).

    Events are pushed onto a thread-safe queue and serialized by a
    dedicated writer thread, mirroring the SPSC design in
    ``timeline.h:47-75`` without stalling collective dispatch.
    """

    def __init__(self, filename: str, mark_cycles: bool = False):
        self._filename = filename
        self._mark_cycles = mark_cycles
        self._queue: "queue.Queue" = queue.Queue()
        self._start_ns = time.monotonic_ns()
        self._active: dict = {}
        self._closed = False
        self._pid = os.getpid()
        self._file = open(filename, "w")
        self._file.write("[\n")
        self._first = True
        self._writer = threading.Thread(target=self._write_loop, daemon=True,
                                        name="hvd_tpu_timeline_writer")
        self._writer.start()

    # -- event API (mirrors Timeline::ActivityStart/End, MarkCycleStart) ----

    def _ts_us(self) -> float:
        return (time.monotonic_ns() - self._start_ns) / 1e3

    def start_activity(self, tensor_name: str, activity: str) -> None:
        self._queue.put({"ph": "B", "name": activity, "cat": activity,
                         "tid": tensor_name, "pid": self._pid,
                         "ts": self._ts_us()})

    def end_activity(self, tensor_name: str) -> None:
        self._queue.put({"ph": "E", "tid": tensor_name, "pid": self._pid,
                         "ts": self._ts_us()})

    def instant(self, name: str, args: Optional[dict] = None) -> None:
        self._queue.put({"ph": "i", "name": name, "s": "p",
                         "tid": "runtime", "pid": self._pid,
                         "ts": self._ts_us(), "args": args or {}})

    def mark_cycle_start(self) -> None:
        """HOROVOD_TIMELINE_MARK_CYCLES analogue (operations.cc:428,578):
        marks each eager-bucket flush cycle."""
        if self._mark_cycles:
            self.instant("CYCLE_START")

    # -- writer thread ------------------------------------------------------

    def _write_loop(self) -> None:
        while True:
            ev = self._queue.get()
            if ev is None:
                return
            if not self._first:
                self._file.write(",\n")
            self._first = False
            json.dump(ev, self._file)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._queue.put(None)
        self._writer.join(timeout=5)
        self._file.write("\n]\n")
        self._file.close()


def activity(tensor_name: str, name: str):
    """Context manager recording one activity on the runtime timeline."""
    from horovod_tpu.runtime import state

    class _Ctx:
        def __enter__(self):
            self.tl = None
            if state.is_initialized():
                self.tl = state.global_state().timeline
            if self.tl is not None:
                self.tl.start_activity(tensor_name, name)
            return self

        def __exit__(self, *exc):
            if self.tl is not None:
                self.tl.end_activity(tensor_name)
            return False

    return _Ctx()
