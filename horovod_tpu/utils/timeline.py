"""Chrome-tracing timeline, the reference's profiling subsystem rebuilt.

The reference's ``Timeline`` (``horovod/common/timeline.{h,cc}``) writes
Chrome ``chrome://tracing`` JSON from a dedicated writer thread fed by a
lock-free SPSC queue (``timeline.h:47-75``); every tensor walks a
NEGOTIATING → TOP_LEVEL → ACTIVITY state machine (``timeline.h:77``) with
activity names from ``common.h:32-62`` (QUEUE, MEMCPY_IN_FUSION_BUFFER,
NCCL_ALLREDUCE, ...).

TPU version: negotiation does not exist, so the per-tensor states collapse
to QUEUE (bucketed, waiting for flush) → COLLECTIVE (dispatched into XLA).
Device-side timing comes from ``jax.profiler`` (Perfetto) — this timeline
records the host-side orchestration view, which is what the reference's
timeline showed too (GPU activities were event-drained estimates,
``gpu_operations.h:110-119``).  Enabled by ``HOROVOD_TIMELINE=file.json``
(``operations.cc:417-424``).
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
from typing import Optional

from horovod_tpu import faults

# Activity names mirroring common.h:32-62
QUEUE = "QUEUE"
NEGOTIATE = "NEGOTIATE"            # NEGOTIATE_ALLREDUCE/... analogue
FUSE = "FUSE"                      # MEMCPY_IN_FUSION_BUFFER analogue
COLLECTIVE = "COLLECTIVE"          # NCCL_ALLREDUCE etc. analogue
XLA_ALLREDUCE = "XLA_ALLREDUCE"
XLA_ALLGATHER = "XLA_ALLGATHER"
XLA_BROADCAST = "XLA_BROADCAST"
XLA_ALLTOALL = "XLA_ALLTOALL"
XLA_REDUCESCATTER = "XLA_REDUCESCATTER"
XLA_BARRIER = "XLA_BARRIER"
COMPILE = "COMPILE"
UNFUSE = "UNFUSE"                  # MEMCPY_OUT_FUSION_BUFFER analogue


class TraceAnnotationBridge:
    """Mirrors timeline activity spans into ``jax.profiler``
    TraceAnnotations — the device-trace correlation hook (SURVEY §5.1's
    TPU mapping: "hook the same phase/activity event model into
    Perfetto/jax.profiler").  A device trace captured under
    ``jax.profiler.trace()`` then carries host ``hvd:ACTIVITY:tensor``
    rows that line up 1:1 with the Chrome-trace QUEUE/NEGOTIATE/XLA_*
    spans (docs/timeline.md "Overlaying with the device trace").
    TraceMe no-ops when no profiler session is active, so the bridge is
    free in normal runs.  Spans are entered/exited on the dispatching
    thread (TraceMe is thread-local); both timeline writers (Python and
    native) share this one bridge implementation."""

    def __init__(self):
        self._open: dict = {}      # (thread id, tensor) -> annotation
        # resolve the class ONCE — this sits on the per-tensor eager
        # hot path, where a try/import per event would not be "free"
        try:
            import jax.profiler as _prof

            self._cls = _prof.TraceAnnotation
        except Exception:          # profiler unavailable in this build
            self._cls = None

    def _annotation(self, name: str):
        return None if self._cls is None else self._cls(name)

    def start(self, tensor_name: str, activity: str) -> None:
        # keyed by (thread, tensor): TraceMe spans are thread-local, so
        # an end_activity arriving on another thread must NOT exit this
        # span (it is dropped instead — an open leftover span in one
        # lane beats a corrupted track), and a duplicate in-flight
        # start for the same tensor keeps the first span
        key = (threading.get_ident(), tensor_name)
        if key in self._open:
            return
        ann = self._annotation(f"hvd:{activity}:{tensor_name}")
        if ann is not None:
            ann.__enter__()
            self._open[key] = ann

    def end(self, tensor_name: str) -> None:
        ann = self._open.pop((threading.get_ident(), tensor_name), None)
        if ann is not None:
            ann.__exit__(None, None, None)

    def clear(self) -> None:
        # drop (don't cross-thread-exit) dangling spans at close:
        # TraceMe is thread-local and spans end with the process anyway
        self._open.clear()


_TICK = object()    # writer-loop sentinel: periodic flush, no event


class Timeline:
    """Asynchronous Chrome-trace writer (reference ``TimelineWriter``).

    Events are pushed onto a thread-safe queue and serialized by a
    dedicated writer thread, mirroring the SPSC design in
    ``timeline.h:47-75`` without stalling collective dispatch.

    The writer flushes on a time/event-count bound
    (``flush_interval_s``/``flush_events``), so a crashed worker leaves
    at most one flush window of events in the libc buffer and the file
    on disk stays *truncated-valid*: :func:`load_trace` recovers every
    complete event from a file whose tail (and closing ``]``) never got
    written.  On each flush tick the writer additionally renders every
    registered telemetry gauge as a Chrome counter row (``"ph": "C"``)
    — queue depth, heartbeat age and friends appear as tracks under the
    collective spans (docs/metrics.md, docs/timeline.md).
    """

    def __init__(self, filename: str, mark_cycles: bool = False,
                 flush_interval_s: float = 5.0, flush_events: int = 128):
        self.filename = filename
        self._filename = filename
        self._mark_cycles = mark_cycles
        self._flush_interval_s = max(float(flush_interval_s), 0.05)
        self._flush_events = max(int(flush_events), 1)
        self._queue: "queue.Queue" = queue.Queue()
        self._start_ns = time.monotonic_ns()
        # wall-clock at the monotonic origin: event wall time =
        # wall_origin_us + ts, the rebasing key for cross-process merge
        self.wall_origin_us = time.time_ns() / 1e3
        self._active: dict = {}
        self._annotations = TraceAnnotationBridge()
        self._closed = False
        self._pid = os.getpid()
        self._file = open(filename, "w")
        self._file.write("[\n")
        self._first = True
        self._writer = threading.Thread(target=self._write_loop, daemon=True,
                                        name="hvd_tpu_timeline_writer")
        self._writer.start()
        # correlation stamp: when a run context was explicitly set
        # (bench/elastic runs), the trace opens with it so spans, metric
        # snapshots and logs share the (run_id, generation) key
        from horovod_tpu.telemetry import context as tel_context

        ctx = tel_context.run_context()
        if ctx.explicit:
            self.instant("run_context", args=ctx.as_dict())

    # -- event API (mirrors Timeline::ActivityStart/End, MarkCycleStart) ----

    def _ts_us(self) -> float:
        return (time.monotonic_ns() - self._start_ns) / 1e3

    def start_activity(self, tensor_name: str, activity: str) -> None:
        self._queue.put({"ph": "B", "name": activity, "cat": activity,
                         "tid": tensor_name, "pid": self._pid,
                         "ts": self._ts_us()})
        self._annotations.start(tensor_name, activity)

    def end_activity(self, tensor_name: str) -> None:
        self._queue.put({"ph": "E", "tid": tensor_name, "pid": self._pid,
                         "ts": self._ts_us()})
        self._annotations.end(tensor_name)

    def instant(self, name: str, args: Optional[dict] = None) -> None:
        self._queue.put({"ph": "i", "name": name, "s": "p",
                         "tid": "runtime", "pid": self._pid,
                         "ts": self._ts_us(), "args": args or {}})

    def mark_cycle_start(self) -> None:
        """HOROVOD_TIMELINE_MARK_CYCLES analogue (operations.cc:428,578):
        marks each eager-bucket flush cycle."""
        if self._mark_cycles:
            self.instant("CYCLE_START")

    # -- writer thread ------------------------------------------------------

    def _write_loop(self) -> None:
        unflushed = 0
        last_flush = time.monotonic()
        while True:
            try:
                ev = self._queue.get(timeout=self._flush_interval_s)
            except queue.Empty:
                ev = _TICK
            if ev is None:
                self._file.flush()
                return
            if ev is _TICK:
                # idle flush: push buffered events to disk so a later
                # crash cannot lose them, and sample the gauges
                self._emit_gauge_counters()
                self._file.flush()
                unflushed = 0
                last_flush = time.monotonic()
                continue
            # chaos hook: a raise/delay models a failing trace sink —
            # tracing must degrade without stalling the training loop
            faults.inject("timeline.write")
            self._write_event(ev)
            unflushed += 1
            now = time.monotonic()
            if unflushed >= self._flush_events or \
                    now - last_flush >= self._flush_interval_s:
                self._emit_gauge_counters()
                self._file.flush()
                unflushed = 0
                last_flush = now

    def _write_event(self, ev: dict) -> None:
        if not self._first:
            self._file.write(",\n")
        self._first = False
        json.dump(ev, self._file)

    def _emit_gauge_counters(self) -> None:
        """Chrome counter rows (``"ph":"C"``) from the telemetry
        registry's gauges, one event per gauge name with the label sets
        as counter series — written inline by the writer thread (never
        queued, so a full queue can't starve the metrics track)."""
        try:
            from horovod_tpu import telemetry

            if not telemetry.enabled():
                return
            samples = telemetry.default_registry().gauge_samples()
        except Exception:      # noqa: BLE001 — metrics must not kill tracing
            return
        if not samples:
            return
        ts = self._ts_us()
        by_name: dict = {}
        for name, labels, value in samples:
            series = ",".join(f"{k}={v}" for k, v in
                              sorted(labels.items())) or "value"
            by_name.setdefault(name, {})[series] = value
        for name, args in sorted(by_name.items()):
            self._write_event({"ph": "C", "name": name, "pid": self._pid,
                               "tid": "metrics", "ts": ts, "args": args})

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._annotations.clear()
        self._queue.put(None)
        self._writer.join(timeout=5)
        self._file.write("\n]\n")
        self._file.close()


def load_trace(filename: str) -> list:
    """Parse a Chrome-trace file, tolerating a truncated tail.

    A cleanly-closed trace is plain JSON.  A crashed worker's trace is
    missing the closing ``]`` and may end mid-event; since the writer
    emits one event per line joined by ``",\\n"``, every *complete*
    event is still recoverable — exactly what the periodic writer flush
    guarantees survived to disk (the reference loses the buffered tail
    entirely).  Chrome's own loader applies the same tolerance; this is
    the programmatic counterpart the aggregation and tests use."""
    with open(filename) as f:
        text = f.read()
    try:
        return json.loads(text)
    except ValueError:
        pass
    events = []
    body = text.lstrip()
    if body.startswith("["):
        body = body[1:]
    for part in body.split(",\n"):
        part = part.strip()
        if part.endswith("]"):
            part = part[:-1].rstrip()
        if not part:
            continue
        try:
            events.append(json.loads(part))
        except ValueError:
            break          # the incomplete tail event — the crash point
    return events


def merge_traces(blobs) -> list:
    """Merge per-process Chrome-trace events into one trace.

    ``blobs`` is ``[(proc_index, wall_origin_us, events), ...]``.  Events
    are rebased onto the earliest wall origin (one consistent time axis),
    their ``pid`` is remapped to the process index, and ``process_name``
    metadata rows label each process — the single-file view the
    reference's rank-0 aggregated timeline gives (``timeline.cc``:
    the controller forwards every rank's negotiation events to rank 0's
    writer).
    """
    if not blobs:
        return []
    base = min(origin for _, origin, _ in blobs)
    merged = []
    for p, origin, events in sorted(blobs):
        merged.append({"ph": "M", "name": "process_name", "pid": p,
                       "args": {"name": f"process {p}"}})
        off = origin - base
        for e in events:
            e = dict(e)
            e["pid"] = p
            if "ts" in e:
                e["ts"] = e["ts"] + off
            merged.append(e)
    return merged


_aggregate_seq = 0


def aggregate_after_close(filename: str, wall_origin_us) -> None:
    """Cross-process timeline aggregation, run after the local writer
    closed its file.

    Non-root processes upload their event file (plus wall origin) to the
    coordination KV; rank 0 collects every upload, merges with its own
    events via :func:`merge_traces`, and rewrites its file as the single
    aggregated trace.  Calls are SPMD-ordered (``stop_timeline`` /
    ``shutdown`` run in program order on every process), so a per-call
    sequence number keeps keys unique across repeated start/stop cycles.
    Best-effort: a missing peer (crashed before upload) is warned about
    and skipped, never hung on.
    """
    global _aggregate_seq
    try:
        from jax._src import distributed as dist

        gs = dist.global_state
        if gs.client is None or not gs.num_processes or \
                gs.num_processes == 1:
            return
        client, me, nproc = gs.client, int(gs.process_id), \
            int(gs.num_processes)
    except Exception:
        return
    _aggregate_seq += 1
    seq = _aggregate_seq
    if wall_origin_us is None:
        wall_origin_us = time.time_ns() / 1e3
    if me != 0:
        try:
            events = load_trace(filename)
        except Exception:
            events = []
        client.key_value_set_bytes(
            f"hvdtl/{seq}/{me}",
            json.dumps({"origin": wall_origin_us,
                        "events": events}).encode())
        return
    blobs = [(0, wall_origin_us, _load_events(filename))]
    # One shared deadline across all peers: shutdown with k crashed
    # peers must cost at most ~30s total, not k*30s sequentially.
    deadline = time.monotonic() + 30.0
    for p in range(1, nproc):
        key = f"hvdtl/{seq}/{p}"
        try:
            timeout_ms = max(1, int((deadline - time.monotonic()) * 1e3))
            raw = client.blocking_key_value_get_bytes(key, timeout_ms)
            payload = json.loads(raw)
            blobs.append((p, payload["origin"], payload["events"]))
            client.key_value_delete(key)
        except Exception:
            from horovod_tpu.utils import logging as hvd_logging

            hvd_logging.warning(
                "timeline aggregation: no upload from process %d; "
                "writing a partial merged trace", p)
    with open(filename, "w") as f:
        json.dump(merge_traces(blobs), f)


def _load_events(filename: str) -> list:
    try:
        return load_trace(filename)
    except Exception:
        return []


def activity(tensor_name: str, name: str):
    """Context manager recording one activity on the runtime timeline."""
    from horovod_tpu.runtime import state

    class _Ctx:
        def __enter__(self):
            self.tl = None
            if state.is_initialized():
                self.tl = state.global_state().timeline
            if self.tl is not None:
                self.tl.start_activity(tensor_name, name)
            return self

        def __exit__(self, *exc):
            if self.tl is not None:
                self.tl.end_activity(tensor_name)
            return False

    return _Ctx()
