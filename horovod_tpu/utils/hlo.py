"""Compiled-HLO collective inspection.

The reference's gradient fusion is *runtime*-observable: the controller
merges pending tensors into one fused buffer per cycle
(``controller.cc:686 FuseResponses``, fusion-buffer threshold
``HOROVOD_FUSION_THRESHOLD``).  Here fusion happens at *compile* time —
autodiff inserts one psum per gradient leaf and XLA's all-reduce
combiner merges them into one grouped collective — so the observable
artifact is the optimized HLO module.  This module parses collectives
out of compiled HLO text so tests can guard the fusion invariant (a
regression that silently de-fuses into per-leaf collectives would pass
every numerics test and only show up as wire overhead on a real pod)
and so the scaling model can count bytes on the wire per step
(``docs/scaling.md``).

Usage::

    txt = step.compiled_text(params, opt_state, batch)
    ops = collective_ops(txt)
    [o for o in ops if o.kind == "all-reduce"]
"""

from __future__ import annotations

import dataclasses
import re
from typing import List, Optional, Tuple

# HLO primitive byte widths (token/opaque excluded — they never carry
# payload).
_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "f8e4m3fn": 1, "f8e5m2": 1,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}

_KINDS = ("all-reduce", "reduce-scatter", "all-gather", "all-to-all",
          "collective-permute", "collective-broadcast")

# one result tensor: dtype[dims]{layout} — layout block optional
_SHAPE_RE = re.compile(r"([a-z]\w*)\[([\d,]*)\](?:\{[^}]*\})?")
# op-definition line: "%name = <result-type> <kind>[-start](operands...)".
# The result type may be a tuple wrapped in extra parens with trailing
# context scalars — newer XLA emits ``((f32[...], f32[...]), u32[])``
# and ``(f32[...], u32[])`` variants — so the kind match anchors on the
# closing bracket/brace of the type (``(?<=[\]})])``) and tolerates a
# missing separator space rather than requiring ``<type> <kind>``.
_OP_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*(.+?)(?<=[\]})])\s*\b("
    + "|".join(_KINDS) + r")(-start)?\(")


@dataclasses.dataclass
class CollectiveOp:
    """One collective in an optimized HLO module."""

    kind: str                      # e.g. "all-reduce"
    shapes: List[Tuple[str, Tuple[int, ...]]]   # (dtype, dims) per operand
    bytes: int                     # payload bytes (sum over operands)
    replica_groups: Optional[str]  # raw attribute text, None if absent
    group_size: Optional[int]      # devices per group, None if unknown
    line: str                      # the full HLO line (diagnostics)
    asynchronous: bool = False     # issued as a -start/-done pair

    @property
    def dtypes(self) -> set:
        return {d for d, _ in self.shapes}


def _parse_shapes(result_type: str) -> List[Tuple[str, Tuple[int, ...]]]:
    shapes = []
    for dt, dims in _SHAPE_RE.findall(result_type):
        if dt not in _DTYPE_BYTES:
            continue                    # token/opaque/etc
        shape = tuple(int(d) for d in dims.split(",") if d) \
            if dims else ()
        shapes.append((dt, shape))
    return shapes


def _nbytes(shapes) -> int:
    total = 0
    for dt, dims in shapes:
        n = 1
        for d in dims:
            n *= d
        total += n * _DTYPE_BYTES[dt]
    return total


def _replica_groups(line: str):
    """Return (raw_attr, group_size) from either the explicit
    ``{{0,1},{2,3}}`` form or the iota ``[2,4]<=[8]`` form."""
    m = re.search(r"replica_groups=(\{\{[^=]*?\}\}|\{\}|\[[\d,]+\]<=\[[\d,]+\](?:T\([\d,]+\))?)",
                  line)
    if not m:
        return None, None
    raw = m.group(1)
    if raw.startswith("{{"):
        first = raw[2:].split("}", 1)[0]
        return raw, len([x for x in first.split(",") if x.strip() != ""])
    if raw == "{}":
        return raw, None
    dims = raw[1:].split("]", 1)[0]     # iota: [G,S]<=[N] — S per group
    parts = [int(x) for x in dims.split(",")]
    return raw, parts[-1]


_IOTA_GROUPS_RE = re.compile(
    r"^\[(\d+),(\d+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?$")


def _transposed_iota(dims: List[int], perm: List[int]) -> List[int]:
    """``transpose(iota(prod(dims)).reshape(dims), perm).flatten()``
    in pure stdlib — the device-id order of an iota replica-group
    attribute with a ``T(...)`` permutation."""
    strides = [1] * len(dims)
    for i in range(len(dims) - 2, -1, -1):
        strides[i] = strides[i + 1] * dims[i + 1]
    shape_t = [dims[p] for p in perm]
    n = 1
    for d in dims:
        n *= d
    out = []
    for flat in range(n):
        rem, idx_t = flat, []
        for d in reversed(shape_t):
            idx_t.append(rem % d)
            rem //= d
        idx_t.reverse()
        out.append(sum(idx_t[k] * strides[perm[k]]
                       for k in range(len(perm))))
    return out


def replica_group_members(raw: Optional[str]
                          ) -> Optional[List[List[int]]]:
    """Materialize a replica-groups attribute into explicit member
    lists — ``[[0,2],[1,3]]`` — from either the explicit
    ``{{0,2},{1,3}}`` form or the iota ``[G,S]<=[dims]`` /
    ``[G,S]<=[dims]T(perm)`` form.  Returns ``None`` for absent/empty
    attributes and spellings this parser cannot expand (the caller
    then falls back to size-only reasoning).  The iota ids are the
    row-major iota over ``dims``, transposed by ``perm`` and reshaped
    to ``[G,S]``; a 1-D ``dims`` with a 2-D ``perm`` (a spelling some
    dumps use) is read with the source shape implied by the transpose
    target."""
    if not raw or raw == "{}":
        return None
    if raw.startswith("{{"):
        inner = raw[2:-2]
        groups = []
        for grp in inner.split("},{"):
            members = [int(x) for x in grp.split(",") if x.strip()]
            if members:
                groups.append(members)
        return groups or None
    m = _IOTA_GROUPS_RE.match(raw.replace(" ", ""))
    if m is None:
        return None
    g, s = int(m.group(1)), int(m.group(2))
    dims = [int(x) for x in m.group(3).split(",")]
    n = g * s
    prod = 1
    for d in dims:
        prod *= d
    if prod != n or n == 0:
        return None
    if m.group(4) is None:
        order = list(range(n))
    else:
        perm = [int(x) for x in m.group(4).split(",")]
        if len(perm) != len(dims):
            # 1-D source with an N-D perm: the source shape is the one
            # whose transpose-by-perm is the [G,S] target
            target = [g, s]
            if len(perm) != 2 or sorted(perm) != [0, 1]:
                return None
            dims = [0, 0]
            for k, p in enumerate(perm):
                dims[p] = target[k]
        order = _transposed_iota(dims, perm)
    return [order[i * s:(i + 1) * s] for i in range(g)]


def replica_group_stride(raw: Optional[str]) -> Optional[int]:
    """Device-id step between consecutive members of the first replica
    group, or ``None`` when unknown (absent attribute, singleton
    groups, or non-uniform spacing).  On a row-major mesh this is the
    signature that separates topology levels of EQUAL extent: level ℓ's
    groups step by the product of the extents inside it (the intra-
    slice scope strides 1, the cross-slice scope strides ``n_ici``) —
    the quantity ``analysis/cost_model.collective_wire_by_level`` keys
    attribution on."""
    groups = replica_group_members(raw)
    if not groups or len(groups[0]) < 2:
        return None
    first = groups[0]
    stride = first[1] - first[0]
    if any(b - a != stride for a, b in zip(first, first[1:])):
        return None
    return stride


def collective_ops(hlo_text: str) -> List[CollectiveOp]:
    """All collective ops in an (optimized) HLO module dump.

    Async pairs (``all-reduce-start``/``-done``) count once, under the
    start op.  Shapes come from the op's *result* type — for
    ``all-reduce`` the result equals the reduced payload; for
    ``all-gather`` it is the gathered (output) size; for
    ``reduce-scatter`` the scattered (per-shard output) size.
    """
    ops = []
    for line in hlo_text.splitlines():
        m = _OP_RE.match(line)
        if m is None:
            continue
        result_type, kind, is_async = m.group(1), m.group(2), m.group(3)
        shapes = _parse_shapes(result_type)
        # async start tuples carry trailing scalar context values on
        # TPU (the u32[] in `(f32[...], u32[])`); they are bookkeeping,
        # not payload — drop them BEFORE picking the output element,
        # otherwise the context scalar is mistaken for the output (and
        # every byte-based fusion guard sees a 4-byte collective)
        if is_async and len(shapes) >= 2:
            while len(shapes) > 1 and shapes[-1][1] == () and \
                    shapes[-1][0] in ("u32", "s32"):
                shapes = shapes[:-1]
        # async starts of gather/scatter/permute carry `(input, output)`
        # tuples; the payload is the output alone — summing the whole
        # tuple double-counts
        if is_async and kind in ("all-gather", "reduce-scatter",
                                 "collective-permute") \
                and len(shapes) >= 2:
            shapes = [shapes[1]]
        raw, gsize = _replica_groups(line)
        ops.append(CollectiveOp(kind=kind, shapes=shapes,
                                bytes=_nbytes(shapes),
                                replica_groups=raw, group_size=gsize,
                                line=line.strip(),
                                asynchronous=bool(is_async)))
    return ops


# -- whole-module accounting (cost model substrate) -------------------------
#
# The collective parser above serves the fusion guards; the functions
# below extend the same text-level parse to the quantities the static
# cost model (analysis/cost_model.py, docs/perf_gate.md) needs from a
# lowered module without hardware: per-op FLOPs for the compute ceiling
# and buffer lifetimes for a memory high-water estimate.

# any op-definition line: "%name = <result-type> <opcode>(..."
_ANY_OP_RE = re.compile(
    r"^\s*(?:ROOT\s+)?(%[\w.\-]+)\s*=\s*(.+?)(?<=[\]})])\s*\b([\w\-]+)\(")
_CONTRACT_RE = re.compile(r"lhs_contracting_dims=\{([\d,]*)\}")
_DIM_LABELS_RE = re.compile(r"dim_labels=(\w+)_(\w+)->(\w+)")
# every %name token on a line (defs and uses alike)
_NAME_RE = re.compile(r"%[\w.\-]+")


def _prod(dims) -> int:
    n = 1
    for d in dims:
        n *= d
    return n


def result_bytes(result_type: str) -> int:
    """Payload bytes of one result type string — tuple types sum their
    elements (the tuple-wrapped async-start variants parse like any
    other tuple; their u32[] context scalars are 4 bytes of noise in a
    *memory* estimate, unlike the wire accounting above where
    :func:`collective_ops` strips them)."""
    return _nbytes(_parse_shapes(result_type))


def _operand_shapes(line: str, opcode: str):
    """Typed operand shapes of an op line: the shapes inside the
    ``opcode(...)`` parens.  Dumps that elide operand types (bare
    ``dot(%a, %b)``) yield [] — FLOP counting then skips the op rather
    than guessing."""
    start = line.find(opcode + "(")
    if start < 0:
        return []
    seg, depth = [], 0
    for ch in line[start + len(opcode):]:
        if ch == "(":
            depth += 1
            if depth == 1:
                continue
        elif ch == ")":
            depth -= 1
            if depth == 0:
                break
        seg.append(ch)
    shapes = []
    for dt, dims in _SHAPE_RE.findall("".join(seg)):
        if dt not in _DTYPE_BYTES:
            continue
        shapes.append((dt, tuple(int(d) for d in dims.split(",") if d)
                       if dims else ()))
    return shapes


def _dot_flops(line: str, result_dims, opcode: str) -> Optional[int]:
    """``2 · |result| · K`` for a dot: every output element costs one
    multiply-add per contracted element.  K comes from the lhs operand
    type + ``lhs_contracting_dims``; batch dims are already in the
    result product."""
    operands = _operand_shapes(line, opcode)
    m = _CONTRACT_RE.search(line)
    if not operands or m is None:
        return None
    lhs_dims = operands[0][1]
    contract = [int(x) for x in m.group(1).split(",") if x != ""]
    if any(c >= len(lhs_dims) for c in contract):
        return None
    k = _prod(lhs_dims[c] for c in contract)
    return 2 * _prod(result_dims) * k


def _conv_flops(line: str, result_dims, opcode: str) -> Optional[int]:
    """``2 · |result| · (kernel elements per output feature)`` for a
    convolution: each output element reduces over the kernel's spatial
    × input-feature window.  The kernel's output-feature dim (``o`` in
    ``dim_labels``' second segment) is excluded — it indexes outputs,
    it is not reduced over."""
    operands = _operand_shapes(line, opcode)
    m = _DIM_LABELS_RE.search(line)
    if len(operands) < 2 or m is None:
        return None
    kernel_dims = operands[1][1]
    kernel_labels = m.group(2)
    o_idx = kernel_labels.find("o")
    if o_idx < 0 or o_idx >= len(kernel_dims) or kernel_dims[o_idx] == 0:
        return None
    window = _prod(kernel_dims) // kernel_dims[o_idx]
    return 2 * _prod(result_dims) * window


def op_flops(hlo_text: str) -> List[Tuple[str, str, int]]:
    """``(op_name, opcode, flops)`` for every countable matmul-class op
    (``dot``, ``convolution``) in the module text.

    Fusion bodies are separate computations in the same dump, so a
    ``fusion(...)`` op's inner dots are counted exactly once — at their
    definition inside the fused computation — and the ``fusion`` line
    itself contributes nothing.  Elementwise/reduce ops are ignored:
    on the MXU the matmul class is the FLOP budget (everything else is
    the memory-bound remainder the roofline's HBM term covers)."""
    out: List[Tuple[str, str, int]] = []
    for line in hlo_text.splitlines():
        m = _ANY_OP_RE.match(line)
        if m is None:
            continue
        name, result_type, opcode = m.group(1), m.group(2), m.group(3)
        result_dims = [dims for dt, dims in _parse_shapes(result_type)]
        if not result_dims:
            continue
        flops = None
        if opcode == "dot":
            flops = _dot_flops(line, result_dims[0], opcode)
        elif opcode == "convolution":
            flops = _conv_flops(line, result_dims[0], opcode)
        if flops:
            out.append((name, opcode, flops))
    return out


def module_flops(hlo_text: str) -> int:
    """Total countable FLOPs of one module dump (see :func:`op_flops`)."""
    return sum(f for _, _, f in op_flops(hlo_text))


def entry_computation(hlo_text: str) -> str:
    """The ENTRY computation's lines (between ``ENTRY ... {`` and its
    matching brace), or the whole text when no ENTRY marker exists.
    Memory accounting scopes here: fusion-body instructions never
    materialize their own buffers, so counting them would double-book
    the fusion op's result."""
    lines = hlo_text.splitlines()
    start = next((i for i, ln in enumerate(lines)
                  if ln.lstrip().startswith("ENTRY ")), None)
    if start is None:
        return hlo_text
    depth, out = 0, []
    for ln in lines[start:]:
        depth += ln.count("{") - ln.count("}")
        out.append(ln)
        if depth <= 0 and out:
            break
    return "\n".join(out)


def buffer_liveness(hlo_text: str) -> List[Tuple[str, int, int, int]]:
    """``(name, bytes, def_index, last_use_index)`` per ENTRY-scope
    instruction, indices into the ENTRY line list.  A buffer is modeled
    live from its defining line through the last line that mentions it
    (a never-used def dies on its own line) — the classic linear-scan
    lifetime, ignoring aliasing/donation, so the estimate is an upper
    bound."""
    lines = entry_computation(hlo_text).splitlines()
    defs: List[Tuple[str, int, int]] = []        # (name, bytes, def idx)
    last_use: dict = {}
    for i, line in enumerate(lines):
        m = _ANY_OP_RE.match(line)
        if m is not None:
            defs.append((m.group(1), result_bytes(m.group(2)), i))
        for name in _NAME_RE.findall(line):
            last_use[name] = i
    return [(name, nbytes, d, max(last_use.get(name, d), d))
            for name, nbytes, d in defs]


# one `{out_index}: (param_number, {param_index}, kind)` entry of the
# module-header input_output_alias attribute
_ALIAS_ENTRY_RE = re.compile(
    r"\{\s*[\d\s,]*\}\s*:\s*\(\s*(\d+)\s*,\s*\{([\d\s,]*)\}")
_PARAM_NUM_RE = re.compile(r"\bparameter\((\d+)\)")


def donated_param_bytes(hlo_text: str) -> int:
    """Total bytes of donated ENTRY parameters — inputs the module
    header's ``input_output_alias`` maps onto outputs (``jit``
    ``donate_argnums``: the step's params/opt_state, and the batch
    under ``donate_batch``).  A donated input's buffer IS its output's
    buffer, so a liveness scan that allocates both double-counts
    exactly these bytes.  Nested alias indices (a donated tuple
    *element*) contribute the whole parameter — an over-subtraction in
    theory, but XLA flattens jit arguments to leaf parameters, so the
    index is ``{}`` in every dump this parser meets.  The attribute is
    captured to its balanced closing brace, so a dump that wraps the
    alias list across lines still counts every entry."""
    m = re.search(r"input_output_alias=\{", hlo_text)
    if m is None:
        return 0
    depth, j = 1, m.end()
    while j < len(hlo_text) and depth:
        c = hlo_text[j]
        if c == "{":
            depth += 1
        elif c == "}":
            depth -= 1
        j += 1
    attr = hlo_text[m.end():j - 1]
    sizes = {}
    for line in entry_computation(hlo_text).splitlines():
        om = _ANY_OP_RE.match(line)
        if om is None or om.group(3) != "parameter":
            continue
        pm = _PARAM_NUM_RE.search(line)
        if pm is not None:
            sizes[int(pm.group(1))] = result_bytes(om.group(2))
    return sum(sizes.get(int(pnum), 0)
               for pnum, _pidx in _ALIAS_ENTRY_RE.findall(attr))


def memory_high_water(hlo_text: str) -> int:
    """Peak sum of simultaneously-live ENTRY buffers — the static
    per-device memory high-water estimate the cost model reports
    (docs/perf_gate.md lists the assumptions: no aliasing between
    distinct values, tuple results counted whole).  Donated inputs
    (``input_output_alias``) are accounted: the ROOT's result reuses
    their buffers, so its allocation is reduced by
    :func:`donated_param_bytes` — without this every donated train
    step double-counted params + opt_state at the update point."""
    live = buffer_liveness(hlo_text)
    if not live:
        return 0
    donated = donated_param_bytes(hlo_text)
    if donated:
        lines = entry_computation(hlo_text).splitlines()
        root = next((i for i, ln in enumerate(lines)
                     if ln.lstrip().startswith("ROOT ")), None)
        if root is not None:
            # credit the donation against the ROOT's own allocation,
            # exactly once — never per-buffer, which double-subtracts
            # when another def shares the ROOT line index
            rm = _ANY_OP_RE.match(lines[root])
            root_name = rm.group(1) if rm is not None else None
            credited, fixed = False, []
            for name, nbytes, d, last in live:
                if not credited and d == root and \
                        (root_name is None or name == root_name):
                    nbytes -= min(nbytes, donated)
                    credited = True
                fixed.append((name, nbytes, d, last))
            live = fixed
    n = max(last for _, _, _, last in live) + 1
    alloc = [0] * n
    free = [0] * n
    for _, nbytes, d, last in live:
        alloc[d] += nbytes
        free[last] += nbytes
    cur = peak = 0
    for i in range(n):
        cur += alloc[i]
        peak = max(peak, cur)
        cur -= free[i]
    return peak


def count_by_kind(ops: List[CollectiveOp]) -> dict:
    out: dict = {}
    for o in ops:
        out[o.kind] = out.get(o.kind, 0) + 1
    return out


def scopes_by_kind(ops: List[CollectiveOp]) -> dict:
    """kind → sorted tuple of distinct replica-group sizes — the
    *scope* structure of a module's collectives.  The hierarchical
    exchange's signature is ``{"reduce-scatter": (dcn, ici), ...}``:
    two distinct scopes, one per mesh level, where the flat exchange
    shows a single world-sized scope.  ``None`` group sizes (HLO's
    "all devices" spellings) are kept so a scopeless op can't hide."""
    out: dict = {}
    for o in ops:
        out.setdefault(o.kind, set()).add(o.group_size)
    return {k: tuple(sorted(v, key=lambda s: (s is None, s)))
            for k, v in out.items()}


# opcodes that count as "compute scheduled between start and done" for
# the serial-tail scan: matmul-class ops, fused elementwise bodies and
# loops all give the async collective something to hide under
_COMPUTE_OPS = ("dot", "convolution", "fusion", "while")


def serial_tail_collectives(hlo_text: str,
                            kinds=("reduce-scatter",
                                   "all-gather")) -> int:
    """1 if the module's FINAL async RS/AG pair is a *serial tail* —
    no compute op scheduled between its ``-start`` and ``-done`` — else
    0.  This is the exposure the tile-fused exchange exists to remove
    (HLO005, docs/fused_kernels.md): the last bucket's collective with
    nothing left to hide under.  Synchronous backends (no -start/-done
    pairs, e.g. this image's CPU XLA) return 0 — a sync schedule has no
    window to judge."""
    lines = entry_computation(hlo_text).splitlines()
    last = None
    for i, ln in enumerate(lines):
        m = _ANY_OP_RE.match(ln)
        if m is None:
            continue
        opcode = m.group(3)
        for k in kinds:
            if opcode == f"{k}-start":
                last = (i, k)
    if last is None:
        return 0
    i, kind = last
    done = None
    for j in range(i + 1, len(lines)):
        m = _ANY_OP_RE.match(lines[j])
        if m is not None and m.group(3) == f"{kind}-done":
            done = j
            break
    if done is None:
        return 0
    for ln in lines[i + 1:done]:
        m = _ANY_OP_RE.match(ln)
        if m is not None and m.group(3) in _COMPUTE_OPS:
            return 0
    return 1
