"""Compiled-HLO collective inspection.

The reference's gradient fusion is *runtime*-observable: the controller
merges pending tensors into one fused buffer per cycle
(``controller.cc:686 FuseResponses``, fusion-buffer threshold
``HOROVOD_FUSION_THRESHOLD``).  Here fusion happens at *compile* time —
autodiff inserts one psum per gradient leaf and XLA's all-reduce
combiner merges them into one grouped collective — so the observable
artifact is the optimized HLO module.  This module parses collectives
out of compiled HLO text so tests can guard the fusion invariant (a
regression that silently de-fuses into per-leaf collectives would pass
every numerics test and only show up as wire overhead on a real pod)
and so the scaling model can count bytes on the wire per step
(``docs/scaling.md``).

Usage::

    txt = step.compiled_text(params, opt_state, batch)
    ops = collective_ops(txt)
    [o for o in ops if o.kind == "all-reduce"]
"""

from __future__ import annotations

import dataclasses
import re
from typing import List, Optional, Tuple

# HLO primitive byte widths (token/opaque excluded — they never carry
# payload).
_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "f8e4m3fn": 1, "f8e5m2": 1,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}

_KINDS = ("all-reduce", "reduce-scatter", "all-gather", "all-to-all",
          "collective-permute", "collective-broadcast")

# one result tensor: dtype[dims]{layout} — layout block optional
_SHAPE_RE = re.compile(r"([a-z]\w*)\[([\d,]*)\](?:\{[^}]*\})?")
# op-definition line: "%name = <result-type> <kind>[-start](operands...)".
# The result type may be a tuple wrapped in extra parens with trailing
# context scalars — newer XLA emits ``((f32[...], f32[...]), u32[])``
# and ``(f32[...], u32[])`` variants — so the kind match anchors on the
# closing bracket/brace of the type (``(?<=[\]})])``) and tolerates a
# missing separator space rather than requiring ``<type> <kind>``.
_OP_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*(.+?)(?<=[\]})])\s*\b("
    + "|".join(_KINDS) + r")(-start)?\(")


@dataclasses.dataclass
class CollectiveOp:
    """One collective in an optimized HLO module."""

    kind: str                      # e.g. "all-reduce"
    shapes: List[Tuple[str, Tuple[int, ...]]]   # (dtype, dims) per operand
    bytes: int                     # payload bytes (sum over operands)
    replica_groups: Optional[str]  # raw attribute text, None if absent
    group_size: Optional[int]      # devices per group, None if unknown
    line: str                      # the full HLO line (diagnostics)
    asynchronous: bool = False     # issued as a -start/-done pair

    @property
    def dtypes(self) -> set:
        return {d for d, _ in self.shapes}


def _parse_shapes(result_type: str) -> List[Tuple[str, Tuple[int, ...]]]:
    shapes = []
    for dt, dims in _SHAPE_RE.findall(result_type):
        if dt not in _DTYPE_BYTES:
            continue                    # token/opaque/etc
        shape = tuple(int(d) for d in dims.split(",") if d) \
            if dims else ()
        shapes.append((dt, shape))
    return shapes


def _nbytes(shapes) -> int:
    total = 0
    for dt, dims in shapes:
        n = 1
        for d in dims:
            n *= d
        total += n * _DTYPE_BYTES[dt]
    return total


def _replica_groups(line: str):
    """Return (raw_attr, group_size) from either the explicit
    ``{{0,1},{2,3}}`` form or the iota ``[2,4]<=[8]`` form."""
    m = re.search(r"replica_groups=(\{\{[^=]*?\}\}|\{\}|\[[\d,]+\]<=\[[\d,]+\](?:T\([\d,]+\))?)",
                  line)
    if not m:
        return None, None
    raw = m.group(1)
    if raw.startswith("{{"):
        first = raw[2:].split("}", 1)[0]
        return raw, len([x for x in first.split(",") if x.strip() != ""])
    if raw == "{}":
        return raw, None
    dims = raw[1:].split("]", 1)[0]     # iota: [G,S]<=[N] — S per group
    parts = [int(x) for x in dims.split(",")]
    return raw, parts[-1]


def collective_ops(hlo_text: str) -> List[CollectiveOp]:
    """All collective ops in an (optimized) HLO module dump.

    Async pairs (``all-reduce-start``/``-done``) count once, under the
    start op.  Shapes come from the op's *result* type — for
    ``all-reduce`` the result equals the reduced payload; for
    ``all-gather`` it is the gathered (output) size; for
    ``reduce-scatter`` the scattered (per-shard output) size.
    """
    ops = []
    for line in hlo_text.splitlines():
        m = _OP_RE.match(line)
        if m is None:
            continue
        result_type, kind, is_async = m.group(1), m.group(2), m.group(3)
        shapes = _parse_shapes(result_type)
        # async start tuples carry trailing scalar context values on
        # TPU (the u32[] in `(f32[...], u32[])`); they are bookkeeping,
        # not payload — drop them BEFORE picking the output element,
        # otherwise the context scalar is mistaken for the output (and
        # every byte-based fusion guard sees a 4-byte collective)
        if is_async and len(shapes) >= 2:
            while len(shapes) > 1 and shapes[-1][1] == () and \
                    shapes[-1][0] in ("u32", "s32"):
                shapes = shapes[:-1]
        # async starts of gather/scatter/permute carry `(input, output)`
        # tuples; the payload is the output alone — summing the whole
        # tuple double-counts
        if is_async and kind in ("all-gather", "reduce-scatter",
                                 "collective-permute") \
                and len(shapes) >= 2:
            shapes = [shapes[1]]
        raw, gsize = _replica_groups(line)
        ops.append(CollectiveOp(kind=kind, shapes=shapes,
                                bytes=_nbytes(shapes),
                                replica_groups=raw, group_size=gsize,
                                line=line.strip(),
                                asynchronous=bool(is_async)))
    return ops


def count_by_kind(ops: List[CollectiveOp]) -> dict:
    out: dict = {}
    for o in ops:
        out[o.kind] = out.get(o.kind, 0) + 1
    return out


def scopes_by_kind(ops: List[CollectiveOp]) -> dict:
    """kind → sorted tuple of distinct replica-group sizes — the
    *scope* structure of a module's collectives.  The hierarchical
    exchange's signature is ``{"reduce-scatter": (dcn, ici), ...}``:
    two distinct scopes, one per mesh level, where the flat exchange
    shows a single world-sized scope.  ``None`` group sizes (HLO's
    "all devices" spellings) are kept so a scopeless op can't hide."""
    out: dict = {}
    for o in ops:
        out.setdefault(o.kind, set()).add(o.group_size)
    return {k: tuple(sorted(v, key=lambda s: (s is None, s)))
            for k, v in out.items()}
