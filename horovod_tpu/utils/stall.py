"""Stall / failure detection watchdog.

The reference's ``StallInspector`` (``horovod/common/stall_inspector.{h,cc}``)
watches the negotiation table for tensors some ranks submitted and others
did not, warning after 60 s and optionally shutting down after
``HOROVOD_STALL_SHUTDOWN_TIME_SECONDS`` (``stall_inspector.h:73-81``), and
``CheckForStalledTensors`` names the *ranks* that never submitted each
stalled tensor.

Under SPMD there is no negotiation table — a "stall" is a collective that
was dispatched but never completes (a peer process died, or host code
diverged so a peer never entered the collective).  This inspector tracks
in-flight eager operations: each dispatched op registers here and clears on
completion; a watcher thread warns when an op has been pending longer than
the threshold and names it.

Missing-rank attribution re-rooted: once an op has been pending for half
the warning threshold, each process best-effort publishes its pending-op
set to the coordination-service KV (a non-collective write — a stalled
world can still reach the KV server).  When the warning fires, the warning
rank lists the directory and names each peer as either co-stalled (it
published the same pending op), diverged (it published, but without this
op), or unreported (no publication — it never submitted the op, or died):
the answer the reference's ``CheckForStalledTensors`` gives from the
negotiation table.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, Optional

from horovod_tpu import faults, telemetry
from horovod_tpu.utils import logging as hvd_logging

_STATUS_DIR = "hvdstall/status"

# hung-worker precursors, scrapeable BEFORE the health plane fires
# (docs/metrics.md): pending-op count and oldest age climb while a
# collective wedges; the warning counter records that the inspector
# spoke; the abort counter that it pulled the shutdown lever
_TEL_PENDING = telemetry.gauge(
    "hvd_stall_pending_ops", "eager collectives dispatched, not complete")
_TEL_OLDEST = telemetry.gauge(
    "hvd_stall_oldest_age_seconds", "age of the oldest pending collective")
_TEL_WARNINGS = telemetry.counter(
    "hvd_stall_warnings_total", "stall warnings emitted")
_TEL_ABORTS = telemetry.counter(
    "hvd_stall_aborts_total",
    "stall-shutdown aborts (HOROVOD_STALL_SHUTDOWN_TIME_SECONDS)")


class ProgressWatchdog:
    """Tracks a monotonically-advancing progress counter and reports how
    long it has been stagnant — the primitive behind hung-but-alive
    detection (a rank whose heartbeats keep arriving while its step
    counter stopped moving is wedged, not dead).

    Pure bookkeeping, no thread: the owner decides when to call
    :meth:`stalled_for` and what stagnation threshold means trouble.
    ``clock`` is injectable for deterministic tests."""

    def __init__(self, clock=time.monotonic, name: Optional[str] = None):
        self._clock = clock
        self._value: Optional[int] = None
        self._since: Optional[float] = None
        # named watchdogs publish their stagnation as a labeled gauge —
        # the hung-worker precursor the health plane acts on later
        # (docs/metrics.md); unnamed ones stay pure bookkeeping
        self._tel_stall = None if name is None else telemetry.gauge(
            "hvd_progress_stall_seconds",
            "seconds since a watched progress counter last advanced"
        ).labels(watchdog=name)

    def update(self, value: int, now: Optional[float] = None) -> None:
        """Record the counter's current value; only an *advance*
        restarts the stagnation clock (a repeated or regressed value —
        a worker re-reporting after restore — does not look like
        progress)."""
        if now is None:
            now = self._clock()
        if self._value is None or value > self._value:
            self._value = value
            self._since = now
            if self._tel_stall is not None:
                self._tel_stall.set(0.0)

    @property
    def value(self) -> Optional[int]:
        return self._value

    def stalled_for(self, now: Optional[float] = None) -> float:
        """Seconds since the counter last advanced (0.0 before the
        first update — never-reported is the startup watchdog's job,
        not this one's)."""
        if self._since is None:
            return 0.0
        if now is None:
            now = self._clock()
        stalled = max(now - self._since, 0.0)
        if self._tel_stall is not None:
            self._tel_stall.set(stalled)
        return stalled


class StallInspector:
    def __init__(self, warning_time_s: float = 60.0,
                 shutdown_time_s: float = 0.0, poll_interval_s: float = 5.0):
        self._warning_time_s = warning_time_s
        self._shutdown_time_s = shutdown_time_s
        self._poll_interval_s = min(poll_interval_s, max(
            warning_time_s / 4.0, 0.05))
        self._pending: Dict[str, float] = {}
        self._warned: set = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._pub_seq = 0
        self._last_pub_key: Optional[str] = None
        self._published: Optional[frozenset] = None
        self._thread = threading.Thread(target=self._watch, daemon=True,
                                        name="hvd_tpu_stall_inspector")
        self._thread.start()

    def record_dispatch(self, name: str) -> None:
        with self._lock:
            self._pending[name] = time.monotonic()

    def record_complete(self, name: str) -> None:
        with self._lock:
            self._pending.pop(name, None)
            self._warned.discard(name)

    def pending_ops(self):
        with self._lock:
            return dict(self._pending)

    # -- cross-process attribution -----------------------------------------

    def _cluster(self):
        """(client, my process index, process count) when a multi-process
        coordination service is reachable, else None.  Reads
        ``jax._src.distributed.global_state`` directly — ``jax.process_
        count()`` would initialize a backend from the watchdog thread."""
        try:
            from jax._src import distributed as dist

            gs = dist.global_state
            if gs.client is None or not gs.num_processes \
                    or gs.num_processes == 1:
                return None
            return gs.client, int(gs.process_id), int(gs.num_processes)
        except Exception:
            return None

    def _publish(self, client, me: int, pending) -> None:
        """Best-effort non-collective status write; re-published only when
        the pending set changes.  Unique seq keys sidestep the KV store's
        no-overwrite rule; the previous key is deleted after the new one
        lands so readers always see at least one."""
        snapshot = frozenset(pending)
        if snapshot == self._published:
            return
        self._pub_seq += 1
        key = f"{_STATUS_DIR}/{me}/{self._pub_seq}"
        try:
            client.key_value_set_bytes(key, json.dumps(
                {"pending": sorted(pending)}).encode())
            if self._last_pub_key is not None:
                client.key_value_delete(self._last_pub_key)
            self._last_pub_key = key
            self._published = snapshot
        except Exception:  # pragma: no cover - KV unreachable
            pass

    def _attribute(self, client, me: int, nproc: int, stalled_names):
        """Name each peer's relation to the stalled ops from the published
        statuses (reference ``CheckForStalledTensors`` missing-rank
        report)."""
        newest: Dict[int, tuple] = {}
        try:
            entries = client.key_value_dir_get_bytes(_STATUS_DIR)
        except Exception:
            entries = []
        for k, v in entries:
            parts = str(k).split("/")
            try:
                pid, seq = int(parts[-2]), int(parts[-1])
            except (ValueError, IndexError):
                continue
            if pid != me and (pid not in newest or seq > newest[pid][0]):
                newest[pid] = (seq, v)
        unreported, diverged, costalled = [], [], []
        for p in range(nproc):
            if p == me:
                continue
            if p not in newest:
                unreported.append(p)
                continue
            try:
                peer_pending = set(json.loads(newest[p][1])["pending"])
            except Exception:
                peer_pending = set()
            missing = sorted(n for n in stalled_names
                             if n not in peer_pending)
            if not peer_pending:
                # published an empty set: nothing pending on its side —
                # it has not submitted the op (or cleared an earlier
                # stall); calling it "stalled on different ops" would
                # send the operator to debug a healthy rank
                unreported.append(p)
            elif missing:
                diverged.append((p, missing))
            else:
                costalled.append(p)
        parts = []
        if unreported:
            parts.append(
                "process(es) %s have not submitted the op (no pending "
                "work published — not reached it yet, or failed)"
                % ", ".join(map(str, unreported)))
        for p, missing in diverged:
            parts.append(
                "process %d is stalled on different op(s) and has not "
                "submitted %s" % (p, ", ".join(missing)))
        if costalled:
            parts.append("process(es) %s are waiting on the same op"
                         % ", ".join(map(str, costalled)))
        return "; ".join(parts)

    # -- watcher ------------------------------------------------------------

    def _watch(self) -> None:
        while not self._stop.wait(self._poll_interval_s):
            # chaos hook: a hang here silences stall warnings — the
            # degradation mode where the inspector itself is wedged
            faults.inject("stall.watch")
            now = time.monotonic()
            stalled, fatal, publish_due = [], [], []
            oldest = 0.0
            with self._lock:
                n_pending = len(self._pending)
                for name, t0 in self._pending.items():
                    age = now - t0
                    oldest = max(oldest, age)
                    if age > self._warning_time_s / 2.0:
                        publish_due.append(name)
                    if age > self._warning_time_s and name not in self._warned:
                        stalled.append((name, age))
                        self._warned.add(name)
                    if self._shutdown_time_s > 0 and age > self._shutdown_time_s:
                        fatal.append((name, age))
            _TEL_PENDING.set(n_pending)
            _TEL_OLDEST.set(oldest)
            if stalled:
                _TEL_WARNINGS.inc(len(stalled))
            # _published non-empty with nothing due means the stall
            # cleared: republish the (empty) set so peers stop blaming us
            cluster = self._cluster() \
                if (publish_due or stalled or self._published) else None
            if cluster is not None:
                self._publish(cluster[0], cluster[1], publish_due)
            if stalled:
                names = ", ".join(f"{n} ({a:.0f}s)" for n, a in stalled)
                who = ""
                if cluster is not None:
                    client, me, nproc = cluster
                    who = self._attribute(client, me, nproc,
                                          [n for n, _ in stalled])
                hvd_logging.warning(
                    "One or more collectives submitted but not completed for "
                    "over %.0fs: %s. A peer process may have failed or host "
                    "control flow may have diverged across processes.%s",
                    self._warning_time_s, names,
                    (" Attribution: " + who) if who else "")
            if fatal:
                hvd_logging.error(
                    "Collective(s) stalled beyond "
                    "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS; aborting process.")
                _TEL_ABORTS.inc()
                import os

                os._exit(1)

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2)
