"""Stall / failure detection watchdog.

The reference's ``StallInspector`` (``horovod/common/stall_inspector.{h,cc}``)
watches the negotiation table for tensors some ranks submitted and others
did not, warning after 60 s and optionally shutting down after
``HOROVOD_STALL_SHUTDOWN_TIME_SECONDS`` (``stall_inspector.h:73-81``).

Under SPMD there is no negotiation table — a "stall" is a collective that
was dispatched but never completes (a peer process died, or host code
diverged so a peer never entered the collective).  This inspector tracks
in-flight eager operations: each dispatched op registers here and clears on
completion; a watcher thread warns when an op has been pending longer than
the threshold and names it — the same observable behavior, re-rooted.
"""

from __future__ import annotations

import threading
import time
from typing import Dict

from horovod_tpu.utils import logging as hvd_logging


class StallInspector:
    def __init__(self, warning_time_s: float = 60.0,
                 shutdown_time_s: float = 0.0, poll_interval_s: float = 5.0):
        self._warning_time_s = warning_time_s
        self._shutdown_time_s = shutdown_time_s
        self._poll_interval_s = poll_interval_s
        self._pending: Dict[str, float] = {}
        self._warned: set = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._watch, daemon=True,
                                        name="hvd_tpu_stall_inspector")
        self._thread.start()

    def record_dispatch(self, name: str) -> None:
        with self._lock:
            self._pending[name] = time.monotonic()

    def record_complete(self, name: str) -> None:
        with self._lock:
            self._pending.pop(name, None)
            self._warned.discard(name)

    def pending_ops(self):
        with self._lock:
            return dict(self._pending)

    def _watch(self) -> None:
        while not self._stop.wait(self._poll_interval_s):
            now = time.monotonic()
            stalled, fatal = [], []
            with self._lock:
                for name, t0 in self._pending.items():
                    age = now - t0
                    if age > self._warning_time_s and name not in self._warned:
                        stalled.append((name, age))
                        self._warned.add(name)
                    if self._shutdown_time_s > 0 and age > self._shutdown_time_s:
                        fatal.append((name, age))
            if stalled:
                names = ", ".join(f"{n} ({a:.0f}s)" for n, a in stalled)
                hvd_logging.warning(
                    "One or more collectives submitted but not completed for "
                    "over %.0fs: %s. A peer process may have failed or host "
                    "control flow may have diverged across processes.",
                    self._warning_time_s, names)
            if fatal:
                hvd_logging.error(
                    "Collective(s) stalled beyond "
                    "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS; aborting process.")
                import os

                os._exit(1)

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2)
