"""Bytes-on-wire scaling model for data-parallel training.

The reference's headline artifact is a measured scaling-efficiency
table (``docs/benchmarks.rst:43`` — 90%/68% at 128 GPUs); this
environment has one physical chip, so multi-chip efficiency is
*modeled* from quantities this repo can measure or pin:

* per-chip step time — measured on the real chip (``BENCH_r0N.json``);
* per-step collective payload — pinned exactly by the compiled-HLO
  guards (``tests/test_hlo_guards.py``: one combined all-reduce whose
  byte count equals the gradient pytree + the scalar loss);
* link bandwidth — the public per-chip ICI/DCN figures.

The model (``docs/scaling.md`` walks the numbers) is the standard ring
cost: an all-reduce of ``B`` payload bytes over ``N`` chips moves
``2·(N-1)/N·B`` bytes through each chip's links; the exposed fraction
after compute/communication overlap sets the efficiency.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional, Union

# Public per-chip interconnect figures (Cloud TPU system docs): v5e has
# 1,600 Gbps of ICI per chip (4 links x 400 Gbps, 2D torus) and ~200
# Gbps of DCN per host (4 chips) on typical v5e pod deployments.
V5E_ICI_BYTES_PER_S = 1600e9 / 8          # 200 GB/s per chip
V5E_DCN_BYTES_PER_S_PER_HOST = 200e9 / 8  # 25 GB/s per host


def allreduce_wire_bytes(payload_bytes: float, n_chips: int) -> float:
    """Bytes through EACH chip's links for one ring all-reduce of
    ``payload_bytes``: reduce-scatter + all-gather phases each move
    ``(N-1)/N`` of the payload (``2·(N-1)/N·B`` total).  XLA's TPU
    all-reduce is bandwidth-optimal on torus meshes, so the ring bound
    is the right cost model (scaling-book recipe)."""
    if n_chips <= 1:
        return 0.0
    return 2.0 * (n_chips - 1) / n_chips * payload_bytes


def step_payload_bytes(params) -> int:
    """Per-step all-reduce payload for a parameter pytree: every
    gradient leaf at its own width, plus the 4-byte scalar loss — the
    exact sum the HLO fusion guard asserts against the compiled step."""
    import jax

    return sum(x.size * x.dtype.itemsize
               for x in jax.tree_util.tree_leaves(params)) + 4


def overlap_fraction_from_artifact(
        artifact: Union[str, os.PathLike, dict],
        prefix: str = "") -> Optional[float]:
    """The MEASURED ``overlap_fraction`` out of a BENCH artifact — a
    ``BENCH_r0N.json`` path (one JSON object on its first line, the
    ``bench.py --json-out`` format) or the already-parsed dict.  The
    field is what ``utils/overlap_probe.py`` measured for that run's
    gradient exchange; ``prefix`` selects a per-model variant (e.g.
    ``"resnet_"``).  Returns None when the artifact has no probe field
    (``--no-overlap-probe`` runs) — callers then fall back to the
    pinned default, never to a silently-invented constant."""
    if not isinstance(artifact, dict):
        with open(artifact) as f:
            artifact = json.loads(f.readline())
    val = artifact.get(prefix + "overlap_fraction")
    return None if val is None else float(val)


def resolve_overlap_fraction(
        overlap_fraction: Optional[float] = None,
        artifact: Union[str, os.PathLike, dict, None] = None,
        prefix: str = "") -> float:
    """The model's one load-bearing assumption, resolved: an explicit
    value wins; else the artifact's measured probe value; else 0.0 —
    the fully-exposed worst case, the only defensible *assumption*
    (VERDICT round 5: the overlap constant must be measured, not
    assumed)."""
    if overlap_fraction is not None:
        return float(overlap_fraction)
    if artifact is not None:
        measured = overlap_fraction_from_artifact(artifact, prefix)
        if measured is not None:
            return measured
    return 0.0


@dataclasses.dataclass
class ScalingPoint:
    n_chips: int
    comm_time_s: float        # full (unoverlapped) wire time
    exposed_time_s: float     # comm left over after overlap
    efficiency: float         # step_time / (step_time + exposed)


def scaling_efficiency(step_time_s: float,
                       payload_bytes: float,
                       n_chips: int,
                       link_bytes_per_s: float = V5E_ICI_BYTES_PER_S,
                       overlap_fraction: Optional[float] = None,
                       artifact=None,
                       artifact_prefix: str = "") -> ScalingPoint:
    """Modeled weak-scaling efficiency at ``n_chips``.

    ``overlap_fraction`` is how much of the collective hides under
    compute.  Pass a value to pin it, or pass ``artifact=`` (a BENCH
    JSON path/dict) to use the run's MEASURED ``overlap_fraction``
    from ``utils/overlap_probe.py`` — the model no longer invites an
    assumed constant where a measurement exists.  With neither, the
    fully-exposed worst case (0.0) applies: collective serial after
    the backward pass.  Efficiency is per-step throughput relative to
    the single-chip rate: ``t / (t + exposed)``.
    """
    overlap = resolve_overlap_fraction(overlap_fraction, artifact,
                                       artifact_prefix)
    comm = allreduce_wire_bytes(payload_bytes, n_chips) / link_bytes_per_s
    exposed = comm * (1.0 - overlap)
    return ScalingPoint(
        n_chips=n_chips, comm_time_s=comm, exposed_time_s=exposed,
        efficiency=step_time_s / (step_time_s + exposed))


def efficiency_curve(step_time_s: float, payload_bytes: float,
                     chip_counts=(8, 16, 32, 64),
                     link_bytes_per_s: float = V5E_ICI_BYTES_PER_S,
                     overlap_fraction: Optional[float] = None,
                     artifact=None,
                     artifact_prefix: str = ""):
    """One :class:`ScalingPoint` per chip count (docs/scaling.md
    table); ``artifact=`` sources the measured overlap exactly as in
    :func:`scaling_efficiency`."""
    return [scaling_efficiency(step_time_s, payload_bytes, n,
                               link_bytes_per_s, overlap_fraction,
                               artifact, artifact_prefix)
            for n in chip_counts]
