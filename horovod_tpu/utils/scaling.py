"""Bytes-on-wire scaling model for data-parallel training.

The reference's headline artifact is a measured scaling-efficiency
table (``docs/benchmarks.rst:43`` — 90%/68% at 128 GPUs); this
environment has one physical chip, so multi-chip efficiency is
*modeled* from quantities this repo can measure or pin:

* per-chip step time — measured on the real chip (``BENCH_r0N.json``);
* per-step collective payload — pinned exactly by the compiled-HLO
  guards (``tests/test_hlo_guards.py``: one combined all-reduce whose
  byte count equals the gradient pytree + the scalar loss);
* link bandwidth — the public per-chip ICI/DCN figures.

The model (``docs/scaling.md`` walks the numbers) is the standard ring
cost: an all-reduce of ``B`` payload bytes over ``N`` chips moves
``2·(N-1)/N·B`` bytes through each chip's links; the exposed fraction
after compute/communication overlap sets the efficiency.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional, Union

# Public per-chip interconnect figures (Cloud TPU system docs): v5e has
# 1,600 Gbps of ICI per chip (4 links x 400 Gbps, 2D torus) and ~200
# Gbps of DCN per host (4 chips) on typical v5e pod deployments.
V5E_ICI_BYTES_PER_S = 1600e9 / 8          # 200 GB/s per chip
V5E_DCN_BYTES_PER_S_PER_HOST = 200e9 / 8  # 25 GB/s per host


def allreduce_wire_bytes(payload_bytes: float, n_chips: int) -> float:
    """Bytes through EACH chip's links for one ring all-reduce of
    ``payload_bytes``: reduce-scatter + all-gather phases each move
    ``(N-1)/N`` of the payload (``2·(N-1)/N·B`` total).  XLA's TPU
    all-reduce is bandwidth-optimal on torus meshes, so the ring bound
    is the right cost model (scaling-book recipe).

    This is the single-fabric (flat, full-width) cost; the two-level
    exchange prices per level through
    :func:`exchange_wire_bytes` — the same cost model
    (``analysis/cost_model.py``) both this module and the perf gate
    consume."""
    from horovod_tpu.analysis import cost_model as CM

    return CM.exchange_wire_bytes(payload_bytes, n_dcn=1,
                                  n_ici=n_chips).ici


def exchange_wire_bytes(payload_bytes: float, n_chips: int,
                        hierarchy: str = "flat",
                        n_ici: Optional[int] = None,
                        wire_bits_dcn: int = 8):
    """Per-level per-chip wire bytes of one gradient exchange over
    ``n_chips`` split as ``(n_chips/n_ici) × n_ici`` (dcn × ici) —
    delegated to :func:`horovod_tpu.analysis.cost_model.\
exchange_wire_bytes`.  With ``hierarchy="two_level"`` the DCN hop
    carries only the ``1/n_ici`` partial-sum shard at ``wire_bits_dcn``
    (the int8 DCN codec), which is what the old flat-fp32-only model
    overstated for the MULTICHIP v5e-64 projections.  Returns the cost
    model's ``WireBytes`` (``.ici``/``.dcn``/``.total``)."""
    from horovod_tpu.analysis import cost_model as CM

    if n_ici in (None, 0):
        if hierarchy == "two_level":
            raise ValueError(
                "hierarchy='two_level' needs n_ici (chips per slice) "
                "to split the mesh; pass e.g. n_ici=4 for v5e hosts")
        n_dcn, n_inner = 1, n_chips
    else:
        if n_chips % n_ici:
            raise ValueError(
                f"n_chips={n_chips} is not divisible by n_ici={n_ici}")
        n_dcn, n_inner = n_chips // n_ici, n_ici
    return CM.exchange_wire_bytes(payload_bytes, n_dcn=n_dcn,
                                  n_ici=n_inner, hierarchy=hierarchy,
                                  wire_bits_dcn=wire_bits_dcn)


def step_payload_bytes(params) -> int:
    """Per-step all-reduce payload for a parameter pytree: every
    gradient leaf at its own width, plus the 4-byte scalar loss — the
    exact sum the HLO fusion guard asserts against the compiled step."""
    import jax

    return sum(x.size * x.dtype.itemsize
               for x in jax.tree_util.tree_leaves(params)) + 4


def overlap_fraction_from_artifact(
        artifact: Union[str, os.PathLike, dict],
        prefix: str = "") -> Optional[float]:
    """The MEASURED ``overlap_fraction`` out of a BENCH artifact — a
    ``BENCH_r0N.json`` path (one JSON object on its first line, the
    ``bench.py --json-out`` format) or the already-parsed dict.  The
    field is what ``utils/overlap_probe.py`` measured for that run's
    gradient exchange; ``prefix`` selects a per-model variant (e.g.
    ``"resnet_"``).  Returns None when the artifact has no probe field
    (``--no-overlap-probe`` runs) — callers then fall back to the
    pinned default, never to a silently-invented constant."""
    if not isinstance(artifact, dict):
        with open(artifact) as f:
            artifact = json.loads(f.readline())
    val = artifact.get(prefix + "overlap_fraction")
    return None if val is None else float(val)


def resolve_overlap_fraction(
        overlap_fraction: Optional[float] = None,
        artifact: Union[str, os.PathLike, dict, None] = None,
        prefix: str = "") -> float:
    """The model's one load-bearing assumption, resolved: an explicit
    value wins; else the artifact's measured probe value; else 0.0 —
    the fully-exposed worst case, the only defensible *assumption*
    (VERDICT round 5: the overlap constant must be measured, not
    assumed)."""
    if overlap_fraction is not None:
        return float(overlap_fraction)
    if artifact is not None:
        measured = overlap_fraction_from_artifact(artifact, prefix)
        if measured is not None:
            return measured
    return 0.0


def hierarchy_from_artifact(
        artifact: Union[str, os.PathLike, dict],
        prefix: str = "") -> Optional[str]:
    """The exchange topology a BENCH artifact ran
    (``{prefix}exchange_hierarchy``, emitted by the overlap probe), or
    None when the run had no sharded exchange."""
    if not isinstance(artifact, dict):
        with open(artifact) as f:
            artifact = json.loads(f.readline())
    val = artifact.get(prefix + "exchange_hierarchy")
    return None if val is None else str(val)


def resolve_exchange_hierarchy(hierarchy: Optional[str] = None,
                               artifact=None, prefix: str = "") -> str:
    """Same precedence discipline as
    :func:`resolve_overlap_fraction`: an explicit mode wins, else the
    artifact's measured ``exchange_hierarchy``, else ``"flat"`` — the
    conservative (most wire) assumption, never a silently-invented
    topology."""
    if hierarchy is not None:
        if hierarchy not in ("flat", "two_level"):
            raise ValueError(f"hierarchy must be flat|two_level, got "
                             f"{hierarchy!r}")
        return hierarchy
    if artifact is not None:
        measured = hierarchy_from_artifact(artifact, prefix)
        if measured is not None:
            return measured
    return "flat"


@dataclasses.dataclass
class ScalingPoint:
    n_chips: int
    comm_time_s: float        # full (unoverlapped) wire time
    exposed_time_s: float     # comm left over after overlap
    efficiency: float         # step_time / (step_time + exposed)
    hierarchy: str = "flat"   # exchange topology the wire was priced at
    wire_bytes_ici: float = 0.0   # per-chip bytes on the ICI fabric
    wire_bytes_dcn: float = 0.0   # per-chip bytes crossing DCN


def scaling_efficiency(step_time_s: float,
                       payload_bytes: float,
                       n_chips: int,
                       link_bytes_per_s: float = V5E_ICI_BYTES_PER_S,
                       overlap_fraction: Optional[float] = None,
                       artifact=None,
                       artifact_prefix: str = "",
                       hierarchy: Optional[str] = None,
                       n_ici: Optional[int] = None,
                       dcn_bytes_per_s: float =
                       V5E_DCN_BYTES_PER_S_PER_HOST,
                       wire_bits_dcn: int = 8) -> ScalingPoint:
    """Modeled weak-scaling efficiency at ``n_chips``.

    ``overlap_fraction`` is how much of the collective hides under
    compute.  Pass a value to pin it, or pass ``artifact=`` (a BENCH
    JSON path/dict) to use the run's MEASURED ``overlap_fraction``
    from ``utils/overlap_probe.py`` — the model no longer invites an
    assumed constant where a measurement exists.  With neither, the
    fully-exposed worst case (0.0) applies: collective serial after
    the backward pass.  Efficiency is per-step throughput relative to
    the single-chip rate: ``t / (t + exposed)``.

    The wire is priced by the cost model
    (``analysis/cost_model.py``), hierarchy-aware: with ``n_ici``
    (chips per slice) the mesh factors into ``(n_chips/n_ici) ×
    n_ici`` and each level pays its own fabric — ICI at
    ``link_bytes_per_s``, DCN at ``dcn_bytes_per_s`` — with
    ``hierarchy="two_level"`` crossing DCN at ``wire_bits_dcn`` on the
    ``1/n_ici`` shard (the int8 DCN codec).  ``hierarchy`` resolves
    like overlap: explicit > the artifact's measured
    ``exchange_hierarchy`` > ``"flat"``.  Without ``n_ici`` the mesh
    is a single ICI domain — exactly the old flat model.
    """
    overlap = resolve_overlap_fraction(overlap_fraction, artifact,
                                       artifact_prefix)
    mode = resolve_exchange_hierarchy(hierarchy, artifact,
                                      artifact_prefix)
    wire = exchange_wire_bytes(payload_bytes, n_chips, hierarchy=mode,
                               n_ici=n_ici,
                               wire_bits_dcn=wire_bits_dcn)
    comm = wire.ici / link_bytes_per_s + wire.dcn / dcn_bytes_per_s
    exposed = comm * (1.0 - overlap)
    return ScalingPoint(
        n_chips=n_chips, comm_time_s=comm, exposed_time_s=exposed,
        efficiency=step_time_s / (step_time_s + exposed),
        hierarchy=mode, wire_bytes_ici=wire.ici,
        wire_bytes_dcn=wire.dcn)


def efficiency_curve(step_time_s: float, payload_bytes: float,
                     chip_counts=(8, 16, 32, 64),
                     link_bytes_per_s: float = V5E_ICI_BYTES_PER_S,
                     overlap_fraction: Optional[float] = None,
                     artifact=None,
                     artifact_prefix: str = "",
                     hierarchy: Optional[str] = None,
                     n_ici: Optional[int] = None,
                     dcn_bytes_per_s: float =
                     V5E_DCN_BYTES_PER_S_PER_HOST,
                     wire_bits_dcn: int = 8):
    """One :class:`ScalingPoint` per chip count (docs/scaling.md
    table); ``artifact=`` sources the measured overlap AND exchange
    hierarchy exactly as in :func:`scaling_efficiency`, and ``n_ici``
    makes every point a two-fabric ``(n/n_ici) × n_ici`` mesh."""
    return [scaling_efficiency(step_time_s, payload_bytes, n,
                               link_bytes_per_s, overlap_fraction,
                               artifact, artifact_prefix,
                               hierarchy=hierarchy, n_ici=n_ici,
                               dcn_bytes_per_s=dcn_bytes_per_s,
                               wire_bits_dcn=wire_bits_dcn)
            for n in chip_counts]
