"""Bytes-on-wire scaling model for data-parallel training.

The reference's headline artifact is a measured scaling-efficiency
table (``docs/benchmarks.rst:43`` — 90%/68% at 128 GPUs); this
environment has one physical chip, so multi-chip efficiency is
*modeled* from quantities this repo can measure or pin:

* per-chip step time — measured on the real chip (``BENCH_r0N.json``);
* per-step collective payload — pinned exactly by the compiled-HLO
  guards (``tests/test_hlo_guards.py``: one combined all-reduce whose
  byte count equals the gradient pytree + the scalar loss);
* link bandwidth — the public per-chip ICI/DCN figures.

The model (``docs/scaling.md`` walks the numbers) is the standard ring
cost: an all-reduce of ``B`` payload bytes over ``N`` chips moves
``2·(N-1)/N·B`` bytes through each chip's links; the exposed fraction
after compute/communication overlap sets the efficiency.
"""

from __future__ import annotations

import dataclasses

# Public per-chip interconnect figures (Cloud TPU system docs): v5e has
# 1,600 Gbps of ICI per chip (4 links x 400 Gbps, 2D torus) and ~200
# Gbps of DCN per host (4 chips) on typical v5e pod deployments.
V5E_ICI_BYTES_PER_S = 1600e9 / 8          # 200 GB/s per chip
V5E_DCN_BYTES_PER_S_PER_HOST = 200e9 / 8  # 25 GB/s per host


def allreduce_wire_bytes(payload_bytes: float, n_chips: int) -> float:
    """Bytes through EACH chip's links for one ring all-reduce of
    ``payload_bytes``: reduce-scatter + all-gather phases each move
    ``(N-1)/N`` of the payload (``2·(N-1)/N·B`` total).  XLA's TPU
    all-reduce is bandwidth-optimal on torus meshes, so the ring bound
    is the right cost model (scaling-book recipe)."""
    if n_chips <= 1:
        return 0.0
    return 2.0 * (n_chips - 1) / n_chips * payload_bytes


def step_payload_bytes(params) -> int:
    """Per-step all-reduce payload for a parameter pytree: every
    gradient leaf at its own width, plus the 4-byte scalar loss — the
    exact sum the HLO fusion guard asserts against the compiled step."""
    import jax

    return sum(x.size * x.dtype.itemsize
               for x in jax.tree_util.tree_leaves(params)) + 4


@dataclasses.dataclass
class ScalingPoint:
    n_chips: int
    comm_time_s: float        # full (unoverlapped) wire time
    exposed_time_s: float     # comm left over after overlap
    efficiency: float         # step_time / (step_time + exposed)


def scaling_efficiency(step_time_s: float,
                       payload_bytes: float,
                       n_chips: int,
                       link_bytes_per_s: float = V5E_ICI_BYTES_PER_S,
                       overlap_fraction: float = 0.0) -> ScalingPoint:
    """Modeled weak-scaling efficiency at ``n_chips``.

    ``overlap_fraction`` is how much of the collective hides under
    compute: 0 is the worst case (fully exposed, serial after the
    backward pass); the XLA latency-hiding scheduler overlaps each
    layer's gradient all-reduce with the remaining backward compute,
    so measured TPU overlap is typically well above 0.5 for
    transformer-shaped steps (the +3% the scheduler measured on the
    single-chip bench is this machinery with nothing to overlap).
    Efficiency is per-step throughput relative to the single-chip rate:
    ``t / (t + exposed)``.
    """
    comm = allreduce_wire_bytes(payload_bytes, n_chips) / link_bytes_per_s
    exposed = comm * (1.0 - overlap_fraction)
    return ScalingPoint(
        n_chips=n_chips, comm_time_s=comm, exposed_time_s=exposed,
        efficiency=step_time_s / (step_time_s + exposed))


def efficiency_curve(step_time_s: float, payload_bytes: float,
                     chip_counts=(8, 16, 32, 64),
                     link_bytes_per_s: float = V5E_ICI_BYTES_PER_S,
                     overlap_fraction: float = 0.0):
    """One :class:`ScalingPoint` per chip count (docs/scaling.md
    table)."""
    return [scaling_efficiency(step_time_s, payload_bytes, n,
                               link_bytes_per_s, overlap_fraction)
            for n in chip_counts]
