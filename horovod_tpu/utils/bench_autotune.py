"""Offline throughput autotuning of jit-path knobs.

The runtime :class:`~horovod_tpu.utils.autotune.ParameterManager` tunes
the *eager* plane's knobs online by bytes/sec — the reference
``parameter_manager.{h,cc}`` lifecycle.  The jit data plane's
throughput knobs (``steps_per_call``, the flash-attention block size,
compile options) cannot move mid-jit: every candidate needs a fresh
compile, so they are tuned *offline* by this driver against the real
measured objective (images/sec, tokens/sec) — the knobs that actually
move BENCH numbers, per the reference's point that autotuning exists
for the perf-critical parameters (``parameter_manager.h:58-78``).

Strategy: coordinate descent over small categorical axes with
memoization.  The per-axis responses are unimodal in practice (the
round-4 hand scans in PERF_NOTES.md: flash block 128→59%, 256→66%,
512→69% peak, 1024→68.7%; steps_per_call saturating), so cycling the
axes to a fixed point finds the grid optimum in far fewer compiles
than the full cross product.  Every sample lands in a CSV log — the
same artifact shape as the online manager's autotune log.

Entry point: ``python bench.py --model transformer --autotune``.
"""

from __future__ import annotations

import csv
import time
from typing import Callable, Dict, List, Optional, Tuple

from horovod_tpu.utils import logging as hvd_logging


class ThroughputAutotuner:
    """Maximize ``measure(point)`` over a categorical grid.

    ``axes`` maps knob name → candidate values (order defines the scan
    order).  ``measure`` builds + runs the workload at a point and
    returns units/sec; each unique point is measured once (memoized).
    ``seed`` picks the starting point (default: middle of each axis —
    a deliberately un-tuned cold start).

    ``predict`` (optional) is a static scorer — higher is better, the
    cost-model contract of
    :func:`horovod_tpu.analysis.cost_model.score_exchange_schedule` —
    used to PRUNE each axis scan to the ``prune_to`` most promising
    candidates (the current value always stays) before paying a
    compile+measure per point.  A predictor that returns ``None`` for
    any candidate, scores every candidate identically, or raises,
    leaves that axis fully measured — the measurement, never the
    model, picks the winner.

    ``feasible`` (optional) is a hard predicate — the HBM-budget
    contract of :func:`horovod_tpu.analysis.cost_model.plan_fits`: a
    point it rejects is never compiled or measured (score ``-inf``),
    so the tuner returns the fastest *feasible* point.  Unlike
    ``predict`` it is a constraint, not a ranking — a raising
    predicate fails the run (a budget that cannot be evaluated must
    not silently become "everything fits").  When every point in the
    grid is rejected, :meth:`run` raises ``RuntimeError``.
    """

    def __init__(self, measure: Callable[[Dict], float],
                 axes: Dict[str, List],
                 seed: Optional[Dict] = None,
                 log_path: Optional[str] = None,
                 max_rounds: int = 3,
                 predict: Optional[Callable[[Dict], Optional[float]]]
                 = None,
                 prune_to: int = 2,
                 feasible: Optional[Callable[[Dict], bool]] = None):
        self._measure = measure
        self._axes = {k: list(v) for k, v in axes.items()}
        self._seed = dict(seed) if seed else \
            {k: v[len(v) // 2] for k, v in self._axes.items()}
        self._log_path = log_path
        self._max_rounds = max_rounds
        self._predict = predict
        self._prune_to = max(1, int(prune_to))
        self._feasible = feasible
        self._cache: Dict[Tuple, float] = {}
        self._rows: List[dict] = []

    def _candidates(self, current: Dict, knob: str,
                    values: List) -> List:
        """The axis candidates to actually measure: cost-model-pruned
        to the top ``prune_to`` (+ the current value) when the
        predictor can rank them, the full axis otherwise."""
        if self._predict is None or len(values) <= self._prune_to:
            return values
        try:
            preds = [self._predict(dict(current, **{knob: v}))
                     for v in values]
        except Exception:   # noqa: BLE001 — broken predictor = no prune
            return values
        if any(p is None for p in preds) or len(set(preds)) <= 1:
            return values
        ranked = [v for _, v in sorted(zip(preds, range(len(values))),
                                       key=lambda t: -t[0])]
        keep = [values[i] for i in ranked[: self._prune_to]]
        if current[knob] not in keep:
            keep.append(current[knob])
        hvd_logging.info(
            "autotune: cost model pruned %s axis %s -> %s", knob,
            values, keep)
        return keep

    def _key(self, point: Dict) -> Tuple:
        return tuple(point[k] for k in self._axes)

    def _score(self, point: Dict) -> float:
        key = self._key(point)
        if key in self._cache:
            return self._cache[key]
        if self._feasible is not None and not self._feasible(dict(point)):
            self._cache[key] = float("-inf")
            self._rows.append(dict(point, units_per_sec="",
                                   measure_seconds=0.0,
                                   infeasible="*"))
            hvd_logging.info("autotune: %s -> infeasible (skipped)",
                             point)
            return float("-inf")
        t0 = time.monotonic()
        rate = float(self._measure(dict(point)))
        self._cache[key] = rate
        self._rows.append(dict(point, units_per_sec=rate,
                               measure_seconds=round(
                                   time.monotonic() - t0, 1),
                               infeasible=""))
        hvd_logging.info("autotune: %s -> %.1f/sec", point, rate)
        return rate

    def run(self) -> Tuple[Dict, float]:
        """Coordinate-descend to a fixed point; returns
        ``(best_point, best_rate)`` and writes the log."""
        current = dict(self._seed)
        for _round in range(self._max_rounds):
            moved = False
            for knob, values in self._axes.items():
                scored = [(self._score(dict(current, **{knob: v})), v)
                          for v in self._candidates(current, knob,
                                                    values)]
                best_rate, best_v = max(scored)
                if best_v != current[knob]:
                    current[knob] = best_v
                    moved = True
            if not moved:
                break
        best = max(self._cache.items(), key=lambda kv: kv[1])
        if best[1] == float("-inf"):
            raise RuntimeError(
                "autotune: no feasible point in the grid — every "
                "candidate was rejected by the feasibility predicate")
        point = dict(zip(self._axes, best[0]))
        self._write_log(point, best[1])
        return point, best[1]

    def _write_log(self, best_point: Dict, best_rate: float) -> None:
        if not self._log_path or not self._rows:
            return
        rows = [dict(r, best="") for r in self._rows]
        for r in rows:
            if all(r[k] == best_point[k] for k in self._axes):
                r["best"] = "*"
        with open(self._log_path, "w", newline="") as f:
            w = csv.DictWriter(f, fieldnames=list(rows[0]))
            w.writeheader()
            w.writerows(rows)
        hvd_logging.info("autotune: winner %s at %.1f/sec; log at %s",
                         best_point, best_rate, self._log_path)
