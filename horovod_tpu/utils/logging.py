"""Leveled, per-rank-prefixed logging.

TPU-native analogue of the reference's C++ logger
(``horovod/common/logging.{h,cc}``): TRACE..FATAL levels selected by the
``HOROVOD_LOG_LEVEL`` env var, optional timestamp hiding via
``HOROVOD_LOG_HIDE_TIME``, and a ``[rank]`` prefix on every line so
interleaved multi-process output stays attributable.
"""

from __future__ import annotations

import logging
import os
import sys
import time

TRACE = 5
DEBUG = logging.DEBUG
INFO = logging.INFO
WARNING = logging.WARNING
ERROR = logging.ERROR
FATAL = logging.CRITICAL

_LEVELS = {
    "trace": TRACE,
    "debug": DEBUG,
    "info": INFO,
    "warning": WARNING,
    "error": ERROR,
    "fatal": FATAL,
}

logging.addLevelName(TRACE, "TRACE")

_logger: logging.Logger | None = None


class _RankFilter(logging.Filter):
    """Injects the current process rank into every record."""

    def filter(self, record: logging.LogRecord) -> bool:
        try:
            from horovod_tpu.runtime import state

            record.rank = state.global_state().rank if state.is_initialized() else -1
        except Exception:
            record.rank = -1
        return True


class _Formatter(logging.Formatter):
    def __init__(self, hide_time: bool):
        self._hide_time = hide_time
        super().__init__()

    def format(self, record: logging.LogRecord) -> str:
        rank = getattr(record, "rank", -1)
        prefix = f"[{record.levelname}"
        if not self._hide_time:
            ts = time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(record.created))
            prefix += f" {ts}.{int(record.msecs):03d}"
        if rank >= 0:
            prefix += f" rank {rank}"
        # run-context correlation (docs/metrics.md): once a run context
        # is explicitly set (metrics-enabled init, bench), log lines
        # carry the same (generation, step) the trace and the metric
        # snapshots stamp — greppable from either side
        try:
            from horovod_tpu.telemetry.context import run_context

            prefix += run_context().log_suffix()
        except Exception:
            pass
        return f"{prefix}] {record.getMessage()}"


def get_logger() -> logging.Logger:
    """Return the process-wide horovod_tpu logger, configuring it on first use."""
    global _logger
    if _logger is not None:
        return _logger
    logger = logging.getLogger("horovod_tpu")
    level_name = os.environ.get("HOROVOD_LOG_LEVEL", "warning").lower()
    logger.setLevel(_LEVELS.get(level_name, WARNING))
    handler = logging.StreamHandler(sys.stderr)
    hide_time = os.environ.get("HOROVOD_LOG_HIDE_TIME", "0") in ("1", "true")
    handler.setFormatter(_Formatter(hide_time))
    handler.addFilter(_RankFilter())
    logger.addHandler(handler)
    logger.propagate = False
    _logger = logger
    return logger


def trace(msg: str, *args) -> None:
    get_logger().log(TRACE, msg, *args)


def debug(msg: str, *args) -> None:
    get_logger().debug(msg, *args)


def info(msg: str, *args) -> None:
    get_logger().info(msg, *args)


def warning(msg: str, *args) -> None:
    get_logger().warning(msg, *args)


def error(msg: str, *args) -> None:
    get_logger().error(msg, *args)
