"""Measured H2D/compute overlap for the input feed.

The input-pipeline claim is that batch ``k+1``'s host→device transfer
hides under batch ``k``'s compute.  Host→device copies never appear in
HLO, so unlike the gradient exchange (``utils/overlap_probe.py``, whose
collectives are pinned in the compiled program) the input claim must be
verified by *timing the transfer against an in-flight step* — the
timeline view, reduced to three numbers:

* ``put_s`` — placing one host batch on the device(s), fenced;
* ``step_s`` — one train-step call on an already-resident batch,
  fenced on a host fetch of its scalar (the bench discipline:
  ``block_until_ready`` can lie through remote-device tunnels);
* ``both_s`` — dispatch the step, then immediately issue the *next*
  batch's placement while the step is in flight, fence both.

If the runtime serializes them, ``both ≈ step + put``; if the transfer
fully hides, ``both ≈ max(step, put)``.  The achieved fraction is::

    h2d_overlap = (step_s + put_s - both_s) / min(step_s, put_s)

clamped to [0, 1] — the same estimator the exchange probe uses, so the
two overlap numbers in a BENCH artifact are directly comparable.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable

import jax
import numpy as np


@dataclasses.dataclass
class H2dReport:
    put_s: float
    step_s: float
    both_s: float
    overlap_fraction: float

    def as_bench_fields(self, prefix: str = "") -> dict:
        return {
            f"{prefix}h2d_overlap_fraction": round(self.overlap_fraction,
                                                   4),
            f"{prefix}h2d_put_s": round(self.put_s, 6),
            f"{prefix}h2d_step_s": round(self.step_s, 6),
        }


def fence_batch(batch) -> None:
    """Wait for a placed batch's transfer: host-fetch one element of
    one leaf (completes only after the copy lands on device)."""
    leaf = jax.tree_util.tree_leaves(batch)[0]
    np.asarray(jax.device_get(leaf.ravel()[:1]))


def measure_h2d_overlap(run_step: Callable, make_batch: Callable,
                        place: Callable, iters: int = 3,
                        warmup: int = 1) -> H2dReport:
    """Time the three phases and return the achieved overlap.

    ``make_batch() -> host batch`` (fresh each call — the probe feeds
    the step real, distinct batches so donation-enabled steps stay
    legal); ``place(host) -> device batch``; ``run_step(device_batch)
    -> fetchable scalar`` (own the train state internally — the probe
    treats the step as a black box)."""
    def t_put():
        b = make_batch()
        t0 = time.perf_counter()
        fence_batch(place(b))
        return time.perf_counter() - t0

    def t_step():
        b = place(make_batch())
        fence_batch(b)
        t0 = time.perf_counter()
        float(np.asarray(jax.device_get(run_step(b))))
        return time.perf_counter() - t0

    def t_both():
        b = place(make_batch())
        fence_batch(b)
        nxt = make_batch()
        t0 = time.perf_counter()
        out = run_step(b)            # async dispatch
        placed = place(nxt)          # H2D issued while the step flies
        fence_batch(placed)
        float(np.asarray(jax.device_get(out)))
        return time.perf_counter() - t0

    def median(fn):
        for _ in range(warmup):
            fn()
        return float(np.median([fn() for _ in range(iters)]))

    put_s, step_s, both_s = median(t_put), median(t_step), median(t_both)
    denom = min(put_s, step_s)
    frac = (put_s + step_s - both_s) / denom if denom > 0 else 0.0
    return H2dReport(put_s=put_s, step_s=step_s, both_s=both_s,
                     overlap_fraction=float(np.clip(frac, 0.0, 1.0)))
