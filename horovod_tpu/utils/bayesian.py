"""Gaussian-process Bayesian optimization for the autotuner.

Reference: ``horovod/common/optim/gaussian_process.{h,cc}`` (GP
regression with an RBF kernel, expected-improvement acquisition,
L-BFGS maximization) and ``optim/bayesian_optimization.{h,cc}`` driving
it over the tunable-parameter space.  Same design in numpy/scipy: the
sample counts are tiny (tens), so exact GP posteriors are cheap.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np


class GaussianProcess:
    """Exact GP regression with an RBF kernel (reference
    ``gaussian_process.cc``: squared-exponential with length-scale ``l``
    and signal variance ``sigma_f``; observation noise ``sigma_n``)."""

    def __init__(self, length_scale: float = 1.0, sigma_f: float = 1.0,
                 sigma_n: float = 1e-4):
        self.length_scale = length_scale
        self.sigma_f = sigma_f
        self.sigma_n = sigma_n
        self._x: Optional[np.ndarray] = None
        self._alpha: Optional[np.ndarray] = None
        self._l_chol: Optional[np.ndarray] = None

    def _kernel(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        return self.sigma_f ** 2 * np.exp(-0.5 * d2 / self.length_scale ** 2)

    def fit(self, x: np.ndarray, y: np.ndarray) -> None:
        from scipy.linalg import cho_factor, cho_solve

        self._x = np.atleast_2d(np.asarray(x, np.float64))
        y = np.asarray(y, np.float64).reshape(-1)
        k = self._kernel(self._x, self._x)
        k[np.diag_indices_from(k)] += self.sigma_n ** 2
        self._l_chol = cho_factor(k, lower=True)
        self._alpha = cho_solve(self._l_chol, y)

    def predict(self, x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Posterior mean and stddev at query points."""
        from scipy.linalg import cho_solve

        x = np.atleast_2d(np.asarray(x, np.float64))
        ks = self._kernel(x, self._x)
        mean = ks @ self._alpha
        v = cho_solve(self._l_chol, ks.T)
        var = self.sigma_f ** 2 - np.sum(ks * v.T, axis=1)
        return mean, np.sqrt(np.maximum(var, 1e-12))


def expected_improvement(mean: np.ndarray, std: np.ndarray,
                         best: float, xi: float = 0.01) -> np.ndarray:
    """EI acquisition (reference ``bayesian_optimization.cc``; maximizing)."""
    from scipy.stats import norm

    imp = mean - best - xi
    z = np.where(std > 0, imp / std, 0.0)
    ei = imp * norm.cdf(z) + std * norm.pdf(z)
    return np.where(std > 0, ei, 0.0)


class BayesianOptimizer:
    """Suggest-observe loop over a box-bounded space (normalized to the
    unit cube internally; observations standardized)."""

    def __init__(self, bounds: Sequence[Tuple[float, float]],
                 seed: int = 0, num_candidates: int = 512,
                 noise: float = 1e-4):
        self._bounds = np.asarray(bounds, np.float64)
        self._rng = np.random.RandomState(seed)
        self._num_candidates = num_candidates
        self._noise = float(noise)
        self._xs: List[np.ndarray] = []
        self._ys: List[float] = []

    def observe(self, x: Sequence[float], y: float) -> None:
        lo, hi = self._bounds[:, 0], self._bounds[:, 1]
        self._xs.append((np.asarray(x, np.float64) - lo) / (hi - lo))
        self._ys.append(float(y))

    def suggest(self) -> np.ndarray:
        lo, hi = self._bounds[:, 0], self._bounds[:, 1]
        dim = len(self._bounds)
        if len(self._xs) < 2:
            return lo + (hi - lo) * self._rng.rand(dim)
        ys = np.asarray(self._ys)
        mu, sd = ys.mean(), max(ys.std(), 1e-12)
        gp = GaussianProcess(length_scale=0.3, sigma_n=self._noise)
        gp.fit(np.stack(self._xs), (ys - mu) / sd)
        cand = self._rng.rand(self._num_candidates, dim)
        mean, std = gp.predict(cand)
        ei = expected_improvement(mean, std, float((ys.max() - mu) / sd))
        best = cand[int(np.argmax(ei))]
        return lo + (hi - lo) * best

    @property
    def best(self) -> Tuple[np.ndarray, float]:
        i = int(np.argmax(self._ys))
        lo, hi = self._bounds[:, 0], self._bounds[:, 1]
        return lo + (hi - lo) * self._xs[i], self._ys[i]
