"""CPU affinity pinning (reference ``HOROVOD_THREAD_AFFINITY``).

The reference pins its background communication thread to a core per
local rank (``parse_and_set_affinity``, ``common/common.cc:~150``).
There is no background thread here — XLA schedules collectives — but
pinning still matters on shared hosts: each worker process (and with it
the gloo/gRPC helper threads jax spawns) can be confined to its own
core set so co-located workers do not migrate onto each other.

``HOROVOD_THREAD_AFFINITY`` holds one core set per local rank,
semicolon-separated; each set is a comma list and/or ranges::

    HOROVOD_THREAD_AFFINITY="0-3;4-7"      # local rank 0 → 0-3, 1 → 4-7
    HOROVOD_THREAD_AFFINITY="0,2;1,3"
"""

from __future__ import annotations

import os
from typing import List, Optional, Set

from horovod_tpu.utils import logging as hvd_logging


def parse_affinity(spec: str) -> List[Set[int]]:
    """Parse the per-local-rank core sets; raises ValueError on junk."""
    out: List[Set[int]] = []
    for rank_spec in spec.split(";"):
        cores: Set[int] = set()
        for part in rank_spec.split(","):
            part = part.strip()
            if not part:
                continue
            if "-" in part:
                lo, _, hi = part.partition("-")
                lo_i, hi_i = int(lo), int(hi)
                if hi_i < lo_i:
                    raise ValueError(
                        f"invalid core range '{part}' in affinity spec")
                cores.update(range(lo_i, hi_i + 1))
            else:
                cores.add(int(part))
        if not cores:
            raise ValueError(
                f"empty core set in HOROVOD_THREAD_AFFINITY: {spec!r}")
        out.append(cores)
    return out


def set_affinity_from_env(local_rank: int,
                          setter=None) -> Optional[Set[int]]:
    """Apply this process's core set from ``HOROVOD_THREAD_AFFINITY``;
    returns the set applied, or None when the knob is unset.  ``setter``
    is injectable for tests (defaults to ``os.sched_setaffinity``)."""
    spec = os.environ.get("HOROVOD_THREAD_AFFINITY")
    if not spec:
        return None
    try:
        sets = parse_affinity(spec)
    except ValueError as e:
        hvd_logging.warning("ignoring HOROVOD_THREAD_AFFINITY: %s", e)
        return None
    if local_rank >= len(sets):
        # never silently share a core set between co-located workers —
        # that is the exact contention pinning exists to prevent (the
        # reference raises when the list is shorter than local size)
        hvd_logging.warning(
            "HOROVOD_THREAD_AFFINITY has %d core set(s) but this is "
            "local rank %d — not pinning; provide one set per local "
            "rank", len(sets), local_rank)
        return None
    cores = sets[local_rank]
    setter = setter or (lambda c: os.sched_setaffinity(0, c))
    try:
        setter(cores)
        hvd_logging.info("pinned process to cores %s (local rank %d)",
                         sorted(cores), local_rank)
        return cores
    except OSError as e:  # pragma: no cover - cores absent on this host
        hvd_logging.warning("could not set CPU affinity %s: %s",
                            sorted(cores), e)
        return None
