"""Measured comm/compute overlap for the DP gradient exchange.

The scaling model (``utils/scaling.py``, ``docs/scaling.md``) needs an
``overlap_fraction`` — how much of the gradient collective hides under
backward compute.  Until now that number was *assumed*; this probe
measures it on whatever devices are present, the way the reference
measures rather than models its benchmark tables
(``docs/benchmarks.rst``).

Method — three compiled programs over the same mesh, batch and
parameters:

* **backward-only**: forward + backward, gradients consumed locally
  (no collective);
* **exchange-only**: the bucketed reduce-scatter → allgather exchange
  on gradient-shaped inputs (no model compute);
* **fused**: the real train-step body — backward feeding the exchange
  inside one program, where XLA's latency-hiding scheduler is free to
  interleave them.

If the scheduler achieves nothing, ``t_fused ≈ t_backward +
t_exchange``; if the shorter phase hides completely under the longer,
``t_fused ≈ max(t_backward, t_exchange)``.  The achieved fraction is::

    overlap = (t_backward + t_exchange - t_fused) / min(t_backward,
                                                        t_exchange)

clamped to [0, 1].  Each timing fences on a host fetch of a scalar
(the same discipline as ``bench.py``: ``block_until_ready`` can lie
through remote-device tunnels) and takes the median over ``iters``
calls.  On a 1-chip world the exchange is pure data movement with no
wire, so the fraction is reported but near-meaningless — the probe
exists to be run on real slices, and the bench records it per run so
the scaling table can cite a measured number
(``BENCH_*.json: overlap_fraction``).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu.ops import collectives as C
from horovod_tpu.ops.collectives import Average, ReduceOp
from horovod_tpu.runtime import state
from horovod_tpu.runtime.topology import GLOBAL_AXES, resolve_hierarchy
from horovod_tpu.utils import hlo as H

AxisSpec = Union[str, Sequence[str]]


@dataclasses.dataclass
class OverlapReport:
    """One probe run: the three phase timings, the derived overlap, and
    — for the hierarchical exchange — the per-level attribution plus the
    compiled collective structure (which scopes actually exist on the
    wire, straight from the optimized HLO of the exchange program)."""

    backward_s: float
    exchange_s: float
    fused_s: float
    overlap_fraction: float
    world: int
    payload_bytes: int
    hierarchy: str = "flat"
    # exchange time left exposed past backward — the serial tail the
    # tile-fused final-bucket exchange attacks (docs/fused_kernels.md):
    # max(0, fused_s - backward_s); 0 = the wire hid completely
    tail_exchange_s: float = 0.0
    # the final-bucket schedule this probe ran: "on" = tile-granular
    # fused tail, "off" = monolithic last collective
    fused_collectives: str = "off"
    # HLO scan of the exchange program: 1 if its final async RS/AG pair
    # has no compute scheduled between start and done (the serial tail
    # HLO005 flags); 0 when overlapped or when the backend issues
    # synchronously (no async pairs to judge)
    serial_tail_collectives: Optional[int] = None
    # two-level only: the intra-slice (ICI) share of the exchange time
    # and the cross-slice (DCN) remainder — measured, not modeled
    exchange_intra_s: Optional[float] = None
    exchange_cross_s: Optional[float] = None
    # compiled structure of the exchange program: kind → distinct
    # replica-group sizes (two reduce-scatter scopes == two levels)
    rs_scopes: tuple = ()
    ag_scopes: tuple = ()
    grad_sized_allreduces: int = 0
    # per-level wire bytes of the compiled exchange (cost-model
    # attribution over the parsed collectives) — the perf gate diffs
    # these across artifacts (PERF003, docs/perf_gate.md)
    wire_bytes_ici: Optional[int] = None
    wire_bytes_dcn: Optional[int] = None

    def as_bench_fields(self, prefix: str = "") -> dict:
        """The fields ``bench.py`` merges into the bench JSON."""
        fields = {
            f"{prefix}overlap_fraction": round(self.overlap_fraction, 4),
            f"{prefix}overlap_backward_s": round(self.backward_s, 6),
            f"{prefix}overlap_exchange_s": round(self.exchange_s, 6),
            f"{prefix}overlap_fused_s": round(self.fused_s, 6),
            f"{prefix}tail_exchange_s": round(self.tail_exchange_s, 6),
            f"{prefix}exchange_hierarchy": self.hierarchy,
            f"{prefix}fused_collectives": self.fused_collectives,
        }
        if self.serial_tail_collectives is not None:
            fields[f"{prefix}exchange_serial_tail_collectives"] = \
                int(self.serial_tail_collectives)
        if self.exchange_intra_s is not None:
            fields[f"{prefix}overlap_exchange_intra_s"] = \
                round(self.exchange_intra_s, 6)
            fields[f"{prefix}overlap_exchange_cross_s"] = \
                round(self.exchange_cross_s, 6)
        if self.rs_scopes:
            fields[f"{prefix}exchange_rs_scopes"] = list(self.rs_scopes)
            # the count the offline HLO lint (analysis/hlo_lint.py
            # HLO001) checks in saved artifacts: any non-zero value
            # means the sharded exchange regressed to allreduce on the
            # wire of the run that produced this JSON
            fields[f"{prefix}exchange_grad_sized_allreduces"] = \
                int(self.grad_sized_allreduces)
        if self.wire_bytes_ici is not None:
            fields[f"{prefix}exchange_wire_bytes_ici"] = \
                int(self.wire_bytes_ici)
            fields[f"{prefix}exchange_wire_bytes_dcn"] = \
                int(self.wire_bytes_dcn or 0)
        return fields


def _median_time(fn, args, iters: int, warmup: int) -> float:
    for _ in range(warmup):
        out = fn(*args)
        float(np.asarray(jax.device_get(out)))
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = fn(*args)
        float(np.asarray(jax.device_get(out)))   # host fetch = fence
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def measure_overlap(loss_fn: Callable,
                    params,
                    batch,
                    mesh=None,
                    axis: AxisSpec = GLOBAL_AXES,
                    op: ReduceOp = Average,
                    bucket_bytes: Optional[int] = None,
                    hierarchy: str = "auto",
                    fused_collectives: str = "off",
                    iters: int = 5,
                    warmup: int = 2) -> OverlapReport:
    """Measure backward/exchange/fused timings for ``loss_fn`` over the
    (dcn, ici) mesh and return the achieved overlap fraction.

    ``params`` replicated, ``batch`` sharded along ``axis`` — the same
    contract as ``DistributedTrainStep``.  ``bucket_bytes`` buckets the
    exchange exactly as ``exchange_bucket_bytes`` would in the train
    step, and ``hierarchy`` selects its topology exactly as the step's
    knob would (``"auto"`` resolves against the mesh factorization), so
    the probe measures the schedule the step will actually run.

    Two-level runs additionally report (a) per-level timing
    attribution — an intra-slice-only RS/AG program is timed separately
    and the cross-slice remainder is the difference, clamped at zero —
    and (b) the compiled collective *structure* of the exchange program
    (distinct reduce-scatter/all-gather scopes, count of gradient-sized
    all-reduces), parsed from its optimized HLO.  The structure fields
    are what the HLO guard tests pin; the bench JSON carries them so a
    silent topology regression is visible in the run artifact too.

    ``fused_collectives`` selects the final-bucket schedule the probed
    exchange runs (``"on"`` = the tile-granular fused tail,
    docs/fused_kernels.md); the report's ``tail_exchange_s`` — exchange
    time left exposed past backward — is the quantity the fused path
    exists to shrink, and ``bench.py`` emits both paths' numbers."""
    from horovod_tpu.ops.pallas_kernels import resolve_fused_collectives

    mesh = mesh or state.global_state().mesh
    axes = (axis,) if isinstance(axis, str) else tuple(axis)
    world = 1
    for a in axes:
        world *= mesh.shape[a]
    mode = resolve_hierarchy(hierarchy,
                             [mesh.shape[a] for a in axes])
    fused_tail = resolve_fused_collectives(fused_collectives)

    shard_map = jax.shard_map
    in_p = (P(), P(axes))

    def grads_of(params, batch):
        _, grads = jax.value_and_grad(loss_fn)(params, batch)
        return grads

    def fingerprint(tree) -> jax.Array:
        leaves = jax.tree_util.tree_leaves(tree)
        return sum(jnp.sum(jnp.abs(x).astype(jnp.float32))
                   for x in leaves)

    def exchange(grads):
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        if mode == "two_level":
            outer, inner = axes
            shards, spec = C.hierarchical_reducescatter(
                leaves, op=op, outer_axis=outer, inner_axis=inner,
                bucket_bytes=bucket_bytes, fused_tail=fused_tail)
            out = C.hierarchical_allgather(shards, spec,
                                           outer_axis=outer,
                                           inner_axis=inner)
        else:
            shards, spec = C.grouped_reducescatter(
                leaves, op=op, axis=axes, bucket_bytes=bucket_bytes,
                fused_tail=fused_tail)
            out = C.grouped_allgather(shards, spec, axis=axes)
        return jax.tree_util.tree_unflatten(treedef, out)

    def intra_exchange(grads):
        # the ICI phase in isolation: RS/AG over the inner axis only —
        # its timing is the intra-slice share of the full exchange
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        shards, spec = C.grouped_reducescatter(
            leaves, op=op, axis=axes[-1], bucket_bytes=bucket_bytes)
        out = C.grouped_allgather(shards, spec, axis=axes[-1])
        return jax.tree_util.tree_unflatten(treedef, out)

    def backward_only(params, batch):
        return fingerprint(grads_of(params, batch))

    def exchange_only(grads):
        return fingerprint(exchange(grads))

    def intra_only(grads):
        return fingerprint(intra_exchange(grads))

    def fused(params, batch):
        return fingerprint(exchange(grads_of(params, batch)))

    bwd = jax.jit(shard_map(backward_only, mesh=mesh, in_specs=in_p,
                            out_specs=P(), check_vma=False))
    fsd = jax.jit(shard_map(fused, mesh=mesh, in_specs=in_p,
                            out_specs=P(), check_vma=False))

    # gradient-shaped input for the exchange-only program: computed
    # once, replicated, so its timing contains zero backward work
    repl = NamedSharding(mesh, P())
    grads = jax.device_put(
        jax.jit(shard_map(grads_of, mesh=mesh, in_specs=in_p,
                          out_specs=P(), check_vma=False))(params, batch),
        repl)
    exc = jax.jit(shard_map(exchange_only, mesh=mesh, in_specs=(P(),),
                            out_specs=P(), check_vma=False))

    # compiled structure of the exchange program (scopes per kind)
    rs_scopes: tuple = ()
    ag_scopes: tuple = ()
    grad_ars = 0
    wire_ici = wire_dcn = None
    serial_tail = None
    payload = sum(x.size * x.dtype.itemsize
                  for x in jax.tree_util.tree_leaves(grads))
    try:
        # the serial-tail scan runs on the FUSED program — that is
        # where backward compute exists to hide the exchange under; an
        # exchange-only module has nothing between start and done by
        # construction
        serial_tail = H.serial_tail_collectives(
            fsd.lower(params, batch).compile().as_text())
    except Exception:      # noqa: BLE001 — structure report is advisory
        pass
    try:
        ops = H.collective_ops(
            exc.lower(grads).compile().as_text())
        scopes = H.scopes_by_kind(ops)
        rs_scopes = scopes.get("reduce-scatter", ())
        ag_scopes = scopes.get("all-gather", ())
        grad_ars = sum(1 for o in ops if o.kind == "all-reduce"
                       and o.bytes >= payload)
        # per-level wire attribution from the compiled collectives —
        # measured structure, not the analytic model, so a
        # de-quantized DCN hop or a de-fused exchange shows up as more
        # bytes in the run's own artifact (perf gate PERF003)
        from horovod_tpu.analysis import cost_model as CM

        n_outer = mesh.shape[axes[0]] if len(axes) == 2 else 1
        levels = CM.collective_wire_by_level(
            ops, n_dcn=n_outer, n_ici=mesh.shape[axes[-1]])
        wire_ici = int(levels["ici"])
        wire_dcn = int(levels["dcn"])
    except Exception:      # noqa: BLE001 — structure report is advisory
        pass

    t_bwd = _median_time(bwd, (params, batch), iters, warmup)
    t_exc = _median_time(exc, (grads,), iters, warmup)
    t_fsd = _median_time(fsd, (params, batch), iters, warmup)

    t_intra = t_cross = None
    if mode == "two_level":
        itr = jax.jit(shard_map(intra_only, mesh=mesh, in_specs=(P(),),
                                out_specs=P(), check_vma=False))
        t_intra = _median_time(itr, (grads,), iters, warmup)
        t_cross = max(0.0, t_exc - t_intra)

    saved = t_bwd + t_exc - t_fsd
    denom = min(t_bwd, t_exc)
    frac = saved / denom if denom > 0 else 0.0
    # the serial tail in time units: whatever the fused program costs
    # beyond backward alone is exchange the schedule failed to hide
    tail_s = max(0.0, t_fsd - t_bwd)
    # registry mirror of the probe's headline numbers (docs/metrics.md):
    # measured per-level exchange time and wire bytes, next to the
    # static model the train step publishes
    from horovod_tpu import telemetry

    if telemetry.enabled():
        tg = telemetry.gauge("hvd_exchange_time_seconds",
                             "measured gradient-exchange time per level")
        tg.set(t_exc, level="total")
        if t_intra is not None:
            tg.set(t_intra, level="ici")
            tg.set(t_cross, level="dcn")
        telemetry.gauge("hvd_overlap_fraction",
                        "measured comm/compute overlap fraction").set(
                            float(np.clip(frac, 0.0, 1.0)))
        telemetry.gauge(
            "hvd_tail_exchange_seconds",
            "exchange time left exposed past backward compute").set(
                tail_s, fused="on" if fused_tail else "off")
        if wire_ici is not None:
            wg = telemetry.gauge(
                "hvd_exchange_measured_wire_bytes",
                "per-level wire bytes of the compiled exchange")
            wg.set(wire_ici, level="ici")
            wg.set(wire_dcn, level="dcn")
    return OverlapReport(
        backward_s=t_bwd, exchange_s=t_exc, fused_s=t_fsd,
        overlap_fraction=float(np.clip(frac, 0.0, 1.0)),
        world=world, payload_bytes=int(payload),
        hierarchy=mode,
        tail_exchange_s=tail_s,
        fused_collectives="on" if fused_tail else "off",
        serial_tail_collectives=serial_tail,
        exchange_intra_s=t_intra, exchange_cross_s=t_cross,
        rs_scopes=rs_scopes, ag_scopes=ag_scopes,
        grad_sized_allreduces=grad_ars,
        wire_bytes_ici=wire_ici, wire_bytes_dcn=wire_dcn)
