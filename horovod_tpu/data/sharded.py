"""Shard-aware dataset views: each rank reads 1/N, never the whole set.

The reference delegates input to framework loaders, and its spark path
ships the *full* dataset to every worker before training — the round-5
VERDICT flags exactly that.  This module is the TPU-native replacement:
a :class:`ShardedDataset` assigns each rank a disjoint slice of a
deterministic per-epoch sample order, so a rank *materializes* only its
~1/N of the data (range reads / index gathers against the source), while
all ranks agree on the global order from one broadcast seed.

Sharding contract (docs/data.md):

* the per-epoch global order is ``permutation(seed, epoch)`` —
  identical on every rank, no communication needed once the seed is
  agreed (:func:`broadcast_seed`);
* consumption advances in *global sample position*: the step at
  position ``p`` hands rank ``r`` the contiguous block
  ``order[p + r*B : p + (r+1)*B]`` and advances ``p`` by ``world*B``
  — so with ``shuffle=False`` each rank's reads are literal index
  ranges (the spark store's range-read fast path);
* drop-remainder: a step exists only if a full ``world*B`` chunk
  remains — no ragged tail batch ever reaches the device, the input
  counterpart of the exchange plane's zero-tail fusion invariant
  (every shard always full, shard-divisible);
* elastic resume: position is world-size-independent, so after a
  reshard (say 2 → 4 ranks) the new world continues the SAME epoch
  order from the restored position — no sample replays, none is
  skipped (up to the drop-remainder tail).  ``reshard()`` +
  ``epoch(e, start_sample=p)`` is the whole protocol; elastic
  ``_reset`` tears down any live prefetchers
  (:func:`horovod_tpu.data.close_all_pipelines`) and the training fn
  re-seeds from the committed ``(epoch, position)``.
"""

from __future__ import annotations

from typing import Iterator, Optional, Sequence, Union

import jax
import numpy as np


def broadcast_seed(seed: Optional[int] = None, root_rank: int = 0) -> int:
    """Agree on one shuffle seed across processes (rank 0's wins).

    ``seed=None`` draws a fresh one on the root.  Single-process (or
    uninitialized) runs return the local value — the broadcast is a
    no-op there, so this is safe to call unconditionally."""
    if seed is None:
        seed = int(np.random.SeedSequence().generate_state(1)[0] >> 1)
    from horovod_tpu.runtime import state

    if state.is_initialized() and state.global_state().process_count > 1:
        from horovod_tpu.functions import broadcast_object

        seed = broadcast_object(int(seed), root_rank=root_rank,
                                name="data.shuffle_seed")
    return int(seed)


class ArraySource:
    """Random-access source over an in-memory pytree of equal-length
    host arrays (a dict of columns, a tuple, a bare array ...).

    ``rows_fetched`` counts rows actually materialized through
    :meth:`take` — the accounting hook the no-full-copy tests assert on
    (a rank driving a :class:`ShardedDataset` must fetch ~1/world of
    the rows, never all of them)."""

    def __init__(self, data):
        self._data = data
        leaves = jax.tree_util.tree_leaves(data)
        if not leaves:
            raise ValueError("ArraySource needs at least one array leaf")
        n = len(leaves[0])
        for leaf in leaves[1:]:
            if len(leaf) != n:
                raise ValueError(
                    f"ArraySource leaves disagree on length: {n} vs "
                    f"{len(leaf)}")
        self._n = n
        self.rows_fetched = 0

    def __len__(self) -> int:
        return self._n

    def take(self, indices: np.ndarray):
        self.rows_fetched += len(indices)
        return jax.tree_util.tree_map(lambda a: a[indices], self._data)


class ParquetSource:
    """Random-access source over a store parquet directory — row-group
    pruned, so :meth:`take` materializes only the groups its indices
    touch (the :class:`~horovod_tpu.spark.store.RowGroupReader` range
    API underneath; ``reader.rows_materialized`` is the accounting)."""

    def __init__(self, path: str):
        from horovod_tpu.spark.store import RowGroupReader

        self.reader = RowGroupReader(path)

    def __len__(self) -> int:
        return self.reader.num_rows

    @property
    def rows_fetched(self) -> int:
        return self.reader.rows_materialized

    def take(self, indices: np.ndarray):
        return self.reader.take(indices)


def _epoch_rng(seed: int, epoch: int) -> np.random.RandomState:
    # golden-ratio mix so (seed, epoch) and (seed+1, epoch-1) diverge
    return np.random.RandomState((seed + 0x9E3779B1 * (epoch + 1))
                                 % (1 << 32))


class ShardedDataset:
    """Disjoint 1/N shard view of a random-access source (module doc
    has the full contract).

    ``source`` is anything with ``__len__`` and ``take(indices)`` —
    :class:`ArraySource`, :class:`ParquetSource`, or your own.
    ``batch_size`` is PER RANK.  ``rank``/``world`` default to the
    runtime's process identity (the reading unit is the host process,
    which feeds all its addressable devices), or (0, 1) before
    ``init()``.  ``seed`` must be process-consistent — pass it through
    :func:`broadcast_seed` in multi-process runs.
    """

    def __init__(self, source, batch_size: int,
                 rank: Optional[int] = None, world: Optional[int] = None,
                 seed: int = 0, shuffle: bool = True):
        if batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        if rank is None or world is None:
            from horovod_tpu.runtime import state

            if state.is_initialized():
                st = state.global_state()
                rank = st.process_rank if rank is None else rank
                world = st.process_count if world is None else world
            else:
                rank = rank or 0
                world = world or 1
        if not 0 <= rank < world:
            raise ValueError(f"rank {rank} outside world of {world}")
        self.source = source
        self.batch_size = int(batch_size)
        self.rank = int(rank)
        self.world = int(world)
        self.seed = int(seed)
        self.shuffle = bool(shuffle)

    # -- geometry ----------------------------------------------------------

    @property
    def num_samples(self) -> int:
        """Global sample count of the underlying source."""
        return len(self.source)

    @property
    def samples_per_step(self) -> int:
        """Global samples one step consumes across all ranks."""
        return self.world * self.batch_size

    @property
    def steps_per_epoch(self) -> int:
        """Full steps in an epoch (drop-remainder)."""
        return self.num_samples // self.samples_per_step

    def position_after(self, steps: int, start_sample: int = 0) -> int:
        """Global sample position after ``steps`` full steps — the value
        to commit for elastic resume (world-size independent)."""
        return start_sample + steps * self.samples_per_step

    # -- iteration ---------------------------------------------------------

    def _order(self, epoch: int) -> np.ndarray:
        if not self.shuffle:
            return np.arange(self.num_samples, dtype=np.int64)
        return _epoch_rng(self.seed, epoch).permutation(
            self.num_samples).astype(np.int64)

    def epoch_indices(self, epoch: int,
                      start_sample: int = 0) -> Iterator[np.ndarray]:
        """This rank's per-step index arrays for ``epoch``, starting at
        global sample position ``start_sample`` (must be a prior
        ``position_after`` value — i.e. a multiple of some generation's
        ``samples_per_step``)."""
        if start_sample < 0:
            raise ValueError(f"start_sample must be >= 0, got "
                             f"{start_sample}")
        order = self._order(epoch)
        n, chunk, b = len(order), self.samples_per_step, self.batch_size
        pos = start_sample
        while pos + chunk <= n:
            lo = pos + self.rank * b
            yield order[lo:lo + b]
            pos += chunk

    def epoch(self, epoch: int, start_sample: int = 0):
        """This rank's batches for one epoch — each a ``source.take`` of
        its own index block only (the no-full-copy guarantee)."""
        for idx in self.epoch_indices(epoch, start_sample):
            yield self.source.take(idx)

    def iter_epochs(self, start_epoch: int = 0, start_sample: int = 0):
        """Endless epoch-after-epoch batch stream (``start_sample``
        applies to the first epoch only) — what a pipeline feeds from."""
        epoch = start_epoch
        while True:
            yield from self.epoch(epoch, start_sample)
            start_sample = 0
            epoch += 1

    # -- elastic -----------------------------------------------------------

    def reshard(self, rank: int, world: int) -> "ShardedDataset":
        """The same dataset (source, seed, order) viewed by a different
        world — the elastic-restart constructor.  Resuming the restored
        epoch at the committed ``position_after`` value replays no
        sample: position is counted in global samples, not steps, so it
        means the same thing at any world size."""
        return ShardedDataset(self.source, self.batch_size, rank=rank,
                              world=world, seed=self.seed,
                              shuffle=self.shuffle)

    def state_dict(self, epoch: int, step: int,
                   start_sample: int = 0) -> dict:
        """The committable resume point after ``step`` full steps of
        ``epoch`` — store it in elastic state (e.g. as ``TpuState``
        kwargs) and hand it back to :meth:`load_position`."""
        return {"epoch": int(epoch), "seed": self.seed,
                "sample": self.position_after(step, start_sample)}

    def load_position(self, state: dict):
        """``(epoch, start_sample)`` for :meth:`epoch` /
        :meth:`iter_epochs` from a :meth:`state_dict` snapshot; checks
        the seed so a mismatched restore fails loudly instead of
        silently replaying a different order."""
        if int(state.get("seed", self.seed)) != self.seed:
            raise ValueError(
                f"restored shuffle seed {state.get('seed')} does not "
                f"match this dataset's {self.seed}; re-seed the dataset "
                f"from the committed state before resuming")
        return int(state["epoch"]), int(state["sample"])
