"""Host-side prefetching + eager device placement for the input feed.

``DistributedTrainStep`` hides the gradient exchange under backward
compute (PR 1–2) and the warm-start cache hides compile cost (PR 3);
the last unhidden serial cost is the input feed — host batch assembly
and the host→device transfer both sat on the critical path between
steps.  :class:`PrefetchIterator` takes them off it:

* a feeder thread pulls host batches from the source iterator (sources
  are rarely thread-safe, so exactly one thread touches the iterator —
  order is preserved by construction);
* each batch's *assembly* — the ``place`` callable, typically
  ``step.shard_batch`` / ``shard_local_batch`` / a ``jax.device_put``
  onto the step's ``NamedSharding`` — runs on a small thread pool
  (``HOROVOD_INPUT_THREADS``), so the H2D transfer for batch ``k+1``
  is *issued* while batch ``k`` computes (double-buffering; JAX
  transfers are async, the pool just gets them dispatched early);
* a bounded queue (``HOROVOD_PREFETCH_DEPTH``) applies backpressure:
  the feeder pulls at most ``depth + 1`` items beyond what the
  consumer took, so host memory holds a bounded number of in-flight
  batches no matter how slow the step is;
* exceptions from the source or from ``place`` surface at ``next()``
  — never silently swallowed on a worker thread;
* ``close()`` is idempotent, unblocks a parked feeder, joins every
  thread and leaves nothing running (the shutdown-without-leak tests
  pin this); iterators also close themselves on exhaustion.

Donation-safe handoff: every batch out of ``next()`` is a fresh set of
arrays (``place`` makes new device buffers per batch), so feeding a
``DistributedTrainStep(donate_batch=True)`` is safe — the step may
donate the input buffers; nothing else aliases them.

Elastic: live iterators register in a process-wide set;
:func:`close_all` tears them all down — ``elastic._reset`` calls it
before rebuilding the backend, because queued device batches pin
buffers of the *old* world's client.  After reset, re-seed the dataset
at the restored step (``ShardedDataset.reshard`` + ``epoch(e,
start_sample=p)``) and build a fresh iterator.
"""

from __future__ import annotations

import queue
import threading
import time
import weakref
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterable, Optional

from horovod_tpu import faults, telemetry
from horovod_tpu.runtime.config import _env_int

_LIVE: "weakref.WeakSet[PrefetchIterator]" = weakref.WeakSet()
_THREAD_PREFIX = "hvd-input"

_DEFAULT_DEPTH = 2
_DEFAULT_THREADS = 2


def _config_default(attr: str, env: str, fallback: int) -> int:
    """Knob resolution: runtime config when initialized (the env
    contract resolved at init()), a direct env read before init, the
    built-in default last."""
    from horovod_tpu.runtime import state

    if state.is_initialized():
        return int(getattr(state.global_state().config, attr))
    return _env_int(env, fallback)


def default_prefetch_depth() -> int:
    return max(_config_default("prefetch_depth", "HOROVOD_PREFETCH_DEPTH",
                               _DEFAULT_DEPTH), 1)


def default_input_threads() -> int:
    return max(_config_default("input_threads", "HOROVOD_INPUT_THREADS",
                               _DEFAULT_THREADS), 1)


class _End:
    """Queue sentinel: normal exhaustion, or a carried source error."""

    __slots__ = ("error",)

    def __init__(self, error: Optional[BaseException] = None):
        self.error = error


class PrefetchIterator:
    """Bounded, ordered, background-assembled batch iterator.

    ::

        feed = PrefetchIterator(dataset.iter_epochs(),
                                place=step.shard_batch)
        for batch in feed:            # or: batch = next(feed)
            params, opt, loss = step(params, opt, batch)
        feed.close()                  # or use as a context manager

    ``source`` is any iterable of host batches; ``place`` (optional)
    maps a host batch to its device placement and runs on the worker
    pool.  ``depth`` bounds the prefetch queue; ``threads`` sizes the
    assembly pool.  Both default to the runtime knobs.

    Instrumentation (what ``bench.py`` emits): ``stall_s`` accumulates
    wall time ``next()`` spent *blocked* waiting for a batch — the
    input stall the pipeline exists to eliminate — ``stall_samples``
    keeps the per-delivery values (medians over a window stay robust
    to one-off wakeup spikes, the ``median_rate`` discipline), and
    ``batches`` counts deliveries.
    """

    def __init__(self, source: Iterable, place: Optional[Callable] = None,
                 depth: Optional[int] = None,
                 threads: Optional[int] = None,
                 name: str = "feed"):
        self._source = iter(source)
        self._place = place
        self.depth = int(depth) if depth is not None \
            else default_prefetch_depth()
        if self.depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got "
                             f"{self.depth}")
        self._threads = int(threads) if threads is not None \
            else default_input_threads()
        if self._threads < 1:
            raise ValueError(f"input threads must be >= 1, got "
                             f"{self._threads}")
        self._queue: "queue.Queue" = queue.Queue(maxsize=self.depth)
        self._stop = threading.Event()
        self._closed = False
        self._exhausted = False
        self._lock = threading.Lock()
        self.stall_s = 0.0
        self.stall_samples: list = []
        self.batches = 0
        # telemetry (docs/metrics.md): queue depth is the gauge the
        # serving plane's autoscaling story scrapes; stall time is the
        # input plane's contract number
        self._tel_batches = telemetry.counter(
            "hvd_input_batches_total",
            "batches delivered by the input pipeline").labels(
                pipeline=name)
        self._tel_stall = telemetry.histogram(
            "hvd_input_stall_seconds",
            "time next() blocked waiting for a batch").labels(
                pipeline=name)
        self._tel_depth = telemetry.gauge(
            "hvd_input_queue_depth",
            "prefetch queue occupancy at delivery").labels(pipeline=name)
        self._pool = ThreadPoolExecutor(
            max_workers=self._threads,
            thread_name_prefix=f"{_THREAD_PREFIX}-{name}")
        self._feeder = threading.Thread(
            target=self._feed, name=f"{_THREAD_PREFIX}-{name}-feeder",
            daemon=True)
        self._feeder.start()
        _LIVE.add(self)

    # -- feeder side -------------------------------------------------------

    def _assemble(self, item):
        return item if self._place is None else self._place(item)

    def _feed(self) -> None:
        try:
            while not self._stop.is_set():
                # chaos hook: a raise here surfaces at next() via the
                # _End sentinel (the documented worker-exception path);
                # a delay models a slow source
                faults.inject("data.feed")
                try:
                    item = next(self._source)
                except StopIteration:
                    self._put(_End())
                    return
                # submit BEFORE the (possibly blocking) queue put: the
                # H2D/device_put dispatch is exactly the work that must
                # start early, and the put is where backpressure parks
                # the feeder — at most depth+1 items are ever pulled
                # beyond what the consumer consumed
                self._put(self._pool.submit(self._assemble, item))
        except BaseException as e:  # noqa: BLE001 — carried to next()
            self._put(_End(e))

    def _put(self, obj) -> None:
        while not self._stop.is_set():
            try:
                self._queue.put(obj, timeout=0.1)
                return
            except queue.Full:
                continue

    # -- consumer side -----------------------------------------------------

    def __iter__(self) -> "PrefetchIterator":
        return self

    def __next__(self):
        if self._exhausted:
            raise StopIteration
        if self._closed:
            raise RuntimeError("PrefetchIterator is closed")
        t0 = time.perf_counter()
        got = self._queue.get()
        if isinstance(got, _End):
            self._exhausted = True
            self.close()
            if got.error is not None:
                raise got.error
            raise StopIteration
        try:
            batch = got.result()
        except BaseException:
            self.close()
            raise
        dt = time.perf_counter() - t0
        self.stall_s += dt
        self.stall_samples.append(dt)
        self.batches += 1
        self._tel_batches.inc()
        self._tel_stall.observe(dt)
        self._tel_depth.set(self._queue.qsize())
        return batch

    def close(self) -> None:
        """Tear down feeder + pool; idempotent, leak-free.  Queued
        batches are dropped (their device buffers released) — an
        elastic reset must not carry arrays of the old world across."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._stop.set()
        # the feeder may be parked in _put; it polls _stop every 100 ms,
        # and draining the queue lets it exit immediately instead
        while self._feeder.is_alive():
            try:
                while True:
                    got = self._queue.get_nowait()
                    if not isinstance(got, _End):
                        got.cancel()
            except queue.Empty:
                pass
            self._feeder.join(timeout=0.05)
        self._pool.shutdown(wait=True)
        while True:     # anything the feeder enqueued while draining
            try:
                self._queue.get_nowait()
            except queue.Empty:
                break
        _LIVE.discard(self)

    @property
    def closed(self) -> bool:
        return self._closed

    def __enter__(self) -> "PrefetchIterator":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):  # pragma: no cover - GC timing dependent
        try:
            self.close()
        except Exception:
            pass


def close_all() -> int:
    """Close every live :class:`PrefetchIterator` in the process —
    the elastic ``_reset`` hook (queued batches hold device buffers of
    the torn-down world).  Returns how many were closed."""
    closed = 0
    for it in list(_LIVE):
        if not it.closed:
            it.close()
            closed += 1
    return closed
