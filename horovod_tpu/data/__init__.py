"""Streaming, shard-aware input pipeline (docs/data.md).

The layer between a dataset and :class:`DistributedTrainStep`:

* :class:`ShardedDataset` — each rank reads a disjoint 1/N of a
  deterministic per-epoch order (no full-copy-per-worker), position is
  world-size independent for elastic resume;
* :class:`PrefetchIterator` — host batch assembly + eager device
  placement on background threads with a bounded queue, so batch
  ``k+1``'s H2D transfer overlaps batch ``k``'s compute;
* :class:`ArraySource` / :class:`ParquetSource` — random-access
  sources over in-memory pytrees and store parquet (row-group pruned
  range reads);
* :func:`broadcast_seed` — one shuffle seed for all processes;
* :func:`close_all_pipelines` — elastic ``_reset``'s teardown hook.

Knobs: ``HOROVOD_PREFETCH_DEPTH`` (queue bound, default 2) and
``HOROVOD_INPUT_THREADS`` (assembly pool, default 2) — see
docs/running.md.
"""

from horovod_tpu.data.prefetch import (
    PrefetchIterator,
    close_all as close_all_pipelines,
    default_input_threads,
    default_prefetch_depth,
)
from horovod_tpu.data.sharded import (
    ArraySource,
    ParquetSource,
    ShardedDataset,
    broadcast_seed,
)

__all__ = [
    "ArraySource",
    "ParquetSource",
    "PrefetchIterator",
    "ShardedDataset",
    "broadcast_seed",
    "close_all_pipelines",
    "default_input_threads",
    "default_prefetch_depth",
]
