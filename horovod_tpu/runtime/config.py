"""Runtime configuration from the ``HOROVOD_*`` environment contract.

The reference funnels three config layers (env vars, ``horovodrun`` CLI flags,
runtime autotune) into ``HOROVOD_*`` env vars read by the C++ core
(``horovod/common/utils/env_parser.{h,cc}``, knob names in
``horovod/common/common.h:64-90``).  We keep the same contract and knob names
where they still make sense on TPU, and add TPU-specific ones
(``HOROVOD_TPU_OPERATIONS``, mesh shape overrides).

Knobs that exist purely because of the reference's negotiation machinery
(cycle time, response cache capacity) are kept as accepted-but-advisory
settings: SPMD compilation removes per-tensor negotiation, so they only
influence the eager bucketing layer.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional


# The HOROVOD_* env-var registry (reference knob table common.h:64-90):
# every knob the package reads OR sets must be declared here — the
# static analyzer (HVD005, docs/analysis.md) fails on any quoted
# HOROVOD_* literal missing from this set, and the doc-drift guard
# (tests/test_env_knob_docs.py) separately requires each to appear in
# docs/.  One greppable place instead of knobs scattered per-module.
KNOWN_KNOBS = frozenset({
    # -- process identity (set by the launcher)
    "HOROVOD_RANK", "HOROVOD_SIZE", "HOROVOD_LOCAL_RANK",
    "HOROVOD_LOCAL_SIZE", "HOROVOD_CROSS_RANK", "HOROVOD_CROSS_SIZE",
    "HOROVOD_HOSTNAME", "HOROVOD_COORDINATOR_ADDR",
    # -- data plane / fusion
    "HOROVOD_TPU_OPERATIONS", "HOROVOD_FUSION_THRESHOLD",
    "HOROVOD_CYCLE_TIME", "HOROVOD_CACHE_CAPACITY",
    "HOROVOD_HIERARCHICAL_ALLREDUCE", "HOROVOD_HIERARCHICAL_ALLGATHER",
    "HOROVOD_EXCHANGE_BUCKET_BYTES", "HOROVOD_EXCHANGE_HIERARCHY",
    "HOROVOD_EXCHANGE_WIRE_DTYPE", "HOROVOD_EXCHANGE_REDUCTION",
    "HOROVOD_FUSED_COLLECTIVES",
    "HOROVOD_ADASUM_NUM_CHUNKS", "HOROVOD_DEBUG_SPARSE",
    "HOROVOD_TPU_MESH_SHAPE",
    # -- N-level exchange codec map (runtime/topology.py,
    #    docs/calibration.md): "dcn=int8,ici=fp32"-style per-level wire
    #    dtypes for hierarchy=tree meshes
    "HOROVOD_EXCHANGE_LEVEL_CODECS",
    # -- measured hardware model (analysis/cost_model.py,
    #    docs/calibration.md): calibration artifact > preset > builtin
    "HOROVOD_CALIBRATION_PATH", "HOROVOD_HW_PRESET",
    # -- parallelism plan (parallel/plan.py, docs/parallelism.md):
    # the ShardingPlan grammar, e.g. "dp=4,tp=2" or "dp=2,pp=2,v=2"
    "HOROVOD_PLAN",
    # -- MoE expert-parallel dispatch (models/moe.py, parallel/expert.py,
    #    docs/fused_kernels.md "Expert-parallel dispatch")
    "HOROVOD_MOE_FUSED_DISPATCH", "HOROVOD_MOE_CAPACITY_FACTOR",
    # -- sequence-parallel ring attention (parallel/ring_attention.py,
    #    ops/pallas_kernels.py, docs/fused_kernels.md "Ring-flash attention")
    "HOROVOD_SP_FUSED_RING", "HOROVOD_SP_LAYOUT",
    # -- warm-start compile cache
    "HOROVOD_COMPILE_CACHE", "HOROVOD_COMPILE_CACHE_DIR",
    # -- input pipeline
    "HOROVOD_PREFETCH_DEPTH", "HOROVOD_INPUT_THREADS",
    # -- autotune
    "HOROVOD_AUTOTUNE", "HOROVOD_AUTOTUNE_LOG",
    "HOROVOD_AUTOTUNE_WARMUP_SAMPLES",
    "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES",
    "HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE",
    "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE",
    # -- telemetry plane (horovod_tpu/telemetry, docs/metrics.md)
    "HOROVOD_METRICS", "HOROVOD_METRICS_PORT", "HOROVOD_METRICS_LOG",
    "HOROVOD_METRICS_INTERVAL_S", "HOROVOD_RUN_ID",
    # -- timeline / stall inspector / logging
    "HOROVOD_TIMELINE", "HOROVOD_TIMELINE_MARK_CYCLES",
    "HOROVOD_TIMELINE_PYTHON", "HOROVOD_STALL_CHECK_DISABLE",
    "HOROVOD_STALL_CHECK_TIME_SECONDS",
    "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS",
    "HOROVOD_LOG_LEVEL", "HOROVOD_LOG_HIDE_TIME",
    # -- elastic runtime
    "HOROVOD_ELASTIC", "HOROVOD_ELASTIC_DRIVER_ADDR",
    "HOROVOD_ELASTIC_NOTIFY_ADDR", "HOROVOD_ELASTIC_GENERATION",
    "HOROVOD_ELASTIC_START_TIMEOUT", "HOROVOD_ELASTIC_HEARTBEAT_TIMEOUT",
    "HOROVOD_ELASTIC_HEARTBEAT_INTERVAL",
    "HOROVOD_ELASTIC_HEARTBEAT_SUSPECT_MISSES",
    "HOROVOD_ELASTIC_HEARTBEAT_DEAD_S",
    "HOROVOD_ELASTIC_PROGRESS_TIMEOUT_S",
    "HOROVOD_ELASTIC_DEPART_GRACE_S",
    "HOROVOD_ELASTIC_STRAGGLER_RATIO",
    # -- plan-aware graceful degradation (elastic/degrade.py,
    #    docs/elastic.md "Degraded mode")
    "HOROVOD_DEGRADE", "HOROVOD_DEGRADE_WAIT_S",
    "HOROVOD_DEGRADE_MIN_DATA_EXTENT", "HOROVOD_DEGRADE_PROMOTE",
    # -- serving plane (horovod_tpu/serve, docs/serving.md)
    "HOROVOD_SERVE_QUEUE_DEPTH", "HOROVOD_SERVE_MAX_REQUEUES",
    "HOROVOD_SERVE_MAX_BATCH", "HOROVOD_SERVE_DRAIN_TIMEOUT_S",
    "HOROVOD_SERVE_SCALE_UP_DEPTH", "HOROVOD_SERVE_SCALE_DOWN_DEPTH",
    # -- hvdfleet: tenancy, live weight refresh, closed-loop autoscale
    #    (serve/tenancy.py, serve/refresh.py, serve/autoscale.py)
    "HOROVOD_SERVE_OVERLOAD_FRACTION", "HOROVOD_SERVE_REFRESH_VERIFY",
    "HOROVOD_SERVE_SCALE_HOLD_S", "HOROVOD_SERVE_SCALE_COOLDOWN_S",
    "HOROVOD_SERVE_SCALE_MIN_REPLICAS",
    "HOROVOD_SERVE_SCALE_MAX_REPLICAS",
    # -- perf regression gate (analysis/perf_gate.py, docs/perf_gate.md)
    "HOROVOD_PERF_GATE_TOLERANCE", "HOROVOD_PERF_GATE_OVERLAP_TOLERANCE",
    "HOROVOD_PERF_GATE_WIRE_TOLERANCE",
    "HOROVOD_PERF_GATE_MEMORY_TOLERANCE",
    # -- memory plane (horovod_tpu/memory, docs/memory.md): remat tier,
    #    HBM budget for the plan autotuner, host offload
    "HOROVOD_REMAT_POLICY", "HOROVOD_HBM_BUDGET_BYTES",
    "HOROVOD_OFFLOAD_OPTIMIZER", "HOROVOD_OFFLOAD_DEPTH",
    # -- training-state integrity plane (horovod_tpu/guard,
    #    docs/guardian.md)
    "HOROVOD_GUARD", "HOROVOD_GUARD_POLICY",
    "HOROVOD_GUARD_CHECK_INTERVAL", "HOROVOD_GUARD_ZSCORE",
    "HOROVOD_GUARD_WARMUP_STEPS", "HOROVOD_GUARD_EMA",
    "HOROVOD_GUARD_PREEMPT",
    # -- health / quarantine / retry / chaos
    "HOROVOD_QUARANTINE_BASE_S", "HOROVOD_QUARANTINE_MAX_S",
    "HOROVOD_QUARANTINE_PROBATION_S", "HOROVOD_QUARANTINE_DISABLE",
    "HOROVOD_RETRY_MAX_ATTEMPTS", "HOROVOD_RETRY_BASE_S",
    "HOROVOD_RETRY_MAX_S", "HOROVOD_RETRY_DEADLINE_S",
    "HOROVOD_RETRY_JITTER", "HOROVOD_FAULT_PLAN",
    # -- launcher / runner / spark
    "HOROVOD_CONTROLLER", "HOROVOD_SECRET_KEY", "HOROVOD_RUN_SECRET",
    "HOROVOD_RUN_SERVICE_ADDR", "HOROVOD_THREAD_AFFINITY",
    "HOROVOD_TPU_DISCOVERY_CACHE_TTL",
    "HOROVOD_LSF_ACCELERATORS_PER_NODE", "HOROVOD_LSF_CORES_PER_NODE",
    "HOROVOD_LSF_THREADS_PER_CORE",
    "HOROVOD_SPARK_ELASTIC_RUN_ID", "HOROVOD_SPARK_HOST_HASH",
    "HOROVOD_SPARK_START_TIMEOUT",
})


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    if v is None or v == "":
        return default
    try:
        return int(v)
    except ValueError:
        raise ValueError(f"{name} must be an integer, got {v!r}")


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    if v is None or v == "":
        return default
    try:
        return float(v)
    except ValueError:
        raise ValueError(f"{name} must be a float, got {v!r}")


def _env_bool(name: str, default: bool) -> bool:
    v = os.environ.get(name)
    if v is None or v == "":
        return default
    return v.lower() in ("1", "true", "yes", "on")


def _env_str(name: str, default: str) -> str:
    return os.environ.get(name, default)


@dataclasses.dataclass
class Config:
    """All runtime knobs, resolved once at ``init()`` time.

    Mirrors the env contract in the reference (``common.h:64-90``,
    ``gloo_context.cc:47-55``) plus TPU-mesh additions.
    """

    # -- process identity (set by the launcher; reference gloo_context.cc:47-55)
    rank: Optional[int] = None
    size: Optional[int] = None
    local_rank: Optional[int] = None
    local_size: Optional[int] = None
    cross_rank: Optional[int] = None
    cross_size: Optional[int] = None

    # -- coordination service (jax.distributed)
    coordinator_addr: Optional[str] = None

    # -- data-plane selection; the analogue of HOROVOD_GPU_OPERATIONS=NCCL
    tpu_operations: str = "XLA"

    # -- fusion / bucketing (reference: 64 MiB default, operations.cc:432)
    fusion_threshold_bytes: int = 64 * 1024 * 1024
    cycle_time_ms: float = 5.0   # advisory: eager bucket flush interval
    # bounds the compiled-executable caches (reference response-cache
    # capacity, response_cache.h): the in-memory AOT LRU held by each
    # DistributedTrainStep and the on-disk AOT store's entry count
    # (runtime/compile_cache.py) both evict past this many entries
    cache_capacity: int = 1024

    # -- warm-start compile cache (runtime/compile_cache.py):
    # persistent XLA cache + serialized AOT executables, shared across
    # process restarts and elastic generations
    compile_cache_enabled: bool = True
    compile_cache_dir: Optional[str] = None   # None → ~/.cache/horovod_tpu

    # -- input pipeline (horovod_tpu/data): prefetch queue bound and
    # host-side batch-assembly thread count (docs/data.md tuning notes)
    prefetch_depth: int = 2
    input_threads: int = 2

    # -- hierarchical collectives (ici/dcn mesh split)
    hierarchical_allreduce: bool = False
    hierarchical_allgather: bool = False

    # -- sharded gradient exchange (shard_optimizer_states paths):
    # bucket byte cap and hierarchy mode defaults, overridable per
    # train step; "auto" consults the mesh factorization at build time
    exchange_bucket_bytes: Optional[int] = None
    exchange_hierarchy: str = "auto"
    # low-precision wire codec dtype for the quantized (DCN) exchange
    # hop: "int8" (shared-scale s8, the PR 2 codec) or "fp8_e4m3"
    # (e4m3 floating wire — coarser mantissa, no shared-scale clipping
    # of outlier segments); docs/overlap.md
    exchange_wire_dtype: str = "int8"
    # per-level wire codec map for N-level (tree) meshes, the
    # "dcn=int8,ici=fp32" grammar of topology.parse_level_codecs();
    # None defers to exchange_wire_dtype on the outermost level only
    exchange_level_codecs: Optional[str] = None
    # combine operator of the sharded exchange: "sum" (plain RS), or
    # "adasum" — AdaSum adaptive summation (arXiv 2006.02924) on the
    # OUTERMOST topology level only, the large-batch scale-out
    # operator (docs/adasum.md)
    exchange_reduction: str = "sum"
    # tile-fused matmul⊗collective kernels (docs/fused_kernels.md):
    # "auto" enables on TPU only, "on"/"off" force; a new autotune
    # axis next to bucket bytes + hierarchy
    fused_collectives: str = "auto"

    # -- autotune (reference parameter_manager.h:58-78)
    autotune: bool = False
    autotune_log: Optional[str] = None
    autotune_warmup_samples: int = 3
    autotune_bayes_opt_max_samples: int = 20
    autotune_gaussian_process_noise: float = 0.8
    autotune_steps_per_sample: int = 10

    # -- telemetry plane (horovod_tpu/telemetry, docs/metrics.md):
    # metrics_enabled None = auto (on iff an exporter is configured);
    # port 0 = no Prometheus endpoint; log None = no JSONL snapshots
    metrics_enabled: Optional[bool] = None
    metrics_port: int = 0
    metrics_log: Optional[str] = None
    metrics_interval_s: float = 10.0
    run_id: Optional[str] = None

    # -- timeline (reference operations.cc:417-424)
    timeline_filename: Optional[str] = None
    timeline_mark_cycles: bool = False

    # -- stall inspector (reference stall_inspector.h:73-81)
    stall_check_enabled: bool = True
    stall_warning_time_seconds: float = 60.0
    stall_shutdown_time_seconds: float = 0.0  # 0 = never

    # -- adasum
    adasum_num_chunks: int = 1

    # -- elastic
    elastic_enabled: bool = False

    # -- training-state integrity plane (horovod_tpu/guard,
    # docs/guardian.md): numerics guardian + replica checksums +
    # rollback-and-replay + preemption grace
    guard_enabled: bool = False
    guard_policy: str = "rollback"       # skip_step | rollback | abort
    guard_check_interval: int = 10       # replica-checksum cadence (steps)
    guard_zscore: float = 6.0            # grad-norm spike threshold
    guard_warmup_steps: int = 10         # steps before spike detection arms
    guard_ema: float = 0.99              # EMA decay for the norm baseline
    guard_preempt: bool = True           # SIGTERM graceful-departure handler

    # -- chaos (horovod_tpu/faults): the seeded fault plan, parsed and
    # installed at init() — docs/faults.md for the grammar
    fault_plan: Optional[str] = None

    # -- mesh overrides: "8" or "2,4" → (dcn, ici) axis sizes
    mesh_shape: Optional[str] = None

    # -- parallelism plan (HOROVOD_PLAN, parallel/plan.py): the
    # declarative ShardingPlan grammar ("dp=4,tp=2", "dp=2,pp=2,v=2");
    # None = data-parallel over the runtime mesh, as before.
    # DistributedTrainStep picks this up when no explicit plan/mesh is
    # passed (docs/parallelism.md)
    plan: Optional[str] = None

    # -- memory plane (horovod_tpu/memory, docs/memory.md): remat tier
    # (None = model/step default), HBM budget the plan autotuner must
    # fit (None = device capacity), host offload of the ZeRO optimizer
    # shard + the offload ring depth (2 = double buffering)
    remat_policy: Optional[str] = None
    hbm_budget_bytes: Optional[int] = None
    offload_optimizer: bool = False
    offload_depth: int = 2

    # -- measured hardware model (analysis/cost_model.py,
    # docs/calibration.md): path to a bench --calibrate artifact and/or
    # a named preset ("v5e"/"v5p"/"v4"/"cpu-twin"); precedence is
    # calibration artifact > preset > device_kind preset > v5e
    calibration_path: Optional[str] = None
    hw_preset: Optional[str] = None

    # knobs the user set explicitly must not be autotuned
    # (reference "fixed" flag, operations.cc:436)
    fixed_knobs: frozenset = frozenset()

    @staticmethod
    def from_env() -> "Config":
        fixed = set()

        def mark(name: str, knob: str):
            if os.environ.get(name) not in (None, ""):
                fixed.add(knob)

        mark("HOROVOD_FUSION_THRESHOLD", "fusion_threshold_bytes")
        mark("HOROVOD_CYCLE_TIME", "cycle_time_ms")
        mark("HOROVOD_CACHE_CAPACITY", "cache_capacity")
        mark("HOROVOD_HIERARCHICAL_ALLREDUCE", "hierarchical_allreduce")
        mark("HOROVOD_HIERARCHICAL_ALLGATHER", "hierarchical_allgather")
        mark("HOROVOD_EXCHANGE_BUCKET_BYTES", "exchange_bucket_bytes")
        mark("HOROVOD_EXCHANGE_HIERARCHY", "exchange_hierarchy")
        mark("HOROVOD_EXCHANGE_WIRE_DTYPE", "exchange_wire_dtype")
        mark("HOROVOD_EXCHANGE_LEVEL_CODECS", "exchange_level_codecs")
        mark("HOROVOD_EXCHANGE_REDUCTION", "exchange_reduction")
        mark("HOROVOD_FUSED_COLLECTIVES", "fused_collectives")
        mark("HOROVOD_PLAN", "plan")
        mark("HOROVOD_REMAT_POLICY", "remat_policy")
        mark("HOROVOD_OFFLOAD_OPTIMIZER", "offload_optimizer")

        def opt_int(name: str) -> Optional[int]:
            v = os.environ.get(name)
            return int(v) if v not in (None, "") else None

        # Identity fallback for jsrun/mpirun launches: when the launcher
        # is JSM/PMIx (hvdrun --jsrun), ranks carry PMIX_*/OMPI_* vars
        # instead of the HOROVOD_* env contract (reference: jsrun workers
        # read identity through the MPI controller; js_run.py).
        jsm = None
        if opt_int("HOROVOD_RANK") is None:
            from horovod_tpu.runner.cluster_env import jsm_identity

            jsm = jsm_identity()

        return Config(
            rank=opt_int("HOROVOD_RANK") if jsm is None else jsm["rank"],
            size=opt_int("HOROVOD_SIZE") if jsm is None else jsm["size"],
            local_rank=opt_int("HOROVOD_LOCAL_RANK")
            if jsm is None else jsm["local_rank"],
            local_size=opt_int("HOROVOD_LOCAL_SIZE")
            if jsm is None else jsm["local_size"],
            cross_rank=opt_int("HOROVOD_CROSS_RANK"),
            cross_size=opt_int("HOROVOD_CROSS_SIZE"),
            coordinator_addr=os.environ.get("HOROVOD_COORDINATOR_ADDR"),
            tpu_operations=_env_str("HOROVOD_TPU_OPERATIONS", "XLA").upper(),
            fusion_threshold_bytes=_env_int(
                "HOROVOD_FUSION_THRESHOLD", 64 * 1024 * 1024),
            cycle_time_ms=_env_float("HOROVOD_CYCLE_TIME", 5.0),
            cache_capacity=_env_int("HOROVOD_CACHE_CAPACITY", 1024),
            compile_cache_enabled=_env_bool("HOROVOD_COMPILE_CACHE", True),
            compile_cache_dir=os.environ.get("HOROVOD_COMPILE_CACHE_DIR"),
            prefetch_depth=_env_int("HOROVOD_PREFETCH_DEPTH", 2),
            input_threads=_env_int("HOROVOD_INPUT_THREADS", 2),
            hierarchical_allreduce=_env_bool(
                "HOROVOD_HIERARCHICAL_ALLREDUCE", False),
            hierarchical_allgather=_env_bool(
                "HOROVOD_HIERARCHICAL_ALLGATHER", False),
            exchange_bucket_bytes=opt_int("HOROVOD_EXCHANGE_BUCKET_BYTES"),
            exchange_hierarchy=_env_str(
                "HOROVOD_EXCHANGE_HIERARCHY", "auto").lower(),
            exchange_wire_dtype=_env_str(
                "HOROVOD_EXCHANGE_WIRE_DTYPE", "int8").lower(),
            exchange_level_codecs=(
                os.environ.get("HOROVOD_EXCHANGE_LEVEL_CODECS") or None),
            exchange_reduction=_env_str(
                "HOROVOD_EXCHANGE_REDUCTION", "sum").lower(),
            fused_collectives=_env_str(
                "HOROVOD_FUSED_COLLECTIVES", "auto").lower(),
            autotune=_env_bool("HOROVOD_AUTOTUNE", False),
            autotune_log=os.environ.get("HOROVOD_AUTOTUNE_LOG"),
            autotune_warmup_samples=_env_int("HOROVOD_AUTOTUNE_WARMUP_SAMPLES", 3),
            autotune_bayes_opt_max_samples=_env_int(
                "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES", 20),
            autotune_gaussian_process_noise=_env_float(
                "HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE", 0.8),
            autotune_steps_per_sample=_env_int(
                "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE", 10),
            metrics_enabled=(None if os.environ.get("HOROVOD_METRICS")
                             in (None, "") else
                             _env_bool("HOROVOD_METRICS", False)),
            metrics_port=_env_int("HOROVOD_METRICS_PORT", 0),
            metrics_log=os.environ.get("HOROVOD_METRICS_LOG"),
            metrics_interval_s=_env_float("HOROVOD_METRICS_INTERVAL_S",
                                          10.0),
            run_id=os.environ.get("HOROVOD_RUN_ID"),
            timeline_filename=os.environ.get("HOROVOD_TIMELINE"),
            timeline_mark_cycles=_env_bool("HOROVOD_TIMELINE_MARK_CYCLES", False),
            stall_check_enabled=not _env_bool("HOROVOD_STALL_CHECK_DISABLE", False),
            stall_warning_time_seconds=_env_float(
                "HOROVOD_STALL_CHECK_TIME_SECONDS", 60.0),
            stall_shutdown_time_seconds=_env_float(
                "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS", 0.0),
            adasum_num_chunks=_env_int("HOROVOD_ADASUM_NUM_CHUNKS", 1),
            elastic_enabled=_env_bool("HOROVOD_ELASTIC", False),
            guard_enabled=_env_bool("HOROVOD_GUARD", False),
            guard_policy=_env_str("HOROVOD_GUARD_POLICY",
                                  "rollback").lower(),
            guard_check_interval=_env_int("HOROVOD_GUARD_CHECK_INTERVAL",
                                          10),
            guard_zscore=_env_float("HOROVOD_GUARD_ZSCORE", 6.0),
            guard_warmup_steps=_env_int("HOROVOD_GUARD_WARMUP_STEPS", 10),
            guard_ema=_env_float("HOROVOD_GUARD_EMA", 0.99),
            guard_preempt=_env_bool("HOROVOD_GUARD_PREEMPT", True),
            fault_plan=os.environ.get("HOROVOD_FAULT_PLAN"),
            mesh_shape=os.environ.get("HOROVOD_TPU_MESH_SHAPE"),
            plan=os.environ.get("HOROVOD_PLAN"),
            remat_policy=(os.environ.get("HOROVOD_REMAT_POLICY") or
                          None),
            hbm_budget_bytes=opt_int("HOROVOD_HBM_BUDGET_BYTES"),
            offload_optimizer=_env_bool("HOROVOD_OFFLOAD_OPTIMIZER",
                                        False),
            offload_depth=_env_int("HOROVOD_OFFLOAD_DEPTH", 2),
            calibration_path=(
                os.environ.get("HOROVOD_CALIBRATION_PATH") or None),
            hw_preset=(os.environ.get("HOROVOD_HW_PRESET") or None),
            fixed_knobs=frozenset(fixed),
        )
