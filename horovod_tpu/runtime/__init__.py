"""Core runtime: global state, config, mesh topology.

TPU-native replacement for the reference's L1/L2 layers
(``horovod/common/operations.cc``, ``global_state.h``, ``controller.cc``):
the negotiation plane disappears under SPMD; what remains is the process
singleton, the env-var contract and the device mesh.
"""

from horovod_tpu.runtime import compile_cache
from horovod_tpu.runtime.config import Config
from horovod_tpu.runtime.state import (
    GlobalState,
    NotInitializedError,
    global_state,
    init,
    is_initialized,
    shutdown,
)
from horovod_tpu.runtime.topology import AXIS_DCN, AXIS_ICI, GLOBAL_AXES, build_mesh

__all__ = [
    "Config",
    "compile_cache",
    "GlobalState",
    "NotInitializedError",
    "global_state",
    "init",
    "is_initialized",
    "shutdown",
    "AXIS_DCN",
    "AXIS_ICI",
    "GLOBAL_AXES",
    "build_mesh",
]
