"""Elastic-capable coordination-service management.

Plain ``jax.distributed.initialize`` has two properties that are fatal
for elastic training:

1. the coordination service is hosted by worker ``process_id == 0`` — if
   that worker dies, every other worker's error-poll RPC fails and
2. the default missed-heartbeat handler terminates the process
   (``LOG(QFATAL)`` in xla's ``client.h:80``) instead of raising.

The net effect is that a single worker death kills the entire world,
which is exactly what elastic mode exists to survive.  The reference has
the same split for the same reason: its rendezvous server lives in the
*launcher* (``gloo_run.py:213 RendezvousServer``), never in a worker,
so worker death cannot take the control plane with it.

This module mirrors that topology for the JAX runtime:

* the elastic **driver** (launcher process) hosts one coordination
  service per world generation (:func:`start_coordination_service`) at
  the per-generation coordinator address it already hands out through
  the rendezvous RPC;
* **workers** connect with :func:`connect_elastic_client`, a distributed
  runtime client whose missed-heartbeat callback logs-and-flags instead
  of terminating — a dead peer then surfaces as a catchable collective
  error (gloo "Connection closed by peer" → ``HorovodInternalError``)
  and the elastic retry loop recovers;
* :func:`disconnect_elastic_client` detaches the client on reset without
  the default shutdown barrier (which would block on dead peers).

Non-elastic runs keep the stock ``jax.distributed.initialize`` path
(worker 0 hosts the service) — no behavior change.

The implementation uses ``jax._src.distributed`` internals (the public
API cannot host a service without also being process 0, nor install a
heartbeat callback); pinned against the image's jax 0.9.
"""

from __future__ import annotations

import threading
from typing import Optional

from horovod_tpu.utils import logging as hvd_logging

# snappy failure detection for elastic worlds; stock default is 100 s
DEFAULT_HEARTBEAT_TIMEOUT_S = 10


class CoordinationService:
    """Driver-side coordination service handle (one per generation)."""

    def __init__(self, port: int, num_processes: int,
                 heartbeat_timeout: int = DEFAULT_HEARTBEAT_TIMEOUT_S):
        from jax._src import distributed as dist

        self._service = dist._jax.get_distributed_runtime_service(
            f"0.0.0.0:{port}", num_processes,
            heartbeat_timeout=heartbeat_timeout)
        self.port = port
        self.num_processes = num_processes

    def shutdown(self) -> None:
        try:
            self._service.shutdown()
        except Exception as e:  # pragma: no cover - teardown best-effort
            hvd_logging.debug("coordination service shutdown: %s", e)


def start_coordination_service(
        port: int, num_processes: int,
        heartbeat_timeout: int = DEFAULT_HEARTBEAT_TIMEOUT_S,
) -> CoordinationService:
    return CoordinationService(port, num_processes, heartbeat_timeout)


_client_lock = threading.Lock()
_live_client = None
_client_generation = 0


def connect_elastic_client(coordinator_addr: str, num_processes: int,
                           process_id: int,
                           heartbeat_timeout: int =
                           DEFAULT_HEARTBEAT_TIMEOUT_S,
                           init_timeout: int = 120) -> None:
    """Worker-side: join the driver-hosted coordination service.

    Installs the client into ``jax._src.distributed.global_state`` so
    backend creation (gloo KV exchange, ``jax.process_index``) sees a
    normal distributed world.
    """
    global _live_client, _client_generation
    from jax._src import distributed as dist

    with _client_lock:
        _client_generation += 1
        my_gen = _client_generation

    def on_missed_heartbeat(status, coordinator_reported_failure):
        # runs on a gRPC thread: never raise, never terminate.  Stale
        # callbacks from a replaced generation's client are silenced.
        with _client_lock:
            stale = my_gen != _client_generation
        if not stale:
            hvd_logging.warning(
                "elastic: coordination service reports failure "
                "(coordinator_reported=%s): %s — a peer likely died; the "
                "next collective will raise and trigger recovery",
                coordinator_reported_failure, status)

    def _connect():
        # chaos hook + retry: a refused/reset connect (driver mid-bind,
        # generation race) is retried with backoff+jitter on a FRESH
        # client — a half-connected client must not be reused
        from horovod_tpu import faults

        faults.inject("coordinator.connect")
        c = dist._jax.get_distributed_runtime_client(
            coordinator_addr, process_id,
            init_timeout=init_timeout,
            heartbeat_timeout=heartbeat_timeout,
            shutdown_timeout=5,
            use_compression=True,
            recoverable=True,
            missed_heartbeat_callback=on_missed_heartbeat,
            shutdown_on_destruction=False)
        c.connect()
        return c

    from horovod_tpu.runtime.retry import RetryPolicy

    client = RetryPolicy(name="coordinator-connect",
                         retry_on=(OSError, TimeoutError),
                         deadline_s=float(init_timeout)).call(_connect)

    state = dist.global_state
    state.client = client
    state.process_id = process_id
    state.num_processes = num_processes
    state.coordinator_address = coordinator_addr
    with _client_lock:
        _live_client = client
    hvd_logging.info(
        "elastic: connected to driver-hosted coordination service %s as "
        "process %d of %d", coordinator_addr, process_id, num_processes)


def disconnect_elastic_client() -> None:
    """Detach from the current generation's service.

    ``client.shutdown()`` must run (a live client whose service died
    later throws ``std::bad_cast`` from its poll thread → process
    terminate), but it must not block the reset: the client is created
    with ``shutdown_timeout=5`` and ``recoverable=True`` so the shutdown
    barrier does not wait on dead peers; failures are swallowed."""
    global _live_client
    from jax._src import distributed as dist

    with _client_lock:
        client, _live_client = _live_client, None
        # advance the generation so late heartbeat callbacks from the old
        # client recognize themselves as stale
        global _client_generation
        _client_generation += 1
    state = dist.global_state
    state.client = None
    state.process_id = 0
    state.num_processes = 1
    state.coordinator_address = None
    state.service = None
    if client is not None:
        try:
            client.shutdown()
        except Exception as e:
            hvd_logging.debug("elastic: client shutdown: %s", e)


def elastic_client_active() -> bool:
    with _client_lock:
        return _live_client is not None
