"""Device-mesh topology: the TPU-native replacement for communicator splits.

The reference models topology as three MPI communicators — GLOBAL, LOCAL
(shared-memory node, ``MPI_Comm_split_type`` in ``mpi/mpi_context.cc:147``)
and CROSS (one rank per node, ``:156``; enum in ``common.h:113-117``) — and
routes hierarchical collectives NCCL-inside × MPI-across
(``ops/nccl_operations.cc:191-341``).

On TPU the same structure is a 2-D ``jax.sharding.Mesh``:

* ``ici`` axis — chips within a slice, connected by the inter-chip
  interconnect (the LOCAL communicator analogue; collectives here are
  cheapest and ride the torus).
* ``dcn`` axis — across slices/hosts over the data-center network (the CROSS
  communicator analogue).

A global collective is a reduction over both axes (``axis_name=("dcn",
"ici")``); XLA lowers it to the hierarchical reduce-scatter/all-gather
pattern the reference hand-codes, so ``NCCLHierarchicalAllreduce`` needs no
manual equivalent.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical axis names.  GLOBAL/LOCAL/CROSS from the reference's
# Communicator enum (common.h:113-117) map to:
AXIS_DCN = "dcn"      # CROSS: across slices / hosts
AXIS_ICI = "ici"      # LOCAL: chips within a slice
GLOBAL_AXES = (AXIS_DCN, AXIS_ICI)   # GLOBAL: every chip


def _detect_num_slices(devices: Sequence[jax.Device]) -> int:
    """Count distinct TPU slices (falls back to process count off-TPU)."""
    slice_ids = set()
    for d in devices:
        sid = getattr(d, "slice_index", None)
        if sid is None:
            sid = d.process_index
        slice_ids.add(sid)
    return max(1, len(slice_ids))


def build_mesh(mesh_shape: Optional[str] = None,
               devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build the (dcn, ici) runtime mesh over all addressable-or-global devices.

    ``mesh_shape`` (from ``HOROVOD_TPU_MESH_SHAPE``) may force the split:
    ``"2,4"`` → 2 slices × 4 chips.  A single number means a flat ici mesh.
    By default the dcn extent is the detected slice count.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)

    if mesh_shape:
        parts = [int(p) for p in mesh_shape.split(",") if p.strip()]
        if len(parts) == 1:
            dcn, ici = 1, parts[0]
        elif len(parts) == 2:
            dcn, ici = parts
        else:
            raise ValueError(
                f"HOROVOD_TPU_MESH_SHAPE must be 'ici' or 'dcn,ici', got {mesh_shape!r}")
        if dcn * ici != n:
            raise ValueError(
                f"mesh shape {dcn}x{ici} does not cover {n} devices")
    else:
        dcn = _detect_num_slices(devices)
        if n % dcn != 0:
            dcn = 1   # heterogeneous slice sizes: flatten
        ici = n // dcn

    if dcn > 1:
        try:
            from jax.experimental import mesh_utils

            dev_array = mesh_utils.create_hybrid_device_mesh(
                (ici,), (dcn,), devices=devices)
            # hybrid mesh returns (dcn, ici)-shaped array already
            dev_array = np.asarray(dev_array).reshape(dcn, ici)
        except Exception:
            dev_array = np.asarray(devices).reshape(dcn, ici)
    else:
        dev_array = np.asarray(devices).reshape(dcn, ici)

    return Mesh(dev_array, GLOBAL_AXES)


def mesh_size(mesh: Mesh) -> int:
    return int(np.prod(list(mesh.shape.values())))


#: Valid values of the exchange ``hierarchy`` knob
#: (DistributedOptimizer / DistributedTrainStep / sharded exchange).
HIERARCHY_MODES = ("auto", "flat", "two_level")


def num_slices(devices: Optional[Sequence[jax.Device]] = None) -> int:
    """Public form of the slice detector :func:`build_mesh` uses for the
    dcn extent: distinct ``slice_index`` values (process count off-TPU)."""
    return _detect_num_slices(jax.devices() if devices is None else devices)


#: Valid values of the generalized topology knob: the exchange
#: ``hierarchy`` vocabulary plus ``"tree"``, the explicit N-level form
#: (:func:`resolve_topology`).
TOPOLOGY_MODES = HIERARCHY_MODES + ("tree",)

#: Canonical level names of an N-level tree, INNERMOST first — the
#: chip < slice < pod < cluster containment order (docs/calibration.md
#: "N-level topology").  A 2-axis mesh keeps the historical
#: (``ici``, ``dcn``) names so every existing artifact field, HLO
#: guard and parity pin reads unchanged.
DEFAULT_LEVEL_NAMES = ("chip", "slice", "pod", "cluster")

#: Per-level wire-codec vocabulary (``HOROVOD_EXCHANGE_LEVEL_CODECS``):
#: dtype name → wire bits (None = full precision).  Mirrors
#: ``ops.collectives.WIRE_DTYPES`` + fp32 by value (collectives
#: imports this module, not the reverse).
LEVEL_CODEC_BITS = {"fp32": None, "int8": 8, "fp8_e4m3": 8}


def parse_level_codecs(spec: Optional[str]) -> Dict[str, Optional[int]]:
    """Parse the per-level codec knob grammar,
    ``"level=dtype,level=dtype"`` (e.g. ``"dcn=int8,ici=fp32"`` or
    ``"pod=fp8_e4m3"``), into ``{level name: wire bits}``.  Unknown
    dtypes raise; an empty/None spec is ``{}`` (level defaults rule:
    codec on the outermost hop only)."""
    out: Dict[str, Optional[int]] = {}
    if not spec:
        return out
    for item in str(spec).split(","):
        item = item.strip()
        if not item:
            continue
        name, sep, dtype = item.partition("=")
        name, dtype = name.strip(), dtype.strip().lower()
        if not sep or not name or dtype not in LEVEL_CODEC_BITS:
            raise ValueError(
                f"bad level codec term {item!r}: expected "
                f"level=dtype with dtype in "
                f"{sorted(LEVEL_CODEC_BITS)}")
        if name in out:
            raise ValueError(f"duplicate level {name!r} in {spec!r}")
        out[name] = LEVEL_CODEC_BITS[dtype]
    return out


@dataclasses.dataclass(frozen=True)
class TopologyLevel:
    """One level of the resolved topology tree.

    ``name`` doubles as the mesh axis name the exchange scopes its
    collectives to (``axes`` widens it for the degenerate flat tree,
    whose single level spans every mesh axis).  ``wire_bits`` is the
    codec width on this level's hop (None = full precision) — the
    per-level generalization of "int8 on the DCN phase only"."""

    name: str
    extent: int
    wire_bits: Optional[int] = None
    axes: Optional[Tuple[str, ...]] = None

    @property
    def axis_spec(self):
        """The axis argument collectives scope to at this level."""
        return self.axes if self.axes is not None else self.name


@dataclasses.dataclass(frozen=True)
class TopologyTree:
    """The resolved N-level topology: levels INNERMOST first (chip <
    slice < pod < cluster), so ``levels[0]`` rides the fastest fabric
    and ``levels[-1]`` the slowest.  The 2-level runtime mesh resolves
    to ``(ici, dcn)`` and the historical ``"flat"``/``"two_level"``
    modes are the 1- and 2-deep degenerate cases — every consumer of
    :func:`resolve_hierarchy` keeps its exact behavior
    (:attr:`mode`)."""

    levels: Tuple[TopologyLevel, ...]

    def __post_init__(self):
        if not self.levels:
            raise ValueError("a topology tree needs >= 1 level")

    @property
    def world(self) -> int:
        n = 1
        for lv in self.levels:
            n *= lv.extent
        return n

    @property
    def names(self) -> Tuple[str, ...]:
        return tuple(lv.name for lv in self.levels)

    @property
    def mode(self) -> str:
        """The legacy hierarchy vocabulary this tree degenerates to:
        1 level = ``"flat"``, 2 = ``"two_level"``, more = ``"tree"``."""
        return {1: "flat", 2: "two_level"}.get(len(self.levels),
                                               "tree")

    def effective(self) -> "TopologyTree":
        """The tree with extent-1 levels dropped (they move no bytes);
        keeps >= 1 level so a 1-device world stays representable."""
        keep = tuple(lv for lv in self.levels if lv.extent > 1)
        return TopologyTree(levels=keep or self.levels[:1])

    def pricing_levels(self) -> Tuple[Tuple[str, int,
                                            Optional[int]], ...]:
        """The ``(name, extent, wire_bits)`` triples the stdlib cost
        model prices (``analysis/cost_model.exchange_wire_by_level``,
        ``collective_wire_by_level(topology=...)``)."""
        return tuple((lv.name, lv.extent, lv.wire_bits)
                     for lv in self.levels)


def resolve_topology(hierarchy: str,
                     axis_sizes: Sequence[int],
                     axis_names: Optional[Sequence[str]] = None,
                     wire_bits: Optional[int] = None,
                     level_codecs: Optional[Dict[str,
                                                 Optional[int]]] = None
                     ) -> TopologyTree:
    """Resolve the topology knob against the mesh factorization into an
    N-level :class:`TopologyTree` — the generalization of
    :func:`resolve_hierarchy` from the hard-coded ICI/DCN pair to
    chip < slice < pod < cluster trees.

    ``axis_sizes``/``axis_names`` are in MESH order (outermost first,
    the existing ``(dcn, ici)`` convention); the tree stores levels
    innermost-first.  Default names: ``("ici",)`` for one axis,
    ``("dcn", "ici")`` for two (the historical mesh), the outermost-
    first reversal of :data:`DEFAULT_LEVEL_NAMES` beyond that.

    * ``"flat"`` — one level spanning every axis: a single collective
      scope over the whole world (``wire_bits`` compresses that whole
      wire, matching the flat quantized path).
    * ``"two_level"`` — demands exactly 2 axes (an explicit request
      must not silently flatten) and scopes ``wire_bits`` to the outer
      hop only.
    * ``"tree"`` — every axis is a level; ``wire_bits`` rides the
      outermost (slowest) hop only.
    * ``"auto"`` — ``two_level``/``tree`` exactly when >= 2 axes have
      extent > 1 (size-1 axes are dropped: they move no bytes), else
      ``flat`` — the same decision rule :func:`resolve_hierarchy`
      makes, extended to N axes.

    ``level_codecs`` (the parsed ``HOROVOD_EXCHANGE_LEVEL_CODECS``
    knob, :func:`parse_level_codecs`) overrides the per-level codec
    width by level name — fp8/int8 on any hop, not just the slowest.
    """
    if hierarchy not in TOPOLOGY_MODES:
        raise ValueError(
            f"hierarchy must be one of {TOPOLOGY_MODES}, got "
            f"{hierarchy!r}")
    sizes = [int(s) for s in axis_sizes]
    if not sizes:
        raise ValueError("axis_sizes must name >= 1 mesh axis")
    if axis_names is None:
        if len(sizes) == 1:
            axis_names = (AXIS_ICI,)
        elif len(sizes) == 2:
            axis_names = GLOBAL_AXES
        elif len(sizes) <= len(DEFAULT_LEVEL_NAMES):
            axis_names = tuple(reversed(
                DEFAULT_LEVEL_NAMES[:len(sizes)]))
        else:
            raise ValueError(
                f"{len(sizes)} axes exceed the default level names "
                f"{DEFAULT_LEVEL_NAMES}; pass axis_names explicitly")
    names = tuple(str(n) for n in axis_names)
    if len(names) != len(sizes):
        raise ValueError(
            f"axis_names {names} does not match {len(sizes)} axis "
            f"size(s)")
    codecs = dict(level_codecs or {})
    unknown = set(codecs) - set(names)
    if unknown:
        raise ValueError(
            f"level codec(s) for unknown level(s) {sorted(unknown)}: "
            f"tree levels are {list(reversed(names))}")
    # innermost-first
    inner_first = list(zip(reversed(names), reversed(sizes)))

    def _level(i, name, extent, default_bits):
        return TopologyLevel(
            name=name, extent=extent,
            wire_bits=codecs.get(name, default_bits))

    if hierarchy == "two_level" and len(sizes) != 2:
        raise ValueError(
            "hierarchy='two_level' needs a 2-axis (dp_outer, "
            f"dp_inner) data-parallel spec, got {len(sizes)} axis/es")
    if hierarchy == "auto":
        effective = [s for s in sizes if s > 1]
        hierarchy = "flat" if len(effective) < 2 else \
            ("two_level" if len(sizes) == 2 else "tree")
    if hierarchy == "flat":
        world = 1
        for s in sizes:
            world *= s
        name = names[-1] if len(names) == 1 else "flat"
        lv = TopologyLevel(name=name, extent=world,
                           wire_bits=codecs.get(name, wire_bits),
                           axes=names if len(names) > 1 else None)
        return TopologyTree(levels=(lv,))
    levels = tuple(
        _level(i, name, extent,
               wire_bits if i == len(inner_first) - 1 else None)
        for i, (name, extent) in enumerate(inner_first))
    return TopologyTree(levels=levels)


def resolve_hierarchy(hierarchy: str, axis_sizes: Sequence[int]) -> str:
    """Resolve the ``hierarchy="auto"|"flat"|"two_level"`` knob against
    the data-parallel axis factorization — the decision rule of the
    two-level exchange, now the 2-axis degenerate case of
    :func:`resolve_topology`.

    ``axis_sizes`` are the extents of the dp axis spec in mesh order,
    i.e. ``(dp_outer, dp_inner)`` = ``(dcn, ici)`` for the runtime mesh.
    ``"auto"`` picks ``"two_level"`` exactly when the factorization is
    real — two axes, both extent > 1 — because that is when the two
    fabrics are actually distinct: a 1-slice mesh (dcn=1) has no DCN hop
    to scope, and a 1-chip-per-slice mesh has no ICI phase to exploit,
    so both degenerate to ``"flat"`` (identical wire, one less collective
    scope to schedule).  ``"two_level"`` demands the 2-D factorization
    and raises otherwise — an explicit request must not silently flatten.
    """
    if hierarchy not in HIERARCHY_MODES:
        raise ValueError(
            f"hierarchy must be one of {HIERARCHY_MODES}, got "
            f"{hierarchy!r}")
    mode = resolve_topology(hierarchy, axis_sizes).mode
    # legacy contract: this resolver only ever answered flat|two_level
    # (an auto'd >2-axis spec flattened before trees existed)
    return "flat" if mode == "tree" else mode
