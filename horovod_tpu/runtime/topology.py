"""Device-mesh topology: the TPU-native replacement for communicator splits.

The reference models topology as three MPI communicators — GLOBAL, LOCAL
(shared-memory node, ``MPI_Comm_split_type`` in ``mpi/mpi_context.cc:147``)
and CROSS (one rank per node, ``:156``; enum in ``common.h:113-117``) — and
routes hierarchical collectives NCCL-inside × MPI-across
(``ops/nccl_operations.cc:191-341``).

On TPU the same structure is a 2-D ``jax.sharding.Mesh``:

* ``ici`` axis — chips within a slice, connected by the inter-chip
  interconnect (the LOCAL communicator analogue; collectives here are
  cheapest and ride the torus).
* ``dcn`` axis — across slices/hosts over the data-center network (the CROSS
  communicator analogue).

A global collective is a reduction over both axes (``axis_name=("dcn",
"ici")``); XLA lowers it to the hierarchical reduce-scatter/all-gather
pattern the reference hand-codes, so ``NCCLHierarchicalAllreduce`` needs no
manual equivalent.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical axis names.  GLOBAL/LOCAL/CROSS from the reference's
# Communicator enum (common.h:113-117) map to:
AXIS_DCN = "dcn"      # CROSS: across slices / hosts
AXIS_ICI = "ici"      # LOCAL: chips within a slice
GLOBAL_AXES = (AXIS_DCN, AXIS_ICI)   # GLOBAL: every chip


def _detect_num_slices(devices: Sequence[jax.Device]) -> int:
    """Count distinct TPU slices (falls back to process count off-TPU)."""
    slice_ids = set()
    for d in devices:
        sid = getattr(d, "slice_index", None)
        if sid is None:
            sid = d.process_index
        slice_ids.add(sid)
    return max(1, len(slice_ids))


def build_mesh(mesh_shape: Optional[str] = None,
               devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build the (dcn, ici) runtime mesh over all addressable-or-global devices.

    ``mesh_shape`` (from ``HOROVOD_TPU_MESH_SHAPE``) may force the split:
    ``"2,4"`` → 2 slices × 4 chips.  A single number means a flat ici mesh.
    By default the dcn extent is the detected slice count.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)

    if mesh_shape:
        parts = [int(p) for p in mesh_shape.split(",") if p.strip()]
        if len(parts) == 1:
            dcn, ici = 1, parts[0]
        elif len(parts) == 2:
            dcn, ici = parts
        else:
            raise ValueError(
                f"HOROVOD_TPU_MESH_SHAPE must be 'ici' or 'dcn,ici', got {mesh_shape!r}")
        if dcn * ici != n:
            raise ValueError(
                f"mesh shape {dcn}x{ici} does not cover {n} devices")
    else:
        dcn = _detect_num_slices(devices)
        if n % dcn != 0:
            dcn = 1   # heterogeneous slice sizes: flatten
        ici = n // dcn

    if dcn > 1:
        try:
            from jax.experimental import mesh_utils

            dev_array = mesh_utils.create_hybrid_device_mesh(
                (ici,), (dcn,), devices=devices)
            # hybrid mesh returns (dcn, ici)-shaped array already
            dev_array = np.asarray(dev_array).reshape(dcn, ici)
        except Exception:
            dev_array = np.asarray(devices).reshape(dcn, ici)
    else:
        dev_array = np.asarray(devices).reshape(dcn, ici)

    return Mesh(dev_array, GLOBAL_AXES)


def mesh_size(mesh: Mesh) -> int:
    return int(np.prod(list(mesh.shape.values())))


#: Valid values of the exchange ``hierarchy`` knob
#: (DistributedOptimizer / DistributedTrainStep / sharded exchange).
HIERARCHY_MODES = ("auto", "flat", "two_level")


def num_slices(devices: Optional[Sequence[jax.Device]] = None) -> int:
    """Public form of the slice detector :func:`build_mesh` uses for the
    dcn extent: distinct ``slice_index`` values (process count off-TPU)."""
    return _detect_num_slices(jax.devices() if devices is None else devices)


def resolve_hierarchy(hierarchy: str, axis_sizes: Sequence[int]) -> str:
    """Resolve the ``hierarchy="auto"|"flat"|"two_level"`` knob against
    the data-parallel axis factorization — the decision rule of the
    two-level exchange.

    ``axis_sizes`` are the extents of the dp axis spec in mesh order,
    i.e. ``(dp_outer, dp_inner)`` = ``(dcn, ici)`` for the runtime mesh.
    ``"auto"`` picks ``"two_level"`` exactly when the factorization is
    real — two axes, both extent > 1 — because that is when the two
    fabrics are actually distinct: a 1-slice mesh (dcn=1) has no DCN hop
    to scope, and a 1-chip-per-slice mesh has no ICI phase to exploit,
    so both degenerate to ``"flat"`` (identical wire, one less collective
    scope to schedule).  ``"two_level"`` demands the 2-D factorization
    and raises otherwise — an explicit request must not silently flatten.
    """
    if hierarchy not in HIERARCHY_MODES:
        raise ValueError(
            f"hierarchy must be one of {HIERARCHY_MODES}, got "
            f"{hierarchy!r}")
    sizes = [int(s) for s in axis_sizes]
    factored = len(sizes) == 2 and all(s > 1 for s in sizes)
    if hierarchy == "two_level":
        if len(sizes) != 2:
            raise ValueError(
                "hierarchy='two_level' needs a 2-axis (dp_outer, "
                f"dp_inner) data-parallel spec, got {len(sizes)} axis/es")
        return "two_level"
    if hierarchy == "flat":
        return "flat"
    return "two_level" if factored else "flat"
