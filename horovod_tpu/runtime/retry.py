"""Unified retry policy: exponential backoff + full jitter + deadline.

Before this module every transient-failure path hand-rolled its own
sleep loop (worker rendezvous polling, coordinator probing, discovery
script execution, checkpoint I/O) with different — and mostly absent —
backoff behavior.  :class:`RetryPolicy` is the one implementation they
all share: capped exponential backoff with *full jitter* (each sleep is
uniform in ``[0, min(max_s, base_s * 2**attempt)]`` — the AWS
architecture-blog result that full jitter minimizes contention when a
fleet retries the same endpoint at once) under both an attempt budget
and a wall-clock deadline.

Env knobs (the process-wide defaults; every call site may override):

=================================  ========  ===============================
``HOROVOD_RETRY_MAX_ATTEMPTS``     5         total tries (1 = no retry)
``HOROVOD_RETRY_BASE_S``           0.1       first backoff cap, seconds
``HOROVOD_RETRY_MAX_S``            5.0       per-sleep cap, seconds
``HOROVOD_RETRY_DEADLINE_S``       60.0      total elapsed budget (0 = none)
``HOROVOD_RETRY_JITTER``           1         0 = deterministic full backoff
=================================  ========  ===============================

Only exceptions in ``retry_on`` are retried — everything else
propagates immediately (a programming error must never be masked by
backoff).  ``seed``/``clock``/``sleep`` are injectable for
deterministic tests.
"""

from __future__ import annotations

import random
import time
from typing import Any, Callable, Optional, Tuple, Type

from horovod_tpu.runtime.config import _env_bool, _env_float, _env_int
from horovod_tpu.utils import logging as hvd_logging

DEFAULT_RETRY_ON: Tuple[Type[BaseException], ...] = (OSError, TimeoutError)


def _tel_counter(name: str, help: str):
    # lazy import: retry is reached from config/bootstrap paths where
    # the telemetry package may not be loaded yet
    from horovod_tpu import telemetry

    return telemetry.counter(name, help)


class RetryPolicy:
    def __init__(self,
                 max_attempts: Optional[int] = None,
                 base_s: Optional[float] = None,
                 max_s: Optional[float] = None,
                 deadline_s: Optional[float] = None,
                 jitter: Optional[bool] = None,
                 retry_on: Tuple[Type[BaseException], ...] = DEFAULT_RETRY_ON,
                 name: str = "retry",
                 seed: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep):
        self.max_attempts = max(
            max_attempts if max_attempts is not None
            else _env_int("HOROVOD_RETRY_MAX_ATTEMPTS", 5), 1)
        self.base_s = base_s if base_s is not None \
            else _env_float("HOROVOD_RETRY_BASE_S", 0.1)
        self.max_s = max_s if max_s is not None \
            else _env_float("HOROVOD_RETRY_MAX_S", 5.0)
        self.deadline_s = deadline_s if deadline_s is not None \
            else _env_float("HOROVOD_RETRY_DEADLINE_S", 60.0)
        self.jitter = jitter if jitter is not None \
            else _env_bool("HOROVOD_RETRY_JITTER", True)
        self.retry_on = retry_on
        self.name = name
        self._rng = random.Random(seed)
        self._clock = clock
        self._sleep = sleep

    def backoff_s(self, attempt: int) -> float:
        """Sleep before retry number ``attempt+1`` (attempt is 0-based)."""
        cap = min(self.max_s, self.base_s * (2.0 ** attempt))
        return self._rng.uniform(0.0, cap) if self.jitter else cap

    def call(self, fn: Callable, *args, **kwargs) -> Any:
        """Run ``fn`` under this policy; re-raises the last retryable
        error once the attempt budget or the deadline is exhausted."""
        start = self._clock()
        last: Optional[BaseException] = None
        for attempt in range(self.max_attempts):
            try:
                return fn(*args, **kwargs)
            except self.retry_on as e:  # noqa: PERF203 — the point
                last = e
                _tel_counter(
                    "hvd_retry_attempts_total",
                    "failed attempts under a retry policy").inc(
                        policy=self.name)
                if attempt + 1 >= self.max_attempts:
                    break
                delay = self.backoff_s(attempt)
                if self.deadline_s > 0:
                    remaining = self.deadline_s - (self._clock() - start)
                    if remaining <= 0:
                        hvd_logging.warning(
                            "%s: deadline %.1fs exhausted after %d "
                            "attempt(s): %s", self.name, self.deadline_s,
                            attempt + 1, e)
                        _tel_counter(
                            "hvd_retry_exhausted_total",
                            "retry policies giving up (attempts or "
                            "deadline)").inc(policy=self.name)
                        raise
                    # the final sleep is clamped to the remaining budget
                    # — a full-jitter draw can no longer overshoot the
                    # deadline, and the budget's tail still buys one
                    # last attempt
                    delay = min(delay, remaining)
                hvd_logging.warning(
                    "%s: attempt %d/%d failed (%s: %s) — retrying in "
                    "%.2fs", self.name, attempt + 1, self.max_attempts,
                    type(e).__name__, e, delay)
                _tel_counter(
                    "hvd_retry_backoff_seconds_total",
                    "cumulative backoff sleep per policy").inc(
                        delay, policy=self.name)
                self._sleep(delay)
        assert last is not None
        _tel_counter(
            "hvd_retry_exhausted_total",
            "retry policies giving up (attempts or deadline)").inc(
                policy=self.name)
        raise last


def retry_call(fn: Callable, *args,
               name: str = "retry",
               retry_on: Tuple[Type[BaseException], ...] = DEFAULT_RETRY_ON,
               **kwargs) -> Any:
    """One-shot convenience: ``fn(*args, **kwargs)`` under the env-default
    :class:`RetryPolicy`."""
    return RetryPolicy(retry_on=retry_on, name=name).call(
        fn, *args, **kwargs)
