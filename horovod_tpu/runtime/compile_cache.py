"""Warm-start compile cache: persistent XLA cache + AOT executable store.

The steady-state hot loop never pays for compilation, but *time to
first step* does: the flagship bench models spend 42-51 s in XLA before
the first optimizer update, and an elastic restart re-pays the full
amount while the rest of the fleet idles (PERF_NOTES round 8).  The
reference framework has no analogue — its per-tensor negotiation plane
is interpreted — but the SPMD re-design moved the whole training step
into one compiled program, so compile latency became an operational
cost this module takes off the training clock.  Two layers:

1. **JAX persistent compilation cache** — ``enable_persistent_cache()``
   points ``jax_compilation_cache_dir`` at ``<cache>/xla`` so every
   jit in the process (train step, eager collectives, init) reuses
   compiled artifacts across process restarts.  Wired automatically by
   ``GlobalState.initialize()`` (knobs: ``HOROVOD_COMPILE_CACHE=0``
   disables, ``HOROVOD_COMPILE_CACHE_DIR`` relocates).

2. **AOT executable store** — :func:`aot_compile` lowers a jitted
   function once, keys the result by a content hash (see
   :func:`executable_key`) and serializes the compiled executable with
   ``jax.experimental.serialize_executable`` into ``<cache>/aot/``.
   The next process start deserializes instead of compiling: seconds
   instead of the full XLA pipeline.  ``DistributedTrainStep`` routes
   its first compile through this path transparently, which is what
   makes ``bench.py`` warm runs and elastic-driver restarts cheap.

Key contract (invalidation): the hash covers the **lowered StableHLO
text** — so any change to the model config, loss, optimizer, mesh
shape, bucket schedule or steps_per_call changes the key by
construction — plus the fields that alter backend codegen without
changing the module: jax/jaxlib versions, platform, device kinds,
device count, process count, compiler options, and caller extras
(hierarchy/bucket knobs are passed explicitly for auditability even
though they also shape the HLO).  A stale entry can therefore never be
*loaded for* a program it wasn't compiled from; deserialization
failures (new jaxlib, corrupted file) degrade to a plain compile.

Disk entries are LRU-bounded by ``Config.cache_capacity``
(``HOROVOD_CACHE_CAPACITY``) — eviction is by mtime, and every load
touches its entry.  See docs/warmstart.md.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import re
import tempfile
import threading
from typing import Any, Optional, Tuple

import jax

from horovod_tpu.utils import logging as hvd_logging

_AOT_SUFFIX = ".aotx"
_lock = threading.Lock()
# process-wide counters; mirrored into GlobalState.cache_stats when the
# runtime is initialized so hvd.cache_stats() / bench.py surface them
_stats = {"aot_disk_hits": 0, "aot_disk_misses": 0}
_persistent_dir: Optional[str] = None


def default_dir() -> str:
    """The default cache root: ``~/.cache/horovod_tpu/compile`` (or
    ``$XDG_CACHE_HOME/horovod_tpu/compile``)."""
    base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache")
    return os.path.join(base, "horovod_tpu", "compile")


def resolve_dir(config=None) -> Optional[str]:
    """The active cache root, or ``None`` when caching is disabled.

    Resolution order: explicit ``config`` → the initialized runtime's
    config → the raw env knobs (so the cache works before
    ``hvd.init()``, e.g. during elastic re-rendezvous)."""
    if config is None:
        from horovod_tpu.runtime import state as rt_state

        if rt_state.is_initialized():
            config = rt_state.global_state().config
    if config is not None:
        if not getattr(config, "compile_cache_enabled", True):
            return None
        return getattr(config, "compile_cache_dir", None) or default_dir()
    v = os.environ.get("HOROVOD_COMPILE_CACHE", "")
    if v.lower() in ("0", "false", "no", "off"):
        return None
    return os.environ.get("HOROVOD_COMPILE_CACHE_DIR") or default_dir()


def enable_persistent_cache(directory: Optional[str] = None,
                            config=None) -> Optional[str]:
    """Point JAX's persistent compilation cache at ``<root>/xla``.

    Idempotent, and safe to re-run after an elastic reset (the config
    value survives ``clear_backends`` but re-asserting costs nothing
    and keeps the warm-start log line next to the re-init).  Returns
    the active root, or ``None`` when disabled."""
    global _persistent_dir
    root = directory or resolve_dir(config)
    if root is None:
        return None
    xla_dir = os.path.join(root, "xla")
    try:
        os.makedirs(xla_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", xla_dir)
    except Exception as e:  # noqa: BLE001 — cache must never sink init
        hvd_logging.warning(
            "compile_cache: persistent XLA cache unavailable (%s)", e)
        return None
    _persistent_dir = root
    return root


def stats() -> dict:
    """Disk-store counters: ``{"aot_disk_hits": n, "aot_disk_misses": n}``."""
    with _lock:
        return dict(_stats)


def _bump(hit: bool) -> None:
    from horovod_tpu import telemetry
    from horovod_tpu.runtime import state as rt_state

    with _lock:
        _stats["aot_disk_hits" if hit else "aot_disk_misses"] += 1
    telemetry.counter(
        "hvd_aot_disk_hits_total" if hit else "hvd_aot_disk_misses_total",
        "persistent AOT executable store hits" if hit
        else "persistent AOT executable store misses").inc()
    if rt_state.is_initialized():
        cs = rt_state.global_state().cache_stats
        cs["aot_disk_hits" if hit else "aot_disk_misses"] = \
            cs.get("aot_disk_hits" if hit else "aot_disk_misses", 0) + 1


def _env_fields() -> dict:
    """The backend identity fields of the AOT key — everything that can
    change generated code without changing the lowered module."""
    import jaxlib

    devs = jax.devices()
    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "platform": devs[0].platform,
        "device_kinds": sorted({d.device_kind for d in devs}),
        "num_devices": len(devs),
        "process_count": jax.process_count(),
    }


# default object repr / bound-method repr memory addresses: a key built
# from them differs every process start, so every warm start misses
_ADDR_RE = re.compile(r" at 0x[0-9a-fA-F]+")


def _stable_repr(obj: Any) -> str:
    """Process-stable fallback serializer for non-JSON key fields.

    ``repr`` of an arbitrary object embeds its memory address
    (``<Mesh object at 0x7f...>``) — a different AOT key every process,
    i.e. a warm start that silently never hits (hvdlint HVD003).  Strip
    the address; the remaining type/name text still distinguishes
    semantically different values, and anything that needs finer
    identity must be passed as a JSON-serializable extra."""
    return _ADDR_RE.sub("", repr(obj))


def executable_key(lowered_text: str, extras: Optional[dict] = None,
                   compiler_options: Optional[dict] = None) -> str:
    """Content hash identifying one compiled executable.

    ``lowered_text`` is the StableHLO of the lowered program — model
    config, mesh shape, exchange schedule and steps_per_call are all
    functions of it, so they invalidate the key by construction.
    ``extras`` carries those same knobs explicitly (mesh shape,
    hierarchy, bucket bytes, ...) so cache entries are auditable and so
    semantically-relevant knobs that *don't* reach the HLO still key."""
    payload = {
        "env": _env_fields(),
        "extras": extras or {},
        "compiler_options": sorted((compiler_options or {}).items()),
        "module_sha": hashlib.sha256(
            lowered_text.encode("utf-8", "replace")).hexdigest(),
    }
    blob = json.dumps(payload, sort_keys=True, default=_stable_repr)
    return hashlib.sha256(blob.encode()).hexdigest()


def _aot_dir(root: str) -> str:
    return os.path.join(root, "aot")


def _entry_path(root: str, key: str) -> str:
    return os.path.join(_aot_dir(root), key + _AOT_SUFFIX)


def load_executable(key: str, root: str):
    """Deserialize a cached executable, or ``None`` on miss/failure.
    A successful load touches the entry's mtime (LRU recency)."""
    path = _entry_path(root, key)
    if not os.path.exists(path):
        return None
    try:
        from jax.experimental import serialize_executable as se

        with open(path, "rb") as f:
            payload = pickle.load(f)
        compiled = se.deserialize_and_load(
            payload["serialized"], payload["in_tree"], payload["out_tree"])
        os.utime(path, None)
        return compiled
    except Exception as e:  # noqa: BLE001 — any failure = plain compile
        hvd_logging.warning(
            "compile_cache: could not load AOT entry %s (%s); recompiling",
            key[:12], e)
        try:
            os.remove(path)
        except OSError:
            pass
        return None


def store_executable(key: str, compiled, root: str,
                     capacity: Optional[int] = None,
                     meta: Optional[dict] = None) -> bool:
    """Serialize ``compiled`` under ``key`` (atomic tmp+rename write),
    then prune least-recently-used entries beyond ``capacity``."""
    try:
        from jax.experimental import serialize_executable as se

        serialized, in_tree, out_tree = se.serialize(compiled)
        payload = {"serialized": serialized, "in_tree": in_tree,
                   "out_tree": out_tree, "meta": meta or {}}
        d = _aot_dir(root)
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                pickle.dump(payload, f, protocol=pickle.HIGHEST_PROTOCOL)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, _entry_path(root, key))
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)
    except Exception as e:  # noqa: BLE001 — never sink the train step
        hvd_logging.warning(
            "compile_cache: could not serialize executable (%s); the "
            "in-memory copy still runs, next start recompiles", e)
        return False
    prune(root, capacity)
    return True


def prune(root: str, capacity: Optional[int] = None) -> int:
    """LRU-evict AOT entries beyond ``capacity`` (default: the runtime
    config's ``cache_capacity``).  Returns the number evicted."""
    if capacity is None:
        from horovod_tpu.runtime import state as rt_state

        capacity = (rt_state.global_state().config.cache_capacity
                    if rt_state.is_initialized() else 1024)
    d = _aot_dir(root)
    try:
        entries = [os.path.join(d, n) for n in os.listdir(d)
                   if n.endswith(_AOT_SUFFIX)]
    except OSError:
        return 0
    if len(entries) <= capacity:
        return 0
    entries.sort(key=lambda p: os.path.getmtime(p))
    evicted = 0
    for path in entries[:len(entries) - capacity]:
        try:
            os.remove(path)
            evicted += 1
        except OSError:
            pass
    if evicted:
        hvd_logging.info(
            "compile_cache: evicted %d LRU AOT entr%s (capacity %d)",
            evicted, "y" if evicted == 1 else "ies", capacity)
    return evicted


def entry_count(root: Optional[str] = None) -> int:
    """Number of AOT entries on disk (0 when the cache is disabled)."""
    root = root or resolve_dir()
    if root is None:
        return 0
    try:
        return sum(1 for n in os.listdir(_aot_dir(root))
                   if n.endswith(_AOT_SUFFIX))
    except OSError:
        return 0


_UNSET = object()


def aot_compile(jitted, args: Tuple[Any, ...],
                extras: Optional[dict] = None,
                compiler_options: Optional[dict] = None,
                directory: Any = _UNSET,
                capacity: Optional[int] = None):
    """Lower + compile ``jitted(*args)`` through the AOT store.

    Returns ``(compiled, cache_hit)``.  Lowering (tracing) always runs —
    it is cheap relative to XLA compilation and its output is the cache
    key — then the executable is either deserialized from disk
    (``cache_hit=True``) or compiled and serialized for the next start.
    ``directory`` defaults to the configured root; pass ``None`` to
    bypass the store — either way a disabled cache degrades to a plain
    ``lower().compile()``."""
    root = resolve_dir() if directory is _UNSET else directory
    lowered = jitted.lower(*args)
    if root is None:
        return lowered.compile(compiler_options=compiler_options), False
    key = executable_key(lowered.as_text(), extras=extras,
                         compiler_options=compiler_options)
    compiled = load_executable(key, root)
    hit = compiled is not None
    if not hit:
        compiled = lowered.compile(compiler_options=compiler_options)
        store_executable(key, compiled, root, capacity=capacity,
                         meta={"extras": extras or {},
                               "env": _env_fields()})
    _bump(hit)
    return compiled, hit
