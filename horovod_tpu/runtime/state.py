"""Process-wide runtime state: the TPU-native ``HorovodGlobalState``.

The reference keeps one ``HorovodGlobalState`` singleton per process
(``horovod/common/global_state.h:42-122``, instantiated at
``operations.cc:114``) owning the background thread, controller, fusion
buffer, timeline and tensor queue.  SPMD compilation removes the
negotiation thread and the tensor queue — XLA schedules collectives inside
the compiled step — but the process singleton survives: it owns the device
mesh, resolved config, timeline, stall watchdog and shutdown flag, and it is
what ``init()``/``shutdown()`` (``operations.cc:679``, ``basics.py:33``)
create and destroy.

Identity semantics (deliberate TPU re-design, documented in README):

* a *worker* in the reference is one process == one GPU; under JAX one
  process drives many chips.  ``rank``/``size`` here are **chip-level** —
  ``size()`` is the data-parallel degree you scale the LR by, exactly as in
  reference examples — while ``process_rank``/``process_count`` give the
  host-process identity.  ``rank() == 0`` iff ``process_rank == 0``, so the
  "checkpoint on rank 0" idiom carries over unchanged.
"""

from __future__ import annotations

import atexit
import os
import threading
from typing import Optional

import jax

from horovod_tpu.runtime.config import Config
from horovod_tpu.runtime import topology
from horovod_tpu.utils import logging as hvd_logging


class NotInitializedError(RuntimeError):
    def __init__(self):
        super().__init__(
            "horovod_tpu has not been initialized; call horovod_tpu.init() first.")


class GlobalState:
    """Singleton runtime object (reference ``HorovodGlobalState``)."""

    def __init__(self, config: Config):
        self.config = config
        self.initialization_done = False
        self.shut_down = False
        self._lock = threading.Lock()

        # populated by initialize()
        self.mesh = None
        self.process_rank = 0
        self.process_count = 1
        self.rank = 0
        self.size = 1
        self.local_rank = 0
        self.local_size = 1
        self.cross_rank = 0
        self.cross_size = 1
        self.is_homogeneous = True

        # aux subsystems, attached lazily to avoid import cycles
        self.timeline = None
        self.stall_inspector = None
        self.parameter_manager = None
        self.elastic_context = None
        # compiled-executable cache counters (the response-cache
        # observability analogue): "hits"/"misses" count the in-memory
        # signature caches (eager negotiation layer + each
        # DistributedTrainStep's AOT LRU); "aot_disk_hits"/"aot_disk_misses"
        # count the persistent AOT store (runtime/compile_cache.py).
        # bench.py surfaces all four in the BENCH JSON.
        self.cache_stats = {"hits": 0, "misses": 0,
                            "aot_disk_hits": 0, "aot_disk_misses": 0}
        # warm-start cache root resolved at initialize() (None = disabled)
        self.compile_cache_dir = None
        # telemetry exporters started at initialize() (None = metrics off;
        # the registry itself is process-global, horovod_tpu/telemetry)
        self.telemetry = None

    # -- bootstrap ---------------------------------------------------------

    def initialize(self, ranks: Optional[list] = None) -> None:
        cfg = self.config

        # chaos layer first: if a fault plan is configured it must be
        # live before any instrumented subsystem starts (the plan's own
        # loader logs loudly — an active plan in production is an
        # operator mistake worth shouting about)
        if cfg.fault_plan:
            from horovod_tpu import faults

            faults.load_env_plan()

        # HOROVOD_THREAD_AFFINITY: confine this worker to its core set
        # (reference parse_and_set_affinity, common.cc).  Must run BEFORE
        # any jax.distributed setup — sched_setaffinity is inherited only
        # by threads created afterwards, and the distributed runtime's
        # gRPC/heartbeat threads are exactly what the mask should cover.
        from horovod_tpu.utils.affinity import set_affinity_from_env

        set_affinity_from_env(cfg.local_rank or 0)

        # Multi-process bootstrap: the coordination-service analogue of the
        # reference's gloo rendezvous (gloo_context.cc:71-91).  The launcher
        # sets HOROVOD_COORDINATOR_ADDR + HOROVOD_RANK/SIZE; jax.distributed
        # then wires all processes into one SPMD world.  Elastic runs use
        # the driver-hosted service + survivable client instead (see
        # runtime/distributed.py: worker death must surface as a catchable
        # error, not the stock client's process termination).
        if cfg.coordinator_addr and cfg.size and cfg.size > 1:
            if cfg.elastic_enabled:
                from horovod_tpu.runtime import distributed as hvd_dist

                if not hvd_dist.elastic_client_active():
                    hvd_dist.connect_elastic_client(
                        cfg.coordinator_addr, cfg.size, cfg.rank,
                        heartbeat_timeout=int(os.environ.get(
                            "HOROVOD_ELASTIC_HEARTBEAT_TIMEOUT",
                            hvd_dist.DEFAULT_HEARTBEAT_TIMEOUT_S)))
            elif not getattr(jax.distributed, "is_initialized",
                             lambda: False)():
                jax.distributed.initialize(
                    coordinator_address=cfg.coordinator_addr,
                    num_processes=cfg.size,
                    process_id=cfg.rank,
                )
                hvd_logging.info(
                    "jax.distributed initialized: process %s of %s via %s",
                    cfg.rank, cfg.size, cfg.coordinator_addr)

        self.process_rank = jax.process_index()
        self.process_count = jax.process_count()

        self.mesh = topology.build_mesh(cfg.mesh_shape)
        self.size = topology.mesh_size(self.mesh)

        local = jax.local_device_count()
        self.local_size = local
        self.local_rank = 0
        self.rank = self.process_rank * local  # chip-rank of first local device
        # homogeneity check mirrors MPIController::DoInitialization
        # (mpi_controller.cc:26): all processes must drive equal chip counts
        # for local/cross arithmetic to be meaningful.
        self.is_homogeneous = (self.size == local * self.process_count)

        # cross = slice/host-level (reference CROSS communicator,
        # common.h:113-117).  A process's CROSS identity is the slice its
        # devices live on — NOT its process rank; slices may span several
        # processes.
        self.cross_size = self.mesh.shape[topology.AXIS_DCN]
        sid = getattr(jax.local_devices()[0], "slice_index", None)
        if sid is None:
            # off-TPU there is no slice topology; processes are laid out
            # over the dcn axis in rank order
            sid = (self.process_rank * self.cross_size) // max(
                self.process_count, 1)
        self.cross_rank = min(int(sid), self.cross_size - 1)
        if cfg.cross_rank is not None:
            self.cross_rank = cfg.cross_rank
        if cfg.cross_size is not None:
            self.cross_size = cfg.cross_size

        # warm-start layer: persistent XLA compilation cache + the AOT
        # executable store root (runtime/compile_cache.py).  Enabled by
        # default — a restarted process (elastic reset, relaunched bench)
        # then reuses compiled artifacts instead of re-paying the 42-51 s
        # flagship warmup (PERF_NOTES round 8).
        if cfg.compile_cache_enabled:
            from horovod_tpu.runtime import compile_cache

            self.compile_cache_dir = \
                compile_cache.enable_persistent_cache(config=cfg)
            if self.compile_cache_dir:
                n = compile_cache.entry_count(self.compile_cache_dir)
                hvd_logging.info(
                    "compile cache: %s (%d AOT entr%s)",
                    self.compile_cache_dir, n, "y" if n == 1 else "ies")

        # telemetry plane BEFORE timeline/stall: both render registered
        # gauges (timeline counter rows) and count through the registry
        from horovod_tpu import telemetry

        self.telemetry = telemetry.start_from_config(
            cfg, process_rank=self.process_rank)

        if cfg.timeline_filename:
            self.timeline = _make_timeline(cfg, self.process_rank
                                           if self.process_count > 1 else 0)
        if cfg.stall_check_enabled:
            from horovod_tpu.utils.stall import StallInspector

            self.stall_inspector = StallInspector(
                warning_time_s=cfg.stall_warning_time_seconds,
                shutdown_time_s=cfg.stall_shutdown_time_seconds)
        if cfg.autotune:
            from horovod_tpu.utils.autotune import ParameterManager

            self.parameter_manager = ParameterManager(
                self.config, log_path=cfg.autotune_log)

        self.initialization_done = True
        hvd_logging.info(
            "horovod_tpu initialized: %d chips (%d process(es) x %d local), "
            "mesh dcn=%d ici=%d",
            self.size, self.process_count, local,
            self.mesh.shape[topology.AXIS_DCN],
            self.mesh.shape[topology.AXIS_ICI])

    def shutdown(self) -> None:
        with self._lock:
            if self.shut_down:
                return
            if self.timeline is not None:
                fname = getattr(self.timeline, "filename", None)
                origin = getattr(self.timeline, "wall_origin_us", None)
                self.timeline.close()
                self.timeline = None
                if fname:
                    from horovod_tpu.utils.timeline import \
                        aggregate_after_close

                    aggregate_after_close(fname, origin)
            if self.stall_inspector is not None:
                self.stall_inspector.stop()
            if self.telemetry is not None:
                # final JSONL snapshot + endpoint teardown; the registry
                # itself survives (elastic resets re-init around it)
                self.telemetry.shutdown()
                self.telemetry = None
            self.shut_down = True
            self.initialization_done = False


def _make_timeline(cfg: Config, process_rank: int = 0):
    """Prefer the native lock-free writer (reference timeline.{h,cc} is
    C++); fall back to the Python writer when the toolchain is absent.

    Non-root processes write a per-rank derived path so a shared
    ``HOROVOD_TIMELINE`` never has two writers; ``stop_timeline``'s
    aggregation then merges everything into rank 0's file — the one
    configured path holds the one trace, the reference's UX."""
    filename = cfg.timeline_filename
    if process_rank:
        filename = f"{filename}.{process_rank}"
    if not os.environ.get("HOROVOD_TIMELINE_PYTHON"):
        try:
            from horovod_tpu.native import NativeTimeline

            return NativeTimeline(filename,
                                  mark_cycles=cfg.timeline_mark_cycles)
        except (RuntimeError, OSError):
            pass
    from horovod_tpu.utils.timeline import Timeline

    return Timeline(filename, mark_cycles=cfg.timeline_mark_cycles)


_state: Optional[GlobalState] = None
_state_lock = threading.Lock()


@atexit.register
def _shutdown_at_exit() -> None:
    # one process-wide hook, not one per init() — elastic resets re-init
    # many times (reference registers its background-thread teardown once
    # in InitializeHorovodOnce)
    if _state is not None:
        _state.shutdown()


def init(ranks: Optional[list] = None, config: Optional[Config] = None) -> GlobalState:
    """Create (or return) the singleton; idempotent like ``horovod_init``
    (reference ``operations.cc:620`` InitializeHorovodOnce)."""
    global _state
    with _state_lock:
        if _state is not None and _state.initialization_done:
            return _state
        cfg = config or Config.from_env()
        st = GlobalState(cfg)
        st.initialize(ranks)
        _state = st
        return st


def shutdown() -> None:
    global _state
    with _state_lock:
        if _state is not None:
            _state.shutdown()
            _state = None


def is_initialized() -> bool:
    return _state is not None and _state.initialization_done


def global_state() -> GlobalState:
    if _state is None or not _state.initialization_done:
        raise NotInitializedError()
    return _state
