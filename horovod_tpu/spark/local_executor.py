"""A ``SparkContext`` contract double backed by local worker processes.

``horovod_tpu.spark.run`` touches exactly this much of the pyspark API:

    sc.defaultParallelism
    sc.parallelize(data, numSlices).mapPartitionsWithIndex(f).collect()

:class:`LocalSparkContext` implements that surface, executing each
partition function in its own spawned process — the shape of a Spark
python worker — with the function shipped by cloudpickle exactly as
Spark ships it.  It serves two roles:

* the executor pool behind ``horovod_tpu.spark.run`` when pyspark is
  not installed (same RPC architecture, localhost workers);
* the contract double the Spark-path tests drive the real
  ``_run_on_spark`` machinery through, playing the part of the
  reference's ``local[2]`` test runs and fake task services
  (``/root/reference/test/test_spark.py``,
  ``/root/reference/test/spark_common.py``).
"""

from __future__ import annotations

import multiprocessing
import os
from typing import Any, Callable, List, Sequence


def _partition_worker(conn, fn_payload: bytes, index: int,
                      items_payload: bytes) -> None:
    """Spawned-process body: run the cloudpickled partition function."""
    import cloudpickle

    try:
        f = cloudpickle.loads(fn_payload)
        out = list(f(index, iter(cloudpickle.loads(items_payload))))
        conn.send(("ok", out))
    except BaseException as e:  # noqa: BLE001 - report, don't swallow
        try:
            conn.send(("err", f"{type(e).__name__}: {e}"))
        except Exception:
            pass
    finally:
        conn.close()


class LocalSparkContext:
    """Drop-in for the slice of ``pyspark.SparkContext`` that
    ``horovod_tpu.spark.run`` uses (see module docstring)."""

    def __init__(self, parallelism: int = 0):
        self.defaultParallelism = parallelism or os.cpu_count() or 1

    def parallelize(self, data: Sequence, numSlices: int = 0) -> "_RDD":
        return _RDD(list(data), numSlices or self.defaultParallelism)


class _RDD:
    def __init__(self, data: list, num_slices: int):
        self._data = data
        self._n = max(int(num_slices), 1)

    def _partitions(self) -> List[list]:
        # Spark's contiguous-chunk partitioner: slice i gets
        # data[floor(i*L/n) : floor((i+1)*L/n)]
        length = len(self._data)
        return [self._data[length * i // self._n:
                           length * (i + 1) // self._n]
                for i in range(self._n)]

    def mapPartitionsWithIndex(self, f: Callable) -> "_MappedRDD":
        return _MappedRDD(self._partitions(), f)


class _MappedRDD:
    def __init__(self, partitions: List[list], f: Callable):
        self._partitions = partitions
        self._f = f

    def collect(self) -> List[Any]:
        import cloudpickle

        payload = cloudpickle.dumps(self._f)
        ctx = multiprocessing.get_context("spawn")
        workers = []
        for i, part in enumerate(self._partitions):
            recv, send = ctx.Pipe(duplex=False)
            # partition DATA rides cloudpickle like the function does,
            # so closures work as parallelize()'d elements here.  (Real
            # pyspark serializes data with plain pickle — closures need
            # a module-level function / functools.partial there.)
            p = ctx.Process(target=_partition_worker,
                            args=(send, payload, i, cloudpickle.dumps(part)),
                            name=f"local-spark-worker-{i}", daemon=True)
            p.start()
            send.close()
            workers.append((p, recv))

        out: List[Any] = []
        errors: List[str] = []
        for i, (p, recv) in enumerate(workers):
            msg = None
            try:
                msg = recv.recv()
            except EOFError:
                pass
            p.join()
            if msg is None:
                errors.append(f"partition {i}: worker died "
                              f"(exit code {p.exitcode})")
            elif msg[0] == "err":
                errors.append(f"partition {i}: {msg[1]}")
            else:
                out.extend(msg[1])
        if errors:
            raise RuntimeError(
                "local executor pool job failed:\n  " + "\n  ".join(errors))
        return out
