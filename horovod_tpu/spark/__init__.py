"""Spark integration layer (reference ``horovod/spark/``).

``horovod_tpu.spark.run(fn, ...)`` executes a training function on
cluster executors; :class:`~horovod_tpu.estimator.Estimator` (re-exported
here as the reference exposes estimators under ``horovod.spark.*``)
offers the fit/transform Pipeline-style API.

When pyspark is not installed, ``run`` executes the same task-service
architecture over :class:`~horovod_tpu.spark.local_executor.LocalSparkContext`
— local spawned workers behind the identical contract — so the Spark
path itself runs everywhere; a real SparkContext is used automatically
when pyspark is importable.
"""

from horovod_tpu.spark.local_executor import LocalSparkContext
from horovod_tpu.spark.runner import run, run_elastic
from horovod_tpu.spark.store import (
    FilesystemStore,
    HDFSStore,
    LocalStore,
    PreparedData,
    Store,
)

__all__ = ["run", "run_elastic", "Estimator", "TpuModel", "load_model",
           "Store",
           "FilesystemStore", "LocalStore", "HDFSStore", "PreparedData",
           "LocalSparkContext"]


def __getattr__(name):
    # estimator imports spark.store; resolving Estimator lazily keeps
    # `horovod_tpu.spark.Estimator` importable without a module cycle
    if name in ("Estimator", "TpuModel", "load_model"):
        from horovod_tpu import estimator

        return getattr(estimator, name)
    raise AttributeError(name)
