"""Spark integration layer (reference ``horovod/spark/``).

``horovod_tpu.spark.run(fn, ...)`` executes a training function on
cluster executors; :class:`~horovod_tpu.estimator.Estimator` (re-exported
here as the reference exposes estimators under ``horovod.spark.*``)
offers the fit/transform Pipeline-style API.

When pyspark is not installed, ``run`` falls back to the localhost
launcher (same contract, same per-rank results) so the API surface works
everywhere; the Spark path activates automatically when pyspark is
importable.
"""

from horovod_tpu.estimator import Estimator, TpuModel
from horovod_tpu.spark.runner import run, run_elastic

__all__ = ["run", "run_elastic", "Estimator", "TpuModel"]
