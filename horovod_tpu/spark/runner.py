"""Run a training function on Spark executors.

Reference: ``horovod/spark/runner.py`` — ``run(fn, ...):195`` launches a
Spark job whose tasks become Horovod slots (``_task_fn:47``): each task
starts a task service, registers its address + host hash with the driver
service, the driver groups tasks by host hash into a host list, computes
rank assignments, and drives execution through the task services instead
of ssh; per-rank results flow back to the driver
(``/root/reference/horovod/spark/driver/driver_service.py``,
``task_service.py``).

Same architecture here, the launcher's pieces underneath: the HMAC
``BasicService`` RPC plane (``runner/network.py``), host-hash grouping
through ``runner.hosts.get_host_assignments``, and the
``jax.distributed`` coordinator env contract that ``hvd.init`` consumes.
Without pyspark the executor pool degrades to
:class:`~horovod_tpu.spark.local_executor.LocalSparkContext` — local
spawned workers behind the identical contract (pickled fn, task
registration, per-rank return values in rank order), so code written
against this API runs anywhere and the Spark path itself is what
executes everywhere.
"""

from __future__ import annotations

import os
import socket
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from horovod_tpu.utils import logging as hvd_logging

#: Seconds to wait for all Spark tasks to register (the reference's
#: ``--start-timeout``, default 600: executors may need to spin up).
_START_TIMEOUT_ENV = "HOROVOD_SPARK_START_TIMEOUT"


# -- wire messages (module-level so stdlib pickle — the wire format of
#    ``runner.network.Wire`` — serializes them by reference on both ends
#    and driver-side isinstance checks match executor instances) --------

class RegisterTask:
    """Executor → driver: announce this task's identity and RPC address.

    ``task_id`` (elastic pools only) is a per-process uuid: Spark reuses
    partition *indices* when it re-runs a lost executor's task, so the
    index cannot key driver-side state across executor replacement."""

    def __init__(self, index: int, host: str, host_hash: str,
                 addr: Tuple[str, int], task_id: Optional[str] = None):
        self.index = index
        self.host = host
        self.host_hash = host_hash
        self.addr = tuple(addr)
        self.task_id = task_id


class TaskResult:
    """Executor → driver: per-partition return value (or _TaskError)."""

    def __init__(self, index: int, value: Any):
        self.index, self.value = index, value


class _TaskError:
    """Result payload marking a raised exception in the task's fn."""

    def __init__(self, message: str):
        self.message = message


class RunFunction:
    """Driver → task: execute the job fn under this worker env."""

    def __init__(self, env: Dict[str, str]):
        self.env = env


class ProbePortRequest:
    """Driver → rank-0 task: pick a free port for the jax.distributed
    coordinator on your host (the rendezvous-server address)."""


class PortResponse:
    def __init__(self, port: int):
        self.port = port


class ShutdownTask:
    """Driver → task: job over, stop your service and finish the
    partition."""


def host_hash() -> str:
    """Physical-host identity for slot grouping (reference
    ``runner/common/util/host_hash.py``: tasks with equal hashes share a
    machine and get consecutive local ranks).  ``HOROVOD_SPARK_HOST_HASH``
    overrides for tests simulating multi-host executor pools."""
    return os.environ.get("HOROVOD_SPARK_HOST_HASH") or socket.gethostname()


def _spark_available() -> bool:
    try:
        import pyspark  # noqa: F401

        return True
    except ImportError:
        return False


def run(fn: Callable, args=(), kwargs=None, num_proc: Optional[int] = None,
        extra_env: Optional[dict] = None, verbose: bool = False) -> List[Any]:
    """Execute ``fn`` on ``num_proc`` distributed workers and return the
    per-rank results (reference ``horovod.spark.run``)."""
    if _spark_available():
        from pyspark import SparkContext

        sc = SparkContext._active_spark_context
        if sc is None:
            raise RuntimeError("no active SparkContext; create a "
                               "SparkSession before horovod_tpu.spark.run")
        return _run_on_spark(sc, fn, args, kwargs, num_proc, extra_env,
                             verbose)
    hvd_logging.debug("pyspark not available; spark.run using the local "
                      "executor pool")
    from horovod_tpu.spark.local_executor import LocalSparkContext

    return _run_on_spark(LocalSparkContext(), fn, args, kwargs,
                         num_proc or 1, extra_env, verbose)


def run_elastic(fn: Callable, args=(), kwargs=None,
                num_proc: Optional[int] = None,
                min_np: Optional[int] = None, max_np: Optional[int] = None,
                **kw) -> List[Any]:
    """Elastic variant (reference ``run_elastic:303``): the executor
    pool's tasks become *potential* slots driven by the
    :class:`~horovod_tpu.elastic.driver.ElasticDriver` over task-service
    RPC — executor loss shrinks the world, new executors grow it.  Like
    :func:`run`, degrades to the local executor pool without pyspark
    (see :mod:`horovod_tpu.spark.elastic`)."""
    from horovod_tpu.spark.elastic import run_elastic_on_context

    if _spark_available():
        from pyspark import SparkContext

        sc = SparkContext._active_spark_context
        if sc is None:
            raise RuntimeError(
                "no active SparkContext; create a SparkSession before "
                "horovod_tpu.spark.run_elastic")
        return run_elastic_on_context(sc, fn, args, kwargs, num_proc,
                                      min_np, max_np, **kw)
    hvd_logging.debug("pyspark not available; spark.run_elastic using the "
                      "local executor pool")
    from horovod_tpu.spark.local_executor import LocalSparkContext

    # default the initial world to the floor the caller asked for —
    # `or 1` would fail run_elastic_on_context's min_np<=num_proc check
    # for any min_np > 1
    return run_elastic_on_context(LocalSparkContext(), fn, args, kwargs,
                                  num_proc or min_np or 1, min_np, max_np,
                                  **kw)


def plan_assignments(registry: Dict[int, RegisterTask], num_proc: int):
    """Host-hash grouping → rank plan (reference
    ``driver_service.py task_host_hash_indices`` +
    ``get_host_assignments``): tasks sharing a host hash become one
    host's slots, so consecutive ranks land on one machine.

    Returns ``(assignments, slot_index)`` where ``slot_index[rank]`` is
    the Spark partition index serving that rank.
    """
    from horovod_tpu.runner.hosts import HostInfo, get_host_assignments

    by_hash: Dict[str, List[int]] = {}
    for idx in sorted(registry):
        by_hash.setdefault(registry[idx].host_hash, []).append(idx)
    hosts = [HostInfo(hh, len(idxs)) for hh, idxs in sorted(by_hash.items())]
    assignments = get_host_assignments(hosts, num_proc, num_proc)
    slot_index = {
        slot.rank: by_hash[slot.hostname][slot.local_rank]
        for slot in assignments
    }
    return assignments, slot_index


def _make_task_fn(driver_addr: Tuple[str, int], key: str, payload: bytes,
                  run_timeout_s: float) -> Callable:
    """The partition function Spark ships to executors (reference
    ``_task_fn:47`` / ``SparkTaskService``)."""

    def _task(index: int, _iterator):
        import cloudpickle

        from horovod_tpu.runner.network import (
            AckResponse,
            BasicClient,
            BasicService,
        )
        from horovod_tpu.spark import runner as _r

        run_req: list = []
        run_evt = threading.Event()    # fires on RunFunction OR shutdown
        stop_evt = threading.Event()

        def handle(req):
            if isinstance(req, _r.RunFunction):
                run_req.append(req.env)
                run_evt.set()
                return AckResponse()
            if isinstance(req, _r.ProbePortRequest):
                with socket.socket() as s:
                    s.bind(("", 0))
                    return _r.PortResponse(s.getsockname()[1])
            if isinstance(req, _r.ShutdownTask):
                stop_evt.set()
                run_evt.set()          # release a task still waiting
                return AckResponse()
            raise ValueError(type(req).__name__)

        service = BasicService(f"spark_task_{index}", key, handle)
        service.start()
        try:
            client = BasicClient(driver_addr, key)
            client.request(_r.RegisterTask(
                index, socket.gethostname(), _r.host_hash(),
                service.address))
            run_evt.wait(run_timeout_s)
            if not run_req:
                if stop_evt.is_set():    # job aborted before our turn
                    return [index]
                raise RuntimeError(
                    f"spark task {index}: no run command from the driver "
                    f"within {run_timeout_s:.0f}s")
            os.environ.update(run_req[0])
            func, fargs, fkwargs = cloudpickle.loads(payload)
            try:
                value = func(*fargs, **fkwargs)
            except BaseException as e:  # noqa: BLE001 - travels to driver
                value = _r._TaskError(f"{type(e).__name__}: {e}")
            client.request(_r.TaskResult(index, value))
            stop_evt.wait(60.0)
            return [index]
        finally:
            service.shutdown()

    return _task


def _run_on_spark(sc, fn, args, kwargs, num_proc, extra_env, verbose,
                  min_np=None, max_np=None) -> List[Any]:
    """The Spark path (reference ``runner.py:195``): parallelize
    ``num_proc`` tasks; each starts a task service and registers with the
    driver service; the driver groups them by host hash, assigns ranks,
    and commands execution through the task services."""
    import cloudpickle

    from horovod_tpu.runner.network import (
        AckResponse,
        BasicClient,
        BasicService,
        make_secret_key,
    )

    num_proc = num_proc or sc.defaultParallelism
    start_timeout = float(os.environ.get(_START_TIMEOUT_ENV, "600"))
    key = make_secret_key()
    payload = cloudpickle.dumps((fn, tuple(args), dict(kwargs or {})))

    registry: Dict[int, RegisterTask] = {}
    results: Dict[int, Any] = {}
    lock = threading.Lock()
    all_registered = threading.Event()
    all_results = threading.Event()

    def handle(req):
        if isinstance(req, RegisterTask):
            with lock:
                registry[req.index] = req
                if len(registry) == num_proc:
                    all_registered.set()
            return AckResponse()
        if isinstance(req, TaskResult):
            with lock:
                results[req.index] = req.value
                if len(results) == num_proc:
                    all_results.set()
            return AckResponse()
        raise ValueError(type(req).__name__)

    service = BasicService("spark_driver", key, handle)
    service.start()
    job_error: List[BaseException] = []

    def _job():
        # the Spark job itself runs aside (reference _make_spark_thread):
        # its tasks block in their service loops until commanded, so
        # collect() cannot return before the driver below finishes
        try:
            sc.parallelize(range(num_proc), num_proc) \
                .mapPartitionsWithIndex(_make_task_fn(
                    service.address, key, payload, start_timeout)) \
                .collect()
        except BaseException as e:  # noqa: BLE001
            job_error.append(e)
            all_registered.set()
            all_results.set()

    spark_thread = threading.Thread(target=_job, daemon=True,
                                    name="hvd_tpu_spark_job")
    spark_thread.start()

    def _shutdown_tasks():
        with lock:
            regs = list(registry.values())
        for reg in regs:
            try:
                BasicClient(reg.addr, key).request(ShutdownTask())
            except Exception:
                pass

    try:
        if not all_registered.wait(start_timeout):
            raise RuntimeError(
                f"only {len(registry)}/{num_proc} Spark tasks registered "
                f"within {start_timeout:.0f}s — the cluster may lack "
                f"executor capacity for num_proc={num_proc} "
                f"({_START_TIMEOUT_ENV} raises the wait)")
        if job_error:
            raise RuntimeError(
                f"Spark job failed during startup: {job_error[0]}")

        assignments, slot_index = plan_assignments(registry, num_proc)
        rank0 = registry[slot_index[0]]
        port = BasicClient(rank0.addr, key).request(ProbePortRequest()).port
        head = rank0.host
        single_host = len({r.host_hash for r in registry.values()}) == 1
        if single_host and head in ("localhost", socket.gethostname()):
            # every worker shares rank 0's machine, so loopback is both
            # valid and immune to hostname-resolution quirks; with
            # workers on other hosts the real hostname must ship
            head = "127.0.0.1"
        coordinator = f"{head}:{port}"
        if verbose:
            import sys

            for slot in assignments:
                print(f"[spark] rank {slot.rank} -> partition "
                      f"{slot_index[slot.rank]} on "
                      f"{registry[slot_index[slot.rank]].host} "
                      f"(local {slot.local_rank}/{slot.local_size})",
                      file=sys.stderr)

        for slot in assignments:
            reg = registry[slot_index[slot.rank]]
            env = dict(extra_env or {})
            env.update(slot.to_env())
            # to_env carries the host hash as HOROVOD_HOSTNAME; workers
            # want the real hostname
            env["HOROVOD_HOSTNAME"] = reg.host
            env["HOROVOD_COORDINATOR_ADDR"] = coordinator
            env["HOROVOD_CONTROLLER"] = "jax"
            BasicClient(reg.addr, key).request(RunFunction(env))

        while not all_results.wait(1.0):
            if job_error:
                raise RuntimeError(f"Spark job failed: {job_error[0]}")
            if not spark_thread.is_alive() and not all_results.is_set():
                missing = sorted(set(range(num_proc)) - set(results))
                raise RuntimeError(
                    f"Spark job finished but partitions {missing} "
                    f"returned no result")
        if job_error:
            raise RuntimeError(f"Spark job failed: {job_error[0]}")

        _shutdown_tasks()
        spark_thread.join(30.0)

        failed = {r: v for r, v in
                  ((slot.rank, results[slot_index[slot.rank]])
                   for slot in assignments)
                  if isinstance(v, _TaskError)}
        if failed:
            detail = "; ".join(f"rank {r}: {v.message}"
                               for r, v in sorted(failed.items()))
            raise RuntimeError(f"spark.run fn raised on "
                               f"{len(failed)}/{num_proc} ranks: {detail}")
        return [results[slot_index[slot.rank]]
                for slot in sorted(assignments, key=lambda s: s.rank)]
    finally:
        _shutdown_tasks()
        service.shutdown()
