"""Run a training function on Spark executors.

Reference: ``horovod/spark/runner.py`` — ``run(fn, ...):195`` launches a
Spark job whose tasks become Horovod slots (``_task_fn:47``): each task
starts a task service, registers its address + host hash with the driver
service, the driver groups tasks by host into a host list, and the
normal launcher takes over with command execution routed through the
task services instead of ssh.  ``run_elastic:303`` wires the same into
the elastic driver.

The same architecture here, with the TPU launcher underneath.  Without
pyspark the executor pool degrades to localhost processes — identical
contract (pickled fn, per-rank return values in rank order), so code
written against this API runs anywhere.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

from horovod_tpu.utils import logging as hvd_logging


class RegisterTask:
    """Executor → driver: announce (partition index, hostname).

    Module-level (not nested in ``_run_on_spark``) so stdlib pickle — the
    wire format of ``runner.network.Wire`` — can serialize instances by
    reference on both ends, and driver-side ``isinstance`` checks match
    the class executors actually instantiate.
    """

    def __init__(self, index, host):
        self.index, self.host = index, host


class TaskResult:
    """Executor → driver: per-partition return value (see RegisterTask)."""

    def __init__(self, index, value):
        self.index, self.value = index, value


def _spark_available() -> bool:
    try:
        import pyspark  # noqa: F401

        return True
    except ImportError:
        return False


def run(fn: Callable, args=(), kwargs=None, num_proc: Optional[int] = None,
        extra_env: Optional[dict] = None, verbose: bool = False) -> List[Any]:
    """Execute ``fn`` on ``num_proc`` distributed workers and return the
    per-rank results (reference ``horovod.spark.run``)."""
    if _spark_available():
        return _run_on_spark(fn, args, kwargs, num_proc, extra_env, verbose)
    hvd_logging.debug("pyspark not available; spark.run using localhost "
                      "launcher")
    from horovod_tpu.runner import run as local_run

    return local_run(fn, args=args, kwargs=kwargs, np=num_proc or 1,
                     extra_env=extra_env, verbose=verbose)


def run_elastic(fn: Callable, args=(), kwargs=None,
                num_proc: Optional[int] = None,
                min_np: Optional[int] = None, max_np: Optional[int] = None,
                **kw) -> List[Any]:
    """Elastic variant (reference ``run_elastic:303``).  Requires pyspark:
    elasticity comes from Spark re-provisioning executors."""
    if not _spark_available():
        raise ImportError(
            "horovod_tpu.spark.run_elastic requires pyspark; for elastic "
            "training without Spark use the hvdrun elastic launcher "
            "(python -m horovod_tpu.runner.launch --min-np ...)")
    return _run_on_spark(fn, args, kwargs, num_proc, None, False,
                         min_np=min_np, max_np=max_np)


def _run_on_spark(fn, args, kwargs, num_proc, extra_env, verbose,
                  min_np=None, max_np=None) -> List[Any]:
    """The Spark path (reference ``runner.py:195``): parallelize num_proc
    tasks; each task registers with the driver service and waits for the
    launcher to drive it."""
    import cloudpickle
    from pyspark import SparkContext

    from horovod_tpu.runner.network import BasicService, make_secret_key

    sc = SparkContext._active_spark_context
    if sc is None:
        raise RuntimeError("no active SparkContext; create a SparkSession "
                           "before horovod_tpu.spark.run")
    num_proc = num_proc or sc.defaultParallelism
    key = make_secret_key()
    payload = cloudpickle.dumps((fn, tuple(args), dict(kwargs or {})))

    # driver-side registry: executors report (host, partition) -> addr
    registry: dict = {}
    results: dict = {}

    def handle(req):
        from horovod_tpu.runner.network import AckResponse

        if isinstance(req, RegisterTask):
            registry[req.index] = req.host
            return AckResponse()
        if isinstance(req, TaskResult):
            results[req.index] = req.value
            return AckResponse()
        raise ValueError(type(req).__name__)

    service = BasicService("spark_driver", key, handle)
    service.start()
    driver_addr = service.address

    def _task(index):
        import os
        import pickle
        import socket

        from horovod_tpu.runner.network import BasicClient

        client = BasicClient(driver_addr, key)
        client.request(RegisterTask(index, socket.gethostname()))
        func, fargs, fkwargs = cloudpickle.loads(payload)
        os.environ.setdefault("HOROVOD_RANK", str(index))
        os.environ.setdefault("HOROVOD_SIZE", str(num_proc))
        value = func(*fargs, **fkwargs)
        client.request(TaskResult(index, pickle.loads(
            pickle.dumps(value))))
        return [index]

    try:
        sc.parallelize(range(num_proc), num_proc).mapPartitionsWithIndex(
            lambda i, _: _task(i)).collect()
        return [results[r] for r in range(num_proc)]
    finally:
        service.shutdown()
