"""Elastic training on an executor pool (Spark or the local pool).

Reference: ``horovod/spark/runner.py:303 run_elastic`` — a Spark job
whose tasks become *potential* Horovod slots, driven by the elastic
driver through task-service RPC instead of ssh
(``runner/gloo_run.py:274 launch_gloo_elastic`` provides the driver
machinery; ``spark/driver/driver_service.py`` the task registry).

Same composition here, over the pieces this repo already ships:

* the task-service RPC plane of :mod:`horovod_tpu.spark.runner`
  (``RegisterTask`` / ``RunFunction`` / ``ShutdownTask`` messages over
  the HMAC ``BasicService``), with executor tasks extended to serve a
  *sequence* of run commands (one per elastic spawn) instead of one;
* :class:`horovod_tpu.elastic.driver.ElasticDriver` — discovery loop,
  rank-stable reassignment, blacklisting, per-generation
  ``jax.distributed`` coordinators — with ``create_worker_fn`` sending
  ``RunFunction`` to an idle executor task rather than exec'ing ssh;
* liveness by RPC ping: a task whose service stops answering is a dead
  executor — its "host" leaves discovery (world shrinks, survivors get
  ``HostsUpdatedInterrupt``) and any worker it was running is recorded
  as failed.

Each executor task is its own elastic *host* (identity
``<hosthash>[<task index>]``): the executor process is the unit that
owns devices, fails, and gets blacklisted — matching Spark deployments
where executors are per-container.  Consequence: a task that ran a
*failed* worker is blacklisted with its host and never reused; a task
whose worker retired cleanly (scale-down) can serve a later spawn.

Works with or without pyspark: ``run_elastic`` picks the active
``SparkContext`` when present and otherwise degrades to
:class:`~horovod_tpu.spark.local_executor.LocalSparkContext`, exactly
like ``horovod_tpu.spark.run``.
"""

from __future__ import annotations

import os
import queue
import socket
import threading
import uuid
from typing import Any, Callable, Dict, List, Optional

from horovod_tpu.elastic.discovery import HostDiscovery
from horovod_tpu.utils import logging as hvd_logging

#: env key carrying the per-spawn id inside RunFunction.env
_RUN_ID_ENV = "HOROVOD_SPARK_ELASTIC_RUN_ID"
_PING_TIMEOUT_S = 2.0
#: one missed ping must not blacklist a healthy executor (a user fn
#: holding the GIL can starve the service thread past one timeout)
_PING_ATTEMPTS = 3
_PING_RETRY_DELAY_S = 0.3


class PingTask:
    """Driver → task: liveness probe (answered by the service thread even
    while the task's fn is computing)."""


class _SpawnEnvApplier:
    """Per-spawn ``RunFunction.env`` application with restore-to-baseline.

    An executor task serves MANY spawns in one process; a bare
    ``os.environ.update(cmd.env)`` per spawn leaks every
    ``HOROVOD_*``/extra_env key the next spawn does not overwrite —
    e.g. a rank that moves between generations keeps the old spawn's
    coordinator or generation number wherever the new env omits a key.
    Before applying a spawn's env, every key the *previous* spawn set
    is restored to its pre-first-spawn value (deleted if it was unset),
    so each spawn starts from the executor's baseline environment."""

    def __init__(self, environ=None):
        self._environ = os.environ if environ is None else environ
        self._baseline: Dict[str, Optional[str]] = {}
        self._applied: tuple = ()

    def apply(self, env: Dict[str, str]) -> None:
        for k in self._applied:
            old = self._baseline[k]
            if old is None:
                self._environ.pop(k, None)
            else:
                self._environ[k] = old
        for k in env:
            if k not in self._baseline:
                self._baseline[k] = self._environ.get(k)
        self._environ.update(env)
        self._applied = tuple(env)


class ElasticTaskResult:
    """Executor → driver: one spawn's return value (or ``_TaskError``)."""

    def __init__(self, index: int, run_id: str, value: Any):
        self.index, self.run_id, self.value = index, run_id, value


def _elastic_task_fn(driver_addr, key: str, payload: bytes) -> Callable:
    """Partition function for elastic pools: register, then serve run
    commands until shutdown (the static task fn serves exactly one).
    Idle tasks wait indefinitely — a spare slot in a max_np pool is
    growth capacity, not a timeout candidate; the driver reaps the pool
    with ``ShutdownTask`` (and local pool processes are daemonic)."""

    def _task(index: int, _iterator):
        import cloudpickle

        from horovod_tpu.runner.network import (
            AckResponse,
            BasicClient,
            BasicService,
        )
        from horovod_tpu.spark import elastic as _e
        from horovod_tpu.spark import runner as _r

        cmds: queue.Queue = queue.Queue()

        def handle(req):
            if isinstance(req, _r.RunFunction):
                cmds.put(req)
                return AckResponse()
            if isinstance(req, _e.PingTask):
                return AckResponse()
            if isinstance(req, _r.ShutdownTask):
                cmds.put(None)
                return AckResponse()
            raise ValueError(type(req).__name__)

        service = BasicService(f"spark_elastic_task_{index}", key, handle)
        service.start()
        try:
            client = BasicClient(driver_addr, key)
            # the executor process is the elastic "host" (unit of failure
            # and blacklisting) — see module docstring
            hh = f"{_r.host_hash()}[{index}]"
            client.request(_r.RegisterTask(
                index, socket.gethostname(), hh, service.address,
                task_id=uuid.uuid4().hex))
            func, fargs, fkwargs = cloudpickle.loads(payload)
            env_applier = _e._SpawnEnvApplier()
            while True:
                try:
                    cmd = cmds.get(timeout=60.0)
                except queue.Empty:
                    continue     # idle growth capacity; keep serving pings
                if cmd is None:
                    break
                # stale HOROVOD_*/extra_env keys from the previous spawn
                # must not leak into this one
                env_applier.apply(cmd.env)
                try:
                    value = func(*fargs, **fkwargs)
                except BaseException as e:  # noqa: BLE001 - to the driver
                    value = _r._TaskError(f"{type(e).__name__}: {e}")
                client.request(_e.ElasticTaskResult(
                    index, cmd.env[_e._RUN_ID_ENV], value))
        finally:
            service.shutdown()
        return [index]

    return _task


class _Run:
    """One worker spawn: which task serves it, where it is assigned, and
    its completion state."""

    def __init__(self, task_id: str, slot_key):
        self.task_id = task_id
        self.slot_key = slot_key           # (hostname, local_rank)
        self.done = threading.Event()
        self.exit_code: Optional[int] = None
        self.value: Any = None

    def complete(self, exit_code: int, value: Any = None) -> None:
        if not self.done.is_set():
            self.exit_code, self.value = exit_code, value
            self.done.set()


class _ExecutorPool:
    """Driver-side view of the registered tasks: registry, liveness,
    busy-tracking, and the discovery adapter the elastic driver polls.

    All state keys are the per-process ``task_id`` uuid, never the Spark
    partition index — Spark reuses indices when it re-runs a lost
    executor's task, and index keys would let the replacement's
    registration collide with the dead task's busy/consumed state."""

    def __init__(self, key: str):
        self._key = key
        self.lock = threading.Lock()
        self.registry: Dict[str, Any] = {}       # task_id -> RegisterTask
        self.busy: Dict[str, str] = {}           # task_id -> run_id
        self.consumed: set = set()               # tasks whose fn failed
        self.runs: Dict[str, _Run] = {}
        self.registered = threading.Event()

    def idle_tasks(self, host_hash: str) -> list:
        """Task ids on ``host_hash`` free to serve a worker, ordered by
        partition index (deterministic pick).  Busy tasks and tasks
        whose fn failed (``consumed`` — their process is poisoned) are
        excluded; keys are per-process uuids, so a replacement task at
        a reused Spark partition index never inherits its dead
        predecessor's state.  Callers must hold ``self.lock``."""
        return [tid for _, tid in sorted(
            (reg.index, tid) for tid, reg in self.registry.items()
            if reg.host_hash == host_hash
            and tid not in self.busy and tid not in self.consumed)]

    def _alive(self, reg) -> bool:
        """Probe with retries: one missed ping (GIL-starved service
        thread, loaded machine) must not read as executor death — death
        blacklists the host and burns a reset."""
        import time

        from horovod_tpu.runner.network import BasicClient

        for attempt in range(_PING_ATTEMPTS):
            try:
                BasicClient(reg.addr, self._key,
                            timeout_s=_PING_TIMEOUT_S).request(PingTask())
                return True
            except Exception:
                if attempt + 1 < _PING_ATTEMPTS:
                    time.sleep(_PING_RETRY_DELAY_S)
        return False

    def check_liveness(self) -> Dict[str, int]:
        """Ping every registered task concurrently; drop dead ones
        (completing any run they were serving as failed) and return
        alive ``{host_hash: slots}`` for discovery.  Concurrency bounds
        the sweep at one probe's worst case instead of one per dead
        task — the discovery loop calls this every second."""
        with self.lock:
            items = list(self.registry.items())
        alive: Dict[str, bool] = {}

        def _probe(tid, reg):
            alive[tid] = self._alive(reg)

        threads = [threading.Thread(target=_probe, args=(tid, reg),
                                    daemon=True) for tid, reg in items]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        hosts: Dict[str, int] = {}
        for tid, reg in items:
            if alive.get(tid):
                hosts[reg.host_hash] = hosts.get(reg.host_hash, 0) + 1
                continue
            hvd_logging.warning(
                "spark elastic: executor task %d (%s) stopped responding "
                "— removing from the pool", reg.index, reg.host_hash)
            with self.lock:
                self.registry.pop(tid, None)
                run_id = self.busy.pop(tid, None)
                run = self.runs.get(run_id) if run_id else None
            if run is not None:
                run.complete(1)
        return hosts


class _ExecutorPoolDiscovery(HostDiscovery):
    """Discovery = the live executor registry (reference: Spark task
    registration IS host discovery, ``spark/runner.py`` task addresses
    grouped by host hash)."""

    def __init__(self, pool: _ExecutorPool):
        self._pool = pool

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        return self._pool.check_liveness()


def run_elastic_on_context(sc, fn: Callable, args=(), kwargs=None,
                           num_proc: Optional[int] = None,
                           min_np: Optional[int] = None,
                           max_np: Optional[int] = None,
                           extra_env: Optional[dict] = None,
                           reset_limit: int = 0,
                           start_timeout: Optional[float] = None,
                           elastic_timeout: float = 600.0,
                           verbose: bool = False) -> List[Any]:
    """Elastic ``run`` over an executor-pool context (pyspark
    ``SparkContext`` or ``LocalSparkContext``) — the architecture of
    ``_run_on_spark`` with the one-shot command phase replaced by the
    :class:`ElasticDriver` lifecycle."""
    import cloudpickle

    from horovod_tpu.elastic.driver import START_TIMEOUT_S, ElasticDriver
    from horovod_tpu.runner.network import (
        AckResponse,
        BasicClient,
        BasicService,
        make_secret_key,
    )
    from horovod_tpu.spark import runner as _r

    num_proc = num_proc or sc.defaultParallelism
    min_np = min_np or num_proc
    max_np = max_np or num_proc
    if not (min_np <= num_proc <= max_np):
        raise ValueError(f"need min_np <= num_proc <= max_np, got "
                         f"{min_np}/{num_proc}/{max_np}")
    pool_size = max_np          # one executor task per potential slot
    register_timeout = float(os.environ.get(_r._START_TIMEOUT_ENV, "600"))
    worker_start_timeout = start_timeout if start_timeout is not None else \
        float(os.environ.get("HOROVOD_ELASTIC_START_TIMEOUT",
                             START_TIMEOUT_S))
    key = make_secret_key()
    payload = cloudpickle.dumps((fn, tuple(args), dict(kwargs or {})))
    pool = _ExecutorPool(key)

    def handle(req):
        if isinstance(req, _r.RegisterTask):
            with pool.lock:
                pool.registry[req.task_id] = req
                if len(pool.registry) >= min_np:
                    pool.registered.set()
            return AckResponse()
        if isinstance(req, ElasticTaskResult):
            with pool.lock:
                run = pool.runs.get(req.run_id)
                if run is not None:
                    pool.busy.pop(run.task_id, None)
                    if isinstance(req.value, _r._TaskError):
                        # this task's process ran a failed fn; its host
                        # gets blacklisted — never hand it another worker
                        pool.consumed.add(run.task_id)
            if run is not None:
                if isinstance(req.value, _r._TaskError):
                    hvd_logging.warning("spark elastic: worker on task %d "
                                        "failed: %s", req.index,
                                        req.value.message)
                    run.complete(1, req.value)
                else:
                    run.complete(0, req.value)
            return AckResponse()
        raise ValueError(type(req).__name__)

    service = BasicService("spark_elastic_driver", key, handle)
    service.start()
    job_error: List[BaseException] = []

    def _job():
        try:
            sc.parallelize(range(pool_size), pool_size) \
                .mapPartitionsWithIndex(_elastic_task_fn(
                    service.address, key, payload)) \
                .collect()
        except BaseException as e:  # noqa: BLE001
            job_error.append(e)

    spark_thread = threading.Thread(target=_job, daemon=True,
                                    name="hvd_tpu_spark_elastic_job")
    spark_thread.start()

    driver = ElasticDriver(_ExecutorPoolDiscovery(pool), min_np, max_np,
                           timeout=elastic_timeout,
                           reset_limit=reset_limit, secret_key=key,
                           start_timeout=worker_start_timeout)
    driver_host, driver_port = driver.address

    def create_worker_fn(slot, coordinator: str, generation: int,
                         abort_event=None) -> int:
        with pool.lock:
            candidates = pool.idle_tasks(slot.hostname)
            if not candidates:
                hvd_logging.warning(
                    "spark elastic: no idle executor task on %s for rank "
                    "%d", slot.hostname, slot.rank)
                return 1
            task_id = candidates[0]
            reg = pool.registry[task_id]
            run_id = uuid.uuid4().hex
            run = _Run(task_id, (slot.hostname, slot.local_rank))
            pool.runs[run_id] = run
            pool.busy[task_id] = run_id
        env = dict(extra_env or {})
        env.update(slot.to_env())
        env.update({
            "HOROVOD_COORDINATOR_ADDR": coordinator,
            "HOROVOD_CONTROLLER": "jax",
            "HOROVOD_ELASTIC": "1",
            "HOROVOD_SECRET_KEY": key,
            "HOROVOD_ELASTIC_DRIVER_ADDR": f"{driver_host}:{driver_port}",
            "HOROVOD_ELASTIC_NOTIFY_ADDR": "1",
            "HOROVOD_ELASTIC_GENERATION": str(generation),
            _RUN_ID_ENV: run_id,
        })
        if verbose:
            import sys

            print(f"[spark elastic] rank {slot.rank} gen {generation} -> "
                  f"task {reg.index} on {slot.hostname}", file=sys.stderr)
        try:
            BasicClient(reg.addr, key).request(_r.RunFunction(env))
        except Exception as e:
            hvd_logging.warning("spark elastic: could not command task %d: "
                                "%s", reg.index, e)
            with pool.lock:
                pool.busy.pop(task_id, None)
            run.complete(1)
            return 1
        while not run.done.wait(1.0):
            if abort_event is not None and abort_event.is_set():
                # in-process task workers can't be killed selectively;
                # consume the task so it is never reused and let the
                # pool's liveness/shutdown machinery reap the process
                with pool.lock:
                    pool.consumed.add(task_id)
                    pool.busy.pop(task_id, None)
                run.complete(1)
        return run.exit_code if run.exit_code is not None else 1

    def _shutdown_tasks():
        with pool.lock:
            regs = list(pool.registry.values())
        for reg in regs:
            try:
                BasicClient(reg.addr, key,
                            timeout_s=_PING_TIMEOUT_S).request(
                    _r.ShutdownTask())
            except Exception:
                pass

    try:
        if not pool.registered.wait(register_timeout):
            raise RuntimeError(
                f"only {len(pool.registry)}/{min_np} executor tasks "
                f"registered within {register_timeout:.0f}s "
                f"({_r._START_TIMEOUT_ENV} raises the wait)")
        if job_error:
            raise RuntimeError(
                f"executor pool failed during startup: {job_error[0]}")
        driver.start(num_proc, create_worker_fn)
        rc = driver.wait_for_completion()
        if rc != 0:
            raise RuntimeError(
                f"spark elastic job failed (exit code {rc})")
        # final-generation results in final-rank order: a surviving
        # worker's rank may differ from the one it spawned with, so map
        # each successful run's (host, local_rank) through the driver's
        # final assignments
        results: Dict[int, Any] = {}
        with pool.lock:
            finished = [r for r in pool.runs.values() if r.exit_code == 0]
        for run in finished:
            slot = driver.get_slot_info(*run.slot_key)
            if slot is not None:
                results[slot.rank] = run.value
        world = driver.world_size
        missing = sorted(set(range(world)) - set(results))
        if missing:
            raise RuntimeError(
                f"spark elastic job completed but ranks {missing} "
                f"returned no result")
        return [results[r] for r in range(world)]
    finally:
        driver.stop()      # no-op exit-code-wise once finished
        _shutdown_tasks()
        service.shutdown()
        spark_thread.join(30.0)
