"""Storage layout for estimator runs: train/val data, checkpoints, logs.

Reference: ``horovod/spark/common/store.py`` — the ``Store`` manages
"train/val/test data paths, checkpoint + runs paths, and filesystem
access" for estimators (``LocalStore``/``HDFSStore``, 433 LoC).  The TPU
edition keeps the same directory contract and method surface over a
plain filesystem (parquet via pyarrow, which the reference also uses
through petastorm), so an estimator run leaves the same artifact layout
a reference user expects:

    <prefix>/
      intermediate_train_data/   (parquet)
      intermediate_val_data/     (parquet)
      runs/<run_id>/
        checkpoint/              (Checkpointer output)
        logs/
        metadata.json            (column specs, see ``infer_metadata``)
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional, Sequence, Union

import numpy as np


class Store:
    """Abstract artifact store (reference ``Store``, ``store.py:29``)."""

    def is_parquet_dataset(self, path: str) -> bool:
        raise NotImplementedError

    def get_train_data_path(self, idx: Union[int, str, None] = None) -> str:
        raise NotImplementedError

    def get_val_data_path(self, idx: Union[int, str, None] = None) -> str:
        raise NotImplementedError

    def get_test_data_path(self, idx: Union[int, str, None] = None) -> str:
        raise NotImplementedError

    def saving_runs(self) -> bool:
        raise NotImplementedError

    def get_runs_path(self) -> str:
        raise NotImplementedError

    def get_run_path(self, run_id: str) -> str:
        raise NotImplementedError

    def get_checkpoint_path(self, run_id: str) -> str:
        raise NotImplementedError

    def get_logs_path(self, run_id: str) -> str:
        raise NotImplementedError

    def get_checkpoint_filename(self) -> str:
        return "checkpoint"

    def get_logs_subdir(self) -> str:
        return "logs"

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def read(self, path: str) -> bytes:
        raise NotImplementedError

    def write(self, path: str, data: bytes) -> None:
        raise NotImplementedError

    def makedirs(self, path: str) -> None:
        raise NotImplementedError

    def delete(self, path: str) -> None:
        raise NotImplementedError

    def new_run_id(self) -> str:
        raise NotImplementedError

    def write_dataframe(self, df, path: str) -> None:
        raise NotImplementedError

    def read_dataframe(self, path: str):
        raise NotImplementedError

    def prepare_data(self, df, feature_cols, label_col,
                     validation_fraction: float = 0.0,
                     rows_per_group: Optional[int] = None,
                     idx="prepared") -> "PreparedData":
        raise NotImplementedError

    def list_runs(self, complete_only: bool = False) -> list:
        raise NotImplementedError

    @staticmethod
    def create(prefix_path: str, *args, **kwargs) -> "Store":
        """Factory by path scheme (reference ``Store.create``,
        ``store.py:141``): URL-prefixed paths go through the fsspec
        store when fsspec is importable."""
        if prefix_path.startswith("file://"):
            prefix_path = prefix_path[len("file://"):]
        if "://" in prefix_path:
            try:
                import fsspec  # noqa: F401
            except ImportError:
                raise NotImplementedError(
                    f"remote store scheme in '{prefix_path}' needs fsspec "
                    f"(plus the scheme's client library, e.g. gcsfs/"
                    f"s3fs/pyarrow-hdfs); install it, or mount the "
                    f"filesystem (fuse) and pass a local path.")
            return FsspecStore(prefix_path, *args, **kwargs)
        return LocalStore(prefix_path, *args, **kwargs)


def _run_no(name: str) -> int:
    """Numeric part of a run id — ``run_007`` and the remote
    uuid-suffixed ``run_007_3fa2b1c4`` both parse to 7; -1 if not a run
    id."""
    try:
        return int(name[4:].split("_", 1)[0])
    except (ValueError, IndexError):
        return -1


class FilesystemStore(Store):
    """Store over a (possibly network-mounted) filesystem (reference
    ``FilesystemStore``, ``store.py:148`` — same path layout)."""

    is_remote = False

    def __init__(self, prefix_path: str,
                 train_path: Optional[str] = None,
                 val_path: Optional[str] = None,
                 test_path: Optional[str] = None,
                 runs_path: Optional[str] = None,
                 save_runs: bool = True):
        self.prefix_path = prefix_path
        self._train_path = train_path or os.path.join(
            prefix_path, "intermediate_train_data")
        self._val_path = val_path or os.path.join(
            prefix_path, "intermediate_val_data")
        self._test_path = test_path or os.path.join(
            prefix_path, "intermediate_test_data")
        self._runs_path = runs_path or os.path.join(prefix_path, "runs")
        self._save_runs = save_runs

    def is_parquet_dataset(self, path: str) -> bool:
        return os.path.isdir(path) and any(
            f.endswith(".parquet") for f in os.listdir(path))

    def get_train_data_path(self, idx: Union[int, str, None] = None) -> str:
        """``idx`` scopes intermediate data per dataset/run (reference
        keys by dataset index; the estimator passes the run id)."""
        return self._train_path if idx is None \
            else f"{self._train_path}.{idx}"

    def get_val_data_path(self, idx: Union[int, str, None] = None) -> str:
        return self._val_path if idx is None else f"{self._val_path}.{idx}"

    def get_test_data_path(self, idx: Union[int, str, None] = None) -> str:
        return self._test_path if idx is None \
            else f"{self._test_path}.{idx}"

    def saving_runs(self) -> bool:
        return self._save_runs

    def get_runs_path(self) -> str:
        return self._runs_path

    def get_run_path(self, run_id: str) -> str:
        return os.path.join(self._runs_path, run_id)

    def get_checkpoint_path(self, run_id: str) -> str:
        return os.path.join(self.get_run_path(run_id),
                            self.get_checkpoint_filename())

    def get_logs_path(self, run_id: str) -> str:
        return os.path.join(self.get_run_path(run_id),
                            self.get_logs_subdir())

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def read(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    def write(self, path: str, data: bytes) -> None:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "wb") as f:
            f.write(data)

    def makedirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def delete(self, path: str) -> None:
        import shutil

        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.remove(path)


    # -- dataset preparation (reference spark/common/util.py:697
    #    prepare_data: DataFrame -> store parquet + metadata) -------------

    SCHEMA_FILE = "_hvd_schema.json"

    def prepare_data(self, df, feature_cols, label_col,
                     validation_fraction: float = 0.0,
                     rows_per_group: Optional[int] = None,
                     idx="prepared") -> "PreparedData":
        """Materialize a DataFrame-shaped source into the store's
        streaming parquet layout, once, ahead of any number of fits.

        ``df`` may be a pandas DataFrame, any object exposing
        ``toPandas()`` (a Spark DataFrame) or ``to_pandas()`` (pyarrow
        Table, polars), or a dict of column arrays.  Schema is inferred
        and validated through :func:`extract_typed` (the reference's
        ``_get_metadata`` inference), rows split train/validation, each
        side written as multi-row-group parquet (the
        :class:`RowGroupReader` sharding unit), and the schema saved as
        a ``_hvd_schema.json`` sidecar so ``Estimator.fit(path)``
        streams without re-probing.  Returns :class:`PreparedData`.

        A pyspark DataFrame takes the executor-side path instead of
        ``toPandas()`` (which lands the whole dataset in driver memory):
        its partitions write their own parquet parts where they already
        live (see :meth:`prepare_data_distributed`).  The routing needs
        an ``.rdd`` (pyspark.pandas / Spark Connect frames fall through
        to their ``to_pandas()``) and a store KNOWN to be reachable from
        executors — a real remote scheme.  A plain local path may or may
        not be a shared mount (the driver cannot tell), so it keeps the
        driver-side write; call :meth:`prepare_data_distributed`
        explicitly when the path is cluster-visible.

        Validation-split semantics (the two paths differ, by
        construction): the driver-side path holds out the GLOBAL tail
        ``validation_fraction`` of the DataFrame's rows — one split
        point over the whole ordered dataset — while the executor-side
        path holds out each PARTITION's tail (the driver never sees the
        rows, so a global split point does not exist there); membership
        and row order of the two splits therefore differ for the same
        call.  To keep ``prepare_data`` deterministic in what it means,
        a pyspark frame with ``validation_fraction > 0`` stays on the
        driver-side (global-tail) path even when the store is
        executor-reachable; per-partition-tail splitting is an explicit
        opt-in via :meth:`prepare_data_distributed`.
        """
        if type(df).__module__.split(".", 1)[0] == "pyspark" and \
                hasattr(df, "rdd") and self._executor_reachable() and \
                not validation_fraction:
            return self._prepare_from_rdd(
                df.rdd, feature_cols, label_col, validation_fraction,
                rows_per_group, idx)
        df = _to_pandas_like(df)
        # validate schema + dtypes column-by-column: each column is
        # materialized (cast-checked) once and immediately discarded, so
        # peak memory is one column, not a full casted dataset copy
        feature_specs = []
        for c in feature_cols:
            _, (spec,) = extract_typed(df, [c])
            feature_specs.append(spec)
        _, (label_spec,) = extract_typed(df, [label_col])
        n = len(df)
        n_val = int(n * validation_fraction)
        split = n - n_val
        rpg = rows_per_group or max(split // 8, 1)
        cols = list(dict.fromkeys(list(feature_cols) + [label_col]))
        train_path = self.get_train_data_path(idx)
        # a prior distributed prepare may have left part-00001.. here;
        # stale parts would silently join this dataset (write_dataframe
        # only overwrites part-00000)
        self.delete(train_path)
        self.write_dataframe(df.iloc[:split][cols], train_path,
                             rows_per_group=rpg)
        val_path = None
        if n_val:
            val_path = self.get_val_data_path(idx)
            self.delete(val_path)
            self.write_dataframe(df.iloc[split:][cols], val_path,
                                 rows_per_group=rpg)
        def schema_json(role):
            return json.dumps({
                "features": [sp.to_json() for sp in feature_specs],
                "label": label_spec.to_json(),
                "val_path": val_path,
                "role": role,
            }, indent=2).encode()

        self.write(os.path.join(train_path, self.SCHEMA_FILE),
                   schema_json("train"))
        if val_path:
            self.write(os.path.join(val_path, self.SCHEMA_FILE),
                       schema_json("val"))
        return PreparedData(train_path, val_path, feature_specs,
                            label_spec)

    def prepare_data_distributed(self, sc, partitions, feature_cols,
                                 label_col,
                                 validation_fraction: float = 0.0,
                                 rows_per_group: Optional[int] = None,
                                 idx="prepared") -> "PreparedData":
        """Executor-side ingestion: each partition materializes and
        writes its rows ON an executor (reference
        ``spark/common/util.py:541-590`` ``_get_or_create_dataset`` —
        ``df.rdd.map(to_petastorm).toDF()`` distributed parquet write);
        the driver never holds more than one partition's *metadata*, so
        dataset size is bounded by executor memory, not driver memory.

        ``validation_fraction`` here splits each PARTITION's tail — not
        the global tail :meth:`prepare_data` takes — because no single
        process ever orders the full dataset.  Same fraction of rows
        held out overall (up to per-partition rounding), different
        membership; see the semantics note on :meth:`prepare_data`.

        ``sc`` is any executor context exposing the ``run()`` RDD slice
        (pyspark ``SparkContext`` or
        :class:`~horovod_tpu.spark.local_executor.LocalSparkContext`).
        ``partitions`` is a list of per-partition sources: each element
        is a DataFrame-shaped chunk or a zero-arg callable returning one
        (callables let executors *generate* their data — e.g. read their
        own files — without it ever existing on the driver).  pyspark
        serializes parallelize()'d data with plain pickle, so callables
        there must be plain-picklable (a module-level function or
        ``functools.partial`` of one, not a closure); the local pool
        ships data via cloudpickle and takes closures too.

        The produced layout is byte-identical in kind to
        :meth:`prepare_data`'s — ``part-NNNNN.parquet`` files +
        ``_meta.json`` + ``_hvd_schema.json`` per side — so every reader
        (``RowGroupReader``, ``Estimator.fit``) is unchanged.

        Note: the store itself must be reachable from executors (a
        shared filesystem or a real remote scheme); a ``memory://``
        store is process-local and cannot receive executor writes.
        """
        parts = list(partitions)   # consume a generator argument ONCE
        rdd = sc.parallelize(parts, max(len(parts), 1))
        return self._prepare_from_rdd(rdd, feature_cols, label_col,
                                      validation_fraction, rows_per_group,
                                      idx)

    def _process_local(self) -> bool:
        """True when this store's filesystem lives inside the calling
        process (executors cannot write into it)."""
        return False

    def _executor_reachable(self) -> bool:
        """True when executor processes are KNOWN to see this store's
        paths (a real remote scheme).  A plain local path is unknowable
        — it may be a private disk or a shared mount — so automatic
        pyspark routing stays conservative and only
        :meth:`prepare_data_distributed` (an explicit claim by the
        caller) uses it."""
        return False

    def _prepare_from_rdd(self, rdd, feature_cols, label_col,
                          validation_fraction, rows_per_group,
                          idx) -> "PreparedData":
        if self._process_local():
            raise ValueError(
                "executor-side prepare needs a store reachable from "
                "executor processes; this store's filesystem is "
                f"process-local ({self.prefix_path!r}) — use a shared "
                "path or a real remote scheme, or pass a pandas "
                "DataFrame for the driver-side path")
        train_path = self.get_train_data_path(idx)
        val_path = self.get_val_data_path(idx) if validation_fraction \
            else None
        # a previous prepare may have left MORE parts than this one
        # writes; stale part files would silently join the dataset
        self.delete(train_path)
        if val_path:
            self.delete(val_path)
        fn = _prepare_part_fn(
            self.prefix_path, list(feature_cols), label_col,
            float(validation_fraction), rows_per_group, train_path,
            val_path)
        metas = [m for m in rdd.mapPartitionsWithIndex(fn).collect() if m]
        if not metas:
            raise ValueError("prepare_data_distributed: no partition "
                             "produced any rows")
        first = metas[0]
        for m in metas[1:]:
            for k in ("features", "label", "shapes"):
                if m[k] != first[k]:
                    raise ValueError(
                        f"partition {m['part']} disagrees with partition "
                        f"{first['part']} on {k}: {m[k]!r} vs "
                        f"{first[k]!r} — executor-side schemas must be "
                        f"identical")
        total_val = sum(m["val_rows"] for m in metas)
        if val_path and not total_val:
            val_path = None
        feature_specs = [ColSpec.from_json(d) for d in first["features"]]
        label_spec = ColSpec.from_json(first["label"])
        # driver-side sidecar merge: one _meta.json + schema per side
        for side in filter(None, (train_path, val_path)):
            self.write(os.path.join(side, "_meta.json"),
                       json.dumps({"shapes": first["shapes"]}).encode())

        def schema_json(role):
            return json.dumps({
                "features": first["features"],
                "label": first["label"],
                "val_path": val_path,
                "role": role,
            }, indent=2).encode()

        self.write(os.path.join(train_path, self.SCHEMA_FILE),
                   schema_json("train"))
        if val_path:
            self.write(os.path.join(val_path, self.SCHEMA_FILE),
                       schema_json("val"))
        return PreparedData(train_path, val_path, feature_specs,
                            label_spec)

    @staticmethod
    def load_schema(path: str) -> Optional["PreparedData"]:
        """Recover :class:`PreparedData` from a prepared directory's
        sidecar (local or any fsspec URL), or None when the directory
        has no sidecar (plain parquet — callers fall back to
        head-probing)."""
        sidecar = path.rstrip("/") + "/" + FilesystemStore.SCHEMA_FILE
        if "://" in path and not path.startswith("file://"):
            import fsspec

            fs, _ = fsspec.core.url_to_fs(path)
            if not fs.exists(sidecar):
                return None
            with fs.open(sidecar, "r") as f:
                raw = json.load(f)
        else:
            if not os.path.exists(sidecar):
                return None
            with open(sidecar) as f:
                raw = json.load(f)
        # a val-side sidecar must not re-propagate its own dir as the
        # validation split — fitting on it directly would train AND
        # validate on the identical rows with no signal
        val = raw.get("val_path") if raw.get("role", "train") == "train" \
            else None
        return PreparedData(
            path, val,
            [ColSpec.from_json(d) for d in raw["features"]],
            ColSpec.from_json(raw["label"]))


    def list_runs(self, complete_only: bool = False) -> list:
        """Run ids under the runs dir, newest last (numeric sort — ids
        grow past the zero padding after run_999; remote uuid-suffixed
        ids ``run_NNN_xxxxxxxx`` order by NNN, ties lexically).
        ``complete_only`` keeps only runs whose metadata landed:
        ``new_run_id`` reserves the directory before any artifact
        exists, so an in-progress or crashed fit otherwise shows up as
        the "newest" run."""
        try:
            entries = self._listdir(self._runs_path)
        except (FileNotFoundError, NotADirectoryError, OSError):
            return []
        names = [str(e).rstrip("/").rsplit("/", 1)[-1] for e in entries]
        runs = sorted((n for n in names
                       if n.startswith("run_") and _run_no(n) >= 0),
                      key=lambda n: (_run_no(n), n))
        if complete_only:
            runs = [r for r in runs if self.exists(
                os.path.join(self.get_run_path(r), "metadata.json"))]
        return runs

    def new_run_id(self) -> str:
        """Next free ``run_NNN`` under the runs dir, reserved atomically
        with ``mkdir`` — two jobs sharing a store prefix must never both
        claim the same run and clobber each other's artifacts."""
        os.makedirs(self._runs_path, exist_ok=True)
        while True:
            existing = [d for d in os.listdir(self._runs_path)
                        if d.startswith("run_")]
            nums = [v for v in map(_run_no, existing) if v >= 0]
            rid = f"run_{(max(nums) + 1) if nums else 1:03d}"
            try:
                os.mkdir(os.path.join(self._runs_path, rid))
                return rid
            except FileExistsError:
                continue   # lost the race; re-scan

    # -- dataframe materialization (reference util.py prepare_data /
    #    petastorm parquet round-trip) -----------------------------------

    # overridable IO primitives shared by the local and fsspec stores
    def _open(self, path: str, mode: str):
        return open(path, mode)

    def _listdir(self, path: str) -> list:
        return [os.path.join(path, f) for f in os.listdir(path)]

    def write_dataframe(self, df, path: str,
                        rows_per_group: Optional[int] = None) -> None:
        """Materialize as parquet.  Multi-dimensional array cells
        (images) are flattened to 1-D lists with their per-row shape
        recorded in ``_meta.json`` — parquet has no tensor type, so the
        reference stores intermediate data exactly this way (petastorm
        flattens ndarrays and reshapes from metadata at read time,
        ``spark/common/util.py``).

        ``rows_per_group`` bounds the parquet row-group size: row groups
        are the streaming/sharding unit :class:`RowGroupReader` hands to
        workers, so a multi-group layout is what makes ``Estimator.fit``
        stream instead of materializing (petastorm's row-group reader
        contract, reference ``spark/common/util.py:697``).
        """
        shapes = self._write_parquet_part(df, path, "part-00000.parquet",
                                          rows_per_group)
        with self._open(path.rstrip("/") + "/_meta.json", "w") as f:
            json.dump({"shapes": shapes}, f)

    def _write_parquet_part(self, df, path: str, part_name: str,
                            rows_per_group: Optional[int] = None) -> dict:
        """One parquet part file of the store data-dir layout (no
        ``_meta.json`` — the caller owns the directory-level sidecars,
        so executor tasks can each write their own part).  Returns the
        tensor-shape map for the sidecar."""
        import pandas as pd
        import pyarrow as pa
        import pyarrow.parquet as pq

        self.makedirs(path)
        if not isinstance(df, pd.DataFrame):
            df = pd.DataFrame({k: list(v) for k, v in df.items()})
        shapes = {}
        out = {}
        for c in df.columns:
            col = df[c]
            first = col.iloc[0] if len(col) else None
            if isinstance(first, np.ndarray) and first.ndim > 1:
                shapes[c] = list(first.shape)
                out[c] = [np.ravel(v) for v in col]
            else:
                out[c] = col
        table = pa.Table.from_pandas(pd.DataFrame(out),
                                     preserve_index=False)
        with self._open(path.rstrip("/") + "/" + part_name, "wb") as f:
            pq.write_table(table, f,
                           row_group_size=rows_per_group or len(df) or 1)
        return shapes

    def read_dataframe(self, path: str, row_range=None):
        """Materialize a store data dir as pandas.  ``row_range=(start,
        stop)`` reads ONLY the parquet row groups overlapping that
        global row interval (footer-pruned through the store's IO
        primitives, so remote stores transfer just those pages too) and
        slices to the exact rows — the shard/range read a worker uses
        to fetch its 1/N instead of the full dataset
        (:class:`RowGroupReader` is the richer local-file API)."""
        import pandas as pd
        import pyarrow.parquet as pq

        parts = sorted(p for p in self._listdir(path)
                       if str(p).endswith(".parquet"))
        if not parts:
            raise FileNotFoundError(f"no parquet files under {path}")
        frames = []
        if row_range is None:
            for part in parts:
                with self._open(part, "rb") as f:
                    frames.append(pq.read_table(f).to_pandas())
        else:
            start, stop = (int(row_range[0]), int(row_range[1]))
            if start < 0 or stop < start:
                raise ValueError(f"bad row_range {row_range!r}")
            pos = 0
            for part in parts:
                with self._open(part, "rb") as f:
                    pf = pq.ParquetFile(f)
                    for g in range(pf.metadata.num_row_groups):
                        n = pf.metadata.row_group(g).num_rows
                        glo, ghi = pos, pos + n
                        pos = ghi
                        if ghi <= start or glo >= stop:
                            continue
                        gdf = pf.read_row_group(g).to_pandas()
                        frames.append(gdf.iloc[max(start - glo, 0):
                                               min(stop, ghi) - glo])
            if not frames:
                raise ValueError(
                    f"row_range {row_range!r} selects no rows of the "
                    f"{pos}-row dataset at {path!r}")
        df = pd.concat(frames, ignore_index=True)
        meta_path = path.rstrip("/") + "/_meta.json"
        if df is not None and self.exists(meta_path):
            with self._open(meta_path, "r") as f:
                shapes = json.load(f).get("shapes", {})
            for c, shape in shapes.items():
                df[c] = [np.asarray(v).reshape(shape) for v in df[c]]
        return df


class RowGroupReader:
    """Streaming shard reader over a store data directory.

    The petastorm-reader analogue (reference ``spark/keras/remote.py:336``
    trains from per-worker parquet shard streams; schema machinery in
    ``spark/common/util.py:697``): parquet row groups are the unit of
    sharding and of IO, so a worker touches only its own groups and holds
    at most one group in memory at a time.  ``groups_read`` /
    ``rows_materialized`` record what was actually read off disk — the
    accounting hooks the sharding tests assert on.  Beyond the classic
    round-robin :meth:`shard_groups`, the range API —
    :meth:`shard_range` / :meth:`read_rows` / :meth:`take` — serves
    index-range shards and shuffled gathers with group-pruned IO (what
    :class:`horovod_tpu.data.ShardedDataset` drives).
    """

    def __init__(self, path: str):
        import glob as _glob

        import pyarrow.parquet as pq

        files = sorted(_glob.glob(os.path.join(path, "*.parquet")))
        if not files:
            raise FileNotFoundError(f"no parquet files under {path!r}")
        self._pfs = [pq.ParquetFile(f) for f in files]
        self._shapes = {}
        meta_path = os.path.join(path, "_meta.json")
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                self._shapes = json.load(f).get("shapes", {})
        # global group index -> (file, local group index, row count);
        # built from parquet footers only — no data pages are read
        self._groups = []
        for pf in self._pfs:
            for g in range(pf.metadata.num_row_groups):
                self._groups.append(
                    (pf, g, pf.metadata.row_group(g).num_rows))
        self.groups_read: List[int] = []
        # rows actually materialized off disk — the no-full-copy
        # accounting (a 1/N shard reader must stay near num_rows/N)
        self.rows_materialized = 0
        # cumulative row offsets: group g spans [offsets[g], offsets[g+1])
        self._offsets = np.concatenate(
            [[0], np.cumsum([n for _, _, n in self._groups])]).astype(
            np.int64)

    @property
    def num_row_groups(self) -> int:
        return len(self._groups)

    @property
    def num_rows(self) -> int:
        """Total rows across every part/group (footer metadata only)."""
        return int(self._offsets[-1])

    @property
    def group_rows(self) -> List[int]:
        """Per-group row counts (footer metadata, identical on every
        process — lets ranks agree on step counts without communicating)."""
        return [n for _, _, n in self._groups]

    def shard_groups(self, shard: int, num_shards: int) -> List[int]:
        """Round-robin group assignment: shard ``p`` of ``n`` owns groups
        ``p, p+n, p+2n, …`` (petastorm ``cur_shard``/``shard_count``)."""
        return list(range(shard, self.num_row_groups, num_shards))

    def shard_range(self, shard: int, num_shards: int):
        """Contiguous row-range assignment ``[lo, hi)``: shard ``p`` of
        ``n`` owns rows ``[p*⌊N/n⌋, (p+1)*⌊N/n⌋)`` — equal-size shards,
        remainder dropped (the input plane's zero-tail invariant: every
        shard identical in size, no ragged tail).  The unit a
        :class:`~horovod_tpu.data.ShardedDataset` maps onto range
        reads."""
        per = self.num_rows // num_shards
        return shard * per, (shard + 1) * per

    def read_group(self, index: int):
        """Materialize one row group as a pandas DataFrame (tensor cells
        reshaped from ``_meta.json``)."""
        pf, local, nrows = self._groups[index]
        self.groups_read.append(index)
        self.rows_materialized += nrows
        df = pf.read_row_group(local).to_pandas()
        for c, shape in self._shapes.items():
            if c in df.columns:
                df[c] = [np.asarray(v).reshape(shape) for v in df[c]]
        return df

    def read_rows(self, start: int, stop: int):
        """Rows ``[start, stop)`` as one DataFrame, touching only the
        row groups overlapping the range (range read: IO cost scales
        with the slice, not the dataset)."""
        import pandas as pd

        if not 0 <= start <= stop <= self.num_rows:
            raise ValueError(
                f"row range [{start}, {stop}) outside the "
                f"{self.num_rows}-row dataset")
        if start == stop:
            raise ValueError("empty row range")
        g_lo = int(np.searchsorted(self._offsets, start, side="right")) - 1
        g_hi = int(np.searchsorted(self._offsets, stop, side="left"))
        frames = []
        for g in range(g_lo, g_hi):
            df = self.read_group(g)
            lo = max(start - int(self._offsets[g]), 0)
            hi = min(stop, int(self._offsets[g + 1])) - int(
                self._offsets[g])
            frames.append(df.iloc[lo:hi])
        return pd.concat(frames, ignore_index=True) if len(frames) > 1 \
            else frames[0].reset_index(drop=True)

    def take(self, indices):
        """Arbitrary global rows, in the requested order, each needed
        group read once (the shuffled-shard gather: a rank fetching its
        permuted 1/N touches ~1/N of the groups, never the rest)."""
        import pandas as pd

        idx = np.asarray(indices, dtype=np.int64)
        if idx.size == 0:
            raise ValueError("take() of no indices")
        if idx.min() < 0 or idx.max() >= self.num_rows:
            raise IndexError(
                f"row indices outside [0, {self.num_rows})")
        gids = np.searchsorted(self._offsets, idx, side="right") - 1
        frames, base, off = {}, {}, 0
        for g in np.unique(gids):
            frames[int(g)] = self.read_group(int(g))
            base[int(g)] = off
            off += len(frames[int(g)])
        cat = pd.concat([frames[g] for g in sorted(frames)],
                        ignore_index=True) if len(frames) > 1 \
            else next(iter(frames.values()))
        pos = np.asarray([base[int(g)] + int(i) - int(self._offsets[g])
                          for g, i in zip(gids, idx)])
        return cat.iloc[pos].reset_index(drop=True)


class LocalStore(FilesystemStore):
    """Local-disk store (reference ``LocalStore``, ``store.py:251``)."""




class FsspecStore(FilesystemStore):
    """Store over any fsspec filesystem — ``hdfs://``, ``gs://``,
    ``s3://``, ``memory://`` ... (reference ``HDFSStore``,
    ``store.py:279``, pyarrow-libhdfs based; fsspec is the TPU-era
    equivalent that covers every remote scheme with one code path).

    Inherits the full path layout and :meth:`prepare_data` from
    :class:`FilesystemStore`; only the IO primitives are rerouted
    through the filesystem handle.  Soft-gated: constructing without
    fsspec (or without the scheme's client library) raises with the
    install hint.  :class:`RowGroupReader` streaming requires a local
    (or fuse-mounted) path — remote stores read datasets whole via
    :meth:`read_dataframe`.
    """

    def __init__(self, prefix_path: str, **kwargs):
        try:
            import fsspec
        except ImportError as e:  # pragma: no cover - fsspec is bundled
            raise NotImplementedError(
                "FsspecStore requires fsspec") from e
        try:
            self._fs, _ = fsspec.core.url_to_fs(prefix_path)
        except ImportError as e:
            raise NotImplementedError(
                f"remote store scheme in '{prefix_path}' needs the "
                f"scheme's fsspec client library (gcsfs/s3fs/...): {e}"
            ) from e
        except OSError as e:
            raise NotImplementedError(
                f"remote store for '{prefix_path}' is not reachable in "
                f"this environment (client library failed to load: {e})"
            ) from e
        super().__init__(prefix_path.rstrip("/"), **kwargs)

    # -- IO primitives over the fsspec handle ---------------------------

    def exists(self, path: str) -> bool:
        return self._fs.exists(path)

    def read(self, path: str) -> bytes:
        with self._fs.open(path, "rb") as f:
            return f.read()

    def write(self, path: str, data: bytes) -> None:
        parent = path.rsplit("/", 1)[0]
        self._fs.makedirs(parent, exist_ok=True)
        with self._fs.open(path, "wb") as f:
            f.write(data)

    def makedirs(self, path: str) -> None:
        self._fs.makedirs(path, exist_ok=True)
        # object stores have no empty directories; a marker makes the
        # path observable (the reference's HDFS mkdir has real dirs)
        marker = path.rstrip("/") + "/.hvd_dir"
        if not self._fs.exists(marker):
            with self._fs.open(marker, "wb") as f:
                f.write(b"")

    def delete(self, path: str) -> None:
        if self._fs.exists(path):
            self._fs.rm(path, recursive=True)

    def is_parquet_dataset(self, path: str) -> bool:
        if not self._fs.exists(path):
            return False
        try:
            return any(str(f).endswith(".parquet")
                       for f in self._fs.ls(path, detail=False))
        except (FileNotFoundError, NotADirectoryError):
            return False

    def new_run_id(self) -> str:
        """Next run id, ``run_NNN_<uuid8>``.  Object stores lack an
        atomic mkdir, so the number alone cannot be a reservation — any
        write-then-list protocol leaves a window where two drivers both
        claim one id.  Remote run ids therefore embed a uuid: two
        drivers sharing a store prefix may both pick the next *number*,
        but their run directories are distinct and artifacts never
        interleave.  ``list_runs`` orders by the numeric part (ties —
        concurrent claims — lexically by suffix)."""
        import uuid

        self._fs.makedirs(self._runs_path, exist_ok=True)
        self._fs.invalidate_cache(self._runs_path)
        try:
            existing = [str(d).rstrip("/").rsplit("/", 1)[-1]
                        for d in self._fs.ls(self._runs_path,
                                             detail=False)]
        except FileNotFoundError:
            existing = []
        nums = [_run_no(d) for d in existing if d.startswith("run_")]
        n = max((v for v in nums if v >= 0), default=0) + 1
        run_id = f"run_{n:03d}_{uuid.uuid4().hex[:8]}"
        self.makedirs(self.get_run_path(run_id))
        return run_id

    is_remote = True

    def download_dir(self, remote: str, local: str) -> None:
        """Fetch a remote directory tree to a local path (checkpoint
        restore staging)."""
        self._fs.get(remote.rstrip("/") + "/", local.rstrip("/") + "/",
                     recursive=True)

    def _process_local(self) -> bool:
        proto = getattr(self._fs, "protocol", "")
        protos = {proto} if isinstance(proto, str) else set(proto)
        return "memory" in protos

    def _executor_reachable(self) -> bool:
        return not self._process_local()

    def upload_file(self, local: str, remote: str) -> None:
        """Streamed single-file upload — ``put_file`` transfers in
        chunks, so multi-GB checkpoint files never materialize as one
        host bytes object (the incremental estimator mirror's path)."""
        self._fs.makedirs(remote.rsplit("/", 1)[0], exist_ok=True)
        self._fs.put_file(local, remote)

    def upload_dir(self, local: str, remote: str) -> None:
        """Push a local directory tree into the store (checkpoint
        staging upload)."""
        self._fs.makedirs(remote, exist_ok=True)
        self._fs.put(local.rstrip("/") + "/", remote.rstrip("/") + "/",
                     recursive=True)

    def _open(self, path: str, mode: str):
        return self._fs.open(path, mode)

    def _listdir(self, path: str) -> list:
        return [str(p) for p in self._fs.ls(path, detail=False)]


class HDFSStore(FsspecStore):
    """HDFS store (reference ``HDFSStore``, ``store.py:279``): the
    fsspec store pinned to the ``hdfs://`` scheme.  Soft-gated — raises
    with an install hint when fsspec (or the hdfs client behind it,
    pyarrow libhdfs) is unavailable, exactly as the reference errors
    without libhdfs."""

    def __init__(self, prefix_path: str, **kwargs):
        if "://" not in prefix_path:
            # bare path -> path on the default namenode; stripping the
            # leading slash would make the first component the host
            prefix_path = "hdfs://" + ("" if prefix_path.startswith("/")
                                       else "/") + prefix_path
        if not prefix_path.startswith("hdfs://"):
            raise ValueError(
                f"HDFSStore expects an hdfs:// path, got '{prefix_path}'"
                " (use Store.create for other schemes)")
        try:
            super().__init__(prefix_path, **kwargs)
        except (ImportError, NotImplementedError) as e:
            raise NotImplementedError(
                "HDFSStore requires fsspec + an HDFS client "
                "(pyarrow libhdfs); install them or use LocalStore over "
                "a mounted path.") from e


# ---------------------------------------------------------------------------
# typed column metadata (reference spark/common/util.py schema inference)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ColSpec:
    """One column's type/shape contract (reference metadata entries:
    per-column dtype, shape and max_size inferred from the DataFrame,
    ``util.py`` ``_get_metadata``)."""

    name: str
    dtype: str            # numpy dtype name, e.g. "float32", "int32"
    shape: tuple          # per-row shape, () for scalars

    def to_json(self) -> dict:
        return {"name": self.name, "dtype": self.dtype,
                "shape": list(self.shape)}

    @staticmethod
    def from_json(d: dict) -> "ColSpec":
        return ColSpec(d["name"], d["dtype"], tuple(d["shape"]))


@dataclasses.dataclass
class PreparedData:
    """Handle to store-prepared training data: paths + schema (the
    reference returns (rows, val_rows, metadata, avg_row_size) from its
    prepare step; paths+specs are the TPU-side equivalent)."""

    train_path: str
    val_path: Optional[str]
    feature_specs: List["ColSpec"]
    label_spec: "ColSpec"


def _prepare_part_fn(store_prefix: str, feature_cols, label_col: str,
                     val_frac: float, rows_per_group, train_path: str,
                     val_path):
    """The executor-side body of distributed prepare: materialize this
    partition's rows, split train/val by the tail fraction, write one
    ``part-NNNNN.parquet`` per side, and return the partition's schema
    for the driver-side agreement check + sidecar merge."""

    def _fn(index: int, iterator):
        import pandas as pd

        from horovod_tpu.spark.store import (
            Store,
            _to_pandas_like,
            extract_typed,
        )

        chunks = []
        rows = []
        for item in iterator:
            if callable(item):
                item = item()
            elif hasattr(item, "asDict"):      # pyspark Row
                rows.append(item.asDict())
                continue
            chunks.append(_to_pandas_like(item))
        if rows:
            chunks.append(pd.DataFrame(rows))
        if not chunks:
            return []
        chunk = chunks[0] if len(chunks) == 1 else \
            pd.concat(chunks, ignore_index=True)
        store = Store.create(store_prefix)
        feature_specs = []
        for c in feature_cols:
            _, (spec,) = extract_typed(chunk, [c])
            feature_specs.append(spec)
        _, (label_spec,) = extract_typed(chunk, [label_col])
        n = len(chunk)
        n_val = int(n * val_frac)
        split = n - n_val
        cols = list(dict.fromkeys(list(feature_cols) + [label_col]))
        part = f"part-{index:05d}.parquet"
        # same default as the driver-side prepare (split // 8): both
        # paths must shard identical data identically
        rpg = rows_per_group or max(split // 8, 1)
        shapes = store._write_parquet_part(chunk.iloc[:split][cols],
                                           train_path, part, rpg)
        if n_val and val_path:
            store._write_parquet_part(chunk.iloc[split:][cols], val_path,
                                      part, rpg)
        import os as _os

        return [{
            "part": index,
            "pid": _os.getpid(),
            "rows": n,
            "val_rows": n_val if val_path else 0,
            "features": [sp.to_json() for sp in feature_specs],
            "label": label_spec.to_json(),
            "shapes": shapes,
        }]

    return _fn


def _to_pandas_like(df):
    """Normalize a DataFrame-shaped source to pandas: pandas passthrough,
    ``toPandas()`` (Spark), ``to_pandas()`` (pyarrow/polars), or a dict
    of column arrays."""
    import pandas as pd

    if isinstance(df, pd.DataFrame):
        return df
    for meth in ("toPandas", "to_pandas"):
        fn = getattr(df, meth, None)
        if callable(fn):
            out = fn()
            if isinstance(out, pd.DataFrame):
                return out
    if isinstance(df, dict):
        return pd.DataFrame({k: list(v) for k, v in df.items()})
    raise TypeError(
        f"cannot interpret {type(df).__name__} as a DataFrame: pass "
        "pandas, an object with toPandas()/to_pandas(), or a dict of "
        "column arrays")


def _column_array(df, name: str) -> np.ndarray:
    col = df[name]
    if not isinstance(df, dict):
        col = list(col)
    arr = np.asarray(col)
    if arr.dtype == object:   # ragged/list column → stack
        arr = np.stack([np.asarray(v) for v in col])
    return arr


def _canonical_dtype(arr: np.ndarray) -> np.dtype:
    """Accelerator-friendly canonical dtypes: float→float32 (unless
    already half/bfloat16), int/uint/bool→int32 — integers stay
    integers (embedding ids, masks) instead of the round-1
    flatten-everything-to-float32."""
    kind = arr.dtype.kind
    if kind == "f":
        return arr.dtype if arr.dtype.itemsize <= 2 else np.dtype(np.float32)
    if kind in "iub":
        return np.dtype(np.int32)
    raise TypeError(f"unsupported column dtype {arr.dtype}")


def _checked_cast(arr: np.ndarray, dtype: np.dtype,
                  name: str) -> np.ndarray:
    """Cast with loud failure on value corruption: int values outside
    the target range would silently wrap and float NaN→int becomes
    INT_MIN with only a RuntimeWarning — garbage ids/labels must raise
    instead."""
    if dtype.kind == "i":
        if arr.dtype.kind in "iu" and arr.size:
            info = np.iinfo(dtype)
            lo, hi = int(arr.min()), int(arr.max())
            if lo < info.min or hi > info.max:
                raise ValueError(
                    f"column '{name}' holds integers in [{lo}, {hi}] "
                    f"which do not fit the canonical {dtype.name}; remap "
                    f"the ids or cast the column explicitly.")
        if arr.dtype.kind == "f" and np.isnan(arr).any():
            raise ValueError(
                f"column '{name}' contains NaN but the model expects "
                f"integer {dtype.name} values — clean the data first.")
    return arr.astype(dtype)


def extract_typed(df, cols: Sequence[str]):
    """One-pass extraction + schema inference: ``({name: typed array},
    [ColSpec])`` (reference schema/metadata inference,
    ``spark/common/util.py``).  Prefer this over ``infer_metadata`` +
    ``extract_columns`` when both the arrays and the specs are needed —
    each column is materialized exactly once."""
    columns: Dict[str, np.ndarray] = {}
    specs: List[ColSpec] = []
    for c in cols:
        arr = _column_array(df, c)
        dtype = _canonical_dtype(arr)
        columns[c] = np.ascontiguousarray(_checked_cast(arr, dtype, c))
        specs.append(ColSpec(c, dtype.name, tuple(arr.shape[1:])))
    return columns, specs


def infer_metadata(df, cols: Sequence[str]) -> List[ColSpec]:
    """Per-column specs from the data (reference schema/metadata
    inference, ``spark/common/util.py``)."""
    return extract_typed(df, cols)[1]


def extract_columns(df, specs: Sequence[ColSpec]) -> Dict[str, np.ndarray]:
    """``{name: typed array}`` per spec — dtype converted, per-row shape
    validated (a same-size shape mismatch, e.g. CHW data against an NHWC
    spec, must fail loudly instead of silently reinterpreting memory)."""
    out = {}
    for s in specs:
        arr = _column_array(df, s.name)
        if tuple(arr.shape[1:]) != s.shape:
            raise ValueError(
                f"column '{s.name}' has per-row shape "
                f"{tuple(arr.shape[1:])} but the model was trained with "
                f"{s.shape}")
        out[s.name] = np.ascontiguousarray(
            _checked_cast(arr, np.dtype(s.dtype), s.name))
    return out


def assemble_features(columns: Dict[str, np.ndarray],
                      specs: Sequence[ColSpec]):
    """Model input from typed columns: a single feature column passes
    through with dtype and shape intact (images stay (H, W, C), int ids
    stay ints); multiple columns of one float dtype concatenate along
    the feature axis; mixed-type multi-column input stays a dict for
    the model to route (the reference feeds named columns through
    petastorm for exactly this reason)."""
    if len(specs) == 1:
        return columns[specs[0].name]
    dtypes = {s.dtype for s in specs}
    if len(dtypes) == 1 and next(iter(dtypes)).startswith("float"):
        return np.concatenate(
            [columns[s.name].reshape(len(columns[s.name]), -1)
             for s in specs], axis=1)
    return {s.name: columns[s.name] for s in specs}


def save_metadata(store: FilesystemStore, run_id: str,
                  feature_specs: Sequence[ColSpec],
                  label_spec: ColSpec) -> None:
    payload = json.dumps({
        "features": [s.to_json() for s in feature_specs],
        "label": label_spec.to_json(),
    }, indent=2).encode()
    store.write(os.path.join(store.get_run_path(run_id), "metadata.json"),
                payload)


def load_metadata(store: FilesystemStore, run_id: str):
    raw = json.loads(store.read(
        os.path.join(store.get_run_path(run_id), "metadata.json")))
    return ([ColSpec.from_json(d) for d in raw["features"]],
            ColSpec.from_json(raw["label"]))
