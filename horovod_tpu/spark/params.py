"""Typed, validated estimator parameters.

Reference ``horovod/spark/common/params.py``: ``EstimatorParams`` gives
every estimator a shared, introspectable config surface — ``Param``
entries with docs and type converters, ``setParams``/getters/setters,
and ``_check_params`` validation.  The reference builds on
``pyspark.ml.param``; this is the standalone equivalent: ``Param``
descriptors with converters/validators that raise errors *naming the
parameter*, and a ``HasParams`` base providing ``set_params``,
``get_param``, ``param_specs()`` introspection and ``explain_params()``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional


class ParamError(ValueError):
    """Invalid parameter value or unknown parameter name."""


# -- converters (reference TypeConverters) ----------------------------------

def to_int(name: str, v) -> int:
    if isinstance(v, bool) or not isinstance(v, (int, float)) or \
            int(v) != v:
        raise ParamError(f"{name} must be an integer, got {v!r}")
    return int(v)


def to_positive_int(name: str, v) -> int:
    v = to_int(name, v)
    if v <= 0:
        raise ParamError(f"{name} must be a positive integer, got {v}")
    return v


def to_fraction(name: str, v) -> float:
    try:
        v = float(v)
    except (TypeError, ValueError):
        raise ParamError(f"{name} must be a number in [0, 1), got {v!r}")
    if not 0.0 <= v < 1.0:
        raise ParamError(f"{name} must be in [0, 1), got {v}")
    return v


def to_str(name: str, v) -> str:
    if not isinstance(v, str):
        raise ParamError(f"{name} must be a string, got {type(v).__name__}")
    return v


def to_str_list(name: str, v) -> List[str]:
    if isinstance(v, str):
        return [v]
    try:
        out = list(v)
    except TypeError:
        raise ParamError(
            f"{name} must be a list of strings, got {type(v).__name__}")
    bad = [x for x in out if not isinstance(x, str)]
    if bad:
        raise ParamError(
            f"{name} must be a list of strings, got entries {bad!r}")
    return out


def to_bool(name: str, v) -> bool:
    if not isinstance(v, bool):
        raise ParamError(f"{name} must be a bool, got {v!r}")
    return v


def optional(conv: Callable) -> Callable:
    def _conv(name, v):
        return None if v is None else conv(name, v)

    return _conv


class Param:
    """One declared parameter: default, doc, optional converter.

    A class-attribute descriptor: reading returns the held value (or
    default), assignment converts + validates, raising ``ParamError``
    messages that name the parameter (the reference's typed Param +
    TypeConverters contract)."""

    def __init__(self, default, doc: str,
                 converter: Optional[Callable] = None):
        self.default = default
        self.doc = doc
        self.converter = converter
        self.name = None          # bound by __set_name__

    def __set_name__(self, owner, name):
        self.name = name

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        return obj.__dict__.get(f"_param_{self.name}", self.default)

    def __set__(self, obj, value):
        if self.converter is not None:
            value = self.converter(self.name, value)
        obj.__dict__[f"_param_{self.name}"] = value


class HasParams:
    """Introspection + bulk assignment over declared :class:`Param`\\ s
    (reference ``Params``/``setParams``)."""

    @classmethod
    def param_specs(cls) -> Dict[str, Param]:
        out: Dict[str, Param] = {}
        for klass in reversed(cls.__mro__):
            for k, v in vars(klass).items():
                if isinstance(v, Param):
                    out[k] = v
        return out

    def set_params(self, **kwargs) -> "HasParams":
        declared = self.param_specs()
        for k, v in kwargs.items():
            if k not in declared:
                import difflib

                hint = difflib.get_close_matches(k, declared, n=1)
                suffix = f"; did you mean {hint[0]!r}?" if hint else ""
                raise ParamError(
                    f"unknown parameter {k!r} for "
                    f"{type(self).__name__}{suffix} (known: "
                    f"{', '.join(sorted(declared))})")
            setattr(self, k, v)
        return self

    def get_param(self, name: str) -> Any:
        if name not in self.param_specs():
            raise ParamError(
                f"unknown parameter {name!r} for {type(self).__name__}")
        return getattr(self, name)

    def explain_params(self) -> str:
        """Human-readable table of every param: value, default, doc
        (reference ``explainParams``)."""
        lines = []
        for name, p in sorted(self.param_specs().items()):
            val = getattr(self, name)
            mark = "" if val == p.default else " (set)"
            lines.append(f"{name} = {val!r}{mark} — {p.doc} "
                         f"[default: {p.default!r}]")
        return "\n".join(lines)
