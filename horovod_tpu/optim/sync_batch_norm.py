"""Cross-replica synchronized batch normalization.

Reference: ``horovod/torch/sync_batch_norm.py`` (199 LoC: allgather of
per-rank mean/var + custom autograd) and
``horovod/tensorflow/sync_batch_norm.py`` (:65).  On TPU the custom
autograd disappears: batch statistics are synchronized with a ``pmean``
inside the compiled step and XLA differentiates through it, fusing the
two reductions (mean, mean-of-squares) into one collective.

Two entry points:

* :class:`SyncBatchNorm` — drop-in flax module for ``shard_map``/``pmap``
  style per-shard code (``axis_name`` bound);
* :func:`sync_batch_stats` — functional statistics sync for hand-rolled
  normalization or unequal per-shard batch sizes (the case the reference
  handles by allgathering counts).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.runtime.topology import GLOBAL_AXES

AxisSpec = Union[str, Sequence[str]]


def sync_batch_stats(x: jax.Array, axis: AxisSpec = GLOBAL_AXES,
                     reduction_dims: Optional[Tuple[int, ...]] = None,
                     counts: Optional[jax.Array] = None
                     ) -> Tuple[jax.Array, jax.Array]:
    """Global (mean, var) of ``x`` over local reduction dims and the mesh
    axis.  With ``counts`` (per-shard element count), shards with unequal
    batches weight correctly — the reference's count-allgather concern
    (``torch/sync_batch_norm.py``) reduces to a weighted psum."""
    if reduction_dims is None:
        reduction_dims = tuple(range(x.ndim - 1))
    x32 = x.astype(jnp.float32)
    if counts is None:
        local_n = 1
        for d in reduction_dims:
            local_n *= x.shape[d]
        counts = jnp.float32(local_n)
    s = lax.psum(jnp.sum(x32, axis=reduction_dims), axis)
    sq = lax.psum(jnp.sum(x32 * x32, axis=reduction_dims), axis)
    n = lax.psum(counts, axis)
    mean = s / n
    var = sq / n - mean * mean
    return mean, jnp.maximum(var, 0.0)


class SyncBatchNorm(nn.Module):
    """BatchNorm whose batch statistics are exact over the global batch.

    Use inside ``shard_map`` (or any context binding ``axis_name``)::

        y = SyncBatchNorm(use_running_average=not train)(x)

    Running averages live in the ``batch_stats`` collection like
    ``nn.BatchNorm``; since the synced statistics are identical on every
    shard, the updated running stats stay replicated with no extra sync —
    the property the reference needs ``broadcast_parameters`` for.
    """

    use_running_average: bool = False
    axis: AxisSpec = GLOBAL_AXES
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Optional[Any] = None
    use_bias: bool = True
    use_scale: bool = True

    @nn.compact
    def __call__(self, x, use_running_average: Optional[bool] = None):
        use_running_average = nn.merge_param(
            "use_running_average", self.use_running_average,
            use_running_average)
        features = x.shape[-1]
        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros(features, jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones(features, jnp.float32))

        if use_running_average:
            mean, var = ra_mean.value, ra_var.value
        elif self.is_initializing():
            # init() runs outside the mesh: local stats, no collective
            x32 = x.astype(jnp.float32)
            dims = tuple(range(x.ndim - 1))
            mean, var = x32.mean(dims), x32.var(dims)
        else:
            mean, var = sync_batch_stats(x, axis=self.axis)
            ra_mean.value = (self.momentum * ra_mean.value
                             + (1 - self.momentum) * mean)
            ra_var.value = (self.momentum * ra_var.value
                            + (1 - self.momentum) * var)

        y = (x.astype(jnp.float32) - mean) * lax.rsqrt(var + self.epsilon)
        if self.use_scale:
            y = y * self.param("scale", nn.initializers.ones_init(),
                               (features,))
        if self.use_bias:
            y = y + self.param("bias", nn.initializers.zeros_init(),
                               (features,))
        return y.astype(self.dtype or x.dtype)
