"""High-level jitted SPMD training step — the framework's hot path.

The reference never owns the training loop (except Spark estimators); its
value is making the user's loop distributed with ~5 changed lines
(``README.rst`` usage recipe).  The TPU equivalent of those 5 lines is one
object: ``DistributedTrainStep`` compiles the user's ``loss_fn`` +
optimizer into a single pjit program over the runtime mesh with the batch
sharded along (dcn, ici) and parameters replicated.  Inside one XLA
program the gradient psum is inserted by autodiff and overlapped with the
backward pass by the compiler — the role of the reference's background
thread + fusion buffer + NCCL streams, with zero host round-trips.

Design notes for the MXU/HBM (see repo guidance):

* a single compiled step keeps matmuls batched and fusible; nothing
  escapes to host between microbatches;
* ``donate_argnums`` on (params, opt_state) makes updates in-place in HBM;
* optional ``jax.checkpoint`` on the loss for rematerialization;
* bf16 compute with fp32 params is the user's choice inside ``loss_fn`` —
  compression hooks apply to the gradient wire format in shard_map mode.
"""

from __future__ import annotations

import os
import time
from functools import partial
from typing import Callable, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu import telemetry
from horovod_tpu.ops import collectives as C
from horovod_tpu.ops.collectives import Average, ReduceOp, Sum
from horovod_tpu.runtime import state
from horovod_tpu.runtime.topology import GLOBAL_AXES

AxisSpec = Union[str, Sequence[str]]


def _sumsq(tree):
    """fp32 sum of squares over every leaf (the global-norm reduction
    the guard computes in-graph)."""
    s = jnp.zeros((), jnp.float32)
    for leaf in jax.tree_util.tree_leaves(tree):
        s = s + jnp.sum(jnp.square(jnp.asarray(leaf, jnp.float32)))
    return s


def _guard_select(ok, new_params, new_opt, params, opt_state):
    """Keep the update only when the guard predicate holds; otherwise
    keep the pre-step state — in-graph, so donation can't lose the
    clean copy."""
    sel = lambda n, o: jnp.where(ok, n, o)  # noqa: E731
    return (jax.tree_util.tree_map(sel, new_params, params),
            jax.tree_util.tree_map(sel, new_opt, opt_state))


class DistributedTrainStep:
    """Compiled data-parallel training step.

    ::

        step = DistributedTrainStep(loss_fn, optax.sgd(0.01 * hvd.size()))
        params, opt_state = step.init(params)
        params, opt_state, loss = step(params, opt_state, batch)

    ``loss_fn(params, batch) -> scalar`` must compute the *mean* loss over
    its batch shard; global averaging across shards then follows from the
    sharded-batch mean (XLA inserts the collective during autodiff).

    ``mode="shard_map"`` lowers through explicit per-device code with the
    gradient reduction done by
    :func:`horovod_tpu.ops.collectives.grouped_allreduce` — useful when the
    user wants Adasum (``op=Adasum``), compression, or explicit control.
    ``op=None`` skips the gradient reduction entirely for optimizers that
    own their distribution, e.g. the delta-form
    :func:`~horovod_tpu.optim.DistributedAdasumOptimizer`.
    """

    def __init__(self,
                 loss_fn: Callable,
                 optimizer: optax.GradientTransformation,
                 mesh=None,
                 mode: str = "pjit",
                 op: Optional[ReduceOp] = Average,
                 compression=None,
                 remat: Union[bool, str] = False,
                 data_axes: AxisSpec = GLOBAL_AXES,
                 donate: bool = True,
                 donate_batch: bool = False,
                 steps_per_call: int = 1,
                 compiler_options: Optional[dict] = None,
                 sparse_params: Optional[dict] = None,
                 fsdp_axis: Optional[str] = None,
                 fsdp_min_weight_size: Optional[int] = None,
                 shard_optimizer_states: bool = False,
                 exchange_bucket_bytes: Optional[int] = None,
                 hierarchy: str = "auto",
                 fused_collectives: str = "auto",
                 error_feedback: bool = False,
                 plan=None,
                 guard=None,
                 moe_fused: Optional[str] = None,
                 moe_capacity_factor: Optional[float] = None,
                 reduction: Optional[str] = None):
        """``steps_per_call > 1`` scans that many optimizer steps inside
        the one compiled program (the Keras ``steps_per_execution``
        knob): one dispatch amortizes per-call host/launch overhead —
        significant through remote-device transports — and the batch is
        reused for every scanned step, so pass fresh data per call.
        ``compiler_options`` are XLA backend flags forwarded to the
        compile (e.g. ``{"xla_tpu_enable_latency_hiding_scheduler":
        "true"}`` — measured ≈+3%% on the ResNet-50 bench).

        ``fsdp_axis`` turns on fully-sharded data parallelism (pjit mode
        only): parameters — and, by jit propagation, optimizer state —
        are *placed* sharded along that mesh axis instead of replicated,
        and GSPMD inserts the all-gather-on-use / reduce-scatter-on-grad
        collectives ZeRO-3 schedules by hand (see
        :mod:`horovod_tpu.parallel.fsdp`).  Typically ``"ici"`` on the
        runtime mesh so gathers ride the fast interconnect while the
        batch stays sharded over (dcn, ici).

        ``shard_optimizer_states=True`` (shard_map mode) swaps the
        monolithic post-backward allreduce for the ZeRO-style bucketed
        reduce-scatter → shard-local optimizer update → allgather
        exchange (:func:`horovod_tpu.optim.sharded_distributed_update`):
        numerically equivalent parameters, 1/N optimizer memory and
        update FLOPs per rank, and a collective schedule XLA overlaps
        with backward.  ``exchange_bucket_bytes`` splits the exchange
        into reverse-layer-order buckets for earlier overlap (measured
        by ``utils/overlap_probe.py``).

        ``donate_batch=True`` adds the batch to the donated argument
        set — the input slot of a *pipeline-fed* step
        (:mod:`horovod_tpu.data`): every call receives a fresh batch
        whose device buffers nothing else references, so the caller may
        hand over ownership and XLA is free to alias the buffers into
        outputs instead of holding live input and results side by side
        (when no output matches, it logs the unused donation and runs
        normally).  Leave it off when a batch is reused across calls
        (the synthetic-bench pattern) — donation invalidates the
        caller's arrays after the call.

        ``fused_collectives`` (``"auto"|"on"|"off"``,
        ``HOROVOD_FUSED_COLLECTIVES``) schedules the sharded
        exchange's FINAL bucket tile-granularly — the one exchange no
        remaining backward work can hide — as independent
        sub-collectives the scheduler overlaps with the shard-update
        math (docs/fused_kernels.md).  ``"auto"`` enables on TPU only;
        numerics are identical either way, and the resolved mode is an
        AOT-key field so a warm start never serves a fused executable
        to an unfused config.

        ``guard`` attaches the numerics guardian
        (:class:`horovod_tpu.guard.TrainingGuard` or anything exposing
        ``current_limit()``/``observe()``): the compiled step takes one
        extra traced scalar — the spike limit — computes the global
        gradient norm, and where-selects the *pre-step* ``(params,
        opt_state)`` whenever the norm is non-finite or above the
        limit, so a poisoned update is never applied even with donated
        buffers.  The limit is a runtime value, so per-step threshold
        changes never recompile.  Requires ``steps_per_call=1`` (each
        optimizer step must be individually observable).  In shard_map
        pre-reduction paths (``shard_optimizer_states`` or ``op=None``)
        the guarded norm is the root-sum-square over all device-local
        gradients — device-consistent via one scalar allreduce — rather
        than the norm of the reduced gradient; the guardian's EMA
        baseline adapts to whichever statistic the mode produces.

        ``hierarchy`` picks the sharded exchange's topology:
        ``"auto"`` (default) resolves against the data-axes
        factorization — the two-level ICI-then-DCN exchange whenever
        both ``(dp_outer, dp_inner)`` extents exceed 1, flat otherwise
        (:func:`horovod_tpu.runtime.topology.resolve_hierarchy`);
        ``"flat"``/``"two_level"`` force a mode.  When unset here, the
        runtime config's ``HOROVOD_EXCHANGE_HIERARCHY`` /
        ``HOROVOD_EXCHANGE_BUCKET_BYTES`` env knobs supply the
        defaults (docs/overlap.md).

        ``error_feedback=True`` (sharded exchange + wire-reduction
        compression only) carries the per-bucket quantization residual
        across steps and additionally quantizes the intra-slice (ICI)
        reduce-scatter hop: each rank re-adds last step's local
        rounding error before quantizing, so the int8/fp8 wire stays
        numerically pinned to the fp32 path over a trajectory instead
        of accumulating rounding bias (docs/parallelism.md).

        ``plan`` (a :class:`~horovod_tpu.parallel.plan.ShardingPlan`
        or its ``HOROVOD_PLAN`` grammar string; falls back to the env
        knob) is the declarative parallelism source of truth: it
        builds the mesh (DCN-outer/ICI-inner ``AXIS_ORDER``) when no
        ``mesh`` is given, scopes the batch sharding and the gradient
        exchange to its data axes (dp/fsdp — plus ``sp`` under
        ``shard_map``, where the batch's token dim shards over the sp
        axis and the token-mean loss makes sp data-axis math for the
        reduction; tp/ep stay out of the exchange scope),
        turns ``fsdp>1`` into ``fsdp_axis`` placement under pjit, and
        stamps its canonical string into the AOT key so a warm start
        never serves an executable compiled for a different plan.
        Pipeline plans (``pp>1``) are rejected here — pipelines run
        through :mod:`horovod_tpu.parallel.pipeline`."""
        from horovod_tpu.parallel.plan import ShardingPlan, as_plan

        plan = as_plan(plan)
        if plan is None and state.is_initialized():
            cfg_plan = getattr(state.global_state().config, "plan", None)
            if cfg_plan:
                plan = ShardingPlan.from_string(cfg_plan)
        if plan is not None:
            if mesh is None:
                plan = plan.resolve(len(jax.devices()))
                mesh = plan.build_mesh()
            else:
                plan = plan.resolve(mesh.size)
                if not plan.matches_mesh(mesh):
                    raise ValueError(
                        f"plan {plan.to_string()} does not match the "
                        f"given mesh {dict(mesh.shape)}: pass one "
                        f"source of truth (the plan builds its own "
                        f"mesh when mesh=None)")
            if plan.pp > 1:
                raise ValueError(
                    f"plan {plan.to_string()} has pp>1: pipeline "
                    "parallelism runs through parallel.pipeline "
                    "(gpipe / interleaved_1f1b inside shard_map), not "
                    "the train step — the step compiles "
                    "dp/fsdp/tp/ep/sp plans")
            blocked_model_axes = tuple(
                a for a in plan.model_axes if a != "sp")
            if mode == "shard_map" and blocked_model_axes:
                raise ValueError(
                    f"plan {plan.to_string()} has model axes "
                    f"{blocked_model_axes}: mode='shard_map' compiles "
                    "data plans (dp/fsdp) plus sequence parallelism "
                    "(sp — the batch's token dim shards over the sp "
                    "axis and the model's ring/ulysses attention owns "
                    "the exchange) — tp/ep plans need mode='pjit', "
                    "where GSPMD places the shardings the model's "
                    "modules declare")
            norm_axes = (data_axes,) if isinstance(data_axes, str) \
                else tuple(data_axes)
            if norm_axes == tuple(GLOBAL_AXES):
                data_axes = plan.data_axes
            elif norm_axes != plan.data_axes:
                raise ValueError(
                    f"data_axes {norm_axes} conflicts with plan "
                    f"{plan.to_string()} (data axes "
                    f"{plan.data_axes}): the plan owns the exchange "
                    "scope — drop the explicit data_axes")
            if mode == "pjit" and plan.fsdp > 1 and fsdp_axis is None:
                fsdp_axis = "fsdp"
        self._plan = plan
        self._mesh = mesh or state.global_state().mesh
        self._mode = mode
        self._optimizer = optimizer
        self._op = op
        if shard_optimizer_states:
            if mode != "shard_map":
                raise ValueError(
                    "shard_optimizer_states requires mode='shard_map' "
                    "(the explicit exchange; under pjit use fsdp_axis, "
                    "where GSPMD inserts the sharded collectives)")
            if op is None or op not in (C.ReduceOp.SUM,
                                        C.ReduceOp.AVERAGE):
                raise ValueError(
                    "shard_optimizer_states performs the gradient "
                    "reduction itself and supports op=Sum/Average")
            if sparse_params:
                raise ValueError(
                    "shard_optimizer_states is incompatible with "
                    "sparse_params (sparse leaves bypass the fused "
                    "flat buffer the shard slicing is defined over)")
        elif exchange_bucket_bytes is not None:
            raise ValueError(
                "exchange_bucket_bytes buckets the sharded exchange; "
                "pass shard_optimizer_states=True to enable it")
        elif hierarchy != "auto":
            raise ValueError(
                "hierarchy selects the sharded exchange topology; pass "
                "shard_optimizer_states=True to enable it")
        elif fused_collectives != "auto":
            raise ValueError(
                "fused_collectives schedules the sharded exchange's "
                "final bucket; pass shard_optimizer_states=True to "
                "enable it")
        elif reduction not in (None, "sum"):
            raise ValueError(
                "reduction selects the sharded exchange's combine "
                "operator; pass shard_optimizer_states=True to enable "
                "it (the replicated path's adasum is op=Adasum / "
                "DistributedAdasumOptimizer)")
        if error_feedback:
            if not shard_optimizer_states:
                raise ValueError(
                    "error_feedback carries the sharded exchange's "
                    "quantization residual; pass "
                    "shard_optimizer_states=True to enable it")
            if compression is None:
                raise ValueError(
                    "error_feedback compensates quantization rounding; "
                    "it needs a wire-reduction compression "
                    "(Compression.int8)")
        self._error_feedback = bool(error_feedback)
        level_codecs = None
        if shard_optimizer_states and state.is_initialized():
            # env-contract defaults (HOROVOD_EXCHANGE_*): explicit
            # arguments rule; unset knobs fall back to runtime config
            cfg = state.global_state().config
            if exchange_bucket_bytes is None:
                exchange_bucket_bytes = cfg.exchange_bucket_bytes
            if hierarchy == "auto" and cfg.exchange_hierarchy:
                hierarchy = cfg.exchange_hierarchy
            if fused_collectives == "auto" and \
                    getattr(cfg, "fused_collectives", "auto") != "auto":
                fused_collectives = cfg.fused_collectives
            if getattr(cfg, "exchange_level_codecs", None):
                from horovod_tpu.runtime.topology import parse_level_codecs

                level_codecs = parse_level_codecs(
                    cfg.exchange_level_codecs)
        self._level_codecs = level_codecs
        # reduction operator of the sharded exchange: explicit arg >
        # runtime config > HOROVOD_EXCHANGE_REDUCTION env > plain sum.
        # The env var is read directly (not only via the init-time
        # config snapshot) so a knob set after hvd.init() still reaches
        # the step — the same late-binding contract as the MoE knobs
        # below.  None when no sharded exchange is active: the knob has
        # nothing to steer there.
        if shard_optimizer_states:
            if reduction is None and state.is_initialized():
                cfg_red = getattr(state.global_state().config,
                                  "exchange_reduction", "sum")
                if cfg_red and cfg_red != "sum":
                    reduction = cfg_red
            if reduction is None:
                env_red = os.environ.get("HOROVOD_EXCHANGE_REDUCTION")
                if env_red:
                    reduction = env_red.lower()
            self._reduction = C._resolve_reduction(reduction)
        else:
            self._reduction = None
        self._hierarchy = hierarchy
        # the mode the compiled exchange will actually run ("auto" made
        # static against the platform) — an AOT-key field and the value
        # bench.py emits as fused_collectives
        from horovod_tpu.ops.pallas_kernels import (
            resolve_fused_collectives,
        )

        self._fused_collectives = (
            "on" if shard_optimizer_states and
            resolve_fused_collectives(fused_collectives) else "off")
        self._shard_opt = shard_optimizer_states
        # MoE schedule fields: the routing config inside a MoE loss_fn
        # is invisible to the step, so callers stamp it here — the
        # resolved expert-dispatch mode and the capacity factor are
        # AOT-key fields, and a warm start never serves a fused-ring
        # executable to an unfused config or mixes capacity geometries
        # (docs/fused_kernels.md "Expert-parallel dispatch").
        if moe_fused is None:
            moe_fused = os.environ.get("HOROVOD_MOE_FUSED_DISPATCH")
        self._moe_fused = (
            None if moe_fused is None else
            ("on" if resolve_fused_collectives(str(moe_fused).lower())
             else "off"))
        if moe_capacity_factor is None:
            env_cf = os.environ.get("HOROVOD_MOE_CAPACITY_FACTOR")
            moe_capacity_factor = float(env_cf) if env_cf else None
        self._moe_capacity_factor = (
            None if moe_capacity_factor is None
            else float(moe_capacity_factor))
        if fsdp_axis is not None and mode != "pjit":
            raise ValueError(
                "fsdp_axis requires mode='pjit' (GSPMD inserts the "
                "gather/reduce-scatter collectives; shard_map mode "
                "manages per-device values by hand)")
        if fsdp_axis is not None and \
                fsdp_axis not in self._mesh.shape:
            raise ValueError(
                f"fsdp_axis {fsdp_axis!r} is not an axis of the mesh "
                f"{tuple(self._mesh.shape)}")
        if fsdp_min_weight_size is not None and fsdp_axis is None:
            raise ValueError(
                "fsdp_min_weight_size has no effect without fsdp_axis")
        self._fsdp_axis = fsdp_axis
        self._fsdp_min = fsdp_min_weight_size
        self._data_axes = tuple(data_axes) if not isinstance(data_axes, str) \
            else (data_axes,)
        # sp>1 under shard_map: the batch's token dim (dim 1) shards
        # over the sp axis — the model's ring/ulysses attention owns
        # the sequence exchange, and because the loss is a token mean,
        # sp joins the gradient/loss reduction scope exactly like a
        # data axis (average of per-shard token means = global mean)
        self._sp = int(plan.sp) if plan is not None else 1
        self._sp_axis = "sp" if (mode == "shard_map" and
                                 self._sp > 1) else None
        # remat accepts the legacy bool or a policy string (none|dots|
        # full|offload).  The resolved policy — including the
        # HOROVOD_REMAT_POLICY env knob, which steers the *models'*
        # per-block remat — is an AOT-key field so a warm start never
        # serves a different remat variant (memory/remat.py,
        # docs/memory.md).  The loss-fn wrap itself only happens when
        # the caller asked for it: an env-driven model already remats
        # per block, and checkpointing the whole loss on top would just
        # replay the forward twice.
        from horovod_tpu.memory.remat import remat_fn, \
            resolve_remat_policy

        self._remat_policy = resolve_remat_policy(remat=remat)
        if remat:
            loss_fn = remat_fn(loss_fn, self._remat_policy)
        self._loss_fn = loss_fn
        if steps_per_call < 1:
            raise ValueError(
                f"steps_per_call must be >= 1, got {steps_per_call}")
        self._steps_per_call = int(steps_per_call)
        self._guard = guard
        if guard is not None and self._steps_per_call != 1:
            raise ValueError(
                "guard= requires steps_per_call=1: the guardian must "
                "observe (and be able to suppress) every optimizer step "
                "individually — a scanned multi-step program would apply "
                "k-1 updates before the host sees the first norm")
        self._compiler_options = dict(compiler_options) \
            if compiler_options is not None else None
        self._donate_batch = bool(donate_batch)
        # the donated argument set: (params, opt_state) in-place in HBM,
        # plus the batch slot when the feed guarantees fresh buffers
        donated = ((0, 1) if donate else ()) + \
            ((2,) if donate_batch else ())

        repl = NamedSharding(self._mesh, P())
        batch_spec = (P(self._data_axes, self._sp_axis)
                      if self._sp_axis is not None
                      else P(self._data_axes))
        batch_sharding = NamedSharding(self._mesh, batch_spec)

        if sparse_params and mode != "shard_map":
            raise ValueError(
                "sparse_params requires mode='shard_map' (pjit autodiff "
                "reduces every gradient densely)")
        if op is None and mode != "shard_map":
            raise ValueError(
                "op=None (gradients stay local; the optimizer chain owns "
                "the reduction, e.g. DistributedAdasumOptimizer) requires "
                "mode='shard_map' — pjit autodiff would mean-reduce the "
                "gradients behind the optimizer's back")
        if op is None and sparse_params:
            raise ValueError(
                "op=None leaves gradients local, so train-step "
                "sparse_params would never route anything; pass "
                "sparse handling to the distributing optimizer instead")
        if op is None and compression is not None:
            raise ValueError(
                "op=None leaves gradients local, so a train-step "
                "compression would never run; pass compression to the "
                "distributing optimizer (e.g. DistributedAdasumOptimizer) "
                "instead")
        if mode == "pjit" and (op != Average or compression is not None):
            # pjit autodiff performs the (mean) gradient reduction itself;
            # custom reductions/wire formats need the explicit path.
            raise ValueError(
                "mode='pjit' performs a plain mean gradient reduction; use "
                "mode='shard_map' for op=Adasum/Sum or compression")
        def multi(step_fn):
            """steps_per_call > 1: scan k optimizer steps into the one
            program — one dispatch, k updates, last loss returned."""
            if self._steps_per_call == 1:
                return step_fn
            k = self._steps_per_call

            def stepped(params, opt_state, batch):
                def body(carry, _):
                    p, o, _loss = step_fn(carry[0], carry[1], batch)
                    return (p, o), _loss

                (params, opt_state), losses = jax.lax.scan(
                    body, (params, opt_state), None, length=k)
                return params, opt_state, losses[-1]

            return stepped

        if mode == "pjit":
            def step(params, opt_state, batch):
                loss, grads = jax.value_and_grad(self._loss_fn)(params, batch)
                updates, opt_state = self._optimizer.update(
                    grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                return params, opt_state, loss

            def guarded_step(params, opt_state, batch, limit):
                loss, grads = jax.value_and_grad(self._loss_fn)(params, batch)
                gnorm = jnp.sqrt(_sumsq(grads))
                ok = jnp.isfinite(gnorm) & (gnorm <= limit)
                updates, new_opt = self._optimizer.update(
                    grads, opt_state, params)
                new_params = optax.apply_updates(params, updates)
                params, opt_state = _guard_select(
                    ok, new_params, new_opt, params, opt_state)
                return params, opt_state, loss, gnorm

            if self._fsdp_axis is not None:
                # params/opt arrive committed with their FSDP placements
                # (init) and GSPMD propagates them through the step,
                # inserting gather/reduce-scatter; the batch keeps its
                # data-axis constraint so data parallelism can't silently
                # degrade to replicated compute on a raw batch
                if guard is not None:
                    self._step = jax.jit(
                        guarded_step,
                        in_shardings=(None, None, batch_sharding, None),
                        donate_argnums=donated)
                else:
                    self._step = jax.jit(
                        multi(step),
                        in_shardings=(None, None, batch_sharding),
                        donate_argnums=donated)
            elif guard is not None:
                self._step = jax.jit(
                    guarded_step,
                    in_shardings=(repl, repl, batch_sharding, repl),
                    out_shardings=(repl, repl, repl, repl),
                    donate_argnums=donated)
            else:
                self._step = jax.jit(
                    multi(step),
                    in_shardings=(repl, repl, batch_sharding),
                    out_shardings=(repl, repl, repl),
                    donate_argnums=donated)
        elif mode == "shard_map":
            shard_map = jax.shard_map

            # sp joins the reduction scope (token-mean losses make it
            # data-axis math); the batch spec already shards tokens
            axes = self._data_axes + (
                (self._sp_axis,) if self._sp_axis is not None else ())

            if shard_optimizer_states:
                from horovod_tpu.optim.optimizer import (
                    sharded_distributed_update,
                )

                qbits = getattr(compression, "wire_reduce_bits", None)
                if compression is not None and qbits is None:
                    raise ValueError(
                        "shard_optimizer_states supports only "
                        "wire-reduction compression (Compression.int8)")
                # the sharded exchange owns the reduction AND the
                # optimizer: RS -> shard-local update -> AG of updates
                world = 1
                for a in axes:
                    world *= self._mesh.shape[a]
                self._optimizer = sharded_distributed_update(
                    optimizer, op=op, axis=axes,
                    quantized_bits=qbits,
                    bucket_bytes=exchange_bucket_bytes,
                    world=world,
                    hierarchy=hierarchy,
                    fused_collectives=self._fused_collectives,
                    error_feedback=self._error_feedback,
                    level_codecs=self._level_codecs,
                    reduction=self._reduction)
                from horovod_tpu.runtime.topology import resolve_topology

                # the mode the compiled step will actually run (the
                # "auto" decision made static against this mesh) — what
                # bench.py emits as exchange_hierarchy
                self._hierarchy = resolve_topology(
                    hierarchy, [self._mesh.shape[a] for a in axes],
                    axis_names=axes).mode
            elif op is not None:
                from horovod_tpu.optim.optimizer import distributed_gradients

                reducer = distributed_gradients(
                    op=op, axis=axes, mode="shard_map",
                    compression=compression, sparse_params=sparse_params)

            def per_device(params, opt_state, batch):
                loss, grads = jax.value_and_grad(self._loss_fn)(params, batch)
                if self._op is not None and not self._shard_opt:
                    grads, _ = reducer.update(grads, optax.EmptyState())
                # op=None: gradients stay local — the optimizer chain owns
                # the cross-shard reduction (the delta-Adasum form, where
                # hvd.DistributedAdasumOptimizer reduces *updates*)
                updates, opt_state = self._optimizer.update(
                    grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                loss = C.allreduce(loss, op=Average, axis=axes)
                return params, opt_state, loss

            def per_device_guarded(params, opt_state, batch, limit):
                loss, grads = jax.value_and_grad(self._loss_fn)(params, batch)
                if self._op is not None and not self._shard_opt:
                    # reducer already made grads identical on every
                    # device: the local norm IS the global norm
                    grads, _ = reducer.update(grads, optax.EmptyState())
                    gnorm = jnp.sqrt(_sumsq(grads))
                else:
                    # pre-reduction grads (the sharded exchange or the
                    # delta-form optimizer owns the reduction): one
                    # scalar allreduce makes the verdict — and therefore
                    # the select — identical on every device
                    gnorm = jnp.sqrt(C.allreduce(
                        _sumsq(grads), op=Sum, axis=axes))
                ok = jnp.isfinite(gnorm) & (gnorm <= limit)
                updates, new_opt = self._optimizer.update(
                    grads, opt_state, params)
                new_params = optax.apply_updates(params, updates)
                params, opt_state = _guard_select(
                    ok, new_params, new_opt, params, opt_state)
                loss = C.allreduce(loss, op=Average, axis=axes)
                return params, opt_state, loss, gnorm

            # out_specs=P() with check_vma=False: params come out
            # genuinely replicated (the reducer or the delta-form
            # optimizer chain makes every shard's update identical), but
            # with op=None the *optimizer state* (e.g. Adasum-wrapped
            # momenta) is per-rank by construction — and with
            # shard_optimizer_states=True deliberately so: each rank
            # stores only its 1/N flat state shard (the ZeRO memory
            # saving); the shard-shaped leaves ride the P() boundary as
            # per-device values.  Host reads and
            # checkpoints of that state then capture device 0's copy —
            # deliberately matching the reference's rank-0-checkpoint
            # semantics (save on rank 0, broadcast on restore); a
            # reshard of a restored checkpoint replicates rank 0's
            # momenta, which is exactly what broadcast-restore does.
            if guard is not None:
                smapped = shard_map(
                    per_device_guarded, mesh=self._mesh,
                    in_specs=(P(), P(), batch_spec, P()),
                    out_specs=(P(), P(), P(), P()),
                    check_vma=False)
                self._step = jax.jit(smapped, donate_argnums=donated)
            else:
                smapped = shard_map(
                    per_device, mesh=self._mesh,
                    in_specs=(P(), P(), batch_spec),
                    out_specs=(P(), P(), P()),
                    check_vma=False)
                self._step = jax.jit(
                    multi(smapped), donate_argnums=donated)
        else:
            raise ValueError(f"unknown mode {mode!r}")

        self._batch_sharding = batch_sharding
        self._replicated = repl
        self._compiled_cache: dict = {}      # insertion-ordered LRU
        # cache_capacity bounds the in-memory executable LRU (the
        # response-cache capacity knob made real); the same value bounds
        # the on-disk AOT store (runtime/compile_cache.py)
        if state.is_initialized():
            self._compiled_cache_max = \
                state.global_state().config.cache_capacity
        else:
            self._compiled_cache_max = self._COMPILED_CACHE_MAX
        # warm-start AOT store root (None = disabled): first compiles of
        # this step go through runtime/compile_cache.aot_compile so a
        # restarted process deserializes instead of recompiling
        from horovod_tpu.runtime import compile_cache as _cc

        self._compile_cache = _cc
        self._persistent_root = _cc.resolve_dir()
        self._last_cache_hit: Optional[bool] = None
        # telemetry handles (docs/metrics.md): cached here so the
        # per-call cost is one enabled-branch when metrics are off
        self._tel_steps = telemetry.counter(
            "hvd_steps_total", "optimizer steps executed")
        self._tel_step_seconds = telemetry.histogram(
            "hvd_step_seconds",
            "host wall time per train-step dispatch call")
        self._tel_cache_hits = telemetry.counter(
            "hvd_compile_cache_hits_total",
            "in-memory executable-cache hits")
        self._tel_cache_misses = telemetry.counter(
            "hvd_compile_cache_misses_total",
            "in-memory executable-cache misses")
        self._tel_wire_done = False

    _COMPILED_CACHE_MAX = 16

    @property
    def batch_sharding(self):
        """The ``NamedSharding`` this step expects its batch in — what
        an input pipeline's ``place`` callable targets when it issues
        ``jax.device_put`` ahead of the step (docs/data.md)."""
        return self._batch_sharding

    @property
    def donates_batch(self) -> bool:
        """Whether the batch argument is donated (the pipeline-fed
        input slot; each call must then receive fresh buffers)."""
        return self._donate_batch

    @property
    def plan(self):
        """The resolved :class:`~horovod_tpu.parallel.plan.ShardingPlan`
        this step was compiled for (None when built from raw
        mesh/data_axes arguments) — ``bench.py`` emits its canonical
        string as the ``plan`` BENCH field."""
        return self._plan

    @property
    def exchange_hierarchy(self):
        """The exchange topology this step runs: ``"two_level"``/
        ``"flat"`` once resolved against the mesh (sharded exchange),
        the raw knob (``"auto"``) when no sharded exchange is active."""
        return self._hierarchy

    @property
    def fused_collectives(self) -> str:
        """The resolved final-bucket schedule: ``"on"`` when the
        sharded exchange runs the tile-granular fused tail, ``"off"``
        otherwise (docs/fused_kernels.md)."""
        return self._fused_collectives

    @property
    def moe_fused(self) -> Optional[str]:
        """The resolved MoE expert-dispatch schedule this step was
        stamped with: ``"on"`` (tile-fused a2a ⊗ expert-matmul ring),
        ``"off"`` (boundary-wide alltoalls), or ``None`` when the step
        carries no MoE schedule.  An AOT-key field; ``bench.py --moe``
        emits it as ``moe_fused_collectives``."""
        return self._moe_fused

    @property
    def moe_capacity_factor(self) -> Optional[float]:
        """The MoE capacity factor stamped into the AOT key (``None``
        when the step carries no MoE schedule) — a capacity change is a
        schedule change, never a warm-start hit."""
        return self._moe_capacity_factor

    @property
    def reduction(self) -> Optional[str]:
        """The sharded exchange's combine operator (``"sum"`` |
        ``"adasum"``) once resolved (explicit argument > runtime config
        > ``HOROVOD_EXCHANGE_REDUCTION``); ``None`` when no sharded
        exchange is active.  An AOT-key field — a warm start never
        serves a sum executable to an adasum config (docs/adasum.md);
        ``bench.py`` emits it as the ``reduction`` BENCH field."""
        return self._reduction

    @property
    def remat_policy(self) -> str:
        """The resolved remat policy (``none|dots|full|offload``) this
        step was built under — explicit ``remat=`` argument or the
        ``HOROVOD_REMAT_POLICY`` knob (memory/remat.py, docs/memory.md).
        An AOT-key field; ``bench.py --hbm-budget`` emits it as the
        ``remat_policy`` BENCH field."""
        return self._remat_policy

    @property
    def compile_cache_hit(self) -> Optional[bool]:
        """Whether this step's most recent XLA compile was served from
        the persistent AOT store (``True``), compiled fresh and
        serialized for the next start (``False``), or has not happened
        / bypassed the store (``None``).  ``bench.py`` emits this as
        the ``cache_hit`` BENCH field."""
        return self._last_cache_hit

    def _aot_extras(self) -> dict:
        """Explicit AOT key fields (docs/warmstart.md): the knobs the
        warm-start contract names, recorded in the entry for audit even
        though each already shapes the lowered module."""
        return {
            "mesh_shape": tuple(sorted(self._mesh.shape.items())),
            "mode": self._mode,
            "hierarchy": self._hierarchy,
            "fused_collectives": self._fused_collectives,
            "shard_optimizer_states": self._shard_opt,
            "data_axes": self._data_axes,
            "fsdp_axis": self._fsdp_axis,
            "steps_per_call": self._steps_per_call,
            "donate_batch": self._donate_batch,
            "guard": self._guard is not None,
            "plan": None if self._plan is None else self._plan.to_string(),
            "error_feedback": self._error_feedback,
            "reduction": self._reduction,
            "remat": self._remat_policy,
            "moe_fused": self._moe_fused,
            "moe_capacity_factor": self._moe_capacity_factor,
            "sp": self._sp,
        }

    def init(self, params):
        """Place params on the mesh replicated and build optimizer state.

        Accepts leaves that are already *cross-process* arrays — e.g.
        the output of ``broadcast_variables``, whose eager plane places
        one replica per process.  ``device_put`` of such an array onto
        the full mesh is an illegal cross-host reshard (the device sets
        differ) whenever processes own more than one device, so
        fully-replicated cross-process leaves are first dropped to their
        local host copy.
        """
        def localize(x):
            if isinstance(x, jax.Array) and \
                    not x.sharding.is_fully_addressable:
                if not x.is_fully_replicated:
                    raise ValueError(
                        "DistributedTrainStep.init expects replicated "
                        f"params; got a cross-process array sharded as "
                        f"{x.sharding}")
                return np.asarray(x)       # local copy of the replica
            return x

        params = jax.tree_util.tree_map(localize, params)
        if self._fsdp_axis is not None:
            from horovod_tpu.parallel import fsdp as _fsdp

            kw = {} if self._fsdp_min is None else \
                {"min_weight_size": self._fsdp_min}
            params = _fsdp.shard_params(params, self._mesh,
                                        self._fsdp_axis, **kw)
            # optimizer state gets the same placement rule: mu/nu carry
            # their parameter's shape so they shard exactly as it does;
            # scalars/counters come out replicated on the mesh (an
            # unconstrained jit would leave them single-device, which a
            # later mesh-wide step rejects)
            shapes = jax.eval_shape(self._optimizer.init, params)
            out_sh = _fsdp.sharding_specs(shapes, self._mesh,
                                          self._fsdp_axis, **kw)
            opt_state = jax.jit(self._optimizer.init,
                                out_shardings=out_sh)(params)
            return params, opt_state
        params = jax.device_put(params, self._replicated)
        opt_state = jax.device_put(self._optimizer.init(params),
                                   self._replicated)
        return params, opt_state

    def shard_batch(self, batch):
        """Place a host batch onto the mesh sharded along the data axis.

        ``batch`` is the *global* batch, identical on every process (the
        reference's data-parallel contract: each worker reads the full
        shuffled stream and consumes its slice).  Multi-process, each
        process materializes only the rows its addressable devices own
        (``make_array_from_callback``) — no cross-process value
        broadcast/compare and no redundant full-batch transfer, which
        ``device_put`` onto a partially-addressable sharding would do."""
        if jax.process_count() == 1:
            return jax.device_put(batch, self._batch_sharding)
        sharding = self._batch_sharding

        def to_global(arr):
            if isinstance(arr, jax.Array) and \
                    not arr.sharding.is_fully_addressable:
                # already global (spans other processes): keep
                # device_put's idempotent semantics.  Fully-addressable
                # arrays — including ones spread over this process's
                # local devices — take the host path below, which works
                # for any local layout.
                return jax.device_put(arr, sharding)
            # host path: feed each addressable shard straight from the
            # numpy buffer — no extra device round-trips (callers should
            # pass host arrays; a single-device jax.Array costs one D2H)
            if not isinstance(arr, np.ndarray):
                arr = np.asarray(arr)
            return jax.make_array_from_callback(
                arr.shape, sharding, lambda idx: arr[idx])

        return jax.tree_util.tree_map(to_global, batch)

    def shard_local_batch(self, batch):
        """Place per-process rows onto the mesh as one global batch.

        The streaming-reader contract (petastorm analogue): each process
        contributes only the rows *it* read — its shard — rather than
        slicing an identical global batch as :meth:`shard_batch` does.
        Every process must pass the same number of rows per call.
        """
        if jax.process_count() == 1:
            return jax.device_put(batch, self._batch_sharding)
        sharding = self._batch_sharding

        def to_global(arr):
            if not isinstance(arr, np.ndarray):
                arr = np.asarray(arr)
            return jax.make_array_from_process_local_data(sharding, arr)

        return jax.tree_util.tree_map(to_global, batch)

    def compiled_text(self, params, opt_state, batch) -> str:
        """Optimized-HLO dump of the step for these arguments — the
        artifact the collective-fusion guard tests and the
        ``docs/scaling.md`` bytes-on-wire model inspect (see
        :mod:`horovod_tpu.utils.hlo`).  Uses the same compile options
        as execution."""
        args = (params, opt_state, batch)
        if self._guard is not None:
            args += (np.float32(np.inf),)
        return self._step.lower(*args).compile(
            compiler_options=self._compiler_options).as_text()

    def _record_step_telemetry(self, params, t0: float) -> None:
        """Per-call telemetry: step count/duration, the run-context step
        for log/trace correlation, and (once) the cost-model wire bytes
        of the configured exchange per fabric level."""
        self._tel_step_seconds.observe(time.perf_counter() - t0)
        self._tel_steps.inc(self._steps_per_call)
        telemetry.run_context().advance_step(self._steps_per_call)
        if self._tel_wire_done or not self._shard_opt:
            return
        self._tel_wire_done = True
        try:
            from horovod_tpu.analysis.cost_model import exchange_wire_bytes

            payload = sum(
                int(np.size(l)) * getattr(getattr(l, "dtype", None),
                                          "itemsize", 4)
                for l in jax.tree_util.tree_leaves(params))
            extents = [self._mesh.shape[a] for a in self._data_axes]
            n_ici = extents[-1]
            n_dcn = 1
            for e in extents[:-1]:
                n_dcn *= e
            hierarchy = self._hierarchy \
                if self._hierarchy in ("flat", "two_level") else "flat"
            wire = exchange_wire_bytes(float(payload), n_dcn=n_dcn,
                                       n_ici=n_ici, hierarchy=hierarchy)
            g = telemetry.gauge(
                "hvd_exchange_wire_bytes",
                "modeled per-step gradient-exchange bytes per fabric "
                "level (analysis/cost_model.py)")
            g.set(wire.ici, level="ici")
            g.set(wire.dcn, level="dcn")
            if self._reduction == "adasum":
                from horovod_tpu.analysis.cost_model import (
                    adasum_extra_wire_bytes,
                )

                telemetry.gauge(
                    "hvd_adasum_dot_wire_bytes",
                    "modeled extra per-step DCN bytes of the adasum "
                    "outer-level exchange (analysis/cost_model.py)"
                ).set(adasum_extra_wire_bytes(
                    float(payload), n_dcn=n_dcn, n_ici=n_ici))
        except Exception:  # noqa: BLE001 — observability must not sink a step
            pass

    def _guard_unpack(self, out, limit):
        """Guarded steps return ``(params, opt_state, loss, gnorm)``:
        surface the norm to the guardian (which may raise per policy)
        and hand the caller the usual 3-tuple.  The device→host read of
        the norm scalar is the enabled-path cost ``bench.py --chaos``
        reports as guard overhead."""
        params, opt_state, loss, gnorm = out
        self._guard.observe(float(gnorm), limit=float(limit))
        return params, opt_state, loss

    def __call__(self, params, opt_state, batch):
        tel_on = telemetry.enabled()
        t0 = time.perf_counter() if tel_on else 0.0
        if self._guard is not None:
            # the limit rides as a traced runtime scalar: threshold
            # drift as the EMA baseline tightens never recompiles
            limit = np.float32(self._guard.current_limit())
            args = (params, opt_state, batch, limit)
        else:
            limit = None
            args = (params, opt_state, batch)
        if self._compiler_options is None and self._persistent_root is None:
            out = self._step(*args)
            if tel_on:
                self._record_step_telemetry(params, t0)
            if limit is not None:
                return self._guard_unpack(out, limit)
            return out
        # AOT path, for two reasons that share the machinery: per-compile
        # XLA options need lower-once-compile-with-options, and the
        # warm-start store needs the explicit compile to intercept.  The
        # in-memory key covers shardings too — an executable compiled
        # for one input layout must not be fed same-shape
        # differently-sharded arrays — and the cache is LRU-bounded
        # (Config.cache_capacity) so varying batch signatures don't
        # accumulate executables for the process lifetime.
        leaves, treedef = jax.tree_util.tree_flatten(args)
        key = (treedef,
               tuple((np.shape(l), str(getattr(l, "dtype",
                                               type(l).__name__)),
                      repr(getattr(l, "sharding", None)))
                     for l in leaves))
        st = state.global_state() if state.is_initialized() else None
        compiled = self._compiled_cache.pop(key, None)
        if compiled is None:
            self._tel_cache_misses.inc()
            if st is not None:
                st.cache_stats["misses"] += 1
            compiled, hit = self._compile_cache.aot_compile(
                self._step, args,
                extras=self._aot_extras(),
                compiler_options=self._compiler_options,
                directory=self._persistent_root,
                capacity=self._compiled_cache_max)
            self._last_cache_hit = \
                hit if self._persistent_root is not None else None
        else:
            self._tel_cache_hits.inc()
            if st is not None:
                st.cache_stats["hits"] += 1
        self._compiled_cache[key] = compiled     # reinsert = most recent
        while len(self._compiled_cache) > self._compiled_cache_max:
            self._compiled_cache.pop(next(iter(self._compiled_cache)))
        out = compiled(*args)
        if tel_on:
            self._record_step_telemetry(params, t0)
        if limit is not None:
            return self._guard_unpack(out, limit)
        return out


def join_step(grads, has_data, axis: AxisSpec = GLOBAL_AXES):
    """Ragged-data gradient reduction: the in-graph JoinOp.

    The reference's ``hvd.join()`` makes joined (out-of-data) ranks
    contribute zero tensors while others finish
    (``collective_operations.h:259 JoinOp``, zero synthesis in
    ``controller.cc:263-274``).  SPMD formulation: every shard always
    participates; shards whose ``has_data`` flag is False contribute zeros
    and the average divides by the count of contributing shards only.

    Call inside ``shard_map``: ``grads = join_step(grads, has_data)``.
    """
    flag = jnp.asarray(has_data, jnp.float32)
    n = C.allreduce(flag, op=Sum, axis=axis)
    inv = jnp.where(n > 0, 1.0 / jnp.maximum(n, 1.0), 0.0)
    leaves, td = jax.tree_util.tree_flatten(grads)
    masked = [jnp.where(flag > 0, g, jnp.zeros_like(g)) for g in leaves]
    summed = C.grouped_allreduce(masked, op=Sum, axis=axis)
    out = [(s.astype(jnp.float32) * inv).astype(s.dtype) for s in summed]
    return jax.tree_util.tree_unflatten(td, out)
