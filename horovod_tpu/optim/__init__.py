"""Distributed optimizer layer: Horovod's user ergonomics on optax/JAX.

Reference surface being re-created: ``horovod/torch/optimizer.py``
(``_DistributedOptimizer`` with per-parameter async hooks),
``horovod/tensorflow/__init__.py`` (``_DistributedOptimizer:289``,
``DistributedGradientTape:508``), plus gradient accumulation
(``backward_passes_per_step``) and Adasum variants.
"""

from horovod_tpu.optim.optimizer import (
    DistributedAdasumOptimizer,
    DistributedGradientTape,
    DistributedOptimizer,
    ShardedOptimizerState,
    adasum_updates,
    distributed_gradients,
    sharded_distributed_update,
)
from horovod_tpu.optim.sync_batch_norm import SyncBatchNorm, sync_batch_stats
from horovod_tpu.optim.train_step import DistributedTrainStep, join_step

__all__ = [
    "DistributedOptimizer",
    "DistributedAdasumOptimizer",
    "DistributedGradientTape",
    "ShardedOptimizerState",
    "distributed_gradients",
    "adasum_updates",
    "sharded_distributed_update",
    "DistributedTrainStep",
    "join_step",
    "SyncBatchNorm",
    "sync_batch_stats",
]
