"""DistributedOptimizer / DistributedGradientTape for JAX training.

The reference wraps a framework optimizer so gradients are allreduced
before ``step()``: torch hooks per-parameter grad accumulators and fires
async allreduces as each gradient is produced
(``torch/optimizer.py:103-200``), TF rewrites ``compute_gradients``
(``tensorflow/__init__.py:289-316``), both honoring
``backward_passes_per_step`` accumulation and compression.

optax formulation: gradient averaging is itself a gradient transformation,
so ``DistributedOptimizer(opt)`` = ``chain(distributed_gradients(...),
opt)``, wrapped in ``optax.MultiSteps`` when ``backward_passes_per_step >
1``.  Three reduction modes, because JAX has three distribution idioms:

* ``"shard_map"`` (default): the transform runs inside
  ``shard_map``/``pmap`` with mesh axes bound; gradients are reduced with
  one fused in-graph collective per dtype
  (:func:`horovod_tpu.ops.collectives.grouped_allreduce`) which XLA
  overlaps with backward compute — the role of the reference's
  hook-fired async NCCL calls.
* ``"pjit"``: under global-array pjit the batch axis is sharded and XLA
  already inserts the gradient psum during autodiff; the transform is the
  identity (documented no-op, so user code is portable between modes).
* ``"process"``: host-level eager reduction across worker processes via
  the async-handle API (the closest literal analogue of the reference's
  per-tensor enqueue path).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence, Union

import os

import jax
import jax.numpy as jnp
import optax

from horovod_tpu.ops import collectives as C
from horovod_tpu.ops.collectives import Average, ReduceOp
from horovod_tpu.runtime.topology import GLOBAL_AXES

AxisSpec = Union[str, Sequence[str]]


def _sparse_leaf_reduce(g: jax.Array, max_rows: int, op: ReduceOp,
                        axis: AxisSpec,
                        prescale_factor: Optional[float] = None,
                        postscale_factor: Optional[float] = None
                        ) -> jax.Array:
    """Row-sparse reduction of one dense-shaped gradient leaf.

    JAX embedding gradients arrive dense (scatter-add of the used rows),
    so the IndexedSlices decomposition is recovered in-graph: the leaf's
    nonzero rows are extracted with a static ``max_rows`` bound
    (``jnp.nonzero(size=...)`` keeps shapes XLA-static) and exchanged via
    :func:`~horovod_tpu.ops.collectives.sparse_allreduce` — allgather of
    ``max_rows`` rows per shard instead of a dense allreduce of the full
    table (reference IndexedSlices path,
    ``tensorflow/__init__.py:100-110``).  Fill slots use the
    out-of-range index ``V``: their gathered values read as zero and the
    scatter drops them.  Rows beyond ``max_rows`` are silently dropped —
    the bound is the caller's promise about touched rows per step.
    """
    rows = g.shape[0]
    mask = jnp.any(g.reshape(rows, -1) != 0, axis=1)
    if os.environ.get("HOROVOD_DEBUG_SPARSE"):
        # opt-in: surface silent gradient truncation (rows beyond the
        # bound are dropped by design; misconfigured bounds degrade
        # training with no other signal)
        touched = jnp.sum(mask)
        jax.lax.cond(
            touched > max_rows,
            lambda: jax.debug.print(
                "sparse_params: {} touched rows exceed max_rows={}; "
                "excess gradients dropped", touched, max_rows),
            lambda: None)
    (idx,) = jnp.nonzero(mask, size=max_rows, fill_value=rows)
    vals = jnp.take(g, idx, axis=0, mode="fill", fill_value=0)
    vals = C._scale(vals, prescale_factor)
    out = C.sparse_allreduce(vals, idx, dense_rows=rows, axis=axis, op=op)
    return C._scale(out, postscale_factor)


def _path_components(path) -> list:
    """Flattened-path entries as plain strings (dict keys, attr names,
    sequence indices)."""
    out = []
    for entry in path:
        for attr in ("key", "name", "idx"):
            if hasattr(entry, attr):
                out.append(str(getattr(entry, attr)))
                break
        else:
            out.append(str(entry))
    return out


def _match_sparse(path, sparse_params) -> Optional[int]:
    """max_rows for a leaf whose path has a component equal to a
    configured name (or whose full '/'-joined path equals one), else
    None.  Whole-component matching: a pattern 'emb' must not
    accidentally route a dense leaf named 'member' through the
    truncating sparse path."""
    if not sparse_params:
        return None
    comps = _path_components(path)
    joined = "/".join(comps)
    for pat, max_rows in sparse_params.items():
        if pat == joined or pat in comps:
            return int(max_rows)
    return None


def distributed_gradients(op: ReduceOp = Average,
                          axis: AxisSpec = GLOBAL_AXES,
                          mode: str = "shard_map",
                          compression=None,
                          prescale_factor: Optional[float] = None,
                          postscale_factor: Optional[float] = None,
                          sparse_params: Optional[dict] = None
                          ) -> optax.GradientTransformation:
    """optax transform that cross-replica-reduces gradients.

    The composable core of :func:`DistributedOptimizer`; usable standalone
    in any optax chain.

    ``sparse_params`` maps leaf-path component names (e.g.
    ``"embedding"``, or a full ``"encoder/embedding"`` path) to a
    ``max_rows`` bound; matching leaves are reduced through the
    row-sparse allgather path instead of the dense allreduce — the
    reference's IndexedSlices routing (``tensorflow/__init__.py:100-110``,
    ``sparse_as_dense`` being the knob that turns it *off* there; here
    dense is already the default and ``sparse_params`` is the opt-in).
    Requires ``mode='shard_map'``.
    """
    if sparse_params and mode != "shard_map":
        raise ValueError(
            "sparse_params requires mode='shard_map' (pjit autodiff "
            "reduces densely; the process plane exchanges whole tensors)")
    if sparse_params and op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise ValueError("sparse_params supports op=Sum/Average")

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        del params
        leaves, treedef = jax.tree_util.tree_flatten(updates)
        if mode == "pjit":
            reduced = leaves  # XLA autodiff already reduced (see docstring)
        elif mode == "shard_map":
            sparse_rows: dict = {}
            if sparse_params:
                paths = jax.tree_util.tree_flatten_with_path(updates)[0]
                for i, (path, _) in enumerate(paths):
                    m = _match_sparse(path, sparse_params)
                    if m is not None:
                        sparse_rows[i] = m
            ins = [g for i, g in enumerate(leaves) if i not in sparse_rows]
            # Compression.int8 is a wire-*reduction* marker, not a
            # compressor: the shared-scale quantized psum runs inside
            # grouped_allreduce (see compression.Int8WireReduction)
            qbits = getattr(compression, "wire_reduce_bits", None)
            ctxs = None
            if compression is not None and qbits is None:
                pairs = [compression.compress(g) for g in ins]
                ins = [p[0] for p in pairs]
                ctxs = [p[1] for p in pairs]
            dense = C.grouped_allreduce(
                ins, op=op, axis=axis,
                prescale_factor=prescale_factor,
                postscale_factor=postscale_factor,
                quantized_bits=qbits)
            if ctxs is not None:
                dense = [compression.decompress(r, c)
                         for r, c in zip(dense, ctxs)]
            dense_iter = iter(dense)
            reduced = [
                _sparse_leaf_reduce(g, sparse_rows[i], op, axis,
                                    prescale_factor, postscale_factor)
                if i in sparse_rows else next(dense_iter)
                for i, g in enumerate(leaves)]
        elif mode == "process":
            from horovod_tpu.ops import eager

            handles = [
                eager.allreduce_async(g, op=op,
                                      prescale_factor=prescale_factor,
                                      postscale_factor=postscale_factor,
                                      compression=compression)
                for g in leaves]
            reduced = [eager.synchronize(h) for h in handles]
        else:
            raise ValueError(f"unknown mode {mode!r}")
        return jax.tree_util.tree_unflatten(treedef, reduced), state

    return optax.GradientTransformation(init_fn, update_fn)


def DistributedOptimizer(optimizer: optax.GradientTransformation,
                         named_parameters=None,
                         op: ReduceOp = Average,
                         axis: AxisSpec = GLOBAL_AXES,
                         mode: str = "shard_map",
                         compression=None,
                         backward_passes_per_step: int = 1,
                         prescale_factor: Optional[float] = None,
                         postscale_factor: Optional[float] = None,
                         sparse_params: Optional[dict] = None,
                         gradient_predivide_factor: float = 1.0
                         ) -> optax.GradientTransformation:
    """Wrap an optax optimizer so each update uses cross-replica-reduced
    gradients (reference ``DistributedOptimizer`` factory,
    ``torch/optimizer.py:381``, ``tensorflow/__init__.py:356``).

    ``named_parameters`` is accepted for reference-signature parity (JAX
    pytrees carry structure; names are not needed).
    ``backward_passes_per_step`` accumulates N micro-batch gradients
    locally before one reduction+step — note the reduction lives *inside*
    MultiSteps, so skipped micro-steps do no communication, matching the
    reference's delayed-allreduce semantics (``torch/optimizer.py``
    backward_passes_per_step counting).
    """
    del named_parameters
    if gradient_predivide_factor != 1.0:
        # reference semantics (torch/optimizer.py:119-123): split the
        # averaging across the sum — grads scale by 1/f before and f/size
        # after (our Average already applies the 1/size)
        if op != Average:
            raise ValueError(
                "gradient_predivide_factor requires op=Average")
        if prescale_factor is not None or postscale_factor is not None:
            raise ValueError(
                "pass either gradient_predivide_factor or explicit "
                "prescale/postscale factors, not both")
        prescale_factor = 1.0 / gradient_predivide_factor
        postscale_factor = gradient_predivide_factor
    chained = optax.chain(
        distributed_gradients(op=op, axis=axis, mode=mode,
                              compression=compression,
                              prescale_factor=prescale_factor,
                              postscale_factor=postscale_factor,
                              sparse_params=sparse_params),
        optimizer,
    )
    if backward_passes_per_step > 1:
        return optax.MultiSteps(chained,
                                every_k_schedule=backward_passes_per_step)
    return chained


def adasum_updates(axis: AxisSpec = GLOBAL_AXES,
                   mode: str = "shard_map",
                   compression=None) -> optax.GradientTransformation:
    """optax transform that Adasum-reduces *updates* (weight deltas).

    The composable core of :func:`DistributedAdasumOptimizer`: placed
    *after* the local optimizer in an optax chain, it sees exactly the
    per-rank weight delta (optax updates are ``new - old``), which is the
    quantity the Adasum paper reduces.  Per-leaf coefficients match the
    reference's per-layer dot/norm treatment.  A thin, eagerly-validated
    facade over :func:`distributed_gradients` with ``op=Adasum`` — optax
    transforms don't care whether the pytree holds gradients or deltas.
    """

    if mode not in ("shard_map", "process"):
        # pjit's autodiff-inserted mean cannot express the adaptive rule,
        # so there is no identity-transform shortcut the way
        # distributed_gradients has
        raise ValueError(
            f"adasum_updates supports mode='shard_map' or 'process', got "
            f"{mode!r} (Adasum cannot be pjit's implicit mean reduction)")
    return distributed_gradients(op=ReduceOp.ADASUM, axis=axis, mode=mode,
                                 compression=compression)


def DistributedAdasumOptimizer(optimizer: optax.GradientTransformation,
                               named_parameters=None,
                               axis: AxisSpec = GLOBAL_AXES,
                               mode: str = "shard_map",
                               compression=None,
                               backward_passes_per_step: int = 1
                               ) -> optax.GradientTransformation:
    """Adasum in its *delta-optimizer* form (reference
    ``_DistributedAdasumOptimizer``, ``torch/optimizer.py:210-380``;
    TF variant ``tensorflow/__init__.py:334-506``).

    ``op=Adasum`` on raw gradients is only correct for plain SGD: for any
    stateful optimizer (momentum, Adam) the reference instead applies the
    *local* optimizer step first and Adasum-reduces the resulting weight
    delta::

        start  = params                      # stash
        local  = step(optimizer, grads)      # per-rank state update
        delta  = local - start
        params = start + adasum(delta)       # reduce the delta, not grads

    In optax the update returned by ``optimizer.update`` *is* that delta,
    so the whole dance is ``chain(optimizer, adasum_updates(...))`` — the
    reduction moves to the other side of the optimizer compared with
    :func:`DistributedOptimizer`.  Optimizer state (momenta, EMAs) evolves
    from local gradients on every rank, exactly as the reference's
    per-parameter local ``step()`` does.

    Hierarchical dispatch over the (dcn, ici) mesh averages deltas within
    ici and Adasums across dcn (``adasum_gpu_operations.cc:38``).

    Note the state semantics this implies: because momenta evolve from
    *local* gradients, optimizer state is per-rank, not replicated.
    Host reads and checkpoints capture rank 0's (device 0's) state — the
    reference's rank-0-checkpoint convention — and restore follows the
    broadcast-restore pattern (every rank resumes from rank 0's state).
    """
    del named_parameters  # JAX pytrees carry structure; parity-only arg
    chained = optax.chain(
        optimizer,
        adasum_updates(axis=axis, mode=mode, compression=compression),
    )
    if backward_passes_per_step > 1:
        return optax.MultiSteps(chained,
                                every_k_schedule=backward_passes_per_step)
    return chained


class DistributedGradientTape:
    """Eager-style gradient wrapper (reference ``DistributedGradientTape``,
    ``tensorflow/__init__.py:508-572``).

    Wraps a JAX gradient function; calling ``.gradient`` computes local
    gradients then reduces them across worker processes with overlapped
    async allreduces::

        tape = hvd.DistributedGradientTape(jax.grad(loss_fn))
        grads = tape.gradient(params, batch)
    """

    def __init__(self, grad_fn, op: ReduceOp = Average, compression=None,
                 prescale_factor: Optional[float] = None,
                 postscale_factor: Optional[float] = None):
        self._grad_fn = grad_fn
        self._op = op
        self._compression = compression
        self._prescale = prescale_factor
        self._postscale = postscale_factor

    def __call__(self, *args, **kwargs):
        return self.gradient(*args, **kwargs)

    def gradient(self, *args, **kwargs):
        from horovod_tpu.ops import eager

        grads = self._grad_fn(*args, **kwargs)
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        handles = [
            eager.allreduce_async(g, op=self._op,
                                  compression=self._compression,
                                  prescale_factor=self._prescale,
                                  postscale_factor=self._postscale)
            for g in leaves]
        reduced = [eager.synchronize(h) for h in handles]
        return jax.tree_util.tree_unflatten(treedef, reduced)
